// Native host-side data loader: idx parsing, batch assembly, shuffling.
//
// Role parity: the reference's compute-critical native layer lives behind
// ND4J (BLAS/CUDA); on TPU the device math belongs to XLA, so the native
// seam that still pays is the *host input pipeline* feeding the chip —
// idx decoding, uint8->float32 conversion, shuffled minibatch gather and
// one-hot expansion run here at memory bandwidth, off the Python heap
// (≙ the reference's datasets/mnist binary readers + DataSet assembly,
// MnistManager.java:130, BaseDataFetcher.fetch).
//
// Exposed as a C ABI for ctypes (no pybind11 in this image).

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

#include "splitmix64.h"

extern "C" {

// Parse an idx file. Returns 0 on success. Caller frees *out with
// free_buffer. dims must hold up to 8 entries; *ndim receives the rank.
int read_idx(const char* path, uint8_t** out, int64_t* dims, int* ndim,
             int64_t* total_bytes) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  uint8_t header[4];
  if (fread(header, 1, 4, f) != 4 || header[0] != 0 || header[1] != 0) {
    fclose(f);
    return -2;
  }
  int dtype = header[2];
  int rank = header[3];
  if (rank > 8 || dtype != 0x08) {  // uint8 payloads only (MNIST family)
    fclose(f);
    return -3;
  }
  int64_t count = 1;
  for (int i = 0; i < rank; i++) {
    uint8_t b[4];
    if (fread(b, 1, 4, f) != 4) {
      fclose(f);
      return -4;
    }
    dims[i] = ((int64_t)b[0] << 24) | (b[1] << 16) | (b[2] << 8) | b[3];
    count *= dims[i];
  }
  uint8_t* buf = (uint8_t*)malloc(count);
  if (!buf) {
    fclose(f);
    return -5;
  }
  if ((int64_t)fread(buf, 1, count, f) != count) {
    free(buf);
    fclose(f);
    return -6;
  }
  fclose(f);
  *out = buf;
  *ndim = rank;
  *total_bytes = count;
  return 0;
}

void free_buffer(void* p) { free(p); }

// uint8 -> float32 scaled to [0,1].
void u8_to_f32(const uint8_t* src, float* dst, int64_t n) {
  static float lut[256];
  static int init = 0;
  if (!init) {
    for (int i = 0; i < 256; i++) lut[i] = (float)i / 255.0f;
    init = 1;
  }
  for (int64_t i = 0; i < n; i++) dst[i] = lut[src[i]];
}

// In-place Fisher-Yates shuffle of an index array.
void shuffle_indices(int64_t* idx, int64_t n, uint64_t seed) {
  uint64_t st = seed;
  for (int64_t i = n - 1; i > 0; i--) {
    int64_t j = (int64_t)(dl4jtpu_splitmix64(&st) % (uint64_t)(i + 1));
    int64_t tmp = idx[i];
    idx[i] = idx[j];
    idx[j] = tmp;
  }
}

// Assemble one shuffled minibatch: gather `batch` rows of u8 features
// (row_len each) into float32 [0,1] and labels into one-hot float32.
void assemble_batch(const uint8_t* features, const uint8_t* labels,
                    const int64_t* order, int64_t start, int64_t batch,
                    int64_t row_len, int num_classes, float* out_x,
                    float* out_y) {
  for (int64_t b = 0; b < batch; b++) {
    int64_t src = order[start + b];
    const uint8_t* row = features + src * row_len;
    float* dst = out_x + b * row_len;
    u8_to_f32(row, dst, row_len);
    float* yrow = out_y + b * num_classes;
    memset(yrow, 0, sizeof(float) * num_classes);
    int lbl = labels[src];
    if (lbl >= 0 && lbl < num_classes) yrow[lbl] = 1.0f;
  }
}

}  // extern "C"
