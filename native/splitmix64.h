// Shared splitmix64 PRNG step — the single source of truth for every
// native component (and the contract the Python fallbacks reproduce
// bit-exactly).  Keep in sync with nothing: include this, don't copy it.
#ifndef DL4JTPU_SPLITMIX64_H_
#define DL4JTPU_SPLITMIX64_H_

#include <cstdint>

static inline uint64_t dl4jtpu_splitmix64(uint64_t* state) {
  uint64_t z = (*state += 0x9e3779b97f4a7c15ULL);
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
  return z ^ (z >> 31);
}

#endif  // DL4JTPU_SPLITMIX64_H_
