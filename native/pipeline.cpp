// Native host-side prefetching batch pipeline.
//
// Role parity: the reference keeps the device fed through host-side job
// dispensing (BatchActor.java:31 pulling jobs per available worker, plus
// ND4J's native DataSet assembly).  On a TPU host the equivalent hot path
// is overlap: assemble the NEXT shuffled minibatch on background threads
// while the chip executes the current step.  This implements a bounded
// producer/consumer queue of fully-assembled float32 batches (features
// scaled to [0,1], labels one-hot), off the Python heap and outside the
// GIL.  Exposed as a C ABI for ctypes (no pybind11 in this image).

#include "splitmix64.h"

#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <vector>

namespace {

struct Batch {
  std::vector<float> x;
  std::vector<float> y;
  int64_t epoch = 0;  // epoch the batch's first row came from
};

struct Prefetcher {
  const uint8_t* features;  // [n_rows, row_len], borrowed from caller
  const uint8_t* labels;    // [n_rows], borrowed
  int64_t n_rows, row_len, batch;
  int num_classes;
  uint64_t seed;
  size_t depth;

  std::vector<int64_t> order;
  int64_t cursor = 0;  // next row within the current epoch
  int64_t epoch = 0;

  std::deque<Batch> ready;
  std::mutex mu;
  std::condition_variable cv_ready;   // consumer waits
  std::condition_variable cv_space;   // producer waits
  bool stop = false;
  std::thread producer;

  void reshuffle() {
    uint64_t st = seed + (uint64_t)epoch * 0x9e3779b97f4a7c15ULL + 1;
    for (int64_t i = n_rows - 1; i > 0; i--) {
      int64_t j = (int64_t)(dl4jtpu_splitmix64(&st) % (uint64_t)(i + 1));
      std::swap(order[i], order[j]);
    }
  }

  // cursor/epoch/order are producer-private: touched only by assemble()
  // and reshuffle() on the producer thread; the consumer learns the epoch
  // from the Batch it dequeues.
  void assemble(Batch* b) {
    b->x.resize((size_t)batch * row_len);
    b->y.assign((size_t)batch * num_classes, 0.0f);
    for (int64_t r = 0; r < batch; r++) {
      if (cursor >= n_rows) {  // epoch boundary: reshuffle, wrap
        epoch++;
        cursor = 0;
        reshuffle();
      }
      if (r == 0) b->epoch = epoch;  // label after any wrap of the first row
      int64_t src = order[cursor++];
      const uint8_t* row = features + src * row_len;
      float* dst = b->x.data() + r * row_len;
      for (int64_t i = 0; i < row_len; i++) dst[i] = (float)row[i] / 255.0f;
      int lbl = labels[src];
      if (lbl >= 0 && lbl < num_classes)
        b->y[(size_t)r * num_classes + lbl] = 1.0f;
    }
  }

  void run() {
    for (;;) {
      Batch b;
      assemble(&b);  // assembly happens outside the lock
      std::unique_lock<std::mutex> lk(mu);
      cv_space.wait(lk, [&] { return stop || ready.size() < depth; });
      if (stop) return;
      ready.push_back(std::move(b));
      cv_ready.notify_one();
    }
  }
};

}  // namespace

extern "C" {

void* prefetch_create(const uint8_t* features, const uint8_t* labels,
                      int64_t n_rows, int64_t row_len, int num_classes,
                      int64_t batch, uint64_t seed, int depth) {
  if (!features || !labels || n_rows <= 0 || batch <= 0 || depth <= 0)
    return nullptr;
  Prefetcher* p = new Prefetcher();
  p->features = features;
  p->labels = labels;
  p->n_rows = n_rows;
  p->row_len = row_len;
  p->num_classes = num_classes;
  p->batch = batch;
  p->seed = seed;
  p->depth = (size_t)depth;
  p->order.resize(n_rows);
  for (int64_t i = 0; i < n_rows; i++) p->order[i] = i;
  p->reshuffle();
  p->producer = std::thread([p] { p->run(); });
  return p;
}

// Blocks until a batch is ready; copies into caller buffers.
// Returns the epoch the batch came from (>=0), or -1 after destroy.
int64_t prefetch_next(void* handle, float* out_x, float* out_y) {
  Prefetcher* p = (Prefetcher*)handle;
  Batch b;
  int64_t ep;
  {
    std::unique_lock<std::mutex> lk(p->mu);
    p->cv_ready.wait(lk, [&] { return p->stop || !p->ready.empty(); });
    if (p->stop && p->ready.empty()) return -1;
    b = std::move(p->ready.front());
    p->ready.pop_front();
    ep = b.epoch;
    p->cv_space.notify_one();
  }
  memcpy(out_x, b.x.data(), b.x.size() * sizeof(float));
  memcpy(out_y, b.y.data(), b.y.size() * sizeof(float));
  return ep;
}

void prefetch_destroy(void* handle) {
  Prefetcher* p = (Prefetcher*)handle;
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->stop = true;
  }
  p->cv_ready.notify_all();
  p->cv_space.notify_all();
  if (p->producer.joinable()) p->producer.join();
  delete p;
}

}  // extern "C"
