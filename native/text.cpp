// Native tokenizer + vocabulary counter.
//
// Role parity: the reference parallelizes vocabulary construction with an
// actor pipeline (VocabActor.java:243, Word2Vec.buildVocab:247) because
// counting words over a big corpus is the host-side bottleneck before
// embedding training starts.  Here the same job is one tight C++ loop:
// lowercase + split on non-alphanumerics, open-addressing hash count.
// Exposed as a C ABI for ctypes (no pybind11 in this image).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct VocabCounter {
  std::unordered_map<std::string, int64_t> counts;
  int64_t total_tokens = 0;
  bool lowercase;
};

inline bool is_token_char(unsigned char c) {
  return (c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z') ||
         (c >= '0' && c <= '9') || c == '\'' || c >= 0x80;  // keep UTF-8 bytes
}

}  // namespace

extern "C" {

void* vocab_create(int lowercase) {
  VocabCounter* v = new VocabCounter();
  v->lowercase = lowercase != 0;
  return v;
}

// Tokenize `len` bytes of text and fold the token counts in.
// Returns the number of tokens seen in this call.
int64_t vocab_add_text(void* handle, const char* text, int64_t len) {
  VocabCounter* v = (VocabCounter*)handle;
  int64_t n = 0;
  std::string tok;
  tok.reserve(32);
  for (int64_t i = 0; i <= len; i++) {
    unsigned char c = i < len ? (unsigned char)text[i] : ' ';
    if (is_token_char(c)) {
      if (v->lowercase && c >= 'A' && c <= 'Z') c = c - 'A' + 'a';
      tok.push_back((char)c);
    } else if (!tok.empty()) {
      v->counts[tok] += 1;
      n++;
      tok.clear();
    }
  }
  v->total_tokens += n;
  return n;
}

int64_t vocab_size(void* handle) {
  return (int64_t)((VocabCounter*)handle)->counts.size();
}

int64_t vocab_total_tokens(void* handle) {
  return ((VocabCounter*)handle)->total_tokens;
}

// Serialize entries with count >= min_count, sorted by (count desc, word
// asc), as "word\n" lines into `buf` (capacity buf_len) with the matching
// counts in `out_counts` (capacity max_words).  Returns the number of
// words written, or -(needed_bytes) if `buf` is too small.
int64_t vocab_dump(void* handle, int64_t min_count, char* buf,
                   int64_t buf_len, int64_t* out_counts, int64_t max_words) {
  VocabCounter* v = (VocabCounter*)handle;
  std::vector<std::pair<const std::string*, int64_t>> items;
  items.reserve(v->counts.size());
  int64_t needed = 0;
  for (auto& kv : v->counts) {
    if (kv.second >= min_count) {
      items.emplace_back(&kv.first, kv.second);
      needed += (int64_t)kv.first.size() + 1;
    }
  }
  if (needed > buf_len || (int64_t)items.size() > max_words) return -needed;
  std::sort(items.begin(), items.end(), [](const auto& a, const auto& b) {
    if (a.second != b.second) return a.second > b.second;
    return *a.first < *b.first;
  });
  char* w = buf;
  for (size_t i = 0; i < items.size(); i++) {
    memcpy(w, items[i].first->data(), items[i].first->size());
    w += items[i].first->size();
    *w++ = '\n';
    out_counts[i] = items[i].second;
  }
  return (int64_t)items.size();
}

void vocab_destroy(void* handle) { delete (VocabCounter*)handle; }

}  // extern "C"
