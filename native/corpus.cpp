// Native skip-gram pair generation.
//
// Role parity: the reference's Word2Vec hot loop walks every (center,
// context) pair in Java across a thread pool (Word2Vec.trainSentence:288,
// skipGram:304) and gets its arithmetic speed from native BLAS underneath.
// Here the arithmetic is batched on the TPU, so the host-side cost that
// remains is enumerating training pairs; this does that for a whole chunk
// of sentences in one C++ pass, with the reference's per-center random
// window reduction (b = random % window).  C ABI for ctypes.

#include <cstdint>

#include "splitmix64.h"

extern "C" {

// ids: concatenated word indices for all sentences in the chunk.
// offsets: n_sents+1 boundaries into ids (sentence s = [offsets[s],
// offsets[s+1])).  For each center i a window reduction b = rand % window
// is drawn and every context j != i within span (window - b) emits the
// pair (input = ids[j], target = ids[i]).  Writes at most `cap` pairs;
// returns the number written, or -1 if the buffers would overflow
// (callers size cap to sum(len_s * 2 * window), which is an upper bound).
int64_t sg_pairs(const int32_t* ids, const int64_t* offsets, int64_t n_sents,
                 int window, uint64_t seed, int32_t* out_in, int32_t* out_tgt,
                 int64_t cap) {
  if (window <= 0) return 0;
  uint64_t st = seed;
  int64_t n_out = 0;
  for (int64_t s = 0; s < n_sents; s++) {
    const int64_t lo = offsets[s], hi = offsets[s + 1];
    const int64_t n = hi - lo;
    if (n < 2) {
      // keep the RNG stream aligned with per-center draws
      for (int64_t i = 0; i < n; i++) dl4jtpu_splitmix64(&st);
      continue;
    }
    for (int64_t i = 0; i < n; i++) {
      int64_t b = (int64_t)(dl4jtpu_splitmix64(&st) % (uint64_t)window);
      int64_t span = window - b;
      int64_t jlo = i - span < 0 ? 0 : i - span;
      int64_t jhi = i + span + 1 > n ? n : i + span + 1;
      for (int64_t j = jlo; j < jhi; j++) {
        if (j == i) continue;
        if (n_out >= cap) return -1;
        out_in[n_out] = ids[lo + j];
        out_tgt[n_out] = ids[lo + i];
        n_out++;
      }
    }
  }
  return n_out;
}

}  // extern "C"
