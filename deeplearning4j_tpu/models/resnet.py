"""ResNet (CIFAR-style) with BatchNorm — beyond-parity modern CNN.

The reference's CNN story ends at forward-only conv+pool
(ConvolutionDownSampleLayer.java:113-121) and predates both residual
connections and batch normalization; LeNet/AlexNet here mirror its era.
This model brings the framework's CNN family to the modern baseline:
3x3 conv / BN / relu basic blocks with identity skips, the He et al.
CIFAR layout (3 stages of n blocks at 16/32/64 channels, stride-2
transitions, global average pool).

TPU-first notes:
- NHWC activations, HWIO kernels (`lax.conv_general_dilated`), bf16
  compute under the dtypes policy with f32 BN statistics;
- BatchNorm keeps its running statistics in an explicit ``state``
  pytree threaded through the train step (the framework is pure
  functions over pytrees — no mutable layers), updated with momentum
  inside the same jitted step;
- the whole model is stacked-layer pytrees + `lax.conv` calls, so it
  shards over the data axis like every other model (works with
  `mesh.shard_batch`/`place_global`).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax

from deeplearning4j_tpu import dtypes


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    num_classes: int = 10
    in_channels: int = 3
    #: blocks per stage (He CIFAR recipe: depth = 6n+2; n=3 -> ResNet-20)
    blocks_per_stage: int = 3
    stage_channels: tuple = (16, 32, 64)
    bn_momentum: float = 0.9
    bn_eps: float = 1e-5


def _conv_init(key, h, w, cin, cout):
    # He normal fan-in init
    scale = np.sqrt(2.0 / (h * w * cin))
    return jax.random.normal(key, (h, w, cin, cout), jnp.float32) * scale


def _bn_params(c):
    return {"scale": jnp.ones((c,)), "bias": jnp.zeros((c,))}


def _bn_state(c):
    return {"mean": jnp.zeros((c,)), "var": jnp.ones((c,))}


def init_resnet(key, cfg: ResNetConfig):
    """Returns (params, bn_state) pytrees."""
    keys = iter(jax.random.split(key, 4 + 3 * cfg.blocks_per_stage * len(cfg.stage_channels)))
    c0 = cfg.stage_channels[0]
    params: dict[str, Any] = {
        "stem": {"w": _conv_init(next(keys), 3, 3, cfg.in_channels, c0),
                 "bn": _bn_params(c0)},
        "stages": [],
        "head": {
            "w": jax.random.normal(
                next(keys), (cfg.stage_channels[-1], cfg.num_classes),
                jnp.float32,
            ) / np.sqrt(cfg.stage_channels[-1]),
            "b": jnp.zeros((cfg.num_classes,)),
        },
    }
    state: dict[str, Any] = {"stem": _bn_state(c0), "stages": []}
    cin = c0
    for cout in cfg.stage_channels:
        stage_p, stage_s = [], []
        for b in range(cfg.blocks_per_stage):
            block = {
                "conv1": _conv_init(next(keys), 3, 3, cin, cout),
                "bn1": _bn_params(cout),
                "conv2": _conv_init(next(keys), 3, 3, cout, cout),
                "bn2": _bn_params(cout),
            }
            bs = {"bn1": _bn_state(cout), "bn2": _bn_state(cout)}
            if cin != cout:
                block["proj"] = _conv_init(next(keys), 1, 1, cin, cout)
            stage_p.append(block)
            stage_s.append(bs)
            cin = cout
        params["stages"].append(stage_p)
        state["stages"].append(stage_s)
    return params, state


def _conv(x, w):
    # all convs are stride-1 SAME by design: downsampling happens only
    # through the count-corrected average pool at stage transitions
    return lax.conv_general_dilated(
        x, w.astype(x.dtype), (1, 1), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _batch_norm(
    x, p, s, train: bool, momentum: float, eps: float,
    axis_name: str | None = None,
):
    """Returns (y, new_state). Statistics in f32 regardless of compute
    dtype; train mode normalizes with batch stats and rolls the running
    averages, eval mode uses the running stats.

    Sync-BN: under jit/pjit with a batch-sharded input, the means below
    are GLOBAL by construction — XLA inserts the cross-replica reduction,
    so the pjit path is synchronized batch norm already (locked by
    ``test_resnet.py::test_pjit_batch_norm_is_sync``). ``axis_name`` is
    for the per-replica regimes (``shard_map``/``pmap``), where each
    replica sees only its shard: the two raw moments are ``pmean``-ed
    over the named axis (pmean of per-shard VARIANCES would be wrong —
    E[x^2] - E[x]^2 needs globally-averaged moments)."""
    x32 = x.astype(jnp.float32)
    if train:
        if axis_name is not None:
            # cross-replica: pmean the raw moments, then E[x^2]-E[x]^2.
            # (The moment form cancels catastrophically for large-mean
            # near-constant channels, so it is confined to this path
            # where per-shard variances cannot be combined directly.)
            mean = lax.pmean(jnp.mean(x32, axis=(0, 1, 2)), axis_name)
            sq = lax.pmean(
                jnp.mean(jnp.square(x32), axis=(0, 1, 2)), axis_name
            )
            var = sq - jnp.square(mean)
        else:
            mean = jnp.mean(x32, axis=(0, 1, 2))
            var = jnp.var(x32, axis=(0, 1, 2))
        new_s = {
            "mean": momentum * s["mean"] + (1 - momentum) * mean,
            "var": momentum * s["var"] + (1 - momentum) * var,
        }
    else:
        mean, var = s["mean"], s["var"]
        new_s = s
    y = (x32 - mean) * lax.rsqrt(var + eps) * p["scale"] + p["bias"]
    return y.astype(x.dtype), new_s


def resnet_apply(cfg: ResNetConfig, train: bool, axis_name: str | None = None):
    """apply(params, state, x NHWC) -> (logits f32, new_state).

    ``axis_name`` enables cross-replica sync-BN inside per-replica
    regimes (shard_map/pmap); the plain jit/pjit path is sync already
    (see ``_batch_norm``)."""

    def block_fn(x, bp, bs):
        h, bs1 = _batch_norm(
            _conv(x, bp["conv1"]), bp["bn1"], bs["bn1"], train,
            cfg.bn_momentum, cfg.bn_eps, axis_name,
        )
        h = jax.nn.relu(h)
        h, bs2 = _batch_norm(
            _conv(h, bp["conv2"]), bp["bn2"], bs["bn2"], train,
            cfg.bn_momentum, cfg.bn_eps, axis_name,
        )
        skip = _conv(x, bp["proj"]) if "proj" in bp else x
        return jax.nn.relu(h + skip), {"bn1": bs1, "bn2": bs2}

    def apply(params, state, x):
        policy = dtypes.get_policy()
        x = x.astype(policy.compute_dtype)
        h = _conv(x, params["stem"]["w"])
        h, stem_s = _batch_norm(
            h, params["stem"]["bn"], state["stem"], train,
            cfg.bn_momentum, cfg.bn_eps, axis_name,
        )
        h = jax.nn.relu(h)
        new_state = {"stem": stem_s, "stages": []}
        for si, (stage_p, stage_s) in enumerate(
            zip(params["stages"], state["stages"])
        ):
            if si > 0:
                # stride-2 stage transition via average pooling (the
                # parameter-free CIFAR-ResNet downsampling); divide by
                # the per-window element count, not a fixed 4 — with odd
                # spatial dims SAME pads the last window, and a fixed
                # divisor would underweight border activations
                pooled = lax.reduce_window(
                    h, 0.0, lax.add, (1, 2, 2, 1), (1, 2, 2, 1), "SAME"
                )
                counts = lax.reduce_window(
                    jnp.ones(h.shape[1:3], h.dtype)[None, :, :, None],
                    0.0, lax.add, (1, 2, 2, 1), (1, 2, 2, 1), "SAME",
                )
                h = pooled / counts
            ss = []
            for bp, bs in zip(stage_p, stage_s):
                h, nbs = block_fn(h, bp, bs)
                ss.append(nbs)
            new_state["stages"].append(ss)
        h = jnp.mean(h.astype(jnp.float32), axis=(1, 2))  # global avg pool
        logits = h @ params["head"]["w"] + params["head"]["b"]
        return logits, new_state

    return apply


def _supervised_loss(cfg: ResNetConfig):
    apply = resnet_apply(cfg, train=True)

    def loss_fn(params, state, x, y):
        logits, new_state = apply(params, state, x)
        return optax.softmax_cross_entropy(logits, y).mean(), new_state

    return loss_fn


def _sgd_update(optimizer, loss_fn, params, state, opt_state, x, y):
    (loss, new_state), grads = jax.value_and_grad(loss_fn, has_aux=True)(
        params, state, x, y
    )
    updates, opt_state = optimizer.update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)
    return params, new_state, opt_state, loss


def resnet_train_step(cfg: ResNetConfig, optimizer=None):
    """Jitted supervised step threading the BN state:
    ``step(params, state, opt_state, x, y) ->
    (params, state, opt_state, loss)``; labels one-hot (B, C)."""
    optimizer = optimizer or optax.sgd(0.1, momentum=0.9)
    loss_fn = _supervised_loss(cfg)

    @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
    def step(params, state, opt_state, x, y):
        return _sgd_update(
            optimizer, loss_fn, params, state, opt_state, x, y
        )

    def init(key):
        params, state = init_resnet(key, cfg)
        return params, state, optimizer.init(params)

    return step, init


def resnet_run_steps(cfg: ResNetConfig, optimizer=None):
    """One jitted program scanning n supervised steps — the bench/tight-
    loop form (per-step dispatch would be tunnel-latency-bound for a
    model this small; the carry is a few MB so the scan copy is noise).
    ``run(params, state, opt_state, x, y, n) ->
    (params, state, opt_state, losses (n,))``."""
    optimizer = optimizer or optax.sgd(0.1, momentum=0.9)
    loss_fn = _supervised_loss(cfg)

    @functools.partial(
        jax.jit, static_argnums=(5,), donate_argnums=(0, 1, 2)
    )
    def run(params, state, opt_state, x, y, n: int):
        def body(carry, _):
            p, s, o = carry
            p, s, o, loss = _sgd_update(optimizer, loss_fn, p, s, o, x, y)
            return (p, s, o), loss

        (params, state, opt_state), losses = lax.scan(
            body, (params, state, opt_state), None, length=n
        )
        return params, state, opt_state, losses

    return run
