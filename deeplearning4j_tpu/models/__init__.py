"""Model zoo: MultiLayerNetwork orchestrator + named model builders."""

from deeplearning4j_tpu.models.multilayer import MultiLayerNetwork  # noqa: F401
