"""LeNet-5-style conv net on MNIST — the flagship benchmark model.

≙ the dl4j-examples LeNet-MNIST configuration (BASELINE.json configs[0]);
the reference's own conv layer was forward-only
(ConvolutionDownSampleLayer.java:113-121), so this model could never train
there — here it is fully trainable and is the throughput benchmark.

Layout notes for the MXU: NHWC activations, HWIO kernels, batch and
channel dims padded by XLA to lane/sublane tiles; with
``dtypes.MIXED_BF16`` the convs and matmuls run in bfloat16 at 2x rate.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.models.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn import conf as C


def lenet_config(num_classes: int = 10) -> C.MultiLayerConfig:
    confs = [
        C.LayerConfig(
            layer_type="conv_downsample", n_in=1, num_feature_maps=6,
            filter_size=(5, 5), stride=(2, 2), activation="tanh",
        ),
        C.LayerConfig(
            layer_type="conv_downsample", n_in=6, num_feature_maps=16,
            filter_size=(5, 5), stride=(2, 2), activation="tanh",
        ),
        C.LayerConfig(layer_type="dense", n_in=16 * 4 * 4, n_out=120, activation="tanh"),
        C.LayerConfig(layer_type="dense", n_in=120, n_out=84, activation="tanh"),
        C.LayerConfig(
            layer_type="output", n_in=84, n_out=num_classes,
            activation="softmax", loss="MCXENT",
        ),
    ]
    return C.MultiLayerConfig(confs=confs, pretrain=False, backward=True)


def build_lenet(seed: int = 0) -> tuple[MultiLayerNetwork, list]:
    net = MultiLayerNetwork(lenet_config(), seed=seed)
    params = net.init()
    return net, params


def lenet_apply(net: MultiLayerNetwork):
    """Pure forward: (params, x[B,784] or [B,28,28,1]) -> probabilities."""

    def apply(params, x):
        return net.feed_forward_fn(params, x)[-1]

    return apply


def lenet_loss(net: MultiLayerNetwork):
    """Pure loss: (params, x, y_onehot, key) -> scalar, for the trainers."""

    def loss(params, x, y, key=None):
        return net.supervised_score_fn(params, x, y)

    return loss
