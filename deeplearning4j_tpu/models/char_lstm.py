"""Character-level LSTM language model (char-RNN).

≙ the reference's LSTM usage (models/classifiers/lstm/LSTM.java — a
Karpathy-style char model with beam-search decoding) and the
GravesLSTM char-RNN config in BASELINE.json configs[3].
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn import conf as C
from deeplearning4j_tpu.nn import layers as L


class CharLSTM:
    def __init__(self, seq_len: int = 32, lr: float = 0.1, seed: int = 0):
        self.seq_len = seq_len
        self.lr = lr
        self.seed = seed
        self.chars: list[str] = []
        self.char_to_ix: dict[str, int] = {}
        self.mod = L.get("lstm")
        self.conf: C.LayerConfig | None = None
        self.params = None

    def build_vocab(self, text: str) -> None:
        self.chars = sorted(set(text))
        self.char_to_ix = {c: i for i, c in enumerate(self.chars)}
        v = len(self.chars)
        self.conf = C.LayerConfig(layer_type="lstm", n_in=v, n_out=v, activation="tanh")
        self.params = self.mod.init(jax.random.key(self.seed), self.conf)

    def _encode(self, text: str) -> np.ndarray:
        return np.array([self.char_to_ix[c] for c in text], np.int32)

    def _batches(self, ids: np.ndarray, batch: int):
        v = len(self.chars)
        t = self.seq_len
        usable = (len(ids) - 1) // t * t
        xs = ids[:usable].reshape(-1, t)
        ys = ids[1 : usable + 1].reshape(-1, t)
        for s in range(0, len(xs) - batch + 1, batch):
            x = np.eye(v, dtype=np.float32)[xs[s : s + batch]]
            y = np.eye(v, dtype=np.float32)[ys[s : s + batch]]
            yield jnp.asarray(x), jnp.asarray(y)

    def fit(self, text: str, epochs: int = 5, batch: int = 16) -> list[float]:
        if not self.chars:
            self.build_vocab(text)
        ids = self._encode(text)
        mod, conf = self.mod, self.conf

        @jax.jit
        def step(params, x, y):
            loss, grads = jax.value_and_grad(
                lambda p: mod.supervised_score(p, conf, x, y)
            )(params)
            params = jax.tree.map(lambda p, g: p - self.lr * g, params, grads)
            return params, loss

        losses = []
        for _ in range(epochs):
            total, n = 0.0, 0
            for x, y in self._batches(ids, batch):
                self.params, loss = step(self.params, x, y)
                total += float(loss)
                n += 1
            losses.append(total / max(n, 1))
        return losses

    def sample(self, seed_char: str, length: int = 50, temperature: float = 1.0,
               rng_seed: int = 0) -> str:
        """Ancestral sampling one char at a time (≙ LSTM.predict:219)."""
        v = len(self.chars)
        eye = np.eye(v, dtype=np.float32)
        h = jnp.zeros((self.conf.n_in,))
        c = jnp.zeros((self.conf.n_in,))
        tick = jax.jit(lambda x, h, c: self.mod.tick(self.params, self.conf, x, h, c))
        ix = self.char_to_ix[seed_char]
        out = [seed_char]
        key = jax.random.key(rng_seed)
        for _ in range(length):
            y, h, c = tick(jnp.asarray(eye[ix]), h, c)
            key, sub = jax.random.split(key)
            ix = int(jax.random.categorical(sub, y / temperature))
            out.append(self.chars[ix])
        return "".join(out)

    def beam_decode(self, seed_char: str, beam_size: int = 3, n_steps: int = 10):
        emb = jnp.eye(len(self.chars))
        return self.mod.beam_search(
            self.params, self.conf, emb[self.char_to_ix[seed_char]], emb,
            beam_size=beam_size, n_steps=n_steps,
        )
