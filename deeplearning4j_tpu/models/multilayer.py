"""MultiLayerNetwork — the orchestrator.

≙ reference nn/multilayer/MultiLayerNetwork.java:43 (1622 LoC): layer
construction (init:306-370), greedy layer-wise pretrain (:139-218),
feedForward (:426-461), finetune (:1024-1080), fit(DataSetIterator) (:999),
predict (:1089), output (:1184), param pack/unpack (params:762, pack:808,
unPack:896, setParameters:1420), distributed merge (:1354), reconstruct
(:1208).

TPU re-design:
- The network is a thin host-side orchestrator over *pure functions*;
  parameters live in a list of per-layer pytree dicts, and every compute
  path (pretrain solver step, finetune step, full-backprop step, forward)
  is a jitted function cached per batch shape.
- The reference's backprop machinery (computeDeltas:629, backPropGradient
  :850, the R-operator family :496,935,1441,1476) is replaced wholesale by
  ``jax.value_and_grad`` through the feed-forward — including the
  Hessian-free path, which consumes the forward/loss split via jvp/vjp.
- Shape adapters between 2-D batches and NHWC conv blocks reproduce the
  Convolution{Input,Post}Processor reshapes
  (nn/layers/convolution/preprocessor/*.java) automatically.
"""

from __future__ import annotations

import functools
import logging
import math
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu import rng as rng_mod
from deeplearning4j_tpu.datasets.base import DataSet
from deeplearning4j_tpu.nn import layers as L
from deeplearning4j_tpu.nn.conf import MultiLayerConfig, OptimizationAlgorithm
from deeplearning4j_tpu.obs.trace import Tracer
from deeplearning4j_tpu.optimize import Solver
from deeplearning4j_tpu.optimize.api import IterationListener, ModelFunctions
from deeplearning4j_tpu.utils import tree_math as tm

log = logging.getLogger(__name__)

#: tracer track for the training orchestrator's phase spans
TRAIN_TRACK = "train"

Params = list[dict[str, jax.Array]]

PRETRAINABLE = {"rbm", "autoencoder"}


def _adapt_input(x: jax.Array, layer_type: str, channels: int) -> jax.Array:
    """Reshape between flat 2-D batches and NHWC conv blocks.

    ≙ ConvolutionInputPreProcessor / ConvolutionPostProcessor — the
    reference wires these explicitly per layer; here the adapter fires
    automatically from the layer type and input rank.
    """
    if layer_type == "conv_downsample" and x.ndim == 2:
        side = int(math.isqrt(x.shape[1] // max(channels, 1)))
        return x.reshape(x.shape[0], side, side, max(channels, 1))
    if layer_type in ("dense", "output", "rbm", "autoencoder") and x.ndim > 2:
        return x.reshape(x.shape[0], -1)
    return x


class MultiLayerNetwork:
    def __init__(self, conf: MultiLayerConfig, params: Params | None = None, seed: int = 123,
                 tracer: Tracer | None = None):
        self.conf = conf
        self.modules = [L.get(c.layer_type) for c in conf.confs]
        self.keys = rng_mod.KeyStream(seed)
        self.params: Params | None = params
        self.listeners: list[IterationListener] = []
        self._jit_cache: dict = {}
        # disabled-by-default tracer: fit/pretrain/finetune record
        # phase spans on the "train" track when one is wired in
        self.tracer = tracer if tracer is not None else Tracer(enabled=False)

    # -- construction ------------------------------------------------------
    def init(self, key: jax.Array | None = None) -> Params:
        """≙ MultiLayerNetwork.init:306-370."""
        key = key if key is not None else self.keys.next()
        subkeys = jax.random.split(key, len(self.modules))
        self.params = [
            mod.init(k, c) for mod, c, k in zip(self.modules, self.conf.confs, subkeys)
        ]
        return self.params

    def _require_params(self) -> Params:
        if self.params is None:
            self.init()
        return self.params

    # -- forward -----------------------------------------------------------
    def feed_forward_fn(self, params: Params, x: jax.Array, upto: int | None = None,
                        key: jax.Array | None = None, training: bool = False) -> list[jax.Array]:
        """Pure feed-forward returning all activations (≙ feedForward:426)."""
        acts = [x]
        n = len(self.modules) if upto is None else upto
        subkeys = (
            jax.random.split(key, n) if key is not None else [None] * n
        )
        for i in range(n):
            c = self.conf.confs[i]
            h = acts[-1]
            if i in self.conf.preprocessors:
                from deeplearning4j_tpu.nn import preprocessors as pp

                h = pp.get(self.conf.preprocessors[i])(
                    h, subkeys[i] if training else None
                )
            h = _adapt_input(h, c.layer_type, c.n_in if c.layer_type == "conv_downsample" else 0)
            acts.append(
                self.modules[i].activate(params[i], c, h, key=subkeys[i], training=training)
            )
        return acts

    def activation_upto(self, params: Params, x: jax.Array, layer_idx: int) -> jax.Array:
        """Input to layer ``layer_idx`` (≙ activationFromPrevLayer:417)."""
        acts = self.feed_forward_fn(params, x, upto=layer_idx)
        c = self.conf.confs[layer_idx]
        return _adapt_input(acts[-1], c.layer_type, 0)

    def output(self, x, params: Params | None = None) -> jax.Array:
        """Class probabilities (≙ output:1184)."""
        params = params if params is not None else self._require_params()
        fn = self._cached_jit("output", lambda p, x: self.feed_forward_fn(p, x)[-1])
        return fn(params, jnp.asarray(x))

    def predict(self, x, params: Params | None = None) -> np.ndarray:
        """≙ predict:1089."""
        return np.asarray(jnp.argmax(self.output(x, params), axis=-1))

    def reconstruct(self, x, layer_idx: int | None = None) -> jax.Array:
        """Decode back from the given layer (≙ reconstruct:1208)."""
        params = self._require_params()
        n = layer_idx if layer_idx is not None else len(self.modules) - 1
        acts = self.feed_forward_fn(params, jnp.asarray(x), upto=n)
        h = acts[-1]
        for i in reversed(range(n)):
            mod, c = self.modules[i], self.conf.confs[i]
            if hasattr(mod, "prop_down"):
                h = mod.prop_down(params[i], c, h)
            elif hasattr(mod, "decode"):
                h = mod.decode(params[i], c, h)
            else:
                w = params[i][L.api.WEIGHT_KEY]
                h = jax.nn.sigmoid(h @ w.T)
        return h

    # -- scoring -----------------------------------------------------------
    def supervised_score_fn(self, params: Params, x, labels, key=None, training=False):
        """Full-network loss: forward to the last layer's supervised score."""
        acts = self.feed_forward_fn(params, x, upto=len(self.modules) - 1,
                                    key=key, training=training)
        c = self.conf.confs[-1]
        h = _adapt_input(acts[-1], c.layer_type, 0)
        return self.modules[-1].supervised_score(
            params[-1], c, h, labels, key=key, training=training
        )

    def score(self, dataset: DataSet) -> float:
        """≙ Model.score on a DataSet."""
        params = self._require_params()
        fn = self._cached_jit(
            "score", lambda p, x, y: self.supervised_score_fn(p, x, y)
        )
        return float(fn(params, jnp.asarray(dataset.features), jnp.asarray(dataset.labels)))

    # -- training ----------------------------------------------------------
    def pretrain(self, iterator) -> None:
        """Greedy layer-wise pretraining (≙ pretrain:139-218).

        For each pretrainable layer: stream batches, feed them through the
        already-trained stack, and run that layer's Solver on the batch.
        """
        params = self._require_params()
        for i, (mod, c) in enumerate(zip(self.modules, self.conf.confs)):
            if c.layer_type not in PRETRAINABLE:
                continue
            log.info("pretraining layer %d (%s)", i, c.layer_type)
            iterator.reset()
            with self.tracer.region(TRAIN_TRACK, "pretrain_layer",
                                    layer=i, type=c.layer_type):
                for n_batch, batch in enumerate(iterator):
                    with self.tracer.region(TRAIN_TRACK, "pretrain_batch",
                                            layer=i, batch=n_batch):
                        x = jnp.asarray(batch.features)
                        layer_input = self.activation_upto(params, x, i)

                        if hasattr(mod, "gradient") and c.layer_type == "rbm":
                            # CD-k statistics are not autodiff of a scalar: drive a
                            # plain (adagrad-adjusted) iterated update instead of the
                            # line-search solvers, inside one jitted while_loop.
                            params[i] = self._pretrain_cdk(mod, c, params[i], layer_input)
                        else:
                            model = ModelFunctions(
                                score_and_grad=lambda p, k, mod=mod, c=c, xi=layer_input: mod.gradient(p, c, xi, k),
                                score=lambda p, k, mod=mod, c=c, xi=layer_input: mod.score(p, c, xi, k),
                            )
                            solver = Solver(c, model, listeners=self.listeners)
                            params[i], _ = solver.optimize(params[i], self.keys.next())

    def _pretrain_cdk(self, mod, c, layer_params, x):
        """Jitted CD-k update loop for one batch (≙ the RBM fit path)."""
        from deeplearning4j_tpu.optimize import updaters

        cache_key = ("cdk", id(mod), c.to_json(), x.shape)
        if cache_key not in self._jit_cache:

            @jax.jit
            def run(p, key):
                state0 = (p, updaters.init(p), 0)

                def body(state, k):
                    p, ust, it = state
                    _, grads = mod.gradient(p, c, x, k)
                    step, ust = updaters.adjust(c, ust, grads, p)
                    return (tm.sub(p, step), ust, it + 1), None

                keys = jax.random.split(key, c.num_iterations)
                (p, _, _), _ = jax.lax.scan(body, state0, keys)
                return p

            self._jit_cache[cache_key] = run
        return self._jit_cache[cache_key](layer_params, self.keys.next())

    def finetune(self, iterator) -> None:
        """≙ finetune:1024-1080: fit the output layer on top of frozen
        features — or, when ``backward``/HESSIAN_FREE is configured, train
        the whole stack with full backprop."""
        params = self._require_params()
        out_conf = self.conf.confs[-1]
        full_backprop = (
            self.conf.backward
            or out_conf.optimization_algo == OptimizationAlgorithm.HESSIAN_FREE
        )
        iterator.reset()
        for n_batch, batch in enumerate(iterator):
            with self.tracer.region(
                TRAIN_TRACK, "finetune_batch", batch=n_batch,
                full_backprop=full_backprop,
            ):
                x = jnp.asarray(batch.features)
                y = jnp.asarray(batch.labels)
                if full_backprop:
                    model = self._full_model_fns(x, y)
                    solver = Solver(out_conf, model, listeners=self.listeners)
                    new_params, _ = solver.optimize(params, self.keys.next())
                    for i in range(len(params)):
                        params[i] = new_params[i]
                else:
                    h = self.activation_upto(params, x, len(self.modules) - 1)
                    mod = self.modules[-1]
                    model = ModelFunctions(
                        score_and_grad=lambda p, k, h=h, y=y: jax.value_and_grad(
                            lambda q: mod.supervised_score(q, out_conf, h, y, k, training=True)
                        )(p),
                        score=lambda p, k, h=h, y=y: mod.supervised_score(p, out_conf, h, y, k),
                    )
                    solver = Solver(out_conf, model, listeners=self.listeners)
                    params[-1], _ = solver.optimize(params[-1], self.keys.next())

    def _full_model_fns(self, x, y) -> ModelFunctions:
        """Whole-network ModelFunctions incl. forward/loss split for HF."""

        def score(p, key=None):
            return self.supervised_score_fn(p, x, y)

        def forward(p):
            acts = self.feed_forward_fn(p, x, upto=len(self.modules) - 1)
            c = self.conf.confs[-1]
            h = _adapt_input(acts[-1], c.layer_type, 0)
            return self.modules[-1].pre_output(p[-1], c, h)

        c = self.conf.confs[-1]
        from deeplearning4j_tpu.nn import losses as loss_mod

        def loss_on_outputs(logits):
            try:
                return loss_mod.logits_loss(c.loss, y, logits)
            except ValueError:
                from deeplearning4j_tpu.nn import activations

                return loss_mod.get(c.loss)(y, activations.get(c.activation)(logits))

        return ModelFunctions.from_score(score, forward=forward, loss_on_outputs=loss_on_outputs)

    def fit(self, iterator) -> None:
        """≙ fit(DataSetIterator):999 — pretrain (if configured) then finetune."""
        with self.tracer.region(TRAIN_TRACK, "fit"):
            if self.conf.pretrain:
                with self.tracer.region(TRAIN_TRACK, "pretrain"):
                    self.pretrain(iterator)
            iterator.reset()
            with self.tracer.region(TRAIN_TRACK, "finetune"):
                self.finetune(iterator)

    def fit_dataset(self, dataset: DataSet, batch_size: int | None = None) -> None:
        from deeplearning4j_tpu.datasets import ListDataSetIterator

        self.fit(ListDataSetIterator(dataset, batch_size or dataset.num_examples()))

    # -- parameter plumbing ------------------------------------------------
    def params_vector(self) -> np.ndarray:
        """Pack all params into one vector (≙ params:762 / pack:808)."""
        flat, _ = tm.ravel(self._require_params())
        return np.asarray(flat)

    def set_params_vector(self, vec: np.ndarray) -> None:
        """≙ setParameters:1420 / unPack:896."""
        _, unravel = tm.ravel(self._require_params())
        self.params = unravel(jnp.asarray(vec))

    def merge(self, others: Sequence["MultiLayerNetwork"]) -> None:
        """Parameter averaging across replicas (≙ merge:1354-1366)."""
        all_params = [self._require_params()] + [o._require_params() for o in others]
        n = len(all_params)
        self.params = jax.tree.map(lambda *xs: sum(xs) / n, *all_params)

    def clone(self) -> "MultiLayerNetwork":
        net = MultiLayerNetwork(self.conf)
        if self.params is not None:
            net.params = jax.tree.map(lambda x: x, self.params)
        return net

    # -- misc --------------------------------------------------------------
    def set_listeners(self, listeners: Sequence[IterationListener]) -> None:
        self.listeners = list(listeners)

    def _cached_jit(self, name: str, fn):
        if name not in self._jit_cache:
            self._jit_cache[name] = jax.jit(fn)
        return self._jit_cache[name]

    # -- serde (≙ MultiLayerNetwork(String conf, INDArray params) resume path :86)
    def to_bytes(self) -> bytes:
        import io

        buf = io.BytesIO()
        flat, _ = tm.ravel(self._require_params())
        np.savez(buf, params=np.asarray(flat), conf=self.conf.to_json())
        return buf.getvalue()

    @classmethod
    def from_bytes(cls, data: bytes) -> "MultiLayerNetwork":
        import io

        with np.load(io.BytesIO(data), allow_pickle=False) as z:
            conf = MultiLayerConfig.from_json(str(z["conf"]))
            net = cls(conf)
            net.init()
            net.set_params_vector(z["params"])
        return net
