"""Decoder-only transformer LM — flagship beyond-parity model.

The reference's only sequence model is a serial-timestep LSTM
(models/classifiers/lstm/LSTM.java:36); this is the modern counterpart,
built TPU-first to exercise the framework's composed parallelism:

- Parameters are stacked over a leading layer axis and the blocks run
  under one ``lax.scan`` — one compiled block body regardless of depth.
- Tensor parallelism is expressed as pjit shardings (Megatron layout:
  QKV/MLP-in column-split on heads/ffn dim, attention-out/MLP-out
  row-split) via :func:`transformer_shardings`; XLA's SPMD partitioner
  inserts the collectives, nothing is hand-scheduled.
- Data parallelism is the batch axis of the same 2-D ``(data, model)``
  mesh; gradient AllReduce falls out of pjit.
- Optional ``remat`` wraps each block in ``jax.checkpoint`` to trade
  recompute for HBM.
- Compute can run in bf16 (MXU native) with f32 params/softmax.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.ops.attention import attention
from deeplearning4j_tpu.parallel import mesh as mesh_lib
from deeplearning4j_tpu.parallel.expert_parallel import MoEParams, moe_ffn
from deeplearning4j_tpu.parallel.sequence_parallel import ring_attention


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 256
    d_model: int = 128
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 512
    max_len: int = 256
    remat: bool = False
    # remat granularity when remat=True: "full" recomputes the whole
    # block in backward (max memory saving); "dots_no_batch" saves the
    # projection/MLP matmul outputs and recomputes only elementwise ops
    # and the (B,H,T,T) attention scores (jax
    # dots_with_no_batch_dims_saveable policy). The selective policy is
    # the single biggest single-chip perf lever at GPT-2 scale: without
    # it the layer scan stacks two full (L,B,H,T,T) attention-prob
    # tensors (~10GB at B=8/T=1024) plus six (L,B,T,4d) gelu
    # intermediates into HBM every step, measured via xplane profile.
    remat_policy: str = "dots_no_batch"
    # True (default): run the blocks under one lax.scan — one compiled
    # block body regardless of depth, fast compiles. False: unroll the
    # layer loop in Python; ~10% faster steps at GPT-2-small scale (the
    # scan's dynamic-slice/stack bookkeeping measured ~26ms/step at
    # B=16/T=1024) at the cost of depth-proportional compile time. The
    # bench uses False; training CLIs default to True.
    scan_layers: bool = True
    compute_dtype: Any = jnp.float32
    # expert parallelism: n_experts > 0 swaps the dense MLP for a routed
    # MoE FFN with experts one-per-device on the mesh's model axis
    n_experts: int = 0
    moe_k: int = 2
    moe_capacity_factor: float = 2.0
    aux_coef: float = 0.01
    # sequence parallelism: shard the sequence over the data axis and run
    # ring attention (heads stay TP-sharded on the model axis)
    sequence_parallel: bool = False
    # pallas flash-attention kernels (causal, custom-vjp backward, O(T)
    # memory) in place of dense attention; needs T <= 128 or T % 128 == 0
    use_flash: bool = False
    # rotary position embeddings on q/k (RoPE) instead of relying solely
    # on the learned absolute table — the modern long-context scheme
    rope: bool = False
    # grouped-query attention: number of KV heads (None = n_heads, plain
    # MHA). Shrinks the decode KV cache n_heads/n_kv_heads-fold
    n_kv_heads: int | None = None
    # decode attention via the pallas flash-decode kernel over the packed
    # (B, T, Hkv*K) cache (lane-aligned: ~1x HBM bytes vs the 2.67x
    # tile-padding tax of a (B, T, H, K) cache). False falls back to the
    # dense einsum path (useful under SPMD sharding or for debugging).
    decode_kernel: bool = True
    # int8 serving mode (r5): decode expects params produced by
    # :func:`quantize_decode_params` (weight-only int8, per-output-
    # channel scales, dequant fused into the matmul reads) AND stores
    # the KV cache int8 with per-row scales (the kernel dequantizes
    # in-register). Halves the two HBM streams that bound decode —
    # the 247MB/step weight stream and the ~345MB/step cache stream at
    # B=16 (PERF.md "0.60-MBU wall"). Training paths ignore this flag.
    decode_int8: bool = False

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads

    # JSON round-trip, matching the framework's config story (nn/conf.py
    # ≙ NeuralNetConfiguration.toJson): dtypes serialize by name
    def to_json(self) -> str:
        import json

        d = dataclasses.asdict(self)
        d["compute_dtype"] = jnp.dtype(self.compute_dtype).name
        return json.dumps(d)

    @classmethod
    def from_json(cls, s: str) -> "TransformerConfig":
        import json

        d = json.loads(s)
        # tolerant like nn/conf.py's from_dict: ignore unknown keys
        # (forward compatibility) and fall back to defaults for missing
        # ones — the checkpoint-config round-trip must survive version
        # skew in either direction
        known = {f.name for f in dataclasses.fields(cls)}
        d = {k: v for k, v in d.items() if k in known}
        if "compute_dtype" in d:
            d["compute_dtype"] = jnp.dtype(d["compute_dtype"])
        return cls(**d)

    def __post_init__(self):
        if self.n_heads % self.kv_heads:
            raise ValueError(
                f"n_kv_heads ({self.kv_heads}) must divide n_heads "
                f"({self.n_heads})"
            )

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads or self.n_heads


def init_transformer(key, cfg: TransformerConfig):
    """Params pytree; block tensors carry a leading (n_layers, ...) axis."""
    ks = jax.random.split(key, 8)  # ks[7] only consumed by the MoE branch
    d, h, k, f, nl = (
        cfg.d_model, cfg.n_heads, cfg.head_dim, cfg.d_ff, cfg.n_layers,
    )
    s_d = 1.0 / jnp.sqrt(d)
    s_f = 1.0 / jnp.sqrt(f)

    def norm(key, shape, scale):
        return jax.random.normal(key, shape, jnp.float32) * scale

    if cfg.n_experts:
        e = cfg.n_experts
        ffn = {
            "moe": MoEParams(
                wg=norm(ks[4], (nl, d, e), s_d),
                w1=norm(ks[5], (nl, e, d, f), s_d),
                b1=jnp.zeros((nl, e, f)),
                w2=norm(ks[7], (nl, e, f, d), s_f),
                b2=jnp.zeros((nl, e, d)),
            )
        }
    else:
        ffn = {
            "w1": norm(ks[4], (nl, d, f), s_d),
            "b1": jnp.zeros((nl, f)),
            "w2": norm(ks[5], (nl, f, d), s_f),
            "b2": jnp.zeros((nl, d)),
        }
    if cfg.kv_heads == h:
        attn = {"wqkv": norm(ks[2], (nl, d, 3, h, k), s_d)}
    else:  # GQA: separate projections, fewer KV heads
        kq, kk = jax.random.split(ks[2])
        attn = {
            "wq": norm(kq, (nl, d, h, k), s_d),
            "wkv": norm(kk, (nl, d, 2, cfg.kv_heads, k), s_d),
        }
    return {
        "embed": norm(ks[0], (cfg.vocab_size, d), 0.02),
        "pos": norm(ks[1], (cfg.max_len, d), 0.02),
        "blocks": {
            "ln1_scale": jnp.ones((nl, d)),
            "ln1_bias": jnp.zeros((nl, d)),
            **attn,
            "wo": norm(ks[3], (nl, h, k, d), s_d),
            "ln2_scale": jnp.ones((nl, d)),
            "ln2_bias": jnp.zeros((nl, d)),
            **ffn,
        },
        "lnf_scale": jnp.ones((d,)),
        "lnf_bias": jnp.zeros((d,)),
        "head": norm(ks[6], (d, cfg.vocab_size), s_d),
    }


# block-weight leaves quantized for int8 decode, with the axes reduced
# by their matmuls (the scale is per-OUTPUT-channel: max|w| over the
# contraction axes). head contracts d (axis 0).
_INT8_BLOCK_AXES = {
    "wqkv": (1,), "wq": (1,), "wkv": (1,),
    "wo": (1, 2), "w1": (1,), "w2": (1,),
}


def _quantize_int8(w, axes):
    amax = jnp.max(jnp.abs(w), axis=axes, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def quantize_decode_params(params, cfg: TransformerConfig):
    """Weight-only int8 quantization of the decode-streamed matmul
    weights (block projections/MLP + head), per-output-channel scales.

    Returns a params pytree of the same structure with each quantized
    leaf ``name`` stored int8 and a sibling ``name_scale`` f32 leaf;
    embeddings/positions (gather-read, not streamed per step) and
    norm scales/biases stay float. Decode paths dequantize inside the
    jitted program — XLA fuses the int8 read + convert + scale into the
    matmul operand, so the per-step HBM weight stream halves vs bf16.
    Pair with ``dataclasses.replace(cfg, decode_int8=True)`` for the
    fully-quantized path (int8 KV cache + int8 kernel). Leaving
    ``decode_int8=False`` with quantized params is the supported
    weight-only split: ``_w`` dequantizes int8 leaves by dtype, the KV
    cache stays at the compute dtype and the bf16 decode kernel runs
    unchanged — the winning composite under GQA, where the cache is
    already 3x smaller and the weight stream dominates (PERF.md r5
    crossover analysis).
    """
    if cfg.n_experts:
        raise NotImplementedError(
            "int8 decode quantization does not cover MoE experts yet"
        )
    blocks = dict(params["blocks"])
    for name, axes in _INT8_BLOCK_AXES.items():
        if name in blocks:
            q, s = _quantize_int8(blocks[name], axes)
            blocks[name] = q
            blocks[name + "_scale"] = s
    out = dict(params)
    out["blocks"] = blocks
    hq, hs = _quantize_int8(params["head"], (0,))
    out["head"] = hq
    out["head_scale"] = hs
    return out


def _w(p, name, dtype):
    """Read a (possibly int8-quantized) weight leaf at compute dtype.

    For quantized leaves the dequant (convert + per-channel scale) is
    expressed inline so XLA fuses it into the consuming matmul's operand
    read — the HBM traffic is the int8 bytes, not a dequantized copy."""
    w = p[name]
    if w.dtype == jnp.int8:
        return (w.astype(jnp.float32) * p[name + "_scale"]).astype(dtype)
    return w.astype(dtype)


def transformer_shardings(mesh: Mesh, cfg: TransformerConfig | None = None):
    """Megatron TP layout over the mesh's model axis, as a shardings pytree
    mirroring ``init_transformer``'s output."""
    m = mesh_lib.MODEL_AXIS

    def ns(*spec):
        return NamedSharding(mesh, P(*spec))

    rep = ns()
    if cfg is not None and cfg.n_experts:
        # experts one-per-device on the model axis; router replicated
        ffn = {
            "moe": MoEParams(
                wg=rep,
                w1=ns(None, m, None, None),
                b1=ns(None, m, None),
                w2=ns(None, m, None, None),
                b2=ns(None, m, None),
            )
        }
    else:
        ffn = {
            "w1": ns(None, None, m),  # column-parallel on d_ff
            "b1": ns(None, m),
            "w2": ns(None, m, None),  # row-parallel
            "b2": rep,
        }
    if cfg is not None and cfg.kv_heads != cfg.n_heads:
        # GQA: q column-parallel on heads; KV sharded on its head dim
        # when it divides the model axis, else replicated (the standard
        # MQA-on-TP layout — every rank holds the single KV head)
        kv_fits = cfg.kv_heads % mesh.shape[m] == 0
        attn = {
            "wq": ns(None, None, m, None),
            "wkv": ns(None, None, None, m, None) if kv_fits else rep,
        }
    else:
        attn = {"wqkv": ns(None, None, None, m, None)}
    return {
        # embed/pos sharded on d_model over the model axis (the
        # activation-sharded Megatron layout): the embedding cotangent
        # is produced d_model-sharded by the backward pass, so this
        # keeps grad and param shardings aligned — with replicated (or
        # data-dim0 FSDP) embeddings XLA has to full-rematerialize the
        # (V, D) grad to reshard it (the SPMD warning the round-1
        # multichip dryrun recorded)
        "embed": ns(None, m),
        "pos": ns(None, m),
        "blocks": {
            "ln1_scale": rep,
            "ln1_bias": rep,
            # column-parallel on heads: each model shard owns H/tp heads
            **attn,
            # row-parallel back to d_model (psum inserted by XLA)
            "wo": ns(None, m, None, None),
            "ln2_scale": rep,
            "ln2_bias": rep,
            **ffn,
        },
        "lnf_scale": rep,
        "lnf_bias": rep,
        "head": ns(None, m),  # vocab-sharded logits
    }


def _quantized_leaf_sharding(mesh: Mesh, weight_sharding, axes):
    """Sharding for an int8 leaf's per-channel scale: the weight's spec
    with the quantized (size-1 keepdims) axes unsharded. Scales are
    computed over the FULL reduction axis before placement, so a scale
    whose weight is sharded along that axis is a single global value —
    replicated there by construction."""
    spec = list(weight_sharding.spec)
    for ax in axes:
        if ax < len(spec):
            spec[ax] = None
    return NamedSharding(mesh, P(*spec))


def place_transformer_params(mesh: Mesh, params, cfg=None):
    """Place a params pytree (float or int8-quantized serving params)
    with the Megatron layout. Quantized pytrees (extra ``name_scale``
    leaves from :func:`quantize_decode_params`) get scale shardings
    derived from their weight's spec, so int8 serving runs under the
    same dp x tp mesh as bf16."""
    shardings = transformer_shardings(mesh, cfg)
    blocks = params["blocks"]
    if any(
        name in blocks and blocks[name].dtype == jnp.int8
        for name in _INT8_BLOCK_AXES
    ):
        sblocks = dict(shardings["blocks"])
        for name, axes in _INT8_BLOCK_AXES.items():
            if name + "_scale" in blocks:
                sblocks[name + "_scale"] = _quantized_leaf_sharding(
                    mesh, sblocks[name], axes
                )
        shardings = dict(shardings)
        shardings["blocks"] = sblocks
        if "head_scale" in params:
            shardings["head_scale"] = _quantized_leaf_sharding(
                mesh, shardings["head"], (0,)
            )
    return jax.tree.map(mesh_lib.place_global, params, shardings)


def serving_tp_shardings(mesh: Mesh, cfg: TransformerConfig,
                         lora: bool = False):
    """Exact-parity tensor-parallel SERVING layout over the mesh's model
    axis, as a shardings pytree mirroring ``init_transformer``.

    This is deliberately NOT :func:`transformer_shardings` (the training
    Megatron layout): row-parallel ``wo``/``w2`` there make XLA psum
    partial contractions, and the reassociated reduction drifts ~1e-6
    from the single-chip result — enough to flip sampled draws and
    break the serving engine's byte-identical parity bar. Here every
    COLUMN projection is sharded (wq/wqkv/wkv on heads, w1/b1 on d_ff,
    head on vocab) — each output element still reduces over the full
    replicated contraction dim in single-chip order — while every ROW
    projection (wo, w2) stays replicated and its sharded input
    activation is all-gathered first (:func:`_tp_replicate` inside the
    decode builders). Gathers are exact concatenations, so the whole
    forward is bitwise identical to TP=1; the price is shipping
    (B, D)/(B, d_ff) activations per layer instead of Megatron's one
    psum, plus replicated wo/w2 weight streams — the sharded attention
    (the part that scales with batch x context) is where the TP win
    lives."""
    m = mesh_lib.MODEL_AXIS
    tp = mesh.shape[m]
    if cfg.n_heads % tp or cfg.kv_heads % tp:
        raise ValueError(
            f"exact-TP serving needs tp ({tp}) dividing n_heads "
            f"({cfg.n_heads}) and kv_heads ({cfg.kv_heads})"
        )
    if cfg.n_experts:
        raise ValueError("exact-TP serving does not support MoE configs")

    def ns(*spec):
        return NamedSharding(mesh, P(*spec))

    rep = ns()
    if cfg.kv_heads != cfg.n_heads:
        attn = {
            "wq": ns(None, None, m, None),
            "wkv": ns(None, None, None, m, None),
        }
    else:
        attn = {"wqkv": ns(None, None, None, m, None)}
    out = {
        "embed": rep,
        "pos": rep,
        "blocks": {
            "ln1_scale": rep,
            "ln1_bias": rep,
            **attn,
            "wo": rep,  # row projection: replicated, input gathered
            "ln2_scale": rep,
            "ln2_bias": rep,
            "w1": ns(None, None, m),  # column-parallel on d_ff
            "b1": ns(None, m),
            "w2": rep,  # row projection: replicated, input gathered
            "b2": rep,
        },
        "lnf_scale": rep,
        "lnf_bias": rep,
        "head": ns(None, m),  # vocab-sharded logits, gathered at the tail
    }
    if lora:
        # the LoRA attach points are both COLUMN projections, so the
        # bank follows the column layout: A factors replicated (their
        # r-dim contraction runs fully on every rank), B factors
        # sharded on the output dim — b_q's packed n_heads*head_dim
        # minor splits head-major, matching wq's head sharding; b_mlp
        # splits d_ff, matching w1. Deltas land shard-local before the
        # forced gathers, so batched LoRA under TP stays bitwise exact.
        out["lora"] = {
            "a_q": rep,
            "b_q": ns(None, None, None, m),
            "a_mlp": rep,
            "b_mlp": ns(None, None, None, m),
        }
    return out


def place_serving_tp_params(mesh: Mesh, params, cfg: TransformerConfig):
    """Place a (float or int8-quantized) serving params pytree with the
    exact-TP layout of :func:`serving_tp_shardings`; int8 ``name_scale``
    leaves get shardings derived from their weight's spec, exactly as
    :func:`place_transformer_params` does for the training layout."""
    shardings = serving_tp_shardings(mesh, cfg, lora="lora" in params)
    blocks = params["blocks"]
    if any(
        name in blocks and blocks[name].dtype == jnp.int8
        for name in _INT8_BLOCK_AXES
    ):
        sblocks = dict(shardings["blocks"])
        for name, axes in _INT8_BLOCK_AXES.items():
            if name + "_scale" in blocks:
                sblocks[name + "_scale"] = _quantized_leaf_sharding(
                    mesh, sblocks[name], axes
                )
        shardings = dict(shardings)
        shardings["blocks"] = sblocks
        if "head_scale" in params:
            shardings["head_scale"] = _quantized_leaf_sharding(
                mesh, shardings["head"], (0,)
            )
    return jax.tree.map(mesh_lib.place_global, params, shardings)


def serving_tp_cache_sharding(mesh: Mesh, cfg: TransformerConfig):
    """Sharding pytree for an ``init_caches`` allocation under exact-TP
    serving: the packed (nl, 2, B, Tpad, Hkv*K) buffer sharded on its
    head-major minor dim (each rank owns its kv heads' rows — writes
    and attention reads stay rank-local). The int8 per-row scale plane
    has a size-1 minor dim (one scale across ALL heads of a row,
    computed via an exact cross-shard max) and is replicated."""
    kv = NamedSharding(
        mesh, P(None, None, None, None, mesh_lib.MODEL_AXIS)
    )
    if cfg.decode_int8:
        return {"kv": kv, "scale": NamedSharding(mesh, P())}
    return kv


def _layer_norm(x, scale, bias, eps=1e-5):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    out = (x32 - mu) * jax.lax.rsqrt(var + eps) * scale + bias
    return out.astype(x.dtype)


def _rope_tables(positions, head_dim: int, dtype, base: float = 10000.0):
    """(cos, sin) tables for RoPE at the given positions: (..., head_dim/2)."""
    positions = jnp.asarray(positions)  # accept plain int positions
    half = head_dim // 2
    freqs = base ** (-jnp.arange(half, dtype=jnp.float32) / half)
    ang = positions[..., None].astype(jnp.float32) * freqs  # (..., half)
    return jnp.cos(ang).astype(dtype), jnp.sin(ang).astype(dtype)


def _apply_rope(x, cos, sin):
    """Rotate pairs of head-dim channels. x: (..., head_dim); cos/sin
    broadcastable to (..., head_dim/2)."""
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    )


# KV caches are padded to a multiple of this row count (the sublane tile;
# masked rows beyond `pos` contribute nothing, so padding is only wasted
# bandwidth — 8 keeps it under 1.5% at serving lengths)
_DECODE_PAD_T = 8


def _flash_seq_ok(t: int) -> bool:
    """Sequence lengths the training flash kernel accepts: sublane-
    aligned (%8 — Mosaic rejects e.g. a 100-row block shape on real
    TPU) and either one block (<=128) or lane-block-aligned (%128). ONE
    predicate shared by the training block (which raises) and bulk
    prefill (which falls back to dense) so the rule cannot drift."""
    return t % 8 == 0 and (t <= 128 or t % 128 == 0)


def _flash_blocks(t: int) -> tuple[int, int]:
    """(block_q, block_k) for the flash kernel at sequence length t:
    1024/1024 preferred (measured fastest on v5e at T=1024 AND T=8192
    with the fused backward kernel — the r2 512/1024 winner predates
    it), falling back to the largest candidate that divides t — callers
    only guarantee t <= 128 or t % 128 == 0. ONE implementation shared
    by the training block and bulk prefill so kernel selection cannot
    drift."""

    def pick(pref: int) -> int:
        if t <= pref:
            return t
        for b in (pref, 512, 256, 128):
            if b <= pref and t % b == 0:
                return b
        return 128  # t % 128 == 0 guaranteed by the callers

    # (r4: a chained-harness sweep preferred 512/1024 for the long-T
    # forward by -11%, but the full bench measured it 3% SLOWER in situ —
    # standalone ordering does not transfer; the bench window is the
    # arbiter, so the forward keeps 1024/1024.)
    return pick(1024), pick(1024)


def _flash_bwd_blocks(t: int) -> tuple[int, int] | tuple[None, None]:
    """Backward-kernel blocks: 512/2048 at long T (measured -18% kernel
    time vs 1024/1024 at T=8192 on v5e with the fused backward — the
    wide KV block quarters the dq HBM revisit count and halves the
    invisible-cell DMA; the short Q block keeps the f32 s/p tiles small
    enough that Mosaic doesn't spill). (None, None) = inherit the
    forward blocks (r3 sweep: 1024/1024 still wins at T=1024)."""
    if t >= 4096 and t % 2048 == 0:
        return 512, 2048
    return None, None


def _project_qkv(cfg: TransformerConfig, p, h_in):
    """Shared QKV projection for all sequence-shaped forwards (training
    block and bulk prefill): h_in (B, T, D) -> q (B, H, T, K) and the
    UNexpanded k/v (B, H_kv, T, K). One implementation so GQA/MHA
    layouts cannot drift between the paths."""
    if cfg.kv_heads != cfg.n_heads:
        q = jnp.einsum("btd,dhk->bhtk", h_in, _w(p, "wq", h_in.dtype))
        kv = jnp.einsum(
            "btd,dshk->sbhtk", h_in, _w(p, "wkv", h_in.dtype)
        )
        return q, kv[0], kv[1]
    qkv = jnp.einsum(
        "btd,dshk->sbhtk", h_in, _w(p, "wqkv", h_in.dtype)
    )
    return qkv[0], qkv[1], qkv[2]


def _expand_kv(cfg: TransformerConfig, k_r, v_r):
    """GQA group-repeat (no-op for MHA): (B, H_kv, T, K) -> (B, H, T, K)."""
    g = cfg.n_heads // cfg.kv_heads
    if g == 1:
        return k_r, v_r
    return jnp.repeat(k_r, g, axis=1), jnp.repeat(v_r, g, axis=1)


def _tp_replicate(x, tp_mesh):
    """Force ``x`` replicated (an all-gather of its sharded axis) under
    the exact-TP serving layout; identity when no mesh is given.

    This is the load-bearing primitive of byte-exact tensor parallelism:
    every matmul whose CONTRACTION dim would otherwise arrive sharded
    (attention out @ wo, gelu hidden @ w2) gathers its activation first
    and contracts against a REPLICATED weight, so the reduction runs in
    the single-chip flop order. Left to GSPMD, a sharded contraction
    becomes partial-sums + psum — a different association that drifts
    ~1e-6 (measured on this backend), which breaks the engine's
    byte-identical parity bar."""
    if tp_mesh is None:
        return x
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(tp_mesh, P())
    )


def tp_collective_contract(
    cfg: TransformerConfig, n_substeps: int = 1,
    scanned: bool = False,
) -> dict[str, int]:
    """The DECLARED collective signature of one TP serving program with
    ``n_substeps`` fused decode substeps (the contract the static
    auditor enforces — see ``analysis/audit.py``).

    The exact-TP layout emits exactly one replication constraint
    (:func:`_tp_replicate`, lowering to ``sharding_constraint``) per
    sharded contraction: the attention output and the gelu hidden in
    each layer, plus the logits at the tail — ``2 * n_layers + 1`` per
    substep. ``scanned`` is for programs that run the blocks under one
    ``lax.scan`` (prefill with ``cfg.scan_layers``): the two per-layer
    constraints then appear ONCE in the scan body jaxpr, so the
    syntactic count is ``2 + 1`` regardless of depth. Anything else (a
    stray ``psum``, an extra gather, a dropped constraint) changes the
    flop association and silently breaks the byte-exact TP=N ≡ TP=1
    parity bar, so drift from this count is a hard audit failure, not
    a tunable."""
    per_layer = 1 if scanned else cfg.n_layers
    return {
        "sharding_constraint": n_substeps * (2 * per_layer + 1),
    }


def _mlp(p, h_in, tp_mesh=None, delta1=None, sel=None):
    """Shared dense FFN (gelu) over (..., D) activations.

    Under the exact-TP serving layout (``tp_mesh`` set) ``w1``/``b1``
    are column-sharded on d_ff and the gelu hidden is all-gathered
    before the ``w2`` matmul against a REPLICATED ``w2`` — the d_ff
    reduction then runs in the single-chip order, so the output is
    bitwise identical to the unsharded path (a row-parallel ``w2``
    would psum partial sums in a different association).

    ``delta1`` (optional) is a batched-LoRA pre-activation delta added
    to the w1 projection before the gelu, gated per row by ``sel``
    (bool, broadcastable to the hidden): rows with ``sel`` False keep
    the exact base activations — adding an all-zero delta instead
    would still flip -0.0 bits and break the adapter-0 parity bar."""
    h = (
        jnp.einsum("...d,df->...f", h_in, _w(p, "w1", h_in.dtype))
        + p["b1"].astype(h_in.dtype)
    )
    if delta1 is not None:
        h = jnp.where(sel, h + delta1, h)
    h = jax.nn.gelu(h)
    h = _tp_replicate(h, tp_mesh)
    return (
        jnp.einsum("...f,fd->...d", h, _w(p, "w2", h_in.dtype))
        + p["b2"].astype(h_in.dtype)
    )


def init_lora_bank(
    key, cfg: TransformerConfig, n_adapters: int, rank: int,
    scale: float = 0.5,
):
    """Stacked low-rank adapter bank for batched-LoRA serving: N
    adapters' (A, B) factors for the q projection and the MLP w1
    projection of every layer, as FOUR stacked device arrays so one
    fused decode step can gather each KV slot's adapter rows by index
    (S-LoRA/Punica style) instead of swapping weights per request.

    Layout (``nl`` layers, ``N`` adapters, rank ``r``)::

        a_q   (nl, N, d_model, r)    b_q   (nl, N, r, n_heads*head_dim)
        a_mlp (nl, N, d_model, r)    b_mlp (nl, N, r, d_ff)

    The leading layer axis matches ``params["blocks"]`` so prefill's
    ``lax.scan`` scans the bank alongside the blocks. Adapter index 0
    is the ZERO adapter (both factors zeroed): slots carrying 0 take
    the base-model path bitwise (the forward selects, not adds — see
    ``_mlp``). Unlike training-style LoRA init (B=0), adapters 1..N-1
    get random nonzero A *and* B so distinct adapters produce distinct
    outputs out of the box — the serving tests and the bench need
    observable divergence without a training loop.

    Attach points are activation-level deltas (q after projection /
    pre-RoPE, MLP pre-gelu), so GQA (wq) and MHA (wqkv) configs share
    one code path; both are COLUMN projections under the exact-TP
    layout, so the bank shards with ``serving_tp_shardings`` (A
    replicated, B on its output dim) and stays bitwise exact.
    """
    if cfg.n_experts:
        raise ValueError("batched LoRA does not support MoE configs")
    if n_adapters < 2:
        raise ValueError(
            f"n_adapters must be >= 2 (index 0 is the zero adapter), "
            f"got {n_adapters}"
        )
    if rank < 1:
        raise ValueError(f"rank must be >= 1, got {rank}")
    nl, d, f = cfg.n_layers, cfg.d_model, cfg.d_ff
    hk = cfg.n_heads * cfg.head_dim
    ks = jax.random.split(key, 4)

    def factor(k, shape):
        a = scale * jax.random.normal(k, shape, jnp.float32)
        return a.at[:, 0].set(0.0)  # adapter 0 = zero adapter

    return {
        "a_q": factor(ks[0], (nl, n_adapters, d, rank)),
        "b_q": factor(ks[1], (nl, n_adapters, rank, hk)),
        "a_mlp": factor(ks[2], (nl, n_adapters, d, rank)),
        "b_mlp": factor(ks[3], (nl, n_adapters, rank, f)),
    }


def _lora_delta(h_in, a, b):
    """Per-row low-rank delta: activations ``h_in`` (B, T, D) through
    each row's gathered adapter factors ``a`` (B, D, r), ``b``
    (B, r, O) -> (B, T, O). Two thin einsums (rank r contraction) —
    decode-step cost is O(B*r*(D+O)), noise next to the weight
    stream."""
    u = jnp.einsum("btd,bdr->btr", h_in, a.astype(h_in.dtype))
    return jnp.einsum("btr,bro->bto", u, b.astype(h_in.dtype))


def transformer_apply(
    cfg: TransformerConfig, mesh: Mesh | None = None,
    upcast_logits: bool = True,
):
    """Build apply(params, tokens) -> (logits (B, T, V), aux_loss), causal.

    ``mesh`` is required for the MoE (``cfg.n_experts``) and
    ``cfg.sequence_parallel`` modes — both embed shard_map collectives
    inside the jitted forward; the dense/dp-only model needs no mesh.
    ``upcast_logits=False`` returns logits in the compute dtype — the
    training path pairs it with the fused CE
    (:mod:`deeplearning4j_tpu.ops.fused_ce`) so no f32 (B, T, V) copy is
    ever materialized.
    """
    if (cfg.n_experts or cfg.sequence_parallel) and mesh is None:
        raise ValueError("MoE / sequence-parallel modes need a mesh")
    if cfg.use_flash and cfg.sequence_parallel:
        raise ValueError(
            "use_flash and sequence_parallel are mutually exclusive: the "
            "sequence-parallel path attends via the ring, not the local "
            "flash kernel"
        )
    if cfg.rope and cfg.head_dim % 2:
        raise ValueError(
            f"rope needs an even head_dim, got {cfg.head_dim} "
            f"(d_model {cfg.d_model} / n_heads {cfg.n_heads})"
        )
    if cfg.n_experts:
        if cfg.n_experts != mesh.shape[mesh_lib.MODEL_AXIS]:
            raise ValueError(
                f"n_experts ({cfg.n_experts}) must equal the mesh's model "
                f"axis size ({mesh.shape[mesh_lib.MODEL_AXIS]})"
            )
        token_spec = (
            P(None, mesh_lib.DATA_AXIS, None)
            if cfg.sequence_parallel
            else P(mesh_lib.DATA_AXIS, None, None)
        )
        moe = moe_ffn(
            mesh,
            k=cfg.moe_k,
            capacity_factor=cfg.moe_capacity_factor,
            token_spec=token_spec,
        )
    if cfg.sequence_parallel:
        # sequence ring over the data axis; heads stay on the model axis
        ring = ring_attention(
            mesh, causal=True, head_axis=mesh_lib.MODEL_AXIS
        )

    def block(x, p):
        # attention sublayer — internally (B, H, T, K) layout so the
        # flash kernel's (B*H, T, K) view is a free reshape; the bthd
        # layout cost ~3ms/step of physical transposes at GPT-2-small
        # scale (B=16, T=1024)
        h_in = _layer_norm(x, p["ln1_scale"], p["ln1_bias"])
        q_h, k_r, v_r = _project_qkv(cfg, p, h_in)
        if cfg.rope:
            t = q_h.shape[2]
            cos, sin = _rope_tables(
                jnp.arange(t), cfg.head_dim, q_h.dtype
            )  # (T, hd/2)
            cos = cos[None, None, :, :]
            sin = sin[None, None, :, :]
            q_h = _apply_rope(q_h, cos, sin)
            k_r = _apply_rope(k_r, cos, sin)
        k_h, v_h = _expand_kv(cfg, k_r, v_r)
        if cfg.sequence_parallel:
            # the ring path works on (B, T, H, K) — the sequence axis is
            # the sharded one; transposes here are per-shard and cheap
            # next to the ring collectives. Named so remat saves the
            # ring output instead of re-running its collectives in the
            # backward pass.
            o = checkpoint_name(
                ring(
                    q_h.transpose(0, 2, 1, 3),
                    k_h.transpose(0, 2, 1, 3),
                    v_h.transpose(0, 2, 1, 3),
                ).transpose(0, 2, 1, 3),
                "attn_out",
            )
        elif cfg.use_flash:
            from deeplearning4j_tpu.ops.pallas_kernels import (
                flash_attention_trainable,
            )

            t = q_h.shape[2]
            if not _flash_seq_ok(t):
                raise ValueError(
                    f"use_flash needs a seq len that is a multiple of 8 "
                    f"and either <= 128 or a multiple of 128, got {t}"
                )
            # no attn_out naming here: the kernel's own flash_out
            # residual is the saveable (naming both would store the
            # same tensor twice and cost ~450MB at GPT-2-small scale)
            bq, bk = _flash_blocks(t)
            bbq, bbk = _flash_bwd_blocks(t)
            o = flash_attention_trainable(
                q_h, k_h, v_h, causal=True,
                block_q=bq, block_k=bk, layout="bhtd",
                bwd_block_q=bbq, bwd_block_k=bbk,
            )
        else:
            o = checkpoint_name(
                attention(q_h, k_h, v_h, causal=True, layout="bhtd"),
                "attn_out",
            )
        x = x + jnp.einsum("bhtk,hkd->btd", o, _w(p, "wo", x.dtype))
        # ffn sublayer: dense MLP or routed MoE
        h_in = _layer_norm(x, p["ln2_scale"], p["ln2_bias"])
        if cfg.n_experts:
            moe_params = jax.tree.map(
                lambda a: a.astype(x.dtype), p["moe"]
            )
            y, aux = moe(moe_params, h_in)
            x = x + y
        else:
            x = x + _mlp(p, h_in)
            aux = jnp.zeros((), x.dtype)
        return x, aux

    if cfg.remat:
        if cfg.remat_policy == "dots_no_batch":
            # also save the flash-attention custom-call outputs by name
            # (attn_out plus the kernel's internal out/lse residuals —
            # they are not dots, and without the names the policy
            # re-runs the whole pallas forward inside the backward pass)
            body = jax.checkpoint(
                block,
                policy=jax.checkpoint_policies.save_from_both_policies(
                    jax.checkpoint_policies
                    .dots_with_no_batch_dims_saveable,
                    jax.checkpoint_policies.save_only_these_names(
                        "attn_out", "flash_out", "flash_lse"
                    ),
                ),
            )
        elif cfg.remat_policy == "full":
            body = jax.checkpoint(block)
        else:
            raise ValueError(
                f"unknown remat_policy {cfg.remat_policy!r} "
                "(expected 'dots_no_batch' or 'full')"
            )
    else:
        body = block

    def apply(params, tokens):
        b, t = tokens.shape
        x = params["embed"][tokens] + params["pos"][:t]
        x = x.astype(cfg.compute_dtype)
        if cfg.scan_layers:
            x, aux = lax.scan(body, x, params["blocks"])
        else:
            auxes = []
            for i in range(cfg.n_layers):
                p_i = jax.tree.map(lambda a: a[i], params["blocks"])
                x, a = body(x, p_i)
                auxes.append(a)
            aux = jnp.stack(auxes)
        x = _layer_norm(x, params["lnf_scale"], params["lnf_bias"])
        # head matmul in compute dtype (bf16 hits the MXU at full rate —
        # the f32-weight variant measured ~3x slower fwd+bwd on v5e and
        # the head is ~30% of GPT-2-small's FLOPs), then upcast so the
        # softmax/CE runs in f32. The upcast also keeps the backward
        # fast: d_logits arrives f32 and is cast to bf16 *before* the
        # two backward matmuls.
        logits = jnp.einsum(
            "btd,dv->btv", x, params["head"].astype(x.dtype)
        )
        if upcast_logits:
            logits = logits.astype(jnp.float32)
        return logits, jnp.sum(aux.astype(jnp.float32))

    return apply


def transformer_loss(cfg: TransformerConfig, mesh: Mesh | None = None):
    """Next-token cross-entropy (+ MoE aux term): loss(params, tokens)
    with tokens (B, T+1). Uses the memory-fused CE on compute-dtype
    logits — no f32 (B, T, V) materialization in either direction."""
    from deeplearning4j_tpu.ops.fused_ce import (
        cross_entropy_with_integer_labels,
    )

    apply = transformer_apply(cfg, mesh, upcast_logits=False)

    if cfg.sequence_parallel:
        # keep the model's T equal to the (shard-divisible) input length:
        # feed all T tokens and mask the final position instead of
        # slicing the sequence-sharded axis to an uneven T-1
        def loss(params, tokens):
            b, t = tokens.shape
            logits, aux = apply(params, tokens)
            targets = jnp.roll(tokens, -1, axis=1)
            ce_tok = cross_entropy_with_integer_labels(logits, targets)
            mask = (jnp.arange(t) < t - 1).astype(ce_tok.dtype)[None, :]
            ce = jnp.sum(ce_tok * mask) / (jnp.sum(mask) * b)
            return ce + cfg.aux_coef * aux
    else:
        def loss(params, tokens):
            logits, aux = apply(params, tokens[:, :-1])
            ce = cross_entropy_with_integer_labels(
                logits, tokens[:, 1:]
            ).mean()
            return ce + cfg.aux_coef * aux

    return loss


def _decode_builder(cfg: TransformerConfig, tp_mesh=None):
    """Shared KV-cache decode machinery: returns
    ``(forward_one, init_caches, prefill)`` used by sampling and beam
    search. ``forward_one(params, caches, token, pos)`` advances one
    position through all layers.

    ``tp_mesh`` (a 1-D model-axis mesh) builds the exact-TP serving
    variant: params placed per :func:`serving_tp_shardings`, caches per
    :func:`serving_tp_cache_sharding`, sharded activations gathered
    before every row projection (:func:`_tp_replicate`) so outputs are
    bitwise identical to the unsharded program. Requires the dense
    decode path (``decode_kernel=False``) — the Pallas decode kernel is
    a custom call GSPMD cannot partition."""
    if tp_mesh is not None and cfg.decode_kernel:
        raise ValueError(
            "tensor-parallel decode requires decode_kernel=False "
            "(the Pallas kernel cannot be GSPMD-partitioned)"
        )

    def quantize_kv_rows(rows):
        """Per-row int8 quantization of new cache rows: ``rows``
        (..., hk) -> (int8 rows, f32 scales (..., 1)). The row (one
        position's packed heads) is the finest granularity the kernel
        can rescale without per-head bookkeeping; measured logits error
        vs bf16 cache is ~0.3% on random models."""
        return _quantize_int8(rows.astype(jnp.float32), (-1,))

    def write_kv_rows(kv_all, i, pos, kv_row):
        """Write one decode step's K/V rows into the stacked cache at
        layer ``i``. ``kv_row``: (1, 2, B, 1, Hkv*K). Scalar ``pos``
        writes every batch row at the same position with a single fused
        ``dynamic_update_slice`` (the generate/beam path — XLA aliases
        it in place); an (B,) vector scatters each row at its own
        position (the serving engine's per-slot decode depths)."""
        if jnp.ndim(pos) == 0:
            if cfg.decode_int8:
                kv_buf, sc_buf = kv_all["kv"], kv_all["scale"]
                q_row, s_row = quantize_kv_rows(kv_row)
                kv_buf = lax.dynamic_update_slice(
                    kv_buf, q_row, (i, 0, 0, pos, 0)
                )
                sc_buf = lax.dynamic_update_slice(
                    sc_buf, s_row, (i, 0, 0, pos, 0)
                )
                return {"kv": kv_buf, "scale": sc_buf}
            return lax.dynamic_update_slice(
                kv_all, kv_row.astype(kv_all.dtype), (i, 0, 0, pos, 0)
            )
        rows = kv_row[0, :, :, 0, :]  # (2, B, Hkv*K)
        bidx = jnp.arange(rows.shape[1])
        if cfg.decode_int8:
            kv_buf, sc_buf = kv_all["kv"], kv_all["scale"]
            q_rows, s_rows = quantize_kv_rows(rows)
            for plane in range(2):
                kv_buf = kv_buf.at[i, plane, bidx, pos].set(q_rows[plane])
                sc_buf = sc_buf.at[i, plane, bidx, pos].set(s_rows[plane])
            return {"kv": kv_buf, "scale": sc_buf}
        rows = rows.astype(kv_all.dtype)
        for plane in range(2):
            kv_all = kv_all.at[i, plane, bidx, pos].set(rows[plane])
        return kv_all

    def block_decode(x, p, kv_all, i, pos, lora=None, adapter=None):
        # x: (B, D) one position; kv_all: the ONE stacked packed cache
        # (nl, 2, B, Tpad, Hkv*K) (axis 1: K then V) — this layer writes
        # its new K and V rows with a single dynamic_update_slice and
        # XLA aliases the update in place. (The round-1 per-layer scan
        # carried the whole cache stack and restacked it every layer:
        # ~126ms/call of dynamic-update-slice + squeeze bookkeeping at
        # GPT-2-small B=16, measured.) The packed minor dim is the perf
        # story: a (B, T, H, K) cache tiles on (12, 64) -> (16, 128) and
        # streams 2.67x the logical bytes every step (601us/step for the
        # QK read alone, measured r2). Under GQA the cache holds only
        # kv_heads — the memory win.
        if not cfg.decode_kernel:
            # the dense fallback IS the C=1 chunk block — one code path
            # (no separate copy to drift), used under SPMD sharding,
            # for debugging, and as speculative decoding's
            # numerics-matched draft mode — and batched LoRA's decode
            # path (adapter deltas ride the same chunk block)
            y, kv_all = _block_chunk(
                cfg, x[:, None, :], p, kv_all, i, pos, tp_mesh=tp_mesh,
                lora=lora, adapter=adapter,
            )
            return y[:, 0], kv_all
        b = x.shape[0]
        kd = cfg.head_dim
        grp = cfg.n_heads // cfg.kv_heads
        h_in = _layer_norm(x, p["ln1_scale"], p["ln1_bias"])
        if cfg.kv_heads != cfg.n_heads:
            q = jnp.einsum("bd,dhk->bhk", h_in, _w(p, "wq", x.dtype))
            kv = jnp.einsum("bd,dshk->sbhk", h_in, _w(p, "wkv", x.dtype))
            k, v = kv[0], kv[1]
        else:
            qkv = jnp.einsum(
                "bd,dshk->sbhk", h_in, _w(p, "wqkv", x.dtype)
            )
            q, k, v = qkv[0], qkv[1], qkv[2]
        if cfg.rope:
            cos, sin = _rope_tables(pos, cfg.head_dim, x.dtype)
            if jnp.ndim(pos) == 1:
                # per-slot positions (serving): (B, hd/2) tables, one
                # rotation per batch row
                cos, sin = cos[:, None, :], sin[:, None, :]
            else:
                cos, sin = cos[None, None], sin[None, None]  # (hd/2,)
            q = _apply_rope(q, cos, sin)
            k = _apply_rope(k, cos, sin)
        kv_row = jnp.stack(
            [k.reshape(b, -1), v.reshape(b, -1)]
        )[None, :, :, None, :]  # (1, 2, B, 1, Hkv*K)
        kv_all = write_kv_rows(kv_all, i, pos, kv_row)
        if cfg.decode_int8:
            kv_buf, sc_buf = kv_all["kv"], kv_all["scale"]
        else:
            kv_buf, sc_buf = kv_all, None
        from deeplearning4j_tpu.ops.pallas_kernels import (
            flash_decode_attention,
        )

        # query head h = kv*G + g (the _expand_kv repeat order):
        # group into (B, G, Hkv*K) so each group is packed head-major
        qp = (
            q.reshape(b, cfg.kv_heads, grp, kd)
            .transpose(0, 2, 1, 3)
            .reshape(b, grp, cfg.kv_heads * kd)
        )
        # the kernel takes the STACKED cache and selects the (static)
        # layer in its index map — slicing here would materialize a
        # full-cache copy per layer (custom calls need dense operands)
        o = flash_decode_attention(
            qp, kv_buf, pos, n_kv_heads=cfg.kv_heads, layer=i,
            kv_scales=sc_buf,
        )
        o_flat = (
            o.reshape(b, grp, cfg.kv_heads, kd)
            .transpose(0, 2, 1, 3)
            .reshape(b, cfg.n_heads * kd)
        )
        x = x + o_flat @ _w(p, "wo", x.dtype).reshape(
            cfg.n_heads * kd, -1
        )
        h_in = _layer_norm(x, p["ln2_scale"], p["ln2_bias"])
        if cfg.n_experts:
            from deeplearning4j_tpu.parallel.expert_parallel import (
                moe_reference,
            )

            moe_params = jax.tree.map(
                lambda a: a.astype(x.dtype), p["moe"]
            )
            # activation must match moe_ffn's (gelu), or decode runs a
            # different model than was trained
            x = x + moe_reference(
                moe_params, h_in, k=cfg.moe_k, activation=jax.nn.gelu
            )
        else:
            x = x + _mlp(p, h_in)
        return x, kv_all

    def forward_one(params, caches, token, pos, adapter=None):
        """One position through all layers; returns (logits, caches).

        ``pos`` is a scalar (every batch row at the same depth — the
        generate/beam/speculative paths) or an (B,) int vector of
        per-row positions (the serving engine, where each slot decodes
        at its own depth).

        ``adapter`` (B,) int rows (with a ``params["lora"]`` bank
        present) applies batched-LoRA deltas per row — dense path only;
        the serving engine forces ``decode_kernel=False`` when a bank
        is loaded.

        The layer loop is UNROLLED (n_layers static python loop): the
        round-1 lax.scan spent a third of decode wall time in while-loop
        bookkeeping alone (measured via hlo_stats), and its cache carry
        defeated in-place updates.
        """
        kv_all = caches
        lora = params.get("lora") if adapter is not None else None
        if lora is not None and cfg.decode_kernel:
            raise ValueError(
                "batched LoRA decode requires decode_kernel=False"
            )
        # explicit clamp, matching forward_chunk's mode='clip': the
        # speculative draft legitimately calls at pos up to total+k-2
        # (scratch slots whose outputs are discarded) and must not rely
        # on XLA's implicit out-of-bounds gather clamping
        emb_pos = jnp.minimum(pos, cfg.max_len - 1)
        x = (params["embed"][token] + params["pos"][emb_pos]).astype(
            cfg.compute_dtype
        )
        for i in range(cfg.n_layers):
            p_i = jax.tree.map(lambda a: a[i], params["blocks"])
            l_i = (None if lora is None
                   else jax.tree.map(lambda a: a[i], lora))
            x, kv_all = block_decode(
                x, p_i, kv_all, i, pos, lora=l_i, adapter=adapter
            )
        x = _layer_norm(x, params["lnf_scale"], params["lnf_bias"])
        # head matmul with bf16 (or dequantized-int8) OPERANDS — half/
        # quarter the weight stream and the MXU fast path; decode is
        # weight-streaming-bound — but f32 ACCUMULATION: a bf16-output
        # dot would quantize the logits to 8 mantissa bits, creating
        # arbitrary ties at the top-k threshold and in beam scores
        logits = jnp.einsum(
            "bd,dv->bv", x, _w(params, "head", x.dtype),
            preferred_element_type=jnp.float32,
        )
        # TP: vocab-sharded logits gather here (exact concatenation) so
        # the host-visible logits buffer — and everything sampling reads
        # — is replicated and bitwise identical to TP=1
        return _tp_replicate(logits, tp_mesh), kv_all

    def cast_params(params):
        """One-time cast of the streamed weights to the compute dtype.

        Decode is HBM-bound on the weight stream: without this, every
        per-step fused matmul re-reads f32 weights and converts inline —
        2x the bytes of the bf16 stream. Called once at the top of the
        jitted generate/beam program; a no-op at f32. int8-quantized
        leaves (and their f32 per-channel scales) pass through
        untouched: the int8 bytes ARE the stream, and the scales must
        stay f32 for the fused dequant."""

        quant_scales = {n + "_scale" for n in _INT8_BLOCK_AXES}

        def cast_leaf(a):
            if jnp.issubdtype(a.dtype, jnp.floating):
                return a.astype(cfg.compute_dtype)
            return a

        def cast(name, a):
            if name in quant_scales:  # NOT ln1_scale/ln2_scale
                return a
            # a may itself be a pytree (MoEParams): cast its leaves
            return jax.tree.map(cast_leaf, a)
        out = dict(params)
        out["blocks"] = {
            name: cast(name, a) for name, a in params["blocks"].items()
        }
        if params["head"].dtype != jnp.int8:
            out["head"] = params["head"].astype(cfg.compute_dtype)
        return out

    def init_caches(batch: int, total: int):
        nl, h, kd = cfg.n_layers, cfg.kv_heads, cfg.head_dim
        # ONE stacked cache (nl, 2, B, Tpad, Hkv*K) — K and V planes in
        # one buffer so each decode layer issues a single fused write.
        # Sized (and thus every step's attention span) to the actual
        # decode length, not max_len, rounded up to the sublane tile —
        # and, above the kernel's 1024-row block cap, to a 512 multiple
        # so the length always factors into large 8-aligned blocks (a
        # Tpad like 8*prime would otherwise degenerate the kernel's
        # block search to 8-row blocks: ~100x the per-cell fixed cost).
        # Packed (Tpad, Hkv*K) minor layout: see block_decode.
        if total <= 1024:
            tpad = -(-total // _DECODE_PAD_T) * _DECODE_PAD_T
        else:
            tpad = -(-total // 512) * 512
        if cfg.decode_int8:
            # int8 rows + per-row f32 scales (trailing singleton keeps
            # the scale blocks Mosaic-legal: last dim 1 = full dim)
            return {
                "kv": jnp.zeros(
                    (nl, 2, batch, tpad, h * kd), jnp.int8
                ),
                "scale": jnp.zeros(
                    (nl, 2, batch, tpad, 1), jnp.float32
                ),
            }
        return jnp.zeros(
            (nl, 2, batch, tpad, h * kd), cfg.compute_dtype
        )

    def prefill(params, caches, prompt, last_idx=None, adapter=None):
        """Bulk prefill: ONE causal forward over the whole prompt fills
        every layer's KV cache and yields the last-position logits —
        the standard inference split (parallel prefill, serial decode).
        Round 1 walked the prompt through ``forward_one`` position by
        position: T_p sequential layer scans; this is a single
        training-shaped pass (T_p-way parallel on the MXU).

        ``last_idx`` (traced int, default ``tp - 1``) selects which row
        the returned logits come from — callers that right-pad the
        prompt to a length bucket (the serving engine) pass the true
        last-token index. Causal masking makes the padded rows
        invisible to rows <= last_idx, so the logits are bitwise
        identical to an exact-length prefill. A (B,) VECTOR ``last_idx``
        selects a per-row last index — the batched-admission path,
        where rows of one dispatch carry prompts of different true
        lengths inside the same bucket; the per-row gather copies the
        same values the scalar program reads, so logits stay row-wise
        bitwise identical to B=1 prefills.

        ``adapter`` (B,) int rows (with a ``params["lora"]`` bank
        present) applies each row's batched-LoRA deltas; the bank's
        leading layer axis scans alongside ``params["blocks"]``.
        """
        b, tp = prompt.shape
        if tp == 0:
            # empty prompt: nothing to prefill — decode starts from
            # uniform logits, as the round-1 per-position walk did
            return caches, jnp.zeros((b, cfg.vocab_size), jnp.float32)
        lora = params.get("lora") if adapter is not None else None
        kv_all = caches  # (nl, 2, B, Tpad, Hkv*K) packed
        x = (params["embed"][prompt] + params["pos"][:tp]).astype(
            cfg.compute_dtype
        )
        if cfg.rope:
            cos, sin = _rope_tables(
                jnp.arange(tp), cfg.head_dim, cfg.compute_dtype
            )  # (Tp, hd/2)
            cos_b = cos[None, None, :, :]
            sin_b = sin[None, None, :, :]

        def layer(x, xs):
            if lora is None:
                p, kv = xs  # kv: (2, B, Tpad, Hkv*K); int8 mode: dict
                lo = None
            else:
                p, lo, kv = xs
            h_in = _layer_norm(x, p["ln1_scale"], p["ln1_bias"])
            q, k_r, v_r = _project_qkv(cfg, p, h_in)
            if lo is not None:
                # same attach point as _block_chunk: q delta pre-RoPE,
                # adapter-0 rows select the untouched base projection
                dq = _lora_delta(
                    h_in,
                    jnp.take(lo["a_q"], adapter, axis=0),
                    jnp.take(lo["b_q"], adapter, axis=0),
                ).reshape(
                    b, tp, cfg.n_heads, cfg.head_dim
                ).transpose(0, 2, 1, 3)
                q = jnp.where(
                    (adapter > 0)[:, None, None, None], q + dq, q
                )
            if cfg.rope:
                q = _apply_rope(q, cos_b, sin_b)
                k_r = _apply_rope(k_r, cos_b, sin_b)
            # cache holds the UNexpanded kv heads packed (B, T, Hkv*K)
            kv_rows = jnp.stack(
                [
                    k_r.transpose(0, 2, 1, 3).reshape(b, tp, -1),
                    v_r.transpose(0, 2, 1, 3).reshape(b, tp, -1),
                ]
            )
            if cfg.decode_int8:
                q_rows, s_rows = quantize_kv_rows(kv_rows)
                kv = {
                    "kv": lax.dynamic_update_slice(
                        kv["kv"], q_rows, (0, 0, 0, 0)
                    ),
                    "scale": lax.dynamic_update_slice(
                        kv["scale"], s_rows, (0, 0, 0, 0)
                    ),
                }
            else:
                kv = lax.dynamic_update_slice(
                    kv, kv_rows.astype(kv.dtype), (0, 0, 0, 0)
                )
            k_h, v_h = _expand_kv(cfg, k_r, v_r)
            if cfg.use_flash and _flash_seq_ok(tp) and tp_mesh is None:
                # keep long-prompt prefill O(T) like training — dense
                # attention would materialize (B, H, Tp, Tp) scores.
                # Prompts of other lengths fall back to dense (inference
                # inputs are arbitrary; training raises instead).
                from deeplearning4j_tpu.ops.pallas_kernels import (
                    flash_attention_trainable,
                )

                # forward-only (prefill never differentiates): no
                # backward block overrides
                bq, bk = _flash_blocks(tp)
                o = flash_attention_trainable(
                    q, k_h, v_h, causal=True,
                    block_q=bq, block_k=bk, layout="bhtd",
                )
            else:
                o = attention(q, k_h, v_h, causal=True, layout="bhtd")
            # TP: gather the head-sharded attention output before the
            # row projection so the h*k reduction keeps single-chip
            # order (see _tp_replicate)
            o = _tp_replicate(o, tp_mesh)
            x = x + jnp.einsum("bhtk,hkd->btd", o, _w(p, "wo", x.dtype))
            h_in = _layer_norm(x, p["ln2_scale"], p["ln2_bias"])
            if cfg.n_experts:
                from deeplearning4j_tpu.parallel.expert_parallel import (
                    moe_reference,
                )

                moe_params = jax.tree.map(
                    lambda a: a.astype(x.dtype), p["moe"]
                )
                # per-token dense routing, matching block_decode
                flat = h_in.reshape(-1, h_in.shape[-1])
                y = moe_reference(
                    moe_params, flat, k=cfg.moe_k, activation=jax.nn.gelu
                )
                x = x + y.reshape(h_in.shape)
            elif lo is not None:
                dm = _lora_delta(
                    h_in,
                    jnp.take(lo["a_mlp"], adapter, axis=0),
                    jnp.take(lo["b_mlp"], adapter, axis=0),
                )
                x = x + _mlp(p, h_in, tp_mesh, delta1=dm,
                             sel=(adapter > 0)[:, None, None])
            else:
                x = x + _mlp(p, h_in, tp_mesh)
            return x, kv

        if lora is None:
            xs = (params["blocks"], kv_all)
        else:
            xs = (params["blocks"], lora, kv_all)
        x, kv_all = lax.scan(layer, x, xs)
        if last_idx is None:
            x_last = x[:, -1]
        elif jnp.ndim(last_idx) == 1:
            x_last = jnp.take_along_axis(
                x, last_idx[:, None, None], axis=1
            )[:, 0]
        else:
            x_last = lax.dynamic_index_in_dim(
                x, last_idx, axis=1, keepdims=False
            )
        x = _layer_norm(
            x_last, params["lnf_scale"], params["lnf_bias"]
        )
        logits = jnp.einsum(
            "bd,dv->bv", x, _w(params, "head", x.dtype),
            preferred_element_type=jnp.float32,
        )  # bf16 operands, f32 accumulation — see forward_one
        return kv_all, _tp_replicate(logits, tp_mesh)

    return forward_one, init_caches, prefill, cast_params


# -- block-paged KV views ---------------------------------------------------
#
# The paged serving pool (serving/cache_pool.py:PagedKVPool) stores KV
# as one shared pool of fixed-size blocks addressed by per-slot int32
# block tables. These helpers bridge that layout and the slab-shaped
# programs _decode_builder emits: gather the table's blocks into a
# contiguous per-slot view, run the UNCHANGED slab program, scatter the
# view back block-by-block. Gather/scatter are pure data movement, so
# the slab program's arithmetic — and therefore its token streams — is
# byte-identical by construction; the engine's paged_parity probe pins
# exactly that. Block 0 is the permanently-zero SENTINEL: unallocated
# table entries point at it, inactive slots' dead decode writes land in
# it, and every scatter re-zeroes it in the same program.


def paged_gather(blocks, tables):
    """Contiguous (n_layers, 2, n_slots, Tpad, Hkv*K) slab view of a
    paged pool: leafwise take of every slot's blocks in table order.
    Sentinel entries contribute exact-zero rows, matching the zero rows
    a slab cache holds beyond each slot's writes."""
    def g(x):
        nl, two, _, bs, hk = x.shape
        b, bps = tables.shape
        v = jnp.take(x, tables.reshape(-1), axis=2)
        return v.reshape(nl, two, b, bps * bs, hk)
    return jax.tree.map(g, blocks)


def paged_scatter(blocks, tables, view):
    """Write a slab view back into the block pool (leafwise scatter in
    table order), then re-zero the sentinel. Duplicate table entries —
    prefix blocks byte-shared across slots — receive identical bytes
    from every writer (their view rows were gathered from the same
    block and decode only rewrites each slot's own position row), so
    the scatter is order-independent; the sentinel is the one target
    that can collect differing garbage (inactive slots' dead rows) and
    is re-zeroed here."""
    def s(x, v):
        nl, two, _, bs, hk = x.shape
        b, bps = tables.shape
        rows = v.reshape(nl, two, b * bps, bs, hk)
        out = x.at[:, :, tables.reshape(-1)].set(rows)
        return out.at[:, :, 0].set(0)
    return jax.tree.map(s, blocks, view)


def paged_slot_gather(blocks, table_row):
    """One slot's contiguous batch-1 slab (the paged seg_fetch /
    partial-hit scratch view): take of a single (blocks_per_slot,)
    table row."""
    def g(x):
        nl, two, _, bs, hk = x.shape
        bps = table_row.shape[0]
        v = jnp.take(x, table_row, axis=2)
        return v.reshape(nl, two, 1, bps * bs, hk)
    return jax.tree.map(g, blocks)


def paged_slot_scatter(blocks, table_row, slab):
    """Land a batch-1 slab (a prefill/chunk scratch cache) in the
    blocks one table row names, re-zeroing the sentinel. The slab
    covers the FULL Tpad rows — zeros beyond the prompt included — so
    the write wipes any stale bytes a reused block carried, exactly as
    the slab insert wiped whole slabs."""
    def s(x, v):
        nl, two, _, bs, hk = x.shape
        bps = table_row.shape[0]
        rows = v.reshape(nl, two, bps, bs, hk)
        out = x.at[:, :, table_row].set(rows)
        return out.at[:, :, 0].set(0)
    return jax.tree.map(s, blocks, slab)


def paged_block_copy(blocks, src, dst):
    """Copy one block's rows (``src`` → ``dst``) across every leaf —
    the full-hit tail-copy / block-zeroing primitive (``src=0`` copies
    the sentinel, i.e. zeroes ``dst``)."""
    return jax.tree.map(
        lambda x: x.at[:, :, dst].set(x[:, :, src]), blocks
    )


def make_paged_fwd1(fwd1):
    """Paged wrapper of a ``_decode_builder`` ``forward_one``: gather
    the block pool into the slab view, run the IDENTICAL slab step
    (same kernel, same arithmetic), scatter back. The paged caches
    pytree is ``{"blocks": pool leaves, "tables": (n_slots,
    blocks_per_slot) int32}`` — tables thread through the jitted
    programs as traced data, so ONE compiled program serves every
    block mapping."""
    def paged_fwd1(params, pcaches, token, pos, adapter=None):
        tables = pcaches["tables"]
        view = paged_gather(pcaches["blocks"], tables)
        logits, view = fwd1(params, view, token, pos, adapter=adapter)
        return logits, {
            "blocks": paged_scatter(pcaches["blocks"], tables, view),
            "tables": tables,
        }
    return paged_fwd1


def _check_decode_len(cfg, tp, max_new):
    total = tp + max_new
    if total > cfg.max_len:
        raise ValueError(
            f"prompt+max_new ({total}) exceeds max_len ({cfg.max_len})"
        )
    return total


def transformer_generate(cfg: TransformerConfig):
    """Autoregressive sampling with a per-layer KV cache.

    ≙ the reference's LSTM sampling decode capability
    (models/classifiers/lstm/LSTM.java:219) at the transformer level.
    Returns ``generate(params, prompt, key, max_new, temperature, top_k)
    -> tokens (B, Tp + max_new)``; the whole decode (prefill + sampling)
    is two ``lax.scan``s inside one jittable function. ``temperature=0``
    decodes greedily. MoE configs decode through the dense per-token
    routing (generation is single-chip; capacity buffers are pointless
    at T=1).
    """
    forward_one, init_caches, do_prefill, cast_params = _decode_builder(cfg)

    def generate(params, prompt, key, max_new: int,
                 temperature: float = 1.0, top_k: int | None = None,
                 approx_top_k: bool = False):
        b, tp = prompt.shape
        total = _check_decode_len(cfg, tp, max_new)
        params = cast_params(params)
        caches, logits = do_prefill(params, init_caches(b, total), prompt)

        def sample(logits, key):
            logits = _top_k_filter(logits, top_k, approx_top_k)
            if temperature == 0:
                return jnp.argmax(logits, axis=-1).astype(prompt.dtype)
            return jax.random.categorical(
                key, logits / temperature, axis=-1
            ).astype(prompt.dtype)

        def step(carry, i):
            caches, logits, key = carry
            key, sub = jax.random.split(key)
            tok = sample(logits, sub)
            logits, caches = forward_one(params, caches, tok, tp + i)
            return (caches, logits, key), tok

        (_, _, _), new_tokens = lax.scan(
            step, (caches, logits, key), jnp.arange(max_new)
        )
        return jnp.concatenate([prompt, new_tokens.T], axis=1)

    return generate


def transformer_beam_search(cfg: TransformerConfig):
    """KV-cached beam-search decoding.

    ≙ the reference's LSTM ``BeamSearch`` (models/classifiers/lstm/
    LSTM.java:241-336) at the transformer level. Returns
    ``beam(params, prompt, beam_width, max_new) ->
    (tokens (B, W, Tp+max_new), log_probs (B, W))`` with beams sorted
    best-first. The whole search is one ``lax.scan``: each step flattens
    the (B, W) beams into the cache batch dim, expands the top W
    continuations of each beam from the W*V candidate pool, and gathers
    the caches of the surviving parents.
    """
    forward_one, init_caches, do_prefill, cast_params = _decode_builder(cfg)

    def beam(params, prompt, beam_width: int, max_new: int):
        b, tp = prompt.shape
        w = beam_width
        v = cfg.vocab_size
        total = _check_decode_len(cfg, tp, max_new)

        # prefill once at batch B, then tile caches/logits to B*W beams
        params = cast_params(params)
        caches, logits = do_prefill(params, init_caches(b, total), prompt)
        # tree-mapped: int8 mode carries {"kv", "scale"}, both with the
        # cache batch on axis 2
        caches = jax.tree.map(
            lambda a: jnp.repeat(a, w, axis=2), caches
        )  # (nl, 2, B*W, Tpad, ...)
        logp = jax.nn.log_softmax(logits, axis=-1)  # (B, V)
        # beam 0 holds the live hypothesis; the rest start at -inf so the
        # first expansion draws W distinct tokens from beam 0's logits
        scores = jnp.full((b, w), -jnp.inf).at[:, 0].set(0.0)
        logp = jnp.repeat(logp[:, None], w, axis=1)  # (B, W, V)
        tokens = jnp.zeros((b, w, max_new), prompt.dtype)

        def step(carry, i):
            caches, logp, scores, tokens = carry
            cand = scores[:, :, None] + logp  # (B, W, V)
            top_scores, flat_idx = lax.top_k(
                cand.reshape(b, w * v), w
            )  # (B, W)
            parent = flat_idx // v  # (B, W) surviving beam index
            tok = (flat_idx % v).astype(tokens.dtype)  # (B, W)
            # reorder history + caches to the surviving parents
            tokens = jnp.take_along_axis(
                tokens, parent[:, :, None], axis=1
            )
            tokens = lax.dynamic_update_index_in_dim(
                tokens, tok, i, axis=2
            )
            flat_parent = (
                jnp.arange(b)[:, None] * w + parent
            ).reshape(-1)  # (B*W,) into the cache batch dim
            caches = jax.tree.map(
                lambda a: jnp.take(a, flat_parent, axis=2), caches
            )
            logits, caches = forward_one(
                params, caches, tok.reshape(-1), tp + i
            )
            logp = jax.nn.log_softmax(logits, axis=-1).reshape(b, w, v)
            return (caches, logp, top_scores, tokens), None

        (caches, logp, scores, tokens), _ = lax.scan(
            step, (caches, logp, scores, tokens), jnp.arange(max_new)
        )
        # sort beams best-first
        order = jnp.argsort(-scores, axis=1)
        scores = jnp.take_along_axis(scores, order, axis=1)
        tokens = jnp.take_along_axis(tokens, order[:, :, None], axis=1)
        full = jnp.concatenate(
            [jnp.repeat(prompt[:, None], w, axis=1), tokens], axis=2
        )
        return full, scores

    return beam


def _top_k_filter(logits, top_k: int | None, approx_top_k: bool):
    """Top-k threshold filter on logits — ONE implementation shared by
    ``transformer_generate``'s sampler and speculative decoding's
    draft/verify distributions, so the filter semantics (exact sort vs
    the TPU-native ``approx_max_k`` threshold — the exact top-40 over
    V=50304 measured 758us/step, 29% of decode device time, vs
    recall~0.95 for the approximate; kth-logit tie handling) cannot
    drift between the paths the bench compares row-to-row."""
    if top_k is None:
        return logits
    if approx_top_k:
        kth = lax.approx_max_k(logits, top_k)[0][..., -1:]
    else:
        kth = lax.top_k(logits, top_k)[0][..., -1:]
    return jnp.where(logits < kth, -jnp.inf, logits)


def _filtered_probs(logits, temperature: float, top_k: int | None,
                    approx_top_k: bool = False):
    """The sampling distribution as explicit probabilities (f32):
    top-k filter then temperature softmax; ``temperature=0`` is a
    one-hot argmax. Shared by speculative decoding's draft and verify
    sides so the acceptance ratio compares the same family of filtered
    distributions the plain sampler uses (the filter DEFINES the target
    distribution, so exactness is w.r.t. the filtered target)."""
    logits = logits.astype(jnp.float32)
    if temperature == 0:
        return jax.nn.one_hot(
            jnp.argmax(logits, -1), logits.shape[-1], dtype=jnp.float32
        )
    logits = _top_k_filter(logits, top_k, approx_top_k)
    return jax.nn.softmax(logits / temperature, axis=-1)


def _block_chunk(cfg: TransformerConfig, x, p, kv_all, i, pos0,
                 tp_mesh=None, lora=None, adapter=None):
    """One transformer block over C consecutive cached-decode positions
    (x: (B, C, D), rows pos0..pos0+C-1): projection, RoPE, cache write,
    dense masked attention against the cache, MLP/MoE tail. ONE
    implementation serving both ``block_decode``'s non-kernel path
    (C=1) and the speculative verify chunk — the dense decode numerics
    cannot drift from the verify numerics because they are the same
    code. ``pos0`` is a scalar start position or an (B,) vector of
    per-row starts (the serving engine's per-slot decode depths).

    ``lora`` (this layer's slice of an :func:`init_lora_bank` bank —
    leaves (N, ...)) with ``adapter`` (B,) int rows adds each row's
    low-rank q and MLP deltas, gathered by adapter index inside the
    traced program so one dispatch serves mixed adapters. Rows with
    adapter 0 SELECT the untouched base activations (``jnp.where``,
    not an add of zeros) so their output is bitwise the base model's."""
    b, c, _ = x.shape
    kd = cfg.head_dim
    grp = cfg.n_heads // cfg.kv_heads
    vec_pos = jnp.ndim(pos0) == 1
    # (C,) shared positions, or (B, C) per-row positions
    positions = (pos0[:, None] if vec_pos else pos0) + jnp.arange(c)
    h_in = _layer_norm(x, p["ln1_scale"], p["ln1_bias"])
    q, k_r, v_r = _project_qkv(cfg, p, h_in)  # (B,H,C,K), (B,Hkv,C,K)
    if lora is not None:
        # q delta BEFORE RoPE — where a merged wq+AB would land it, so
        # a slot's stream matches a single-adapter engine's flop order
        dq = _lora_delta(
            h_in,
            jnp.take(lora["a_q"], adapter, axis=0),
            jnp.take(lora["b_q"], adapter, axis=0),
        ).reshape(b, c, cfg.n_heads, kd).transpose(0, 2, 1, 3)
        q = jnp.where((adapter > 0)[:, None, None, None], q + dq, q)
    if cfg.rope:
        cos, sin = _rope_tables(positions, cfg.head_dim, x.dtype)
        if vec_pos:  # (B, C, hd/2): per-row tables over the head axis
            cos, sin = cos[:, None], sin[:, None]
        else:  # (C, hd/2)
            cos, sin = cos[None, None], sin[None, None]
        q = _apply_rope(q, cos, sin)
        k_r = _apply_rope(k_r, cos, sin)
    kv_rows = jnp.stack(
        [
            k_r.transpose(0, 2, 1, 3).reshape(b, c, -1),
            v_r.transpose(0, 2, 1, 3).reshape(b, c, -1),
        ]
    )[None]  # (1, 2, B, C, Hkv*K)
    if cfg.decode_int8:
        kv_buf, sc_buf = kv_all["kv"], kv_all["scale"]
        q_rows, s_rows = _quantize_int8(
            kv_rows.astype(jnp.float32), (-1,)
        )
        if vec_pos:
            bidx = jnp.arange(b)[:, None]
            for plane in range(2):
                kv_buf = kv_buf.at[i, plane, bidx, positions].set(
                    q_rows[0, plane]
                )
                sc_buf = sc_buf.at[i, plane, bidx, positions].set(
                    s_rows[0, plane]
                )
        else:
            kv_buf = lax.dynamic_update_slice(
                kv_buf, q_rows, (i, 0, 0, pos0, 0)
            )
            sc_buf = lax.dynamic_update_slice(
                sc_buf, s_rows, (i, 0, 0, pos0, 0)
            )
        kv_all = {"kv": kv_buf, "scale": sc_buf}
        ck = (kv_buf[i, 0].astype(jnp.float32)
              * sc_buf[i, 0]).astype(x.dtype)
        cv = (kv_buf[i, 1].astype(jnp.float32)
              * sc_buf[i, 1]).astype(x.dtype)
    else:
        if vec_pos:
            bidx = jnp.arange(b)[:, None]
            rows = kv_rows.astype(kv_all.dtype)
            for plane in range(2):
                kv_all = kv_all.at[i, plane, bidx, positions].set(
                    rows[0, plane]
                )
        else:
            kv_all = lax.dynamic_update_slice(
                kv_all, kv_rows.astype(kv_all.dtype), (i, 0, 0, pos0, 0)
            )
        ck, cv = kv_all[i, 0], kv_all[i, 1]
    tpad = ck.shape[1]
    ck4 = ck.reshape(b, tpad, cfg.kv_heads, kd)
    cv4 = cv.reshape(b, tpad, cfg.kv_heads, kd)
    qg = q.reshape(b, cfg.kv_heads, grp, c, kd)  # head = kv*G + g
    att = jnp.einsum(
        "bhgck,bthk->bhgct", qg, ck4
    ) / jnp.sqrt(kd).astype(x.dtype)
    # causal against the cache: (C, Tpad) shared, or (B, C, Tpad)
    mask = jnp.arange(tpad)[None, :] <= positions[..., None]
    att = jnp.where(
        mask[:, None, None] if vec_pos else mask[None, None, None], att,
        -jnp.inf,
    )
    w_att = jax.nn.softmax(att, axis=-1)
    o = jnp.einsum("bhgct,bthk->bhgck", w_att, cv4)
    o_flat = o.transpose(0, 3, 1, 2, 4).reshape(
        b, c, cfg.n_heads * kd
    )
    # TP: gather the head-sharded attention output before the row
    # projection so the reduction keeps single-chip order
    o_flat = _tp_replicate(o_flat, tp_mesh)
    x = x + jnp.einsum(
        "bch,hd->bcd", o_flat,
        _w(p, "wo", x.dtype).reshape(cfg.n_heads * kd, -1),
    )
    h_in = _layer_norm(x, p["ln2_scale"], p["ln2_bias"])
    if cfg.n_experts:
        from deeplearning4j_tpu.parallel.expert_parallel import (
            moe_reference,
        )

        moe_params = jax.tree.map(
            lambda a: a.astype(x.dtype), p["moe"]
        )
        flat = h_in.reshape(-1, h_in.shape[-1])
        y = moe_reference(
            moe_params, flat, k=cfg.moe_k, activation=jax.nn.gelu
        )
        x = x + y.reshape(h_in.shape)
    elif lora is not None:
        dm = _lora_delta(
            h_in,
            jnp.take(lora["a_mlp"], adapter, axis=0),
            jnp.take(lora["b_mlp"], adapter, axis=0),
        )
        x = x + _mlp(p, h_in, tp_mesh, delta1=dm,
                     sel=(adapter > 0)[:, None, None])
    else:
        x = x + _mlp(p, h_in, tp_mesh)
    return x, kv_all

def _chunk_builder(cfg: TransformerConfig, tp_mesh=None):
    """Chunked cached forward — the verify side of speculative decoding:
    ``forward_chunk(params, caches, toks (B, C), pos0)`` advances C
    consecutive positions (pos0..pos0+C-1) through all layers against
    the live KV cache in ONE pass and returns (logits (B, C, V),
    caches). Decode is weight-stream-bound, so verifying C=k+1 draft
    positions costs ~one decode step of HBM traffic, not k: the C
    queries ride the same streamed weights as a single wide MXU dot.
    Per-layer work delegates to :func:`_block_chunk` — the same code
    ``block_decode``'s non-kernel path runs at C=1."""

    def forward_chunk(params, caches, toks, pos0, last_idx=None,
                      adapter=None):
        b, c = toks.shape
        # per-index clip: positions past max_len (possible only for
        # slots whose outputs are discarded at the buffer slice) clamp
        # individually instead of shifting the whole slice
        pos_rows = jnp.take(
            params["pos"], pos0 + jnp.arange(c), axis=0, mode="clip"
        )
        x = (params["embed"][toks] + pos_rows[None]).astype(
            cfg.compute_dtype
        )
        kv_all = caches
        lora = params.get("lora") if adapter is not None else None
        for i in range(cfg.n_layers):
            p_i = jax.tree.map(lambda a, i=i: a[i], params["blocks"])
            l_i = (None if lora is None
                   else jax.tree.map(lambda a, i=i: a[i], lora))
            x, kv_all = _block_chunk(
                cfg, x, p_i, kv_all, i, pos0, tp_mesh=tp_mesh,
                lora=l_i, adapter=adapter,
            )
        if last_idx is not None:
            # single-row logits (bucketed-prefill chunking: only the
            # true last token's row matters; skips the (C, V) head).
            # Vector last_idx = per-row last index, for the batched
            # suffix-prefill of prefix-cache hits.
            if jnp.ndim(last_idx) == 1:
                x_last = jnp.take_along_axis(
                    x, last_idx[:, None, None], axis=1
                )[:, 0]
            else:
                x_last = lax.dynamic_index_in_dim(
                    x, last_idx, axis=1, keepdims=False
                )
            x_last = _layer_norm(
                x_last, params["lnf_scale"], params["lnf_bias"]
            )
            logits = jnp.einsum(
                "bd,dv->bv", x_last, _w(params, "head", x_last.dtype),
                preferred_element_type=jnp.float32,
            )
            return _tp_replicate(logits, tp_mesh), kv_all
        x = _layer_norm(x, params["lnf_scale"], params["lnf_bias"])
        logits = jnp.einsum(
            "bcd,dv->bcv", x, _w(params, "head", x.dtype),
            preferred_element_type=jnp.float32,
        )
        return _tp_replicate(logits, tp_mesh), kv_all

    return forward_chunk


def transformer_speculative_generate(
    cfg: TransformerConfig, draft_cfg: TransformerConfig | None = None
):
    """Speculative decoding: a cheap draft model proposes ``draft_k``
    tokens autoregressively, the target model verifies all of them in
    one chunked forward, and rejection sampling keeps the output an
    exact sample from the target's (filtered) distribution
    [Leviathan et al. 2023; Chen et al. 2023 — the published
    algorithm, implemented here from the math].

    Exactness caveat (true of ANY floating-point implementation of the
    algorithm): "the target's distribution" means the target weights as
    computed by the chunked verify program. That program is a
    differently-scheduled XLA lowering than ``transformer_generate``'s
    serial decode, so their logits agree only to float-reassociation
    level (~1e-2 relative on random-init models) — at temperature 0
    the two decoders emit identical tokens except where the top-2
    logit margin is inside that band (near-ties). The acceptance MATH
    is exact for whatever p the verify program produces; the
    guarantee is distribution-level w.r.t. that program, not bitwise
    token equality with the serial decoder.

    TPU-first shape: the natural draft on one chip is the SAME model
    weight-only int8 quantized (``quantize_decode_params`` with
    ``draft_cfg`` = the int8 variant) — near-1 acceptance because
    draft≈target, ~half the weight stream per draft step, and
    target-distribution outputs, turning the lossy quantization
    speedup into a distribution-preserving one at B=1 (the latency
    row PERF.md's wall analysis says no byte savings can otherwise
    reach).

    Returns ``generate(params, draft_params, prompt, key, max_new,
    draft_k, temperature, top_k, approx_top_k, return_stats) ->
    tokens (1, Tp + max_new)`` (with ``return_stats`` also a
    ``{"rounds": n}`` dict — rounds ≈ max_new/(k+1) at perfect
    acceptance, the efficiency diagnostic). Batch is fixed at 1:
    acceptance lengths are ragged across batch rows, and the feature
    targets interactive latency (the B>=16 throughput rows are already
    weight-amortized). Prompts need >= 2 tokens (each round's first
    draft step is a 2-token catch-up chunk). The whole loop is one
    jittable ``lax.while_loop``; both caches stay device-resident.

    ≙ the serving capability the reference's era lacked entirely; the
    sampling surface matches ``transformer_generate``
    (LSTM.java:219 ≙ sampleDoc at the transformer level).
    """
    if draft_cfg is None:
        draft_cfg = cfg
    _, t_init, t_prefill, t_cast = _decode_builder(cfg)
    t_chunk = _chunk_builder(cfg)
    d_fwd1, d_init, d_prefill, d_cast = _decode_builder(draft_cfg)
    d_chunk = _chunk_builder(draft_cfg)

    def generate(params, draft_params, prompt, key, max_new: int,
                 draft_k: int = 4, temperature: float = 1.0,
                 top_k: int | None = None, approx_top_k: bool = False,
                 return_stats: bool = False):
        b, tp = prompt.shape
        if b != 1:
            raise ValueError(
                "speculative decode is the B=1 latency path (acceptance "
                "lengths are ragged across batch rows)"
            )
        if tp < 2:
            raise ValueError(
                "speculative decode needs a prompt of >= 2 tokens (each "
                "round's first draft step is a 2-token catch-up chunk)"
            )
        k = int(draft_k)
        assert k >= 1
        total = _check_decode_len(cfg, tp, max_new)
        _check_decode_len(draft_cfg, tp, max_new)
        v = cfg.vocab_size
        params = t_cast(params)
        draft_params = d_cast(draft_params)
        # caches padded by k+1 rows: a round may write (and later
        # overwrite) up to k+1 positions past the accepted prefix
        caches_t = t_init(b, total + k + 1)
        caches_d = d_init(b, total + k + 1)
        # lag-one prefill: the last prompt token is NOT consumed — each
        # round's chunk/draft feeds it first, so the target cache always
        # trails the emitted prefix by exactly one row. The lag would
        # push a flash-aligned prompt (%128 above one block —
        # _flash_seq_ok) off the kernel path, so bulk-prefill the
        # aligned PREFIX and chunk-forward the <=127-token remainder.
        pre = tp - 1  # >= 1: the tp >= 2 guard above
        aligned = pre - (pre % 128) if pre > 128 else pre
        if aligned:
            caches_t, _ = t_prefill(
                params, caches_t, prompt[:, :aligned]
            )
            caches_d, _ = d_prefill(
                draft_params, caches_d, prompt[:, :aligned]
            )
        if pre - aligned:
            rest = prompt[:, aligned:pre]
            _, caches_t = t_chunk(params, caches_t, rest, aligned)
            _, caches_d = d_chunk(draft_params, caches_d, rest, aligned)
        c_prev2 = prompt[:, -2].astype(jnp.int32)
        c_prev = prompt[:, -1].astype(jnp.int32)
        buf = jnp.zeros((b, total + k + 1), jnp.int32)
        buf = lax.dynamic_update_slice(
            buf, prompt.astype(jnp.int32), (0, 0)
        )

        def pick(p, kk):
            if temperature == 0:
                return jnp.argmax(p, -1).astype(jnp.int32)
            return jax.random.categorical(
                kk, jnp.log(p + 1e-30), axis=-1
            ).astype(jnp.int32)

        def cond(carry):
            return carry[3] < total

        def body(carry):
            caches_t, caches_d, buf, pos, c_prev2, c_prev, key, rounds = carry
            key, kd1, kdr, ku, kc = jax.random.split(key, 5)

            # first draft step is a 2-token catch-up chunk over the two
            # tokens behind the cursor: after a fully-accepted round the
            # draft cache is missing d_k's row (the draft sampled d_k
            # but never fed it) AND the correction token's row — this
            # chunk writes both (rewrites are idempotent: a row is a
            # deterministic function of its token, position, and the
            # rows before it), so no permanent zero row can enter the
            # attention window and erode acceptance
            pair = jnp.concatenate(
                [c_prev2[:, None], c_prev[:, None]], axis=1
            )
            lg2, caches_d = d_chunk(draft_params, caches_d, pair, pos - 2)
            q1 = _filtered_probs(
                lg2[:, 1], temperature, top_k, approx_top_k
            )  # (B, V)
            d1 = pick(q1, kd1)

            # remaining k-1 draft tokens serially (each step ~the
            # quantized weight stream), recording proposal distributions
            def dstep(dc, i):
                caches_d, tok, kk = dc
                kk, ks = jax.random.split(kk)
                lg, caches_d = d_fwd1(
                    draft_params, caches_d, tok, pos - 1 + i
                )
                qv = _filtered_probs(
                    lg, temperature, top_k, approx_top_k
                )  # (B, V)
                d = pick(qv, ks)
                return (caches_d, d, kk), (d, qv)

            (caches_d, _, _), (ds_rest, qs_rest) = lax.scan(
                dstep, (caches_d, d1, kdr), jnp.arange(1, k)
            )
            ds_t = jnp.concatenate(
                [d1[:, None], ds_rest.T], axis=1
            )  # (B, k)
            qs_t = jnp.concatenate(
                [q1[:, None], jnp.transpose(qs_rest, (1, 0, 2))], axis=1
            )  # (B, k, V)

            # verify: ONE chunked target forward over
            # [c_prev, d_1..d_k] yields p for every draft slot + bonus
            chunk_toks = jnp.concatenate(
                [c_prev[:, None], ds_t], axis=1
            )  # (B, k+1)
            vlg, caches_t = t_chunk(params, caches_t, chunk_toks, pos - 1)
            ps = _filtered_probs(
                vlg, temperature, top_k, approx_top_k
            )  # (B, k+1, V)

            # rejection sampling: accept d_i with prob min(1, p/q);
            # u*q < p is the division-free form
            p_d = jnp.take_along_axis(
                ps[:, :k], ds_t[..., None], -1
            )[..., 0]  # (B, k)
            q_d = jnp.take_along_axis(qs_t, ds_t[..., None], -1)[..., 0]
            u = jax.random.uniform(ku, (b, k))
            accept = u * jnp.maximum(q_d, 1e-30) < p_d
            n = jnp.sum(
                jnp.cumprod(accept.astype(jnp.int32), axis=1), axis=1
            )  # (B,) accepted count; k = all accepted

            # correction token: on reject at slot n sample the residual
            # max(p-q, 0)/Z; with n=k the padded q row is zero so the
            # SAME formula samples the bonus token from p directly
            qs_pad = jnp.concatenate(
                [qs_t, jnp.zeros((b, 1, v), qs_t.dtype)], axis=1
            )
            pn = jnp.take_along_axis(ps, n[:, None, None], axis=1)[:, 0]
            qn = jnp.take_along_axis(
                qs_pad, n[:, None, None], axis=1
            )[:, 0]
            resid = jnp.maximum(pn - qn, 0.0)
            rs = jnp.sum(resid, axis=-1, keepdims=True)
            resid = jnp.where(rs > 0, resid / rs, pn)
            ctok = pick(resid, kc)  # (B,)

            # emit d_1..d_n then the correction token at slot n; slots
            # past n are scratch (overwritten by later rounds, sliced
            # off at the end)
            jj = jnp.arange(k + 1)[None, :]
            ds_pad = jnp.concatenate(
                [ds_t, jnp.zeros((b, 1), ds_t.dtype)], axis=1
            )
            tile = jnp.where(
                jj < n[:, None], ds_pad,
                jnp.where(jj == n[:, None], ctok[:, None], 0),
            ).astype(jnp.int32)
            buf = lax.dynamic_update_slice(buf, tile, (0, pos))
            # the new cursor is pos+n+1; the token two behind it is d_n
            # (n>=1) or the incoming c_prev (n==0)
            prev2_new = jnp.where(
                n == 0, c_prev,
                jnp.take_along_axis(
                    ds_t, jnp.maximum(n - 1, 0)[:, None], axis=1
                )[:, 0],
            )
            return (caches_t, caches_d, buf, pos + n[0] + 1,
                    prev2_new, ctok, key, rounds + 1)

        init = (caches_t, caches_d, buf, jnp.int32(tp), c_prev2, c_prev,
                key, jnp.int32(0))
        fin = lax.while_loop(cond, body, init)
        out = fin[2][:, :total]
        if return_stats:
            return out, {"rounds": fin[7]}
        return out

    return generate


def fsdp_shardings(mesh: Mesh, cfg: TransformerConfig):
    """ZeRO-3-style augmentation of the TP layout: additionally shard
    each large param leaf over the *data* axis (first dim that the data
    axis divides and that isn't already sharded), so params — and the
    optimizer state, which mirrors them — consume 1/dp of the HBM per
    device. XLA inserts the all-gathers at use sites and reduce-scatters
    the matching gradient shards; nothing is hand-scheduled.
    """
    dp = mesh.shape[mesh_lib.DATA_AXIS]
    base = transformer_shardings(mesh, cfg)
    shapes = jax.eval_shape(
        lambda: init_transformer(jax.random.key(0), cfg)
    )

    def augment(sharding, shape):
        spec = list(sharding.spec) + [None] * (
            len(shape.shape) - len(sharding.spec)
        )
        if int(np.prod(shape.shape)) < 2 * dp:
            return sharding  # tiny leaf: replication is cheaper
        for i, (dim, s) in enumerate(zip(shape.shape, spec)):
            if s is None and dim % dp == 0 and dim >= dp:
                spec[i] = mesh_lib.DATA_AXIS
                return NamedSharding(mesh, P(*spec))
        return sharding

    return jax.tree.map(augment, base, shapes)


# param leaves exempt from AdamW weight decay: layernorm scales/biases,
# biases, and the learned position table — the standard LM recipe decays
# only the matmul weights. Matched by leaf *name* because the stacked
# (n_layers, ...) leading axis makes block biases 2-D, so an ndim test
# would misclassify them.
_NO_DECAY = frozenset({
    "ln1_scale", "ln1_bias", "ln2_scale", "ln2_bias",
    "lnf_scale", "lnf_bias", "b1", "b2", "pos",
})


def _decay_mask(params):
    """True where AdamW weight decay applies (matmul weights only)."""

    def leaf_name(path):
        last = path[-1]
        return getattr(last, "key", None) or getattr(last, "name", "")

    return jax.tree_util.tree_map_with_path(
        lambda path, _: leaf_name(path) not in _NO_DECAY, params
    )


def lm_optimizer(
    peak_lr: float = 3e-4,
    total_steps: int = 10_000,
    warmup_steps: int | None = None,
    clip_norm: float = 1.0,
    weight_decay: float = 0.01,
) -> optax.GradientTransformation:
    """Standard LM training recipe: global-norm clipping + AdamW on a
    linear-warmup / cosine-decay schedule. Pass to
    ``transformer_train_step(optimizer=...)``; the state mirrors the
    param tree, so TP/FSDP shardings carry over unchanged. Weight decay
    is masked off norm scales/biases, biases, and the position table
    (``_decay_mask``), matching the standard LM recipe."""
    warmup = warmup_steps if warmup_steps is not None else max(
        1, total_steps // 20
    )
    sched = optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=peak_lr,
        warmup_steps=warmup,
        # optax needs decay_steps > warmup_steps; tiny smoke runs
        # (total_steps <= warmup) must still construct
        decay_steps=max(total_steps, warmup + 1),
        end_value=peak_lr * 0.1,
    )
    return optax.chain(
        optax.clip_by_global_norm(clip_norm),
        optax.adamw(sched, weight_decay=weight_decay, mask=_decay_mask),
    )


def transformer_train_step(
    mesh: Mesh, cfg: TransformerConfig, optimizer=None, fsdp: bool = False
):
    """Jitted composed dp x tp train step over a 2-D (data, model) mesh.

    Returns ``(step, init_state, shard_tokens)``:
    ``step(params, opt_state, tokens) -> (params, opt_state, loss)`` with
    params TP-sharded, tokens batch-sharded; both factory helpers place
    their outputs with the right shardings. ``fsdp=True`` additionally
    shards params/optimizer state over the data axis (ZeRO-3 layout via
    :func:`fsdp_shardings`).
    """
    optimizer = optimizer or optax.adamw(3e-4)
    loss_fn = transformer_loss(cfg, mesh)
    shardings = (
        fsdp_shardings(mesh, cfg) if fsdp else transformer_shardings(mesh, cfg)
    )
    batch_sh = NamedSharding(
        mesh,
        P(None, mesh_lib.DATA_AXIS)
        if cfg.sequence_parallel
        else P(mesh_lib.DATA_AXIS, None),
    )

    def init_state(key):
        # place_global handles the multi-process case (device_put cannot
        # address remote shards)
        params = jax.tree.map(
            mesh_lib.place_global, init_transformer(key, cfg), shardings
        )
        # adamw state mirrors the param tree, so it inherits the TP shardings
        opt_state = optimizer.init(params)
        return params, opt_state

    def shard_tokens(tokens):
        return mesh_lib.place_global(tokens, batch_sh)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, tokens):
        l, g = jax.value_and_grad(loss_fn)(params, tokens)
        updates, opt_state = optimizer.update(g, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, l

    return step, init_state, shard_tokens
