"""Recursive Neural Tensor Network (Socher) over binarized trees.

≙ reference models/rntn/RNTN.java:55-1392: composition
``h = f(W [l; r; 1] + [l; r]^T V [l; r])`` bottom-up over a binary tree,
per-node softmax sentiment classification, AdaGrad training, RNTNEval.

TPU re-design: the reference fits trees through actor futures
(RNTN.fit:341) with per-label ``MultiDimensionalMap`` parameter maps; here
a single shared (W, V, Wc, embeddings) parameter set (the common Socher
formulation — per-label maps collapse to one because binarized trees have
one composition type) and the whole per-tree forward+backward is one
jitted autodiff program over a *level-packed* representation: tree nodes
are topologically ordered so composition is a ``lax.scan`` over a node
table instead of Python recursion.
"""

from __future__ import annotations

import functools
from typing import Iterable

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.tree import Tree
from deeplearning4j_tpu.nlp.vocab import VocabCache


def topo_pack(tree: Tree, cache: VocabCache, num_classes: int):
    """Pack a binary tree into arrays for scan execution.

    Returns (word_ids, left, right, is_leaf, labels) over nodes in
    topological (children-first) order.  Leaf nodes reference embedding
    rows; internal nodes reference child slots.
    """
    nodes: list[Tree] = []

    def visit(t: Tree):
        for c in t.children:
            visit(c)
        nodes.append(t)

    visit(tree)
    n = len(nodes)
    index = {id(t): i for i, t in enumerate(nodes)}
    word_ids = np.zeros(n, np.int32)
    left = np.zeros(n, np.int32)
    right = np.zeros(n, np.int32)
    leaf = np.zeros(n, np.float32)
    labels = np.zeros(n, np.int32)
    for i, t in enumerate(nodes):
        try:
            labels[i] = int(t.label.lstrip("@")) % num_classes
        except ValueError:
            labels[i] = 0
        if t.is_leaf():
            leaf[i] = 1.0
            word_ids[i] = max(cache.index_of(t.word or ""), 0)
        elif len(t.children) == 1:
            leaf[i] = 0.0
            left[i] = right[i] = index[id(t.children[0])]
        else:
            left[i] = index[id(t.children[0])]
            right[i] = index[id(t.children[1])]
    return word_ids, left, right, leaf, labels


class RNTN:
    def __init__(
        self,
        num_classes: int = 2,
        dim: int = 16,
        lr: float = 0.05,
        use_tensor: bool = True,
        seed: int = 123,
        max_nodes: int = 64,
    ):
        self.num_classes = num_classes
        self.dim = dim
        self.lr = lr
        self.use_tensor = use_tensor
        self.seed = seed
        self.max_nodes = max_nodes
        self.cache = VocabCache()
        self.params = None
        self._adagrad = None

    def init_params(self) -> None:
        d, c, v = self.dim, self.num_classes, max(len(self.cache), 1)
        k = jax.random.split(jax.random.key(self.seed), 4)
        r = 1.0 / np.sqrt(2 * d)
        self.params = {
            "W": jax.random.uniform(k[0], (d, 2 * d + 1), minval=-r, maxval=r),
            "V": jax.random.uniform(k[1], (2 * d, 2 * d, d), minval=-r, maxval=r)
            * (1.0 if self.use_tensor else 0.0),
            "Wc": jax.random.uniform(k[2], (c, d + 1), minval=-r, maxval=r),
            "emb": 0.1 * jax.random.normal(k[3], (v, d)),
        }
        self._adagrad = jax.tree.map(jnp.zeros_like, self.params)

    # -- forward over the packed tree (scan) -------------------------------
    def _tree_loss(self, params, word_ids, left, right, leaf, labels, node_mask):
        d = self.dim
        n = word_ids.shape[0]
        vecs0 = jnp.zeros((n, d))

        def body(i, vecs):
            l = vecs[left[i]]
            r_vec = vecs[right[i]]
            lr_cat = jnp.concatenate([l, r_vec, jnp.ones(1)])
            linear = params["W"] @ lr_cat
            lr2 = jnp.concatenate([l, r_vec])
            tensor = jnp.einsum("a,abd,b->d", lr2, params["V"], lr2)
            composed = jnp.tanh(linear + tensor)
            leaf_vec = jnp.tanh(params["emb"][word_ids[i]])
            vec = jnp.where(leaf[i] > 0, leaf_vec, composed)
            return vecs.at[i].set(vec)

        vecs = jax.lax.fori_loop(0, n, body, vecs0)
        logits = vecs @ params["Wc"][:, :d].T + params["Wc"][:, d]
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -logp[jnp.arange(n), labels] * node_mask
        return jnp.sum(nll) / jnp.maximum(jnp.sum(node_mask), 1.0), vecs

    @functools.partial(jax.jit, static_argnames=("self",))
    def _step(self, params, hist, word_ids, left, right, leaf, labels, node_mask, lr):
        (loss, _), grads = jax.value_and_grad(
            lambda p: self._tree_loss(p, word_ids, left, right, leaf, labels, node_mask),
            has_aux=True,
        )(params)
        hist = jax.tree.map(lambda h, g: h + g * g, hist, grads)
        params = jax.tree.map(
            lambda p, g, h: p - lr * g / (jnp.sqrt(h) + 1e-8), params, grads, hist
        )
        return params, hist, loss

    def _pad(self, arrs):
        """Pad packed tree arrays to max_nodes (one compiled step shape)."""
        word_ids, left, right, leaf, labels = arrs
        n = len(word_ids)
        m = self.max_nodes
        if n > m:
            raise ValueError(f"tree has {n} nodes > max_nodes={m}")
        pad = m - n
        mask = np.concatenate([np.ones(n, np.float32), np.zeros(pad, np.float32)])
        out = [np.concatenate([a, np.zeros(pad, a.dtype)]) for a in (word_ids, left, right)]
        leaf_p = np.concatenate([leaf, np.ones(pad, np.float32)])  # pads act as leaves
        labels_p = np.concatenate([labels, np.zeros(pad, np.int32)])
        return (*out, leaf_p, labels_p, mask)

    def fit_trees(self, trees: Iterable[Tree], epochs: int = 1) -> list[float]:
        """≙ RNTN.fit:341 (actor-parallel loop -> sequential jitted steps)."""
        trees = list(trees)
        if len(self.cache) == 0:
            self.cache.fit([t.words() for t in trees])
        if self.params is None:
            self.init_params()
        losses = []
        for _ in range(epochs):
            total = 0.0
            for t in trees:
                packed = self._pad(topo_pack(t, self.cache, self.num_classes))
                args = [jnp.asarray(a) for a in packed]
                self.params, self._adagrad, loss = self._step(
                    self.params, self._adagrad, *args, jnp.float32(self.lr)
                )
                total += float(loss)
            losses.append(total / max(len(trees), 1))
        return losses

    def predict_root(self, tree: Tree) -> int:
        packed = self._pad(topo_pack(tree, self.cache, self.num_classes))
        word_ids, left, right, leaf, labels, mask = (jnp.asarray(a) for a in packed)
        _, vecs = self._tree_loss(
            self.params, word_ids, left, right, leaf, labels, mask
        )
        n_real = int(mask.sum())
        root_vec = vecs[n_real - 1]
        d = self.dim
        logits = self.params["Wc"][:, :d] @ root_vec + self.params["Wc"][:, d]
        return int(jnp.argmax(logits))


class RNTNEval:
    """≙ RNTNEval.java:61 — accuracy over tree root labels."""

    def __init__(self):
        self.correct = 0
        self.total = 0

    def eval(self, model: RNTN, trees: Iterable[Tree]) -> None:
        for t in trees:
            try:
                gold = int(t.label) % model.num_classes
            except ValueError:
                continue
            self.total += 1
            if model.predict_root(t) == gold:
                self.correct += 1

    def accuracy(self) -> float:
        return self.correct / self.total if self.total else 0.0
