"""Recursive Neural Tensor Network (Socher) over binarized trees.

≙ reference models/rntn/RNTN.java:55-1392: composition
``h = f(W [l; r; 1] + [l; r]^T V [l; r])`` bottom-up over a binary tree,
per-node softmax classification, AdaGrad training, RNTNEval.

TPU re-design, two axes:

- **Per-production parameter tables.** The reference keys binary
  transform/tensor/classification matrices by the children's syntactic
  categories in ``MultiDimensionalMap``s (RNTN.java:94-135,372-411) —
  but its only *runnable* mode is ``simplifiedModel`` where
  ``basicCategory`` maps every label to ``""`` (RNTN.java:450-455; the
  untied path throws UnsupportedOperationException at :207). Here the
  map is a dense ``(n_productions, ...)`` leading axis + jittable
  gather: ``simplified_model=True`` (default) reproduces the
  one-shared-matrix behavior with ``n_productions == 1``, and
  ``simplified_model=False`` delivers the untied capability the
  reference declared: productions discovered from the training trees
  (≙ the "figure out what binary productions we have" TODO at :205),
  ``combine_classification=False`` splitting binary vs unary
  classification matrices (≙ :245,259).
- **Batched tree training.** The reference fits trees through actor
  futures (RNTN.fit:341), one tree at a time; here padded node tables
  stack into ``(batch, max_nodes)`` arrays and ``jax.vmap`` runs the
  whole batch in ONE jitted dispatch (the per-tree scan is a
  ``fori_loop`` over the topologically-packed node table).
"""

from __future__ import annotations

import functools
from typing import Iterable

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.tree import Tree
from deeplearning4j_tpu.nlp.vocab import VocabCache


def basic_category(label: str, simplified: bool = True) -> str:
    """≙ RNTN.basicCategory:450 — "" collapses every label (flat model);
    the untied variant strips binarization markers (``@NP`` from the
    binarizer) and PTB functional annotations (``NP-SBJ=2`` -> ``NP``)."""
    if simplified:
        return ""
    return label.lstrip("@").split("-")[0].split("=")[0]


def topo_pack(tree: Tree, cache: VocabCache, num_classes: int):
    """Pack a binary tree into arrays for scan execution.

    Returns (word_ids, left, right, is_leaf, labels) over nodes in
    topological (children-first) order.  Leaf nodes reference embedding
    rows; internal nodes reference child slots.
    """
    p = _pack_full(tree, cache, num_classes)
    return p["word_ids"], p["left"], p["right"], p["leaf"], p["labels"]


def _pack_full(
    tree: Tree,
    cache: VocabCache,
    num_classes: int,
    prod_index: dict | None = None,
    unary_index: dict | None = None,
    simplified: bool = True,
):
    """topo_pack + per-node production / unary-category indices.

    ``prod`` indexes the (left-cat, right-cat) production tables for
    internal nodes (≙ getBinaryTransform:472); ``ucat`` indexes the
    unary classification table by the node's own category
    (≙ getUnaryClassification:457). Unseen keys fall back to slot 0.
    """
    nodes: list[Tree] = []

    def visit(t: Tree):
        for c in t.children:
            visit(c)
        nodes.append(t)

    visit(tree)
    n = len(nodes)
    index = {id(t): i for i, t in enumerate(nodes)}
    word_ids = np.zeros(n, np.int32)
    left = np.zeros(n, np.int32)
    right = np.zeros(n, np.int32)
    leaf = np.zeros(n, np.float32)
    labels = np.zeros(n, np.int32)
    prod = np.zeros(n, np.int32)
    ucat = np.zeros(n, np.int32)
    for i, t in enumerate(nodes):
        try:
            labels[i] = int(t.label.lstrip("@")) % num_classes
        except ValueError:
            labels[i] = 0
        if unary_index is not None:
            if len(t.children) == 2:
                # binary nodes classify through the production table
                # (getClassWForNode:400 routes two-child nodes to
                # binaryClassification); -1 marks "not unary"
                ucat[i] = -1
            else:
                # leaves AND one-child internal nodes classify by their
                # own category (≙ getUnaryClassification:457)
                ucat[i] = unary_index.get(
                    basic_category(t.label, simplified), 0
                )
        if t.is_leaf():
            leaf[i] = 1.0
            word_ids[i] = max(cache.index_of(t.word or ""), 0)
        else:
            left[i] = index[id(t.children[0])]
            right[i] = index[id(t.children[-1])]
            if prod_index is not None:
                key = (
                    basic_category(t.children[0].label, simplified),
                    basic_category(t.children[-1].label, simplified),
                )
                prod[i] = prod_index.get(key, 0)
    return dict(
        word_ids=word_ids, left=left, right=right, leaf=leaf,
        labels=labels, prod=prod, ucat=ucat,
    )


class RNTN:
    def __init__(
        self,
        num_classes: int = 2,
        dim: int = 16,
        lr: float = 0.05,
        use_tensor: bool = True,
        seed: int = 123,
        max_nodes: int = 64,
        simplified_model: bool = True,
        combine_classification: bool = True,
        batch_size: int = 8,
    ):
        self.num_classes = num_classes
        self.dim = dim
        self.lr = lr
        self.use_tensor = use_tensor
        self.seed = seed
        self.max_nodes = max_nodes
        self.simplified_model = simplified_model
        self.combine_classification = combine_classification
        self.batch_size = batch_size
        self.cache = VocabCache()
        self.params = None
        self._adagrad = None
        # production / unary-category registries (slot 0 = fallback);
        # simplified mode keeps exactly the one ("","") production the
        # reference seeds at RNTN.java:202
        self.prod_index: dict[tuple[str, str], int] = {("", ""): 0}
        self.unary_index: dict[str, int] = {"": 0}

    # -- production discovery ----------------------------------------------
    def discover_productions(self, trees: Iterable[Tree]) -> None:
        """≙ the binaryProductions/unaryProductions discovery the
        reference left as a TODO (RNTN.java:205-219). No-op in
        simplified mode (everything is the "" category)."""
        if self.simplified_model:
            return
        for t in trees:
            for node in t.subtrees():
                cat = basic_category(node.label, False)
                if cat not in self.unary_index:
                    self.unary_index[cat] = len(self.unary_index)
                if node.children:
                    key = (
                        basic_category(node.children[0].label, False),
                        basic_category(node.children[-1].label, False),
                    )
                    if key not in self.prod_index:
                        self.prod_index[key] = len(self.prod_index)

    def init_params(self) -> None:
        d, c, v = self.dim, self.num_classes, max(len(self.cache), 1)
        np_, nu = len(self.prod_index), len(self.unary_index)
        k = jax.random.split(jax.random.key(self.seed), 6)
        r = 1.0 / np.sqrt(2 * d)
        self.params = {
            # leading production axis ≙ binaryTransform / binaryTensors
            # MultiDimensionalMaps (RNTN.java:94-101); n_prod==1 in
            # simplified mode = the reference's flat model
            "W": jax.random.uniform(
                k[0], (np_, d, 2 * d + 1), minval=-r, maxval=r
            ),
            "V": jax.random.uniform(
                k[1], (np_, 2 * d, 2 * d, d), minval=-r, maxval=r
            )
            * (1.0 if self.use_tensor else 0.0),
            "Wc": jax.random.uniform(k[2], (c, d + 1), minval=-r, maxval=r),
            "emb": 0.1 * jax.random.normal(k[3], (v, d)),
        }
        if not self.combine_classification:
            # ≙ binaryClassification (:251) + unaryClassification (:259)
            self.params["Wc_bin"] = jax.random.uniform(
                k[4], (np_, c, d + 1), minval=-r, maxval=r
            )
            self.params["Wc_un"] = jax.random.uniform(
                k[5], (nu, c, d + 1), minval=-r, maxval=r
            )
        self._adagrad = jax.tree.map(jnp.zeros_like, self.params)

    def _grow_tables(self) -> None:
        """Extend the production/unary-keyed tables to the registry
        sizes, preserving trained slots; new slots init like
        init_params and start with fresh AdaGrad history."""
        d, c = self.dim, self.num_classes
        targets = {
            "W": (len(self.prod_index), (d, 2 * d + 1)),
            "V": (len(self.prod_index), (2 * d, 2 * d, d)),
        }
        if not self.combine_classification:
            targets["Wc_bin"] = (len(self.prod_index), (c, d + 1))
            targets["Wc_un"] = (len(self.unary_index), (c, d + 1))
        r = 1.0 / np.sqrt(2 * d)
        key = jax.random.key(self.seed + 1)
        for name, (n_new, shape) in targets.items():
            cur = self.params[name]
            if cur.shape[0] >= n_new:
                continue
            key, sub = jax.random.split(key)
            fresh = jax.random.uniform(
                sub, (n_new - cur.shape[0], *shape), minval=-r, maxval=r
            )
            if name == "V" and not self.use_tensor:
                fresh = fresh * 0.0
            self.params[name] = jnp.concatenate([cur, fresh])
            self._adagrad[name] = jnp.concatenate(
                [self._adagrad[name], jnp.zeros_like(fresh)]
            )

    # -- forward over the packed tree (fori_loop) ---------------------------
    def _tree_loss(
        self, params, word_ids, left, right, leaf, labels, node_mask,
        prod, ucat,
    ):
        d = self.dim
        n = word_ids.shape[0]
        vecs0 = jnp.zeros((n, d))

        def body(i, vecs):
            l = vecs[left[i]]
            r_vec = vecs[right[i]]
            lr_cat = jnp.concatenate([l, r_vec, jnp.ones(1)])
            linear = params["W"][prod[i]] @ lr_cat
            lr2 = jnp.concatenate([l, r_vec])
            tensor = jnp.einsum("a,abd,b->d", lr2, params["V"][prod[i]], lr2)
            composed = jnp.tanh(linear + tensor)
            leaf_vec = jnp.tanh(params["emb"][word_ids[i]])
            vec = jnp.where(leaf[i] > 0, leaf_vec, composed)
            return vecs.at[i].set(vec)

        vecs = jax.lax.fori_loop(0, n, body, vecs0)
        logits = self._node_logits(params, vecs, leaf, prod, ucat)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -logp[jnp.arange(n), labels] * node_mask
        loss = jnp.sum(nll) / jnp.maximum(jnp.sum(node_mask), 1.0)
        return loss, (vecs, logits)

    def _node_logits(self, params, vecs, leaf, prod, ucat):
        d = self.dim
        if self.combine_classification:
            wc = params["Wc"]
            return vecs @ wc[:, :d].T + wc[:, d]
        # untied classification: binary nodes read the production table;
        # leaves AND unary internal nodes read the category table —
        # ucat == -1 marks binary nodes (≙ getClassWForNode:400)
        wsel = jnp.where(
            (ucat >= 0)[:, None, None],
            params["Wc_un"][jnp.maximum(ucat, 0)],
            params["Wc_bin"][prod],
        )  # (n, c, d+1)
        return jnp.einsum("nd,ncd->nc", vecs, wsel[:, :, :d]) + wsel[:, :, d]

    @functools.partial(jax.jit, static_argnames=("self",))
    def _batch_step(self, params, hist, batch, tree_w, lr):
        """One AdaGrad step on the mean per-tree loss of a vmapped batch
        of padded trees — B trees per dispatch instead of per actor
        round-trip (≙ RNTN.fit:341)."""

        def mean_loss(p):
            per_tree, _ = jax.vmap(
                lambda wi, le, ri, lf, la, ma, pr, uc: self._tree_loss(
                    p, wi, le, ri, lf, la, ma, pr, uc
                )
            )(*batch)
            return jnp.sum(per_tree * tree_w) / jnp.maximum(
                jnp.sum(tree_w), 1.0
            )

        loss, grads = jax.value_and_grad(mean_loss)(params)
        hist = jax.tree.map(lambda h, g: h + g * g, hist, grads)
        params = jax.tree.map(
            lambda p, g, h: p - lr * g / (jnp.sqrt(h) + 1e-8),
            params, grads, hist,
        )
        return params, hist, loss

    def _pad(self, packed: dict):
        """Pad packed tree arrays to max_nodes (one compiled step shape)."""
        n = len(packed["word_ids"])
        m = self.max_nodes
        if n > m:
            raise ValueError(f"tree has {n} nodes > max_nodes={m}")
        pad = m - n
        mask = np.concatenate(
            [np.ones(n, np.float32), np.zeros(pad, np.float32)]
        )

        def ext(a, fill=0):
            return np.concatenate([a, np.full(pad, fill, a.dtype)])

        return (
            ext(packed["word_ids"]), ext(packed["left"]),
            ext(packed["right"]),
            ext(packed["leaf"], 1),  # pads act as leaves
            ext(packed["labels"]), mask,
            ext(packed["prod"]), ext(packed["ucat"]),
        )

    def _pack_padded(self, tree: Tree):
        return self._pad(
            _pack_full(
                tree, self.cache, self.num_classes,
                self.prod_index, self.unary_index, self.simplified_model,
            )
        )

    def fit_trees(
        self, trees: Iterable[Tree], epochs: int = 1,
        batch_size: int | None = None,
    ) -> list[float]:
        """≙ RNTN.fit:341 (actor-parallel loop -> vmapped jitted batches)."""
        trees = list(trees)
        if len(self.cache) == 0:
            self.cache.fit([t.words() for t in trees])
        self.discover_productions(trees)
        if self.params is None:
            self.init_params()
        else:
            # a later fit may register new productions/categories — the
            # tables must grow with the registries (a stale table would
            # silently clamp the new indices onto the last slot in jit)
            self._grow_tables()
        b = max(1, min(batch_size or self.batch_size, len(trees)))
        # pack once, train many epochs (trees are static); the last
        # batch is padded to the same B with zero-weight repeats so one
        # compiled step shape covers the whole run
        packed = [self._pack_padded(t) for t in trees]
        n = len(trees)
        pad = (-n) % b
        cols = [
            jnp.asarray(np.stack(col + col[:1] * pad))
            for col in (list(z) for z in zip(*packed))
        ]
        weights = np.concatenate(
            [np.ones(n, np.float32), np.zeros(pad, np.float32)]
        )
        losses = []
        for _ in range(epochs):
            total, nw = 0.0, 0.0
            for s in range(0, n + pad, b):
                batch = tuple(c[s:s + b] for c in cols)
                w = jnp.asarray(weights[s:s + b])
                self.params, self._adagrad, loss = self._batch_step(
                    self.params, self._adagrad, batch, w,
                    jnp.float32(self.lr),
                )
                bw = float(weights[s:s + b].sum())
                total += float(loss) * bw
                nw += bw
            losses.append(total / max(nw, 1.0))
        return losses

    def predict_root(self, tree: Tree) -> int:
        # the root is the last real node in topological order
        return int(self.predict_nodes(tree)[-1])

    def predict_nodes(self, tree: Tree) -> np.ndarray:
        """Per-node class predictions in topological order (real nodes
        only) — the node-level view RNTNEval.java:61 accumulates."""
        padded = self._pack_padded(tree)
        word_ids, left, right, leaf, labels, mask, prod, ucat = (
            jnp.asarray(a) for a in padded
        )
        _, (_, logits) = self._tree_loss(
            self.params, word_ids, left, right, leaf, labels, mask,
            prod, ucat,
        )
        n_real = int(mask.sum())
        return np.asarray(jnp.argmax(logits[:n_real], axis=-1))


class RNTNEval:
    """≙ RNTNEval.java:61 — accuracy over tree root labels."""

    def __init__(self):
        self.correct = 0
        self.total = 0

    def eval(self, model: RNTN, trees: Iterable[Tree]) -> None:
        for t in trees:
            try:
                gold = int(t.label) % model.num_classes
            except ValueError:
                continue
            self.total += 1
            if model.predict_root(t) == gold:
                self.correct += 1

    def accuracy(self) -> float:
        return self.correct / self.total if self.total else 0.0
