"""Word2Vec: skip-gram with hierarchical softmax + negative sampling.

≙ reference models/word2vec/Word2Vec.java:41-640 (vocab build :247,
Huffman :340, window sampling skipGram:304/trainSentence:288, lr decay by
words seen :181) and the fused training kernel
InMemoryLookupTable.iterateSample:171-270 (exp-table sigmoid, BLAS axpy
row updates, unigram^0.75 negative table).

TPU re-design (SURVEY §7 "Word2Vec throughput" hard part): the reference
gets speed from *racy* per-pair BLAS axpy updates across threads
(Hogwild).  Here training pairs are generated host-side (numpy), batched,
and each batch is ONE jitted XLA program:

- gather input rows -> batched HS/NS dot products on the MXU ->
  scatter-add row updates (``.at[].add``, XLA scatter) for syn0/syn1.
- Within a batch, colliding row updates *accumulate* (scatter-add) rather
  than race — deterministic, and mathematically the minibatch version of
  the reference's sequential SGD.
- The dense (V, max_code_len) Huffman code/point arrays come from
  ``VocabCache.huffman_arrays`` so the HS tree walk is a dense gather.

The distributed variant (sharded batches + periodic AllReduce of deltas)
≙ Word2VecPerformer/Word2VecJobAggregator lives in ``fit_distributed``.
"""

from __future__ import annotations

import functools
import logging
from pathlib import Path
from typing import Iterable

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.sentence_iterator import SentenceIterator
from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizer
from deeplearning4j_tpu.nlp.vocab import VocabCache

log = logging.getLogger(__name__)

MAX_EXP = 6.0  # ≙ the reference's exp-table domain
# HS batches folded into one dispatch by _hs_scan. Sized so the ~3ms
# per-dispatch overhead of the tunneled TPU backend is noise next to
# device time (~0.2ms/batch): 128 batches ≈ 24ms device work/dispatch.
# lr freshness is preserved because _hs_scan takes a per-batch lr vector.
_SCAN_WIDTH = 128


# -- jitted batch kernels -----------------------------------------------------

def _hs_math_merged(S, v, inputs, codes, points, mask, lr):
    """One HS batch update on the merged (2V, D) table.

    ``S[:v]`` is syn0, ``S[v:]`` is syn1. Merging the tables turns the
    two row scatter-adds (the hot write path, ≙ the reference's per-bit
    BLAS axpy in InMemoryLookupTable.iterateSample:171-270) into ONE
    scatter on the combined index set — measured 1.6x the split version
    on v5e (the scatter is VMEM-write-bound; one fused pass beats two).
    Keep the scatter UNSORTED: pre-sorting the indices costs an extra
    full materialization of the reordered updates and measured ~1.5x
    slower in the scanned kernel.
    """
    h = S[inputs]  # (B, D)
    w1 = S[v + points]  # (B, L, D)
    dot = jnp.einsum("bd,bld->bl", h, w1)
    f = jax.nn.sigmoid(dot)
    # saturated dots are SKIPPED, not clipped, exactly as the reference's
    # exp-table range check does (InMemoryLookupTable.iterateSample:
    # continue when |dot| >= MAX_EXP). Clipping instead keeps updating
    # saturated pairs with a constant-magnitude g, which feeds an
    # oscillating syn0<->syn1 instability that blows weights up on small
    # corpora trained for many epochs.
    in_range = (jnp.abs(dot) < MAX_EXP).astype(f.dtype)
    g = (1.0 - codes - f) * lr * mask * in_range  # (B, L)
    grad_in = jnp.einsum("bl,bld->bd", g, w1)
    d = S.shape[-1]
    rows = jnp.concatenate([inputs, (v + points).reshape(-1)])
    deltas = jnp.concatenate(
        [grad_in, (g[:, :, None] * h[:, None, :]).reshape(-1, d)]
    )
    return S.at[rows].add(deltas)


def _hs_math(syn0, syn1, inputs, codes, points, mask, lr):
    """One hierarchical-softmax batch update (pure math, jit-composable).

    inputs: (B,) input-word rows of syn0.
    codes/points/mask: (B, L) Huffman path of the target words.
    """
    v = syn0.shape[0]
    S = jnp.concatenate([syn0, syn1])
    S = _hs_math_merged(S, v, inputs, codes, points, mask, lr)
    return S[:v], S[v:]


_hs_step = jax.jit(_hs_math, donate_argnums=(0, 1))


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _hs_scan(syn0, syn1, ins, tgts, codes, points, mask, lrs):
    """k HS batch updates in one dispatch (lax.scan over stacked batches).

    ins/tgts: (k, B); lrs: (k,).  The Huffman-path gather happens inside
    the scan so only the compact (k, B) index arrays cross the host
    boundary per flush. The merged (2V, D) table is concatenated ONCE
    per dispatch (16MB of copies amortized over k batches), scanned as a
    single carry, and split back at the end.
    """
    v = syn0.shape[0]
    S = jnp.concatenate([syn0, syn1])

    def body(S, xs):
        i, t, lr = xs
        return _hs_math_merged(S, v, i, codes[t], points[t], mask[t], lr), ()

    S, _ = jax.lax.scan(body, S, (ins, tgts, lrs))
    return S[:v], S[v:]


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _ns_step(syn0, syn1neg, inputs, targets, negatives, lr):
    """One negative-sampling batch update.

    targets: (B,) positive rows of syn1neg; negatives: (B, K) sampled rows.
    """
    v, d = syn0.shape
    S = jnp.concatenate([syn0, syn1neg])
    h = S[inputs]  # (B, D)
    rows = jnp.concatenate([targets[:, None], negatives], axis=1)  # (B, 1+K)
    labels = jnp.concatenate(
        [jnp.ones_like(targets[:, None]), jnp.zeros_like(negatives)], axis=1
    ).astype(syn0.dtype)
    w = S[v + rows]  # (B, 1+K, D)
    dot = jnp.einsum("bd,bkd->bk", h, w)
    # negative sampling SATURATES out-of-range dots to f=1/0 (full
    # corrective update) — unlike HS, which skips them; this mirrors
    # word2vec.c's `if (f > MAX_EXP) g = (label - 1) * alpha` branch
    f = jnp.where(
        dot > MAX_EXP, 1.0,
        jnp.where(dot < -MAX_EXP, 0.0, jax.nn.sigmoid(dot)),
    )
    g = (labels - f) * lr
    grad_in = jnp.einsum("bk,bkd->bd", g, w)
    # single merged scatter (see _hs_math_merged for why)
    all_rows = jnp.concatenate([inputs, (v + rows).reshape(-1)])
    deltas = jnp.concatenate(
        [grad_in, (g[:, :, None] * h[:, None, :]).reshape(-1, d)]
    )
    S = S.at[all_rows].add(deltas)
    return S[:v], S[v:]


# -- pair generation (host) ---------------------------------------------------

def skipgram_pairs(
    sentence_ids: list[int], window: int, rng: np.random.Generator
) -> tuple[np.ndarray, np.ndarray]:
    """(input, target) pairs with per-center random window reduction
    (≙ Word2Vec.skipGram:304 — b = random % window)."""
    arr = np.asarray(sentence_ids, dtype=np.int32)
    n = len(arr)
    ins, tgts = [], []
    if n < 2:
        return np.zeros(0, np.int32), np.zeros(0, np.int32)
    bs = rng.integers(0, window, size=n)
    for i in range(n):
        span = window - int(bs[i])
        lo, hi = max(0, i - span), min(n, i + span + 1)
        for j in range(lo, hi):
            if j != i:
                ins.append(arr[j])  # context word is the input
                tgts.append(arr[i])  # center word supplies the HS path
    return np.asarray(ins, np.int32), np.asarray(tgts, np.int32)


class _PairBuffer:
    """Shared sentence→pair plumbing for ``fit`` and ``fit_distributed``.

    Buffers encoded sentences and drains them through one native
    ``sg_pairs_chunk`` pass per chunk (≙ the Java skipGram loop, now C++),
    accumulating (input, target) pair arrays until the trainer consumes
    them.  The chunk seed stream is ``seed, seed+1, ...`` so both training
    paths see identical pair enumeration for the same corpus."""

    def __init__(self, window: int, seed: int, chunk_words: int):
        self.window = window
        self.next_seed = seed
        self.chunk_words = chunk_words
        self.sents: list[np.ndarray] = []
        self.words = 0
        self._ins: list[np.ndarray] = []
        self._tgts: list[np.ndarray] = []
        self.count = 0  # pairs pending

    @staticmethod
    def words_per_chunk(batch_pairs: int, window: int) -> int:
        # E[span] ≈ window/2 each side -> ~window pairs per word; size
        # chunks to ~one batch of pairs so the lr schedule stays fresh
        return max(batch_pairs // max(window, 1), 64)

    def add(self, ids: list[int]) -> bool:
        """Buffer one encoded sentence; True when a chunk is pending."""
        if len(ids) >= 2:
            self.sents.append(np.asarray(ids, np.int32))
            self.words += len(ids)
        return self.words >= self.chunk_words

    def drain(self) -> None:
        """Enumerate pairs for all buffered sentences in one native pass."""
        if not self.sents:
            return
        from deeplearning4j_tpu import native_io

        ins, tgts = native_io.sg_pairs_chunk(
            self.sents, self.window, self.next_seed
        )
        self.next_seed += 1
        self.sents.clear()
        self.words = 0
        if len(ins):
            self._ins.append(ins)
            self._tgts.append(tgts)
            self.count += len(ins)

    def take_all(self) -> tuple[np.ndarray, np.ndarray]:
        ins = np.concatenate(self._ins) if self._ins else np.zeros(0, np.int32)
        tgts = (
            np.concatenate(self._tgts) if self._tgts else np.zeros(0, np.int32)
        )
        self._ins.clear()
        self._tgts.clear()
        self.count = 0
        return ins, tgts

    def put_back(self, ins: np.ndarray, tgts: np.ndarray) -> None:
        if len(ins):
            self._ins.append(ins)
            self._tgts.append(tgts)
            self.count += len(ins)


class Word2Vec:
    """Skip-gram embeddings (Builder fields ≙ Word2Vec.Builder:397+)."""

    def __init__(
        self,
        layer_size: int = 50,
        window: int = 5,
        min_word_frequency: int = 1,
        use_hierarchical_softmax: bool = True,
        negative: int = 0,  # number of negative samples (0 = HS only)
        lr: float = 0.025,
        min_lr: float = 1e-4,
        epochs: int = 1,
        batch_pairs: int = 4096,
        sample: float = 0.0,  # frequent-word subsampling threshold
        seed: int = 123,
        tokenizer=None,
    ):
        self.layer_size = layer_size
        self.window = window
        self.use_hs = use_hierarchical_softmax
        self.negative = negative
        self.lr = lr
        self.min_lr = min_lr
        self.epochs = epochs
        self.batch_pairs = batch_pairs
        self.sample = sample
        self.seed = seed
        self.tokenizer = tokenizer or DefaultTokenizer()
        self.cache = VocabCache(min_word_frequency)
        self.syn0: jax.Array | None = None
        self.syn1: jax.Array | None = None
        self.syn1neg: jax.Array | None = None
        self._codes = self._points = self._mask = None
        self._table: np.ndarray | None = None

    # -- vocab -------------------------------------------------------------
    def tokenize(self, sentence: str) -> list[str]:
        return self.tokenizer.tokens(sentence)

    def build_vocab(self, sentences: SentenceIterator) -> None:
        """≙ Word2Vec.buildVocab:247 + buildBinaryTree:340."""
        self.cache.fit(self.tokenize(s) for s in sentences)
        self.cache.build_huffman()
        self._codes, self._points, self._mask = self.cache.huffman_arrays()
        if self.negative > 0:
            self._table = self.cache.unigram_table()

    def reset_weights(self) -> None:
        """≙ Word2Vec.resetWeights:350 / InMemoryLookupTable init."""
        v, d = len(self.cache), self.layer_size
        key = jax.random.key(self.seed)
        self.syn0 = (jax.random.uniform(key, (v, d)) - 0.5) / d
        self.syn1 = jnp.zeros((max(v - 1, 1), d))
        self.syn1neg = jnp.zeros((v, d))

    # -- training ----------------------------------------------------------
    def _subsample(self, ids: list[int], rng: np.random.Generator) -> list[int]:
        if self.sample <= 0:
            return ids
        total = self.cache.total_word_count
        out = []
        for i in ids:
            freq = self.cache.vocab[self.cache.index_to_word[i]].count / total
            keep = (np.sqrt(freq / self.sample) + 1) * (self.sample / freq)
            if rng.random() < keep:
                out.append(i)
        return out

    def fit(self, sentences: SentenceIterator) -> None:
        """≙ Word2Vec.fit:93-203 (multithreaded Hogwild loop -> batched
        jitted scatter-add steps with linear lr decay by words seen)."""
        if len(self.cache) == 0:
            self.build_vocab(sentences)
        if self.syn0 is None:
            self.reset_weights()

        rng = np.random.default_rng(self.seed)
        total_words = max(self.cache.total_word_count * self.epochs, 1)
        words_seen = 0

        codes = jnp.asarray(self._codes)
        points = jnp.asarray(self._points)
        mask = jnp.asarray(self._mask)
        table = jnp.asarray(self._table) if self._table is not None else None

        buf = _PairBuffer(
            self.window,
            self.seed,
            _PairBuffer.words_per_chunk(self.batch_pairs, self.window),
        )

        # HS-only training queues full batches (each with its own lr
        # snapshot — _hs_scan applies a per-batch lr vector) and ships
        # them _SCAN_WIDTH at a time: one dispatch ≈ 12ms of device work,
        # so the ~3ms tunnel dispatch overhead stops dominating. Mixed
        # HS+NS training keeps the per-batch path (the NS kernel needs
        # host-side negative sampling between batches).
        scan_path = self.use_hs and self.negative == 0
        batchq: list[tuple[np.ndarray, np.ndarray, float]] = []

        def dispatch_queue():
            if not batchq:
                return
            K = _SCAN_WIDTH
            b = self.batch_pairs
            # pad to the fixed scan width with lr=0 no-op batches (g is
            # proportional to lr, so a zero-lr batch changes nothing) —
            # one compiled program regardless of queue fill
            ins_k = np.zeros((K, b), np.int32)
            tgts_k = np.zeros((K, b), np.int32)
            lrs_k = np.zeros((K,), np.float32)
            for j, (bi, bt, blr) in enumerate(batchq):
                ins_k[j], tgts_k[j], lrs_k[j] = bi, bt, blr
            self.syn0, self.syn1 = _hs_scan(
                self.syn0, self.syn1, jnp.asarray(ins_k), jnp.asarray(tgts_k),
                codes, points, mask, jnp.asarray(lrs_k),
            )
            batchq.clear()

        def flush(train_tail: bool = False):
            buf.drain()
            if buf.count == 0:
                if train_tail:
                    dispatch_queue()
                return
            ins, tgts = buf.take_all()
            b = self.batch_pairs
            n_full = len(ins) // b
            lr_now = getattr(self, "_lr_now", self.lr)
            for k in range(n_full):
                sl = slice(k * b, (k + 1) * b)
                if scan_path:
                    batchq.append((ins[sl], tgts[sl], lr_now))
                    if len(batchq) == _SCAN_WIDTH:
                        dispatch_queue()
                else:
                    self._train_batch(
                        ins[sl], tgts[sl], codes, points, mask, table, rng
                    )
            tail = len(ins) - n_full * b
            if train_tail and tail:
                # pad the final partial batch; on the scan path it is
                # queued and flushed through dispatch_queue with the
                # other buffered batches, otherwise it trains via the
                # per-batch step
                pad = b - tail
                ins_t = np.concatenate([ins[-tail:], np.zeros(pad, np.int32)])
                tgts_t = np.concatenate([tgts[-tail:], np.zeros(pad, np.int32)])
                if scan_path:
                    batchq.append((ins_t, tgts_t, lr_now))
                else:
                    self._train_batch(
                        ins_t, tgts_t, codes, points, mask, table, rng
                    )
            elif tail:
                buf.put_back(ins[-tail:], tgts[-tail:])
            if train_tail:
                dispatch_queue()

        # pair enumeration happens once per chunk in native code; buffering
        # sentences (not pairs) keeps the Python loop to encode+subsample.
        # Chunks hold ~one batch of pairs so the lr schedule stays fresh
        # (batching many steps behind one stale lr measurably hurts
        # small-corpus convergence); _hs_scan still folds multi-batch
        # flushes into one dispatch
        for _ in range(self.epochs):
            sentences.reset()
            for sent in sentences:
                ids = self._subsample(self.cache.encode(self.tokenize(sent)), rng)
                words_seen += len(ids)
                self._lr_now = max(
                    self.min_lr, self.lr * (1.0 - words_seen / total_words)
                )
                if buf.add(ids):
                    flush()
            # epoch boundary: train all *full* batches buffered; a
            # sub-batch tail carries over to the next epoch (padding it
            # with junk (0,0) pairs every epoch measurably degrades
            # small-corpus embeddings — only the single final flush pads)
            flush()
        flush(train_tail=True)

    def _train_batch(self, ins, tgts, codes, points, mask, table, rng):
        lr = jnp.float32(getattr(self, "_lr_now", self.lr))
        ins_j = jnp.asarray(ins)
        tgts_j = jnp.asarray(tgts)
        if self.use_hs:
            self.syn0, self.syn1 = _hs_step(
                self.syn0, self.syn1, ins_j, codes[tgts_j], points[tgts_j],
                mask[tgts_j], lr,
            )
        if self.negative > 0 and table is not None:
            neg_idx = rng.integers(0, len(table), size=(len(ins), self.negative))
            negatives = table[jnp.asarray(neg_idx, jnp.int32)]
            self.syn0, self.syn1neg = _ns_step(
                self.syn0, self.syn1neg, ins_j, tgts_j, negatives, lr
            )

    # -- distributed (≙ Word2VecPerformer + Word2VecJobAggregator) ----------
    def fit_distributed(self, sentences: SentenceIterator, mesh=None) -> None:
        """Data-parallel Word2Vec: each device trains on a shard of each
        pair-batch and the parameter *deltas* are averaged — reproducing the
        master-side delta merge (Word2VecJobAggregator.java:23-36) as an
        in-graph pmean over the mesh."""
        from deeplearning4j_tpu.utils.compat import shard_map
        from jax.sharding import PartitionSpec as P

        from deeplearning4j_tpu.parallel import mesh as mesh_lib

        mesh = mesh or mesh_lib.data_parallel_mesh()
        n_dev = mesh.devices.size

        if len(self.cache) == 0:
            self.build_vocab(sentences)
        if self.syn0 is None:
            self.reset_weights()

        codes = jnp.asarray(self._codes)
        points = jnp.asarray(self._points)
        mask = jnp.asarray(self._mask)

        def per_device(syn0, syn1, ins, cds, pts, msk, lr):
            new0, new1 = _hs_math(syn0, syn1, ins[0], cds[0], pts[0], msk[0], lr)
            # average deltas across devices == average of updated params
            # since all started from the same replicated copy
            new0 = jax.lax.pmean(new0, mesh_lib.DATA_AXIS)
            new1 = jax.lax.pmean(new1, mesh_lib.DATA_AXIS)
            return new0, new1

        axis = mesh_lib.DATA_AXIS
        step = jax.jit(
            shard_map(
                per_device,
                mesh=mesh,
                in_specs=(P(), P(), P(axis), P(axis), P(axis), P(axis), P()),
                out_specs=(P(), P()),
                check_vma=False,
            )
        )

        b = self.batch_pairs - self.batch_pairs % n_dev
        buf = _PairBuffer(
            self.window, self.seed, _PairBuffer.words_per_chunk(b, self.window)
        )
        sentences.reset()

        def train_full_batches():
            while buf.count >= b:
                allin, alltg = buf.take_all()
                batch_i, batch_t = allin[:b], alltg[:b]
                buf.put_back(allin[b:], alltg[b:])
                per = b // n_dev
                bi = jnp.asarray(batch_i).reshape(n_dev, per)
                bt = jnp.asarray(batch_t)
                self.syn0, self.syn1 = step(
                    self.syn0, self.syn1, bi,
                    codes[bt].reshape(n_dev, per, codes.shape[1]),
                    points[bt].reshape(n_dev, per, points.shape[1]),
                    mask[bt].reshape(n_dev, per, mask.shape[1]),
                    jnp.float32(self.lr),
                )

        for sent in sentences:
            ids = self.cache.encode(self.tokenize(sent))
            if buf.add(ids):
                buf.drain()
            train_full_batches()
        buf.drain()
        train_full_batches()  # tail < b pairs is dropped, as before

    # -- WordVectors API (≙ WordVectorsImpl.java:361) -----------------------
    def get_word_vector(self, word: str) -> np.ndarray | None:
        i = self.cache.index_of(word)
        return None if i < 0 else np.asarray(self.syn0[i])

    def _normed(self) -> np.ndarray:
        m = np.asarray(self.syn0)
        return m / (np.linalg.norm(m, axis=1, keepdims=True) + 1e-9)

    def similarity(self, w1: str, w2: str) -> float:
        """Cosine similarity (≙ WordVectorsImpl.similarity)."""
        a, b = self.get_word_vector(w1), self.get_word_vector(w2)
        if a is None or b is None:
            return float("nan")
        return float(
            np.dot(a, b) / ((np.linalg.norm(a) * np.linalg.norm(b)) + 1e-9)
        )

    def words_nearest(self, word_or_vec, top: int = 10, exclude: set[str] = frozenset()) -> list[str]:
        """≙ WordVectorsImpl.wordsNearest — cosine ranking."""
        if isinstance(word_or_vec, str):
            vec = self.get_word_vector(word_or_vec)
            exclude = set(exclude) | {word_or_vec}
            if vec is None:
                return []
        else:
            vec = np.asarray(word_or_vec)
        normed = self._normed()
        q = vec / (np.linalg.norm(vec) + 1e-9)
        sims = normed @ q
        order = np.argsort(-sims)
        out = []
        for i in order:
            w = self.cache.word_for(int(i))
            if w not in exclude:
                out.append(w)
            if len(out) >= top:
                break
        return out

    def _answer_analogy(self, normed, a, b, c, d):
        """Top-1 analogy answer against a pre-normalized matrix:
        True/False, or None when any word is out of vocabulary (the
        word2vec.c skip convention). ONE implementation behind both
        accuracy surfaces."""
        va, vb, vc = (self.get_word_vector(w) for w in (a, b, c))
        if va is None or vb is None or vc is None or d not in self.cache:
            return None
        q = vb - va + vc
        sims = normed @ (q / (np.linalg.norm(q) + 1e-9))
        exclude = {a, b, c}
        for i in np.argsort(-sims):
            w = self.cache.word_for(int(i))
            if w not in exclude:
                return w == d
        return False

    def accuracy(self, questions: list[tuple[str, str, str, str]]) -> float:
        """Analogy accuracy a:b :: c:d (≙ WordVectors.accuracy)."""
        return self.accuracy_report({"all": questions})["TOTAL"]["accuracy"]

    def accuracy_report(
        self, path_or_categories
    ) -> dict[str, dict[str, float]]:
        """Per-category analogy report from the Google questions-words
        format (≙ the reference's ``accuracy`` surface consuming the
        standard file, WordVectorsImpl.java — which took the raw lines;
        here also a path or pre-parsed {category: [(a,b,c,d), ...]}).

        Returns ``{category: {"accuracy", "correct", "total",
        "skipped"}}`` plus a ``"TOTAL"`` row; ``total`` counts questions
        whose four words are all in vocabulary (the word2vec.c
        convention — OOV questions are skipped, reported per category).
        """
        if isinstance(path_or_categories, (str, Path)):
            cats = parse_questions_words(path_or_categories)
        else:
            cats = dict(path_or_categories)
        # normalize the matrix ONCE: the standard questions-words file
        # holds ~19.5K analogies, and a per-question _normed() would
        # redo the full-vocab normalization every time
        normed = self._normed()
        report: dict[str, dict[str, float]] = {}
        g_corr = g_tot = g_skip = 0
        for cat, questions in cats.items():
            corr = tot = skip = 0
            for a, b, c, d in questions:
                ans = self._answer_analogy(normed, a, b, c, d)
                if ans is None:
                    skip += 1
                    continue
                tot += 1
                corr += bool(ans)
            report[cat] = {
                "accuracy": corr / tot if tot else 0.0,
                "correct": corr, "total": tot, "skipped": skip,
            }
            g_corr += corr
            g_tot += tot
            g_skip += skip
        report["TOTAL"] = {
            "accuracy": g_corr / g_tot if g_tot else 0.0,
            "correct": g_corr, "total": g_tot, "skipped": g_skip,
        }
        return report


def parse_questions_words(path: str | Path) -> dict[str, list[tuple]]:
    """Parse the Google ``questions-words.txt`` analogy format:
    ``: category`` headers followed by ``a b c d`` lines (≙ the file the
    reference's WordVectorsImpl accuracy surface consumes). Lines that
    are not exactly four tokens are skipped, like word2vec.c's
    compute-accuracy."""
    cats: dict[str, list[tuple]] = {}
    current = "uncategorized"
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            if line.startswith(":"):
                current = line[1:].strip() or current
                cats.setdefault(current, [])
                continue
            parts = line.split()
            if len(parts) == 4:
                cats.setdefault(current, []).append(tuple(parts))
    return cats
