"""ParagraphVectors (doc2vec): PV-DBOW / PV-DM over labeled documents.

≙ reference models/paragraphvectors/ParagraphVectors.java:37-480
(trainSentence:149, dbow:172): label (paragraph) vectors are trained
against the words of their windows through the SAME fused HS +
negative-sampling kernel as Word2Vec (inherited from
InMemoryLookupTable.iterateSample:171); ``train_words=False`` freezes
word vectors (pure DBOW).

TPU re-design: label rows are appended to the word table as a merged
``(V + n_labels, D)`` input matrix, so a label update IS a word-kernel
update with input row ``V + label_id`` — the batched scan dispatch
(``_hs_scan``, _SCAN_WIDTH batches per device call) and the NS kernel
(``_ns_step``) apply unchanged.  The previous design dispatched one
jitted call per document per epoch, paying the ~3ms tunnel overhead
documented in word2vec.py per sentence; the merged-table scan folds
thousands of documents into each dispatch.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.models.word2vec import (
    _SCAN_WIDTH,
    Word2Vec,
    _hs_scan,
    _ns_step,
    skipgram_pairs,  # noqa: F401  (re-exported; public through this module)
)


class ParagraphVectors(Word2Vec):
    def __init__(self, train_words: bool = True, **kw):
        super().__init__(**kw)
        self.train_words = train_words
        self.labels: dict[str, int] = {}
        self.syn0_labels: jax.Array | None = None

    def fit_labeled(self, labeled_sentences) -> None:
        """labeled_sentences: iterable of (label, sentence) pairs
        (e.g. LabelAwareSentenceIterator)."""
        pairs = list(labeled_sentences)
        from deeplearning4j_tpu.nlp.sentence_iterator import (
            CollectionSentenceIterator,
        )

        sents = CollectionSentenceIterator([s for _, s in pairs])
        if len(self.cache) == 0:
            self.build_vocab(sents)
        if self.syn0 is None:
            self.reset_weights()
        for label, _ in pairs:
            if label not in self.labels:
                self.labels[label] = len(self.labels)
        key = jax.random.key(self.seed + 1)
        self.syn0_labels = (
            jax.random.uniform(key, (len(self.labels), self.layer_size)) - 0.5
        ) / self.layer_size

        if self.train_words:
            self.fit(sents)

        # PV-DBOW label pass: enumerate (label-row, word) pairs host-side
        # ONCE (≙ ParagraphVectors.dbow:172 — the label predicts each word
        # of its document), then stream them through the batched kernels
        # against the merged (V + L, D) input table.
        v = self.syn0.shape[0]
        ins_list, tgt_list = [], []
        for label, sent in pairs:
            ids = self.cache.encode(self.tokenize(sent))
            if not ids:
                continue
            ins_list.append(
                np.full(len(ids), v + self.labels[label], np.int32)
            )
            tgt_list.append(np.asarray(ids, np.int32))
        if not ins_list:
            return
        all_ins = np.concatenate(ins_list)
        all_tgts = np.concatenate(tgt_list)

        # input table = words + labels + ONE zero scratch row: padding
        # pairs point their input at the scratch row, so their syn1/
        # syn1neg deltas are exactly g*h = 0 (h is gathered before the
        # batch's scatter) and the only garbage lands on the scratch
        # row, which is dropped after training. This keeps one compiled
        # batch shape without training junk (0,0) pairs — the
        # small-corpus degradation word2vec.py's fit documents.
        d = self.syn0.shape[1]
        merged = jnp.concatenate(
            [self.syn0, self.syn0_labels,
             jnp.zeros((1, d), self.syn0.dtype)]
        )
        scratch = v + len(self.labels)
        b = self.batch_pairs
        rng = np.random.default_rng(self.seed + 2)

        # the label pass trains at a fixed lr, so "epochs" is literally
        # the same pair stream repeated; chunk a virtual epochs-fold
        # stream by modulo indexing (no epochs-sized host copies)
        n0 = len(all_ins)
        total = n0 * self.epochs

        def chunk(s, e):
            idx = np.arange(s, min(e, total)) % n0
            return all_ins[idx], all_tgts[idx]

        if self.use_hs:
            codes = jnp.asarray(self._codes)
            points = jnp.asarray(self._points)
            mask = jnp.asarray(self._mask)
            per_dispatch = _SCAN_WIDTH * b
            for s in range(0, total, per_dispatch):
                chunk_i, chunk_t = chunk(s, s + per_dispatch)
                k = _SCAN_WIDTH
                ins_k = np.full((k, b), scratch, np.int32)
                tgts_k = np.zeros((k, b), np.int32)
                lrs_k = np.zeros((k,), np.float32)
                ins_k.reshape(-1)[: len(chunk_i)] = chunk_i
                tgts_k.reshape(-1)[: len(chunk_t)] = chunk_t
                # full batches + the (final) partial tail train at lr;
                # all-scratch filler batches ride at lr=0 (exact no-op)
                lrs_k[: -(-len(chunk_i) // b)] = self.lr
                merged, self.syn1 = _hs_scan(
                    merged, self.syn1, jnp.asarray(ins_k),
                    jnp.asarray(tgts_k), codes, points, mask,
                    jnp.asarray(lrs_k),
                )
        if self.negative > 0:
            # negative-sampling path (≙ iterateSample's negative branch,
            # InMemoryLookupTable.java:217-243): the label row is pulled
            # toward its words' syn1neg rows and away from unigram-table
            # draws. _ns_step offsets targets by len(merged) internally,
            # so word-id targets index syn1neg directly.
            if self._table is None:
                self._table = self.cache.unigram_table()
            table = self._table
            # the HS phase may have accumulated garbage on the scratch
            # row; NS pads must gather h=0 again for exact no-op deltas
            merged = merged.at[scratch].set(0.0)
            for s in range(0, total, b):
                chunk_i, chunk_t = chunk(s, s + b)
                if len(chunk_i) < b:
                    pad = b - len(chunk_i)
                    chunk_i = np.concatenate(
                        [chunk_i, np.full(pad, scratch, np.int32)]
                    )
                    chunk_t = np.concatenate(
                        [chunk_t, np.zeros(pad, np.int32)]
                    )
                negs = table[
                    rng.integers(0, len(table), size=(b, self.negative))
                ]
                merged, self.syn1neg = _ns_step(
                    merged, self.syn1neg, jnp.asarray(chunk_i),
                    jnp.asarray(chunk_t),
                    jnp.asarray(negs, jnp.int32), jnp.float32(self.lr),
                )

        self.syn0 = merged[:v]
        self.syn0_labels = merged[v:scratch]

    def get_label_vector(self, label: str) -> np.ndarray | None:
        i = self.labels.get(label)
        return None if i is None else np.asarray(self.syn0_labels[i])

    def infer_nearest_label(self, sentence: str) -> str | None:
        """Classify by cosine between doc's mean word vector and labels."""
        ids = self.cache.encode(self.tokenize(sentence))
        if not ids or not self.labels:
            return None
        doc = np.asarray(self.syn0)[ids].mean(0)
        mat = np.asarray(self.syn0_labels)
        sims = mat @ doc / (
            np.linalg.norm(mat, axis=1) * np.linalg.norm(doc) + 1e-9
        )
        inv = {v: k for k, v in self.labels.items()}
        return inv[int(np.argmax(sims))]
