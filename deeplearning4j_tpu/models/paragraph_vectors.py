"""ParagraphVectors (doc2vec): PV-DBOW / PV-DM over labeled documents.

≙ reference models/paragraphvectors/ParagraphVectors.java:37-480
(trainSentence:149, dbow:172): label (paragraph) vectors are trained
against the words of their windows through the same hierarchical-softmax
path as Word2Vec; ``train_words=False`` freezes word vectors (pure DBOW).

TPU re-design: label rows live in a separate ``syn0_labels`` matrix; each
batch is the same jitted HS scatter-add kernel as Word2Vec with inputs
taken from the label matrix.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.models.word2vec import Word2Vec, _hs_math, skipgram_pairs


class ParagraphVectors(Word2Vec):
    def __init__(self, train_words: bool = True, **kw):
        super().__init__(**kw)
        self.train_words = train_words
        self.labels: dict[str, int] = {}
        self.syn0_labels: jax.Array | None = None

    def fit_labeled(self, labeled_sentences) -> None:
        """labeled_sentences: iterable of (label, sentence) pairs
        (e.g. LabelAwareSentenceIterator)."""
        pairs = list(labeled_sentences)
        from deeplearning4j_tpu.nlp.sentence_iterator import CollectionSentenceIterator

        sents = CollectionSentenceIterator([s for _, s in pairs])
        if len(self.cache) == 0:
            self.build_vocab(sents)
        if self.syn0 is None:
            self.reset_weights()
        for label, _ in pairs:
            if label not in self.labels:
                self.labels[label] = len(self.labels)
        key = jax.random.key(self.seed + 1)
        self.syn0_labels = (
            jax.random.uniform(key, (len(self.labels), self.layer_size)) - 0.5
        ) / self.layer_size

        if self.train_words:
            self.fit(sents)

        codes = jnp.asarray(self._codes)
        points = jnp.asarray(self._points)
        mask = jnp.asarray(self._mask)
        rng = np.random.default_rng(self.seed)
        step = jax.jit(_hs_math, donate_argnums=(0, 1))

        for _ in range(self.epochs):
            for label, sent in pairs:
                ids = self.cache.encode(self.tokenize(sent))
                if not ids:
                    continue
                # PV-DBOW: the label vector predicts every word in the doc
                # (≙ ParagraphVectors.dbow:172)
                tgts = np.asarray(ids, np.int32)
                ins = np.full(len(ids), self.labels[label], np.int32)
                self.syn0_labels, self.syn1 = step(
                    self.syn0_labels, self.syn1,
                    jnp.asarray(ins), codes[tgts], points[tgts], mask[tgts],
                    jnp.float32(self.lr),
                )

    def get_label_vector(self, label: str) -> np.ndarray | None:
        i = self.labels.get(label)
        return None if i is None else np.asarray(self.syn0_labels[i])

    def infer_nearest_label(self, sentence: str) -> str | None:
        """Classify by cosine between doc's mean word vector and labels."""
        ids = self.cache.encode(self.tokenize(sentence))
        if not ids or not self.labels:
            return None
        doc = np.asarray(self.syn0)[ids].mean(0)
        mat = np.asarray(self.syn0_labels)
        sims = mat @ doc / (
            np.linalg.norm(mat, axis=1) * np.linalg.norm(doc) + 1e-9
        )
        inv = {v: k for k, v in self.labels.items()}
        return inv[int(np.argmax(sims))]
