"""GloVe: AdaGrad weighted least squares on co-occurrence log-counts.

≙ reference models/glove/Glove.java:42 (fit:91, doIteration:151),
GloveWeightLookupTable (bias vectors + per-row AdaGrad), and
CoOccurrences.java:41 (window-weighted co-occurrence counting, the actor
pipeline replaced by a plain host-side pass).

TPU re-design: co-occurrence triples (i, j, X_ij) are counted host-side
once, then shuffled into fixed-size batches; each epoch's updates run as
jitted scatter-add AdaGrad steps — the batched equivalent of the
reference's per-pair ``iterateSample`` loop.
"""

from __future__ import annotations

import functools
from collections import Counter

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.sentence_iterator import SentenceIterator
from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizer
from deeplearning4j_tpu.nlp.vocab import VocabCache


def count_cooccurrences(
    encoded_sentences, window: int = 5
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Window-weighted counts (weight 1/distance, ≙ CoOccurrences.fit:69).

    Returns (rows, cols, values) for the upper+lower triangle.
    """
    counts: Counter = Counter()
    for ids in encoded_sentences:
        n = len(ids)
        for i in range(n):
            for off in range(1, window + 1):
                j = i + off
                if j < n:
                    counts[(ids[i], ids[j])] += 1.0 / off
                    counts[(ids[j], ids[i])] += 1.0 / off
    if not counts:
        return (np.zeros(0, np.int32),) * 2 + (np.zeros(0, np.float32),)
    keys = np.array(list(counts.keys()), dtype=np.int32)
    vals = np.array(list(counts.values()), dtype=np.float32)
    return keys[:, 0], keys[:, 1], vals


def _glove_math(w, wc, b, bc, hw, hwc, hb, hbc, rows, cols, logx, fx, lr):
    """One batched AdaGrad WLS step (pure math, reused by the sharded path).

    w/wc: word and context embeddings (V, D); b/bc biases (V,);
    h*: AdaGrad accumulators.  loss = f(X) * (w_i.wc_j + b_i + bc_j - logX)^2
    """
    wi = w[rows]
    wj = wc[cols]
    diff = jnp.einsum("bd,bd->b", wi, wj) + b[rows] + bc[cols] - logx
    fdiff = fx * diff  # (B,)
    g_wi = fdiff[:, None] * wj
    g_wj = fdiff[:, None] * wi
    # AdaGrad per-row
    hw = hw.at[rows].add(g_wi**2)
    hwc = hwc.at[cols].add(g_wj**2)
    w = w.at[rows].add(-lr * g_wi / jnp.sqrt(hw[rows] + 1e-8))
    wc = wc.at[cols].add(-lr * g_wj / jnp.sqrt(hwc[cols] + 1e-8))
    hb = hb.at[rows].add(fdiff**2)
    hbc = hbc.at[cols].add(fdiff**2)
    b = b.at[rows].add(-lr * fdiff / jnp.sqrt(hb[rows] + 1e-8))
    bc = bc.at[cols].add(-lr * fdiff / jnp.sqrt(hbc[cols] + 1e-8))
    loss = 0.5 * jnp.mean(fx * diff**2)
    return w, wc, b, bc, hw, hwc, hb, hbc, loss


_glove_step = jax.jit(
    _glove_math, donate_argnums=(0, 1, 2, 3, 4, 5, 6, 7)
)


class Glove:
    """≙ Glove.Builder fields: layer_size, xMax, alpha, lr, epochs."""

    def __init__(
        self,
        layer_size: int = 50,
        window: int = 5,
        min_word_frequency: int = 1,
        lr: float = 0.05,
        x_max: float = 100.0,
        alpha: float = 0.75,
        epochs: int = 5,
        batch: int = 4096,
        seed: int = 123,
        tokenizer=None,
    ):
        self.layer_size = layer_size
        self.window = window
        self.lr = lr
        self.x_max = x_max
        self.alpha = alpha
        self.epochs = epochs
        self.batch = batch
        self.seed = seed
        self.tokenizer = tokenizer or DefaultTokenizer()
        self.cache = VocabCache(min_word_frequency)
        self.w = self.wc = self.b = self.bc = None
        self._acc = None  # AdaGrad history, kept for continue-training
        self.loss_history: list[float] = []

    def _prepare(self, sentences: SentenceIterator):
        """Vocab + co-occurrence counting + weight/accumulator init; returns
        (rows, cols, logx, fx) host arrays and the AdaGrad accumulators."""
        toks = [self.tokenizer.tokens(s) for s in sentences]
        self.cache.fit(toks)
        encoded = [self.cache.encode(t) for t in toks]
        rows, cols, vals = count_cooccurrences(encoded, self.window)
        if len(rows) == 0:
            raise ValueError("empty co-occurrence matrix")
        logx, fx, acc = self._init_weights(vals)
        return rows, cols, logx, fx, acc

    def _init_weights(self, vals: np.ndarray, reset: bool = True):
        """Weight/bias/AdaGrad init + the GloVe weighting terms, shared
        by the sentence and precomputed-co-occurrence fit paths.
        ``reset=False`` keeps already-trained weights (the continue-
        training path) and only rebuilds the per-triple terms."""
        v, d = len(self.cache), self.layer_size
        if reset or self.w is None:
            key = jax.random.key(self.seed)
            k1, k2 = jax.random.split(key)
            self.w = (jax.random.uniform(k1, (v, d)) - 0.5) / d
            self.wc = (jax.random.uniform(k2, (v, d)) - 0.5) / d
            self.b = jnp.zeros((v,))
            self.bc = jnp.zeros((v,))
            self._acc = None
        if self._acc is not None:
            acc = self._acc  # continue-training keeps the AdaGrad history
        else:
            acc = (
                jnp.ones((v, d)), jnp.ones((v, d)),
                jnp.ones((v,)), jnp.ones((v,)),
            )
        logx = np.log(vals).astype(np.float32)
        fx = np.minimum((vals / self.x_max) ** self.alpha, 1.0).astype(
            np.float32
        )
        return logx, fx, acc

    def _run_epochs(self, step, data, acc, bsz: int, reshape=None) -> None:
        """Shared shuffle/batch/loss-history loop over the co-occurrence
        triples; ``reshape`` folds each batch to (n_dev, per) for shard_map."""
        rows, cols, logx, fx = data
        hw, hwc, hb, hbc = acc
        rng = np.random.default_rng(self.seed)
        n = len(rows)
        for _ in range(self.epochs):
            order = rng.permutation(n)
            epoch_loss, nb = 0.0, 0
            for s in range(0, n - bsz + 1, bsz):
                idx = order[s : s + bsz]
                batch = [jnp.asarray(a[idx]) for a in data]
                if reshape is not None:
                    batch = [a.reshape(reshape) for a in batch]
                (self.w, self.wc, self.b, self.bc, hw, hwc, hb, hbc, loss) = step(
                    self.w, self.wc, self.b, self.bc, hw, hwc, hb, hbc,
                    *batch, jnp.float32(self.lr),
                )
                epoch_loss += float(loss)
                nb += 1
            self.loss_history.append(epoch_loss / max(nb, 1))
        # keep the final AdaGrad history so a continue-training call
        # (fit_cooccurrences after fit) steps with the accumulated h,
        # not a fresh near-full-lr restart on already-trained rows
        self._acc = (hw, hwc, hb, hbc)

    def fit(self, sentences: SentenceIterator) -> None:
        rows, cols, logx, fx, acc = self._prepare(sentences)
        bsz = min(self.batch, len(rows))
        self._run_epochs(_glove_step, (rows, cols, logx, fx), acc, bsz)

    def fit_cooccurrences(self, triples) -> None:
        """Train directly on precomputed ``(word_i, word_j, X_ij)``
        triples — the artifact CoOccurrences.fit produces and
        Glove.doIteration consumes in the reference (Glove.java:91,151;
        CoOccurrences.java:69). Lets a real co-occurrence dump (e.g.
        the reference's big/coc.txt fixture) drive the AdaGrad WLS
        optimizer without re-counting.

        Caveats (ADVICE r4):

        - ``min_word_frequency`` here counts how often a word appears
          across the *triples* (each triple contributes one occurrence
          per member), NOT corpus token frequency — the corpus is not
          available in this path, so the cutoff semantics necessarily
          diverge from the reference's CoOccurrences (which prunes on
          corpus counts before counting pairs).
        - if a vocab was already built (``fit()`` ran first), it is
          reused rather than rebuilt, trained weights AND AdaGrad
          history are kept (continue-training), and triples whose words
          are out-of-vocab are dropped. (``fit()`` itself has no such
          guard: VocabCache.fit ACCUMULATES, so calling ``fit()`` twice
          on one model corrupts the word↔index mapping — build the
          vocab once, then continue with this method.)
        """
        triples = [
            (w1, w2, x) for w1, w2, x in
            ((w1, w2, float(x)) for w1, w2, x in triples) if x > 0
        ]
        if not triples:
            raise ValueError("empty co-occurrence input")
        had_vocab = len(self.cache) > 0
        if not had_vocab:
            self.cache.fit([w1, w2] for w1, w2, _ in triples)
        # drop triples whose words the min-frequency cutoff pruned: a -1
        # index would wrap to the last vocab row in the jitted scatter
        # and silently corrupt another word's embedding
        kept = [
            (self.cache.index_of(w1), self.cache.index_of(w2), x)
            for w1, w2, x in triples
        ]
        kept = [(i, j, x) for i, j, x in kept if i >= 0 and j >= 0]
        if not kept:
            raise ValueError("all co-occurrence words pruned by "
                             "min_word_frequency")
        rows = np.asarray([i for i, _, _ in kept], np.int32)
        cols = np.asarray([j for _, j, _ in kept], np.int32)
        vals = np.asarray([x for _, _, x in kept], np.float32)
        logx, fx, acc = self._init_weights(vals, reset=not had_vocab)
        bsz = min(self.batch, len(rows))
        self._run_epochs(_glove_step, (rows, cols, logx, fx), acc, bsz)

    def fit_distributed(self, sentences: SentenceIterator, mesh=None) -> None:
        """Data-parallel GloVe: each device runs the AdaGrad WLS step on its
        shard of every co-occurrence batch from replicated tables, then the
        updated tables (and accumulators) are averaged — the in-graph pmean
        equivalent of the master-side table merge in the reference's
        GloveJobAggregator (scaleout/perform/models/glove/, SURVEY §2-P8)."""
        from deeplearning4j_tpu.utils.compat import shard_map
        from jax.sharding import PartitionSpec as P

        from deeplearning4j_tpu.parallel import mesh as mesh_lib

        mesh = mesh or mesh_lib.data_parallel_mesh()
        n_dev = mesh.devices.size
        axis = mesh_lib.DATA_AXIS

        rows, cols, logx, fx, acc = self._prepare(sentences)
        hw, hwc, hb, hbc = acc

        def per_device(w, wc, b, bc, hw, hwc, hb, hbc, rows, cols, logx, fx, lr):
            out = _glove_math(
                w, wc, b, bc, hw, hwc, hb, hbc,
                rows[0], cols[0], logx[0], fx[0], lr,
            )
            *tables, loss = out
            tables = [jax.lax.pmean(t, axis) for t in tables]
            return (*tables, jax.lax.pmean(loss, axis))

        rep, sh = P(), P(axis)
        step = jax.jit(
            shard_map(
                per_device,
                mesh=mesh,
                in_specs=(rep,) * 8 + (sh,) * 4 + (rep,),
                out_specs=(rep,) * 9,
                check_vma=False,
            )
        )

        n = len(rows)
        bsz = min(self.batch, n)
        bsz -= bsz % n_dev
        if bsz == 0:
            raise ValueError(
                f"co-occurrence batch ({min(self.batch, n)}) smaller than mesh ({n_dev})"
            )
        self._run_epochs(
            step, (rows, cols, logx, fx), (hw, hwc, hb, hbc), bsz,
            reshape=(n_dev, bsz // n_dev),
        )

    # combined representation (standard GloVe: w + wc)
    @property
    def syn0(self):
        return self.w + self.wc

    def get_word_vector(self, word: str) -> np.ndarray | None:
        i = self.cache.index_of(word)
        return None if i < 0 else np.asarray(self.syn0[i])

    def similarity(self, w1: str, w2: str) -> float:
        a, b = self.get_word_vector(w1), self.get_word_vector(w2)
        if a is None or b is None:
            return float("nan")
        return float(np.dot(a, b) / (np.linalg.norm(a) * np.linalg.norm(b) + 1e-9))
