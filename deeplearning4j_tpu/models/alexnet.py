"""AlexNet-style conv net for CIFAR-10 — the second benchmark config
(BASELINE.json configs: 'AlexNet-CIFAR10 samples/sec/chip').

A CIFAR-scale adaptation (32x32x3 inputs) of the AlexNet shape: stacked
conv+pool blocks widening channels, then dense classifier head — all
through the same trainable conv_downsample layer.
"""

from __future__ import annotations

from deeplearning4j_tpu.models.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn import conf as C


def alexnet_cifar_config(num_classes: int = 10) -> C.MultiLayerConfig:
    confs = [
        C.LayerConfig(
            layer_type="conv_downsample", n_in=3, num_feature_maps=64,
            filter_size=(3, 3), stride=(2, 2), activation="relu",
        ),  # 32 -> conv 30 -> pool 15
        C.LayerConfig(
            layer_type="conv_downsample", n_in=64, num_feature_maps=128,
            filter_size=(3, 3), stride=(2, 2), activation="relu",
        ),  # 15 -> 13 -> 6
        C.LayerConfig(
            layer_type="conv_downsample", n_in=128, num_feature_maps=256,
            filter_size=(3, 3), stride=(2, 2), activation="relu",
        ),  # 6 -> 4 -> 2
        C.LayerConfig(layer_type="dense", n_in=256 * 2 * 2, n_out=512, activation="relu"),
        C.LayerConfig(layer_type="dense", n_in=512, n_out=256, activation="relu"),
        C.LayerConfig(
            layer_type="output", n_in=256, n_out=num_classes,
            activation="softmax", loss="MCXENT",
        ),
    ]
    return C.MultiLayerConfig(confs=confs, pretrain=False, backward=True)


def build_alexnet(seed: int = 0):
    net = MultiLayerNetwork(alexnet_cifar_config(), seed=seed)
    return net, net.init()


def synthetic_cifar(n: int = 1024, seed: int = 0):
    """CIFAR-shaped synthetic data (NHWC 32x32x3) for offline benches."""
    import numpy as np

    from deeplearning4j_tpu.datasets.base import DataSet, to_one_hot

    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, n)
    yy, xx = np.mgrid[0:32, 0:32].astype(np.float32) / 31
    imgs = np.zeros((n, 32, 32, 3), np.float32)
    for c in range(10):
        m = labels == c
        angle = c * np.pi / 10
        base = 0.5 + 0.5 * np.sin(2 * np.pi * (np.cos(angle) * xx + np.sin(angle) * yy) * 3)
        imgs[m] = np.stack([base * (0.3 + 0.07 * ((c + k) % 3)) for k in range(3)], -1)
    imgs += rng.normal(0, 0.1, imgs.shape).astype(np.float32)
    return DataSet(np.clip(imgs, 0, 1).reshape(n, -1), to_one_hot(labels, 10))
