"""Convex solvers: line-searched gradient descent, plain iteration GD,
Polak-Ribière conjugate gradient, L-BFGS, stochastic Hessian-free.

≙ reference optimize/solvers/ — GradientAscent.java, IterationGradientDescent.java,
ConjugateGradient.java (Polak-Ribière), LBFGS.java (two-loop recursion),
StochasticHessianFree.java (CG on Gauss-Newton products with damping), all
driven by the BaseOptimizer.optimize loop (BaseOptimizer.java:97-160).

TPU re-design: each solver's full iteration loop — gradient adjustment,
line search, parameter update, termination checks — is a single
``lax.while_loop`` compiled once per (config, batch-shape).  The reference
runs this loop in Java, re-entering BLAS per score evaluation.  The
convention is *minimization* throughout (scores are losses); the
reference's maximize/minimize flag and negative step functions collapse
into the sign of the descent direction.
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.conf import LayerConfig, OptimizationAlgorithm
from deeplearning4j_tpu.optimize import linesearch, updaters
from deeplearning4j_tpu.optimize.api import ModelFunctions
from deeplearning4j_tpu.utils import tree_math as tm


class SolverState(NamedTuple):
    params: Any
    updater: updaters.UpdaterState
    extra: Any  # algorithm-specific carry
    score: jax.Array
    old_score: jax.Array
    step_size: jax.Array
    key: jax.Array
    iteration: jax.Array
    done: jax.Array


# -- per-algorithm direction rules -------------------------------------------

def _gd_extra(params):
    return ()


def _gd_direction(conf, extra, adj_grad, raw_grad):
    return tm.neg(adj_grad), ()


def _cg_extra(params):
    # (prev_raw_grad, prev_direction, have_prev)
    return (tm.zeros_like(params), tm.zeros_like(params), jnp.asarray(False))


def _cg_direction(conf, extra, adj_grad, raw_grad):
    """Polak-Ribière conjugate direction (≙ ConjugateGradient.java)."""
    prev_g, prev_d, have_prev = extra
    g = adj_grad
    denominator = tm.vdot(prev_g, prev_g)
    beta_pr = tm.vdot(g, tm.sub(g, prev_g)) / jnp.maximum(denominator, 1e-20)
    beta = jnp.where(have_prev, jnp.maximum(beta_pr, 0.0), 0.0)
    d = tm.axpy(beta, prev_d, tm.neg(g))
    # restart with steepest descent if d is not a descent direction
    descent = tm.vdot(d, g) < 0
    d = tm.where(descent, d, tm.neg(g))
    return d, (g, d, jnp.asarray(True))


def _lbfgs_extra_factory(m: int):
    def make(params):
        zeros = tm.zeros_like(params)
        s_hist = jax.tree.map(lambda z: jnp.stack([z] * m), zeros)
        y_hist = jax.tree.map(lambda z: jnp.stack([z] * m), zeros)
        rho = jnp.zeros((m,))
        return (
            s_hist,
            y_hist,
            rho,
            jnp.asarray(0, jnp.int32),  # count of stored pairs
            tm.zeros_like(params),  # prev params
            tm.zeros_like(params),  # prev raw grad
            jnp.asarray(False),
        )

    return make


def _lbfgs_direction_factory(m: int):
    def direction(conf, extra, adj_grad, raw_grad):
        """Two-loop recursion (≙ LBFGS.java)."""
        s_hist, y_hist, rho, count, prev_p, prev_g, have_prev = extra
        g = adj_grad

        def hist_at(hist, i):
            return jax.tree.map(lambda h: h[i], hist)

        q = g
        alphas = jnp.zeros((m,))
        # newest pair is at index (count-1) % m; iterate newest -> oldest
        def bw(i, carry):
            q, alphas = carry
            idx = (count - 1 - i) % m
            valid = i < count
            s_i, y_i = hist_at(s_hist, idx), hist_at(y_hist, idx)
            alpha = rho[idx] * tm.vdot(s_i, q)
            alpha = jnp.where(valid, alpha, 0.0)
            q = tm.axpy(-alpha, y_i, q)
            return q, alphas.at[idx].set(alpha)

        q, alphas = lax.fori_loop(0, m, bw, (q, alphas))

        # initial Hessian scaling gamma = <s,y>/<y,y> of newest pair
        newest = (count - 1) % m
        s_n, y_n = hist_at(s_hist, newest), hist_at(y_hist, newest)
        gamma = tm.vdot(s_n, y_n) / jnp.maximum(tm.vdot(y_n, y_n), 1e-20)
        gamma = jnp.where(count > 0, gamma, 1.0)
        z = tm.scale(q, gamma)

        def fw(i, z):
            idx = (count - m + i) % m  # oldest -> newest among valid
            valid = i >= (m - jnp.minimum(count, m))
            s_i, y_i = hist_at(s_hist, idx), hist_at(y_hist, idx)
            beta = rho[idx] * tm.vdot(y_i, z)
            corr = tm.scale(s_i, alphas[idx] - beta)
            z2 = tm.add(z, corr)
            return tm.where(valid, z2, z)

        z = lax.fori_loop(0, m, fw, z)
        d = tm.neg(z)
        descent = tm.vdot(d, g) < 0
        d = tm.where(descent, d, tm.neg(g))
        return d, extra

    return direction


def _lbfgs_post_factory(m: int):
    def post(extra, new_params, new_raw_grad):
        s_hist, y_hist, rho, count, prev_p, prev_g, have_prev = extra
        s = tm.sub(new_params, prev_p)
        y = tm.sub(new_raw_grad, prev_g)
        sy = tm.vdot(s, y)
        store = have_prev & (sy > 1e-10)
        idx = count % m

        def put(hist, v):
            return jax.tree.map(
                lambda h, vi: jnp.where(store, h.at[idx].set(vi), h), hist, v
            )

        s_hist = put(s_hist, s)
        y_hist = put(y_hist, y)
        rho = jnp.where(store, rho.at[idx].set(1.0 / jnp.maximum(sy, 1e-20)), rho)
        count = jnp.where(store, count + 1, count)
        return (s_hist, y_hist, rho, count, new_params, new_raw_grad, jnp.asarray(True))

    return post


_ALGOS = {
    OptimizationAlgorithm.GRADIENT_DESCENT: (_gd_extra, _gd_direction, None, True),
    OptimizationAlgorithm.ITERATION_GRADIENT_DESCENT: (
        _gd_extra,
        _gd_direction,
        None,
        False,
    ),
    OptimizationAlgorithm.CONJUGATE_GRADIENT: (_cg_extra, _cg_direction, None, True),
}
_LBFGS_M = 10
_ALGOS[OptimizationAlgorithm.LBFGS] = (
    _lbfgs_extra_factory(_LBFGS_M),
    _lbfgs_direction_factory(_LBFGS_M),
    _lbfgs_post_factory(_LBFGS_M),
    True,
)


def make_step(conf: LayerConfig, model: ModelFunctions, algo: str | None = None):
    """Build (init_state, step) for one solver iteration, jit-compatible."""
    algo = algo or conf.optimization_algo
    if algo == OptimizationAlgorithm.HESSIAN_FREE:
        return _make_hf_step(conf, model)
    if algo not in _ALGOS:
        raise ValueError(f"Unknown optimization algorithm {algo!r}")
    make_extra, direction_fn, post_fn, use_line_search = _ALGOS[algo]

    def init_state(params, key) -> SolverState:
        k0, key = jax.random.split(key)
        score = model.score(params, k0)
        return SolverState(
            params=params,
            updater=updaters.init(params),
            extra=make_extra(params),
            score=score,
            old_score=jnp.asarray(jnp.inf, jnp.float32),
            step_size=jnp.asarray(1.0, jnp.float32),
            key=key,
            iteration=jnp.asarray(0, jnp.int32),
            done=jnp.asarray(False),
        )

    def step(state: SolverState) -> SolverState:
        key, k_grad, k_score = jax.random.split(state.key, 3)
        score, raw_grad = model.score_and_grad(state.params, k_grad)
        adj_grad, updater = updaters.adjust(conf, state.updater, raw_grad, state.params)
        direction, extra = direction_fn(conf, state.extra, adj_grad, raw_grad)

        if use_line_search:
            result = linesearch.backtrack(
                lambda p: model.score(p, k_score),
                state.params,
                direction,
                raw_grad,
                initial_step=1.0,
                max_iterations=conf.num_line_search_iterations,
            )
            step_size = result.step
            new_params = tm.axpy(step_size, direction, state.params)
            new_score = result.score
        else:
            step_size = jnp.asarray(1.0, jnp.float32)
            new_params = tm.add(state.params, direction)
            new_score = model.score(new_params, k_score)

        if post_fn is not None:
            # the curvature pair g(new)-g(old) wants correlated sampling,
            # so the second eval reuses k_grad on purpose
            _, new_raw_grad = model.score_and_grad(new_params, k_grad)  # lint: prng-ok correlated curvature pair
            extra = post_fn(extra, new_params, new_raw_grad)

        grad_norm = tm.norm2(raw_grad)
        improvement = jnp.abs(score - new_score)
        eps_hit = improvement < 1e-6 * (jnp.abs(score) + jnp.abs(new_score) + 1e-10)
        norm_hit = grad_norm < 1e-8
        stalled = use_line_search and False  # step=0 handled via eps_hit
        done = eps_hit | norm_hit | jnp.asarray(stalled)

        return SolverState(
            params=new_params,
            updater=updater,
            extra=extra,
            score=new_score,
            old_score=score,
            step_size=step_size,
            key=key,
            iteration=state.iteration + 1,
            done=done,
        )

    return init_state, step


# -- Hessian-free ------------------------------------------------------------

class HFExtra(NamedTuple):
    damping: jax.Array
    x0: Any  # CG warm start


def _gvp_fn(model: ModelFunctions, params):
    """Gauss-Newton vector product v -> J'H_L J v at `params`.

    ≙ the reference's R-operator path (MultiLayerNetwork.computeDeltasR /
    backPropGradient2, MultiLayerNetwork.java:496,935) — re-expressed as
    jvp over the forward + loss Hessian + vjp, which is exactly the
    Gauss-Newton product without any hand-written R-op.
    Falls back to the full Hessian-vector product when the model does not
    expose a forward/loss split.
    """
    if model.forward is not None and model.loss_on_outputs is not None:
        z, jvp_to_z = jax.linearize(model.forward, params)
        _, vjp_from_z = jax.vjp(model.forward, params)
        loss_grad = jax.grad(model.loss_on_outputs)

        def gvp(v):
            z_dot = jvp_to_z(v)
            hz = jax.jvp(loss_grad, (z,), (z_dot,))[1]
            return vjp_from_z(hz)[0]

        return gvp

    # full HVP fallback: d/dp <grad(score), v>
    def hvp(v):
        key = jax.random.key(0)
        return jax.jvp(lambda p: model.score_and_grad(p, key)[1], (params,), (v,))[1]

    return hvp


def _cg_solve(matvec, b, x0, max_iters: int = 50, tol: float = 1e-5):
    """Conjugate-gradient solve of matvec(x)=b (≙ StochasticHessianFree.conjGradient:72)."""

    def cond(state):
        x, r, p, rs, it = state
        return (rs > tol * tol) & (it < max_iters)

    def body(state):
        x, r, p, rs, it = state
        ap = matvec(p)
        alpha = rs / jnp.maximum(tm.vdot(p, ap), 1e-20)
        x = tm.axpy(alpha, p, x)
        r = tm.axpy(-alpha, ap, r)
        rs_new = tm.vdot(r, r)
        p = tm.axpy(rs_new / jnp.maximum(rs, 1e-20), p, r)
        return (x, r, p, rs_new, it + 1)

    r0 = tm.sub(b, matvec(x0))
    state = (x0, r0, r0, tm.vdot(r0, r0), jnp.asarray(0, jnp.int32))
    x, r, p, rs, it = lax.while_loop(cond, body, state)
    return x


def _make_hf_step(conf: LayerConfig, model: ModelFunctions):
    """Stochastic Hessian-free (Martens): CG on damped Gauss-Newton products
    with Levenberg-Marquardt damping adaptation and line-searched update.

    ≙ StochasticHessianFree.java (optimize/solvers/, 245 LoC) including its
    CG-with-damping core; the reference's hand-built R-op forward/backward
    is replaced by jvp/vjp (see _gvp_fn).
    """

    def init_state(params, key) -> SolverState:
        k0, key = jax.random.split(key)
        return SolverState(
            params=params,
            updater=updaters.init(params),
            extra=HFExtra(
                damping=jnp.asarray(conf.__dict__.get("damping", 10.0), jnp.float32),
                x0=tm.zeros_like(params),
            ),
            score=model.score(params, k0),
            old_score=jnp.asarray(jnp.inf, jnp.float32),
            step_size=jnp.asarray(1.0, jnp.float32),
            key=key,
            iteration=jnp.asarray(0, jnp.int32),
            done=jnp.asarray(False),
        )

    def step(state: SolverState) -> SolverState:
        key, k_grad, k_score = jax.random.split(state.key, 3)
        score, grad = model.score_and_grad(state.params, k_grad)
        lam = state.extra.damping
        gvp = _gvp_fn(model, state.params)

        def damped(v):
            return tm.axpy(lam, v, gvp(v))

        delta = _cg_solve(damped, tm.neg(grad), state.extra.x0)

        # quadratic-model reduction for the LM ratio
        q_red = -(tm.vdot(grad, delta) + 0.5 * tm.vdot(delta, damped(delta)))
        result = linesearch.backtrack(
            lambda p: model.score(p, k_score),
            state.params,
            delta,
            grad,
            initial_step=1.0,
            max_iterations=conf.num_line_search_iterations,
        )
        new_params = tm.axpy(result.step, delta, state.params)
        new_score = result.score

        actual_red = score - new_score
        rho = actual_red / jnp.maximum(q_red, 1e-20)
        lam = jnp.where(rho > 0.75, lam * (2.0 / 3.0), lam)
        lam = jnp.where(rho < 0.25, lam * 1.5, lam)

        improvement = jnp.abs(score - new_score)
        done = improvement < 1e-6 * (jnp.abs(score) + jnp.abs(new_score) + 1e-10)

        return SolverState(
            params=new_params,
            updater=state.updater,
            extra=HFExtra(damping=lam, x0=tm.scale(delta, 0.95)),
            score=new_score,
            old_score=score,
            step_size=result.step,
            key=key,
            iteration=state.iteration + 1,
            done=done,
        )

    return init_state, step


def optimize_jit(
    conf: LayerConfig,
    model: ModelFunctions,
    params,
    key: jax.Array,
    num_iterations: int | None = None,
    algo: str | None = None,
):
    """Run the full solver loop inside one jitted while_loop.

    Returns (params, final_score, iterations_run).
    """
    n = num_iterations or conf.num_iterations
    init_state, step = make_step(conf, model, algo)

    @jax.jit
    def run(params, key):
        state = init_state(params, key)

        def cond(s):
            return (~s.done) & (s.iteration < n)

        state = lax.while_loop(cond, step, state)
        return state.params, state.score, state.iteration

    return run(params, key)
