"""Optimizer API: model-function bundle, termination conditions, listeners.

≙ reference optimize/api/* (ConvexOptimizer, TerminationCondition,
IterationListener, StepFunction).  A "model" for the solvers is a bundle
of pure functions closed over the current minibatch — the functional
replacement for the reference's stateful ``Model`` object that holds its
input (nn/api/Model.java:16).
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp

log = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class ModelFunctions:
    """Pure functions the solvers drive.

    score_and_grad: (params, key) -> (score, grad_pytree) — lower is better
    score:          (params, key) -> score
    forward:        optional (params,) -> outputs, for Gauss-Newton products
    loss_on_outputs: optional (outputs,) -> scalar, for Gauss-Newton products
    """

    score_and_grad: Callable[[Any, jax.Array], tuple[jax.Array, Any]]
    score: Callable[[Any, jax.Array], jax.Array]
    forward: Callable[[Any], Any] | None = None
    loss_on_outputs: Callable[[Any], jax.Array] | None = None

    @classmethod
    def from_score(cls, score_fn, **kw) -> "ModelFunctions":
        return cls(
            score_and_grad=jax.value_and_grad(score_fn), score=score_fn, **kw
        )


# -- termination conditions (≙ optimize/terminations/*) -----------------------

def eps_termination(eps: float = 1e-4, tolerance: float = 1e-10):
    """≙ EpsTermination: relative score improvement below eps."""

    def cond(score, old_score, grad_norm):
        improvement = jnp.abs(old_score - score)
        return improvement < eps * (jnp.abs(old_score) + jnp.abs(score) + tolerance)

    return cond


def norm2_termination(gradient_tolerance: float = 1e-8):
    """≙ Norm2Termination: gradient 2-norm below tolerance."""

    def cond(score, old_score, grad_norm):
        return grad_norm < gradient_tolerance

    return cond


def default_terminations() -> list:
    return [eps_termination(), norm2_termination()]


# -- listeners (≙ optimize/api/IterationListener.java:13) ---------------------

class IterationListener:
    def iteration_done(self, info: dict) -> None:  # pragma: no cover
        raise NotImplementedError


class ScoreIterationListener(IterationListener):
    """Logs score every N iterations (≙ the reference's per-iteration
    'Score at iteration i is s' logging, BaseOptimizer.java:160)."""

    def __init__(self, print_every: int = 10):
        self.print_every = print_every
        self.history: list[float] = []

    def iteration_done(self, info: dict) -> None:
        self.history.append(float(info["score"]))
        i = info["iteration"]
        if i % self.print_every == 0:
            log.info("Score at iteration %d is %s", i, info["score"])


class ComposableIterationListener(IterationListener):
    """≙ ComposableIterationListener: fan out to several listeners."""

    def __init__(self, listeners: Sequence[IterationListener]):
        self.listeners = list(listeners)

    def iteration_done(self, info: dict) -> None:
        for listener in self.listeners:
            listener.iteration_done(info)
