"""Solver facade.

≙ reference ``optimize/Solver.java:15-45``: select the optimizer from the
config's OptimizationAlgorithm and run it.  Two execution modes:

- no listeners: the whole iteration loop runs inside one jitted
  ``lax.while_loop`` (fastest; zero host round-trips);
- with listeners: a jitted single-iteration step driven by a Python loop,
  invoking IterationListener hooks with the live score each iteration
  (≙ BaseOptimizer.java:146-148) — the listener path necessarily syncs
  device→host once per iteration.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax

from deeplearning4j_tpu.nn.conf import LayerConfig
from deeplearning4j_tpu.optimize import solvers
from deeplearning4j_tpu.optimize.api import IterationListener, ModelFunctions


class Solver:
    def __init__(
        self,
        conf: LayerConfig,
        model: ModelFunctions,
        listeners: Sequence[IterationListener] = (),
        algo: str | None = None,
    ):
        self.conf = conf
        self.model = model
        self.listeners = list(listeners)
        self.algo = algo or conf.optimization_algo
        self._init_state, self._step = solvers.make_step(conf, model, self.algo)
        self._jit_step = jax.jit(self._step)

    def optimize(
        self, params: Any, key: jax.Array, num_iterations: int | None = None
    ) -> tuple[Any, float]:
        """Run the solver; returns (new_params, final_score)."""
        n = num_iterations or self.conf.num_iterations
        if not self.listeners:
            params, score, _ = solvers.optimize_jit(
                self.conf, self.model, params, key, n, self.algo
            )
            return params, float(score)

        state = self._init_state(params, key)
        for i in range(n):
            state = self._jit_step(state)
            info = {
                "iteration": i,
                "score": float(state.score),
                "old_score": float(state.old_score),
                "step_size": float(state.step_size),
            }
            for listener in self.listeners:
                listener.iteration_done(info)
            if bool(state.done):
                break
        return state.params, float(state.score)
