"""Optimization subsystem: updaters, line search, convex solvers, listeners.

≙ reference ``org.deeplearning4j.optimize`` (Solver facade +
GradientAscent / IterationGradientDescent / ConjugateGradient / LBFGS /
StochasticHessianFree solvers + BackTrackLineSearch + GradientAdjustment).
"""

from deeplearning4j_tpu.optimize.solver import Solver  # noqa: F401
