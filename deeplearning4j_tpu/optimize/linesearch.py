"""Backtracking line search.

≙ reference ``BackTrackLineSearch`` (optimize/solvers/BackTrackLineSearch.java,
the MALLET lnsrch port): walk back along a descent direction until the
Armijo sufficient-decrease condition holds.

TPU re-design: the whole search is one ``lax.while_loop`` inside jit —
each trial step re-evaluates the jitted score, so a line-searched solver
iteration compiles to a single XLA computation with no host round-trips
(the reference re-scores the mutable model object per trial step from
Java).
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.utils import tree_math as tm


class LineSearchResult(NamedTuple):
    step: jax.Array  # chosen step size (0.0 if no acceptable step)
    score: jax.Array  # score at the chosen step
    n_evals: jax.Array


def backtrack(
    score_fn: Callable,
    params,
    direction,
    grad,
    initial_step: float | jax.Array = 1.0,
    max_iterations: int = 5,
    c1: float = 1e-4,
    rho: float = 0.5,
    min_step: float = 1e-12,
) -> LineSearchResult:
    """Find t with score(params + t*direction) <= score(params) + c1*t*<g,d>.

    ``direction`` must be a descent direction (<grad, direction> < 0);
    if it is not, the search degenerates to accepting the smallest trial.
    """
    phi0 = score_fn(params)
    slope = tm.vdot(grad, direction)

    def trial(t):
        return score_fn(tm.axpy(t, direction, params))

    def cond(state):
        t, score, it = state
        armijo = score <= phi0 + c1 * t * slope
        return (~armijo) & (it < max_iterations) & (t > min_step)

    def body(state):
        t, _, it = state
        t_new = t * rho
        return (t_new, trial(t_new), it + 1)

    t0 = jnp.asarray(initial_step, jnp.float32)
    init = (t0, trial(t0), jnp.asarray(1, jnp.int32))
    t, score, n = lax.while_loop(cond, body, init)
    # if even the smallest step failed to decrease, report step=0
    ok = score <= phi0 + c1 * t * slope
    t = jnp.where(ok, t, 0.0)
    score = jnp.where(ok, score, phi0)
    return LineSearchResult(step=t, score=score, n_evals=n)
