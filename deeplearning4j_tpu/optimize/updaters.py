"""Gradient adjustment: AdaGrad / lr scaling, momentum schedule, L2,
unit-norm constraint, batch-size division.

≙ reference ``GradientAdjustment.updateGradientAccordingToParams``
(optimize/GradientAdjustment.java:40-90), re-expressed as a pure
stateful transform (optax-style: ``init`` + ``update``) so it composes
into jitted training steps.

Deliberate divergences from the reference:
- Momentum: the reference's line ``g.addi(g.mul(m).addi(g.mul(1-m)))``
  reduces algebraically to ``g *= 2`` for every momentum value — a bug,
  not momentum.  Implemented here as standard heavy-ball velocity
  ``v = m*v + g`` instead.  The ``momentum_after`` iteration schedule is
  honored (GradientAdjustment.java:63-70).
- L2: applied as descent-direction weight decay ``g += l2*params``
  (the reference subtracts because its convention maximizes).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf import LayerConfig
from deeplearning4j_tpu.utils import tree_math as tm


class UpdaterState(NamedTuple):
    adagrad_hist: object  # pytree like params
    velocity: object  # pytree like params
    iteration: jax.Array  # scalar int32


def init(params) -> UpdaterState:
    return UpdaterState(
        adagrad_hist=tm.zeros_like(params),
        velocity=tm.zeros_like(params),
        iteration=jnp.asarray(0, jnp.int32),
    )


def _momentum_at(conf: LayerConfig, iteration: jax.Array) -> jax.Array:
    """Momentum with the momentum_after schedule applied."""
    m = jnp.asarray(conf.momentum, jnp.float32)
    for thresh, value in sorted(conf.momentum_after.items()):
        m = jnp.where(iteration >= thresh, value, m)
    return m


def adjust(
    conf: LayerConfig,
    state: UpdaterState,
    grads,
    params,
    batch_size: int | None = None,
) -> tuple[object, UpdaterState]:
    """Adjusted (descent) update direction + new state.

    Mirrors the reference's order: adagrad-or-lr -> momentum -> L2 ->
    unit-norm clip -> divide by batch size.
    """
    it = state.iteration

    # reset adagrad history every reset_adagrad_iterations
    hist = state.adagrad_hist
    if conf.reset_adagrad_iterations > 0:
        do_reset = (it > 0) & (it % conf.reset_adagrad_iterations == 0)
        hist = tm.where(do_reset, tm.zeros_like(hist), hist)

    if conf.use_adagrad:
        hist = jax.tree.map(lambda h, g: h + g * g, hist, grads)
        step = jax.tree.map(
            lambda g, h: conf.lr * g / (jnp.sqrt(h) + 1e-6), grads, hist
        )
    else:
        step = tm.scale(grads, conf.lr)

    m = _momentum_at(conf, it)
    velocity = jax.tree.map(lambda v, s: m * v + s, state.velocity, step)
    step = velocity

    if conf.use_regularization and conf.l2 > 0:
        step = jax.tree.map(lambda s, p: s + conf.l2 * conf.lr * p, step, params)

    if conf.constrain_gradient_to_unit_norm:
        step = tm.scale(step, 1.0 / (tm.norm2(step) + 1e-12))

    if batch_size is not None and batch_size > 1:
        # ≙ gradient.divi(batchSize) (GradientAdjustment.java:85).  Scores
        # here are already batch means, so this is only applied when the
        # caller explicitly passes batch_size for reference parity.
        step = tm.scale(step, 1.0 / batch_size)

    return step, UpdaterState(adagrad_hist=hist, velocity=velocity, iteration=it + 1)
