"""String utilities.

≙ the useful surface of the reference's vendored Berkeley
``StringUtils`` (berkeley/StringUtils.java, ~1040 LoC): edit distance,
LCS, n-gram helpers. The bulk of the Java file (join/pad, argmax
maps, reflection helpers, CSV escaping) is stdlib Python
(str methods, csv, itertools) and is deliberately not re-implemented;
likewise berkeley ``PriorityQueue``/``Pair``/``Triple``/``Iterators``
are ``heapq``/tuples/``itertools``.
"""

from __future__ import annotations


def edit_distance(a: str, b: str) -> int:
    """Levenshtein distance (unit costs), O(len(a)*len(b)) two-row DP."""
    if len(a) < len(b):
        a, b = b, a
    if not b:
        return len(a)
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        cur = [i]
        for j, cb in enumerate(b, 1):
            cur.append(
                min(prev[j] + 1, cur[j - 1] + 1, prev[j - 1] + (ca != cb))
            )
        prev = cur
    return prev[-1]


def longest_common_substring(a: str, b: str) -> str:
    """Longest contiguous common substring (Berkeley StringUtils parity)."""
    best_len, best_end = 0, 0
    prev = [0] * (len(b) + 1)
    for i, ca in enumerate(a, 1):
        cur = [0] * (len(b) + 1)
        for j, cb in enumerate(b, 1):
            if ca == cb:
                cur[j] = prev[j - 1] + 1
                if cur[j] > best_len:
                    best_len, best_end = cur[j], i
        prev = cur
    return a[best_end - best_len : best_end]


def ngrams(tokens: list[str], n: int) -> list[tuple[str, ...]]:
    """All order-n contiguous token n-grams."""
    if n <= 0 or n > len(tokens):
        return []
    return [tuple(tokens[i : i + n]) for i in range(len(tokens) - n + 1)]
