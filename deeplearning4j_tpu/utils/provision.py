"""Cluster provisioning EXECUTOR — actually runs host setup, not just
renders it.

≙ the reference's EC2 provisioning pair: ClusterSetup
(deeplearning4j-scaleout/deeplearning4j-aws/.../provision/
ClusterSetup.java:24 — spins up the boxes then provisions master +
workers) and HostProvisioner (HostProvisioner.java:24 — per-host SSH
session: runRemoteCommand, uploadForDeployment, uploadAndRun,
addKeyFile). Re-expressed for the TPU world: hosts are TPU VMs created
via gcloud, per-host commands ride ``gcloud compute tpus tpu-vm ssh`` /
``scp`` (or plain ssh for generic hosts).

Everything executes through an injectable :class:`CommandRunner`, so
the zero-egress environment (and the tests) drive the full
orchestration against a :class:`RecordingRunner` while production uses
:class:`SubprocessRunner` — the reference hard-wired JSch and was
untestable without live EC2.
"""

from __future__ import annotations

import dataclasses
import shlex
import subprocess
from typing import Protocol, Sequence

from deeplearning4j_tpu.utils.cloud_io import render_tpu_vm_provision


@dataclasses.dataclass
class CommandResult:
    returncode: int
    stdout: str = ""
    stderr: str = ""


class CommandRunner(Protocol):
    def run(self, argv: Sequence[str]) -> CommandResult: ...


class SubprocessRunner:
    """Executes for real (production path)."""

    def __init__(self, timeout: float | None = 600.0):
        self.timeout = timeout

    def run(self, argv: Sequence[str]) -> CommandResult:
        p = subprocess.run(
            list(argv), capture_output=True, text=True,
            timeout=self.timeout,
        )
        return CommandResult(p.returncode, p.stdout, p.stderr)


class RecordingRunner:
    """Records every command; used for --dry-run and offline tests.

    ``responses`` optionally maps a substring to a canned
    :class:`CommandResult` so failure paths are testable.
    """

    def __init__(self, responses: dict[str, CommandResult] | None = None):
        self.commands: list[list[str]] = []
        self.responses = responses or {}

    def run(self, argv: Sequence[str]) -> CommandResult:
        argv = list(argv)
        self.commands.append(argv)
        joined = " ".join(argv)
        for key, result in self.responses.items():
            if key in joined:
                return result
        return CommandResult(0)


class ProvisionError(RuntimeError):
    """A provisioning command failed; carries the failing argv + stderr."""


def _check(runner: CommandRunner, argv: Sequence[str]) -> CommandResult:
    res = runner.run(argv)
    if res.returncode != 0:
        raise ProvisionError(
            f"command failed ({res.returncode}): "
            f"{shlex.join(argv)}\n{res.stderr[-2000:]}"
        )
    return res


class HostProvisioner:
    """Per-host command/upload session (≙ HostProvisioner.java:24).

    ``tpu_vm=True`` routes through ``gcloud compute tpus tpu-vm ssh/scp``
    (worker addressing on GCP); ``False`` uses plain ssh/scp for generic
    hosts (the reference's regime).
    """

    def __init__(self, host: str, user: str | None = None,
                 zone: str | None = None, key_file: str | None = None,
                 tpu_vm: bool = False, runner: CommandRunner | None = None):
        self.host = host
        self.user = user
        self.zone = zone
        self.key_file = key_file
        self.tpu_vm = tpu_vm
        self.runner = runner or SubprocessRunner()

    def _target(self) -> str:
        return f"{self.user}@{self.host}" if self.user else self.host

    def _ssh_base(self) -> list[str]:
        if self.tpu_vm:
            cmd = ["gcloud", "compute", "tpus", "tpu-vm", "ssh",
                   self._target()]
            if self.zone:
                cmd.append(f"--zone={self.zone}")
            return cmd
        cmd = ["ssh"]
        if self.key_file:
            cmd += ["-i", self.key_file]
        return cmd + [self._target()]

    def run_remote_command(self, command: str) -> CommandResult:
        """≙ HostProvisioner.runRemoteCommand:89 (raises on rc != 0,
        like the reference's 'exec did not succeed' path)."""
        if self.tpu_vm:
            argv = self._ssh_base() + [f"--command={command}"]
        else:
            argv = self._ssh_base() + [command]
        return _check(self.runner, argv)

    def upload_for_deployment(self, src: str, dst: str) -> None:
        """≙ HostProvisioner.uploadForDeployment:138 (scp a file/dir)."""
        if self.tpu_vm:
            argv = ["gcloud", "compute", "tpus", "tpu-vm", "scp", src,
                    f"{self._target()}:{dst}"]
            if self.zone:
                argv.append(f"--zone={self.zone}")
        else:
            argv = ["scp"]
            if self.key_file:
                argv += ["-i", self.key_file]
            argv += [src, f"{self._target()}:{dst}"]
        _check(self.runner, argv)

    def upload_and_run(self, script: str, root_dir: str = "") -> None:
        """≙ HostProvisioner.uploadAndRun:80 — upload a setup script,
        chmod, execute."""
        name = script.rsplit("/", 1)[-1]
        remote = f"{root_dir.rstrip('/')}/{name}" if root_dir else name
        self.upload_for_deployment(script, remote)
        # execute by explicit path: absolute stays as-is, relative gets
        # ./ — both quoted (an unquoted exec of a name with spaces would
        # chmod one file and run another)
        exec_path = remote if remote.startswith("/") else f"./{remote}"
        self.run_remote_command(
            f"chmod +x {shlex.quote(remote)} && {shlex.quote(exec_path)}"
        )

    def add_key_file(self, pub_key_path: str) -> None:
        """≙ HostProvisioner.addKeyFile:148 — append a public key to
        authorized_keys (read locally, appended remotely)."""
        with open(pub_key_path) as f:
            key = f.read().strip()
        self.run_remote_command(
            "mkdir -p ~/.ssh && "
            f"echo {shlex.quote(key)} >> ~/.ssh/authorized_keys"
        )


@dataclasses.dataclass
class ClusterSpec:
    """What to provision (≙ ClusterSetup's args4j options, TPU-flavored:
    worker count, machine shape, region/zone, setup scripts)."""

    name: str = "dl4j"
    num_workers: int = 1
    accelerator_type: str = "v5litepod-8"
    zone: str = "us-central1-a"
    version: str = "tpu-ubuntu2204-base"
    master_script: str | None = None
    worker_script: str | None = None


class ClusterSetup:
    """Provision a whole cluster (≙ ClusterSetup.java:24: create the
    boxes, then provision master + workers with their setup scripts).

    The master is ``<name>-master``; workers ``<name>-worker-<i>``. All
    commands flow through the injected runner — pass a
    :class:`RecordingRunner` for a dry run (the CLI's default)."""

    def __init__(self, spec: ClusterSpec,
                 runner: CommandRunner | None = None):
        self.spec = spec
        self.runner = runner or SubprocessRunner()

    def _hosts(self) -> list[tuple[str, str | None]]:
        s = self.spec
        hosts = [(f"{s.name}-master", s.master_script)]
        hosts += [
            (f"{s.name}-worker-{i}", s.worker_script)
            for i in range(s.num_workers)
        ]
        return hosts

    def provision(self) -> list[str]:
        """Create every VM, then run its setup script (when given).
        Returns the provisioned host names, master first."""
        s = self.spec
        names = []
        for host, script in self._hosts():
            _check(self.runner, render_tpu_vm_provision(
                host, accelerator_type=s.accelerator_type, zone=s.zone,
                version=s.version,
            ))
            if script:
                HostProvisioner(
                    host, zone=s.zone, tpu_vm=True, runner=self.runner
                ).upload_and_run(script)
            names.append(host)
        return names

    def teardown(self) -> None:
        """Delete every VM of the cluster (reverse order). Best-effort:
        a failed delete must not leave the REMAINING (billed) VMs
        running — every delete is attempted, failures collected and
        raised once at the end."""
        failures = []
        for host, _ in reversed(self._hosts()):
            try:
                _check(self.runner, [
                    "gcloud", "compute", "tpus", "tpu-vm", "delete", host,
                    f"--zone={self.spec.zone}", "--quiet",
                ])
            except ProvisionError as e:
                failures.append(str(e))
        if failures:
            raise ProvisionError(
                f"{len(failures)} delete(s) failed:\n" + "\n".join(failures)
            )
