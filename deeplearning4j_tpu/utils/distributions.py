"""Distribution factory.

≙ reference distributions/Distributions.java:109 (commons-math factory
for normal/uniform/binomial used by weight init and sampling).  Names map
to functional ``jax.random`` samplers.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

Sampler = Callable[..., jax.Array]


def normal(mean: float = 0.0, std: float = 1.0) -> Sampler:
    def sample(key, shape, dtype=jnp.float32):
        return mean + std * jax.random.normal(key, shape, dtype)

    return sample


def uniform(low: float = 0.0, high: float = 1.0) -> Sampler:
    def sample(key, shape, dtype=jnp.float32):
        return jax.random.uniform(key, shape, dtype, minval=low, maxval=high)

    return sample


def binomial(n: int = 1, p: float = 0.5) -> Sampler:
    def sample(key, shape, dtype=jnp.float32):
        if n == 1:
            return jax.random.bernoulli(key, p, shape).astype(dtype)
        return jax.random.binomial(key, n, p, shape).astype(dtype)

    return sample


def get(name: str, *args, **kw) -> Sampler:
    try:
        return {"normal": normal, "uniform": uniform, "binomial": binomial}[name](*args, **kw)
    except KeyError:
        raise ValueError(f"Unknown distribution {name!r}") from None
