"""Math utilities.

≙ reference util/MathUtils.java:1272 + berkeley/SloppyMath.java:1026 —
the subset with live call sites in the reference (entropy, information
gain helpers, correlation, distances, log-sum-exp, sigmoid variants,
normalization, permutations).
"""

from __future__ import annotations

import math

import numpy as np


def entropy(probs) -> float:
    p = np.asarray(probs, dtype=np.float64)
    p = p[p > 0]
    return float(-(p * np.log2(p)).sum())


def information_gain(parent_probs, splits: list[tuple[float, list]]) -> float:
    """Entropy(parent) - sum_i w_i * Entropy(split_i)."""
    return entropy(parent_probs) - sum(w * entropy(p) for w, p in splits)


def log_sum_exp(xs) -> float:
    xs = np.asarray(xs, dtype=np.float64)
    m = xs.max()
    return float(m + np.log(np.exp(xs - m).sum()))


def sigmoid(x):
    return 1.0 / (1.0 + np.exp(-np.asarray(x)))


def euclidean_distance(a, b) -> float:
    return float(np.linalg.norm(np.asarray(a) - np.asarray(b)))


def manhattan_distance(a, b) -> float:
    return float(np.abs(np.asarray(a) - np.asarray(b)).sum())


def cosine_similarity(a, b) -> float:
    a, b = np.asarray(a, np.float64), np.asarray(b, np.float64)
    return float(a @ b / (np.linalg.norm(a) * np.linalg.norm(b) + 1e-12))


def correlation(a, b) -> float:
    """Pearson correlation (≙ MathUtils.correlation)."""
    a, b = np.asarray(a, np.float64), np.asarray(b, np.float64)
    return float(np.corrcoef(a, b)[0, 1])

def ssr(predicted, actual) -> float:
    """Sum of squared residuals."""
    p, a = np.asarray(predicted), np.asarray(actual)
    return float(((p - a) ** 2).sum())


def normalize(x, min_v=None, max_v=None):
    x = np.asarray(x, dtype=np.float64)
    lo = x.min() if min_v is None else min_v
    hi = x.max() if max_v is None else max_v
    return (x - lo) / max(hi - lo, 1e-12)


def bernoulli_log_likelihood(x, p) -> float:
    x, p = np.asarray(x, np.float64), np.clip(np.asarray(p, np.float64), 1e-12, 1 - 1e-12)
    return float((x * np.log(p) + (1 - x) * np.log(1 - p)).sum())


def factorial(n: int) -> float:
    return math.factorial(n)


def combinations(n: int, r: int) -> float:
    return math.comb(n, r)


def permutations(n: int, r: int) -> float:
    return math.perm(n, r)


def round_to(x: float, decimals: int) -> float:
    return round(x, decimals)


def next_power_of_2(n: int) -> int:
    return 1 if n <= 1 else 2 ** math.ceil(math.log2(n))


def clamp(x, lo, hi):
    return max(lo, min(hi, x))
