"""Counter / CounterMap — vendored-Berkeley-utils parity.

≙ reference berkeley/Counter.java:598 + CounterMap.java:390 (used
throughout the NLP stack for counts and probabilities).
"""

from __future__ import annotations

from collections import defaultdict
from typing import Generic, Hashable, Iterable, TypeVar

K = TypeVar("K", bound=Hashable)
K2 = TypeVar("K2", bound=Hashable)


class Counter(Generic[K]):
    def __init__(self, items: Iterable[K] | None = None):
        self._m: dict[K, float] = defaultdict(float)
        if items:
            for i in items:
                self.increment(i)

    def increment(self, key: K, amount: float = 1.0) -> None:
        self._m[key] += amount

    def set_count(self, key: K, value: float) -> None:
        self._m[key] = value

    def get_count(self, key: K) -> float:
        return self._m.get(key, 0.0)

    def total_count(self) -> float:
        return sum(self._m.values())

    def normalize(self) -> None:
        total = self.total_count()
        if total:
            for k in self._m:
                self._m[k] /= total

    def arg_max(self) -> K | None:
        return max(self._m, key=self._m.get) if self._m else None

    def max_count(self) -> float:
        return max(self._m.values(), default=0.0)

    def sorted_keys(self) -> list[K]:
        return sorted(self._m, key=self._m.get, reverse=True)

    def keys(self):
        return self._m.keys()

    def items(self):
        return self._m.items()

    def __len__(self) -> int:
        return len(self._m)

    def __contains__(self, key: K) -> bool:
        return key in self._m


class CounterMap(Generic[K, K2]):
    def __init__(self):
        self._m: dict[K, Counter[K2]] = defaultdict(Counter)

    def increment_count(self, key: K, sub: K2, amount: float = 1.0) -> None:
        self._m[key].increment(sub, amount)

    def get_count(self, key: K, sub: K2) -> float:
        return self._m[key].get_count(sub) if key in self._m else 0.0

    def get_counter(self, key: K) -> Counter[K2]:
        return self._m[key]

    def normalize(self) -> None:
        for c in self._m.values():
            c.normalize()

    def total_count(self) -> float:
        return sum(c.total_count() for c in self._m.values())

    def keys(self):
        return self._m.keys()

    def __len__(self) -> int:
        return len(self._m)
