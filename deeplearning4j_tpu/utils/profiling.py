"""Tracing / profiling.

The reference has no structured tracing — only StopWatch logging in the
YARN worker (WorkerNode.java:39-75) and per-iteration score logs
(BaseOptimizer.java:160); SURVEY §5 prescribes a first-class profiler
module for the TPU build.  This wraps the JAX profiler (XPlane/Perfetto
traces viewable in TensorBoard/Perfetto) plus a host-side StopWatch.
"""

from __future__ import annotations

import contextlib
import time
from pathlib import Path


class StopWatch:
    """≙ commons StopWatch usage in WorkerNode: wall-clock segments."""

    def __init__(self):
        self._start: float | None = None
        self.total = 0.0
        self.laps: list[float] = []

    def start(self) -> "StopWatch":
        self._start = time.perf_counter()
        return self

    def stop(self) -> float:
        assert self._start is not None, "not started"
        lap = time.perf_counter() - self._start
        self.laps.append(lap)
        self.total += lap
        self._start = None
        return lap

    @contextlib.contextmanager
    def lap(self):
        self.start()
        try:
            yield self
        finally:
            self.stop()


@contextlib.contextmanager
def trace(log_dir: str | Path = "/tmp/dl4j_tpu_trace"):
    """Capture an XPlane/Perfetto trace around a code region."""
    import jax

    jax.profiler.start_trace(str(log_dir))
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region that shows up in device traces."""
    import jax

    return jax.profiler.TraceAnnotation(name)


@contextlib.contextmanager
def timed(label: str, sink=None):
    """Host-side timing context; sink(label, seconds) or print."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        if sink:
            sink(label, dt)
        else:
            print(f"[timing] {label}: {dt:.4f}s")
