"""Small collection/IO utilities.

≙ reference util leftovers with live call sites: MultiDimensionalMap
(util/MultiDimensionalMap.java:785 — used by RNTN's per-label parameter
tables), SummaryStatistics, ArchiveUtils (tar/gz/zip extraction for
dataset downloads), SetUtils.
"""

from __future__ import annotations

import math
import shutil
import tarfile
import zipfile
from pathlib import Path
from typing import Generic, Hashable, TypeVar

K1 = TypeVar("K1", bound=Hashable)
K2 = TypeVar("K2", bound=Hashable)
V = TypeVar("V")


class MultiDimensionalMap(Generic[K1, K2, V]):
    """Pair-keyed map (≙ MultiDimensionalMap with entrySet/get/put)."""

    def __init__(self):
        self._m: dict[tuple[K1, K2], V] = {}

    def put(self, k1: K1, k2: K2, v: V) -> None:
        self._m[(k1, k2)] = v

    def get(self, k1: K1, k2: K2, default: V | None = None) -> V | None:
        return self._m.get((k1, k2), default)

    def contains(self, k1: K1, k2: K2) -> bool:
        return (k1, k2) in self._m

    def remove(self, k1: K1, k2: K2) -> None:
        self._m.pop((k1, k2), None)

    def entries(self):
        return self._m.items()

    def __len__(self) -> int:
        return len(self._m)


class SummaryStatistics:
    """Streaming mean/variance (Welford) ≙ util/SummaryStatistics."""

    def __init__(self):
        self.n = 0
        self._mean = 0.0
        self._m2 = 0.0
        self.min = math.inf
        self.max = -math.inf

    def add(self, x: float) -> None:
        self.n += 1
        d = x - self._mean
        self._mean += d / self.n
        self._m2 += d * (x - self._mean)
        self.min = min(self.min, x)
        self.max = max(self.max, x)

    @property
    def mean(self) -> float:
        return self._mean

    @property
    def variance(self) -> float:
        return self._m2 / (self.n - 1) if self.n > 1 else 0.0

    @property
    def std(self) -> float:
        return math.sqrt(self.variance)


def extract_archive(path: str | Path, dest: str | Path) -> Path:
    """≙ util/ArchiveUtils: unpack tar/tar.gz/tgz/zip/gz."""
    path, dest = Path(path), Path(dest)
    dest.mkdir(parents=True, exist_ok=True)
    name = path.name
    if name.endswith((".tar.gz", ".tgz", ".tar")):
        with tarfile.open(path) as t:
            t.extractall(dest, filter="data")
    elif name.endswith(".zip"):
        with zipfile.ZipFile(path) as z:
            z.extractall(dest)
    elif name.endswith(".gz"):
        import gzip

        out = dest / path.stem
        with gzip.open(path, "rb") as f_in, open(out, "wb") as f_out:
            shutil.copyfileobj(f_in, f_out)
    else:
        raise ValueError(f"Unknown archive format: {name}")
    return dest


def intersection(a, b) -> set:
    return set(a) & set(b)


def difference(a, b) -> set:
    return set(a) - set(b)
