"""Disk-backed FIFO queue.

≙ reference util/DiskBasedQueue.java:187 — spill queue elements to disk so
unbounded producers don't exhaust memory (used for worker update spill,
LocalFileUpdateSaver-style).  JSON-serializable payloads only.
"""

from __future__ import annotations

import json
import tempfile
import threading
from pathlib import Path


class DiskBasedQueue:
    def __init__(self, directory: str | Path | None = None):
        self.dir = Path(directory) if directory else Path(tempfile.mkdtemp(prefix="dl4jq_"))
        self.dir.mkdir(parents=True, exist_ok=True)
        self._lock = threading.Lock()
        self._head = 0
        self._tail = 0
        # resume from existing files
        existing = sorted(int(p.stem) for p in self.dir.glob("*.json"))
        if existing:
            self._head = existing[0]
            self._tail = existing[-1] + 1

    def add(self, item) -> None:
        with self._lock:
            (self.dir / f"{self._tail}.json").write_text(json.dumps(item))
            self._tail += 1

    def poll(self):
        with self._lock:
            if self._head >= self._tail:
                return None
            p = self.dir / f"{self._head}.json"
            item = json.loads(p.read_text())
            p.unlink()
            self._head += 1
            return item

    def peek(self):
        with self._lock:
            if self._head >= self._tail:
                return None
            return json.loads((self.dir / f"{self._head}.json").read_text())

    def __len__(self) -> int:
        with self._lock:
            return self._tail - self._head

    def is_empty(self) -> bool:
        return len(self) == 0
