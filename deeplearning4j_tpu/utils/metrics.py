"""Metrics writer + timing listener.

≙ SURVEY §5 observability: replaces the reference's scattered slf4j
logging + dropwizard resources with a structured scalar writer (JSONL —
greppable, plottable, no extra deps) and an optimizer listener that
records score/step-time series.
"""

from __future__ import annotations

import json
import time
from pathlib import Path

from deeplearning4j_tpu.optimize.api import IterationListener


class MetricsWriter:
    def __init__(self, path: str | Path):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._f = open(self.path, "a", encoding="utf-8")

    def scalar(self, tag: str, value: float, step: int | None = None) -> None:
        rec = {"tag": tag, "value": float(value), "time": time.time()}
        if step is not None:
            rec["step"] = step
        self._f.write(json.dumps(rec) + "\n")
        self._f.flush()

    def close(self) -> None:
        self._f.close()

    @staticmethod
    def read(path: str | Path) -> list[dict]:
        out = []
        with open(path, encoding="utf-8") as f:
            for line in f:
                if line.strip():
                    out.append(json.loads(line))
        return out


class MetricsIterationListener(IterationListener):
    """Streams optimizer scores + inter-iteration wall time to a writer."""

    def __init__(self, writer: MetricsWriter, prefix: str = "train"):
        self.writer = writer
        self.prefix = prefix
        self._last: float | None = None

    def iteration_done(self, info: dict) -> None:
        now = time.perf_counter()
        step = info["iteration"]
        self.writer.scalar(f"{self.prefix}/score", info["score"], step)
        if self._last is not None:
            self.writer.scalar(f"{self.prefix}/step_seconds", now - self._last, step)
        self._last = now
