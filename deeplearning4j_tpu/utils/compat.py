"""Version-compat shims for the jax API surface this repo uses.

The codebase targets the modern jax API (``jax.shard_map`` with
``check_vma=``); older jaxlib builds (< 0.6) ship the same machinery as
``jax.experimental.shard_map.shard_map`` with the replication check
spelled ``check_rep=``. Import ``shard_map`` from here instead of from
``jax`` so every call site keeps the one modern spelling and the
translation lives in exactly one place.
"""

from __future__ import annotations

try:  # jax >= 0.6: top-level export, check_vma kwarg
    from jax import shard_map as _shard_map_impl

    _VMA_KWARG = "check_vma"
except ImportError:  # older jax: experimental module, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map_impl

    _VMA_KWARG = "check_rep"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True, **kwargs):
    """``jax.shard_map`` with the modern keyword signature on any jax."""
    kwargs[_VMA_KWARG] = check_vma
    return _shard_map_impl(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
    )
