"""Pytree linear algebra.

The reference's CG/LBFGS/HF solvers operate on one packed parameter vector
(``MultiLayerNetwork.params()``/``pack()``, reference:
nn/multilayer/MultiLayerNetwork.java:762,808).  On TPU, packing would
force large concat copies through HBM; instead the solvers do their
vector algebra directly on parameter pytrees — XLA fuses the per-leaf
elementwise work, and dot products reduce per-leaf then sum scalars.
``ravel``/``unravel`` remain available for wire formats and checkpoints.
"""

from __future__ import annotations

import jax
import jax.flatten_util
import jax.numpy as jnp

Tree = object  # any pytree of arrays


def vdot(a: Tree, b: Tree) -> jax.Array:
    leaves_a = jax.tree.leaves(a)
    leaves_b = jax.tree.leaves(b)
    return sum(
        (jnp.vdot(x, y) for x, y in zip(leaves_a, leaves_b)),
        start=jnp.asarray(0.0),
    )


def add(a: Tree, b: Tree) -> Tree:
    return jax.tree.map(jnp.add, a, b)


def sub(a: Tree, b: Tree) -> Tree:
    return jax.tree.map(jnp.subtract, a, b)


def scale(a: Tree, s) -> Tree:
    return jax.tree.map(lambda x: x * s, a)


def axpy(alpha, x: Tree, y: Tree) -> Tree:
    """alpha*x + y."""
    return jax.tree.map(lambda xi, yi: alpha * xi + yi, x, y)


def neg(a: Tree) -> Tree:
    return jax.tree.map(jnp.negative, a)


def norm2(a: Tree) -> jax.Array:
    return jnp.sqrt(vdot(a, a))


def zeros_like(a: Tree) -> Tree:
    return jax.tree.map(jnp.zeros_like, a)


def ones_like(a: Tree) -> Tree:
    return jax.tree.map(jnp.ones_like, a)


def where(pred, a: Tree, b: Tree) -> Tree:
    return jax.tree.map(lambda x, y: jnp.where(pred, x, y), a, b)


def ravel(a: Tree) -> tuple[jax.Array, callable]:
    """Pack to one vector (wire/checkpoint format; ≙ MultiLayerNetwork.pack)."""
    return jax.flatten_util.ravel_pytree(a)


def cast(a: Tree, dtype) -> Tree:
    return jax.tree.map(lambda x: x.astype(dtype), a)
