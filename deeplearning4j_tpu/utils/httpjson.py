"""Shared plumbing for the stdlib HTTP servers (cluster REST, network
registry, t-SNE render): one place for response framing and request-log
silencing, so charset/Content-Length/error-shape fixes don't have to be
repeated per server."""

from __future__ import annotations

import json
from http.server import BaseHTTPRequestHandler


def send_body(handler: BaseHTTPRequestHandler, code: int, body: bytes,
              content_type: str) -> None:
    handler.send_response(code)
    handler.send_header("Content-Type", content_type)
    handler.send_header("Content-Length", str(len(body)))
    handler.end_headers()
    handler.wfile.write(body)


def send_json(handler: BaseHTTPRequestHandler, code: int,
              payload=None) -> None:
    send_body(
        handler, code,
        json.dumps(payload if payload is not None else {}).encode(),
        "application/json",
    )


def read_json_body(handler: BaseHTTPRequestHandler):
    """Parse the request body as JSON; returns None on malformed input
    (callers answer 400)."""
    n = int(handler.headers.get("Content-Length", 0))
    try:
        return json.loads(handler.rfile.read(n) or b"{}")
    except json.JSONDecodeError:
        return None


class QuietHandler(BaseHTTPRequestHandler):
    """BaseHTTPRequestHandler with request logging silenced."""

    def log_message(self, *a):  # noqa: D102
        pass
