"""String-dedup utilities: fingerprint keying + grid clustering.

≙ reference util/FingerPrintKeyer.java + StringGrid.java (~1100 LoC of
OpenRefine-style text dedup used for corpus cleaning).
"""

from __future__ import annotations

import string
import unicodedata
from collections import defaultdict


def fingerprint(s: str) -> str:
    """Normalized key: strip accents/punct, lowercase, unique sorted tokens
    (≙ FingerPrintKeyer.key)."""
    s = unicodedata.normalize("NFD", s)
    s = "".join(c for c in s if unicodedata.category(c) != "Mn")
    s = s.translate(str.maketrans("", "", string.punctuation)).lower()
    return " ".join(sorted(set(s.split())))


def ngram_fingerprint(s: str, n: int = 2) -> str:
    base = fingerprint(s).replace(" ", "")
    grams = sorted({base[i : i + n] for i in range(max(len(base) - n + 1, 1))})
    return "".join(grams)


class StringGrid:
    """Rows of string records with fingerprint-cluster dedup
    (≙ StringGrid's cluster-by-fingerprint columns)."""

    def __init__(self, rows: list[list[str]], sep: str = ","):
        self.rows = [list(r) for r in rows]
        self.sep = sep

    @classmethod
    def from_lines(cls, lines: list[str], sep: str = ",") -> "StringGrid":
        return cls([line.split(sep) for line in lines], sep)

    def get_column(self, i: int) -> list[str]:
        return [r[i] for r in self.rows]

    def clusters_by_fingerprint(self, column: int, keyer=fingerprint) -> dict[str, list[int]]:
        out: dict[str, list[int]] = defaultdict(list)
        for idx, row in enumerate(self.rows):
            out[keyer(row[column])].append(idx)
        return dict(out)

    def dedup_column(self, column: int, keyer=fingerprint) -> "StringGrid":
        """Keep the first row of each fingerprint cluster."""
        seen = set()
        kept = []
        for row in self.rows:
            k = keyer(row[column])
            if k not in seen:
                seen.add(k)
                kept.append(row)
        return StringGrid(kept, self.sep)
