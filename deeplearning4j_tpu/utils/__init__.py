"""Utility modules (tree algebra, math, serialization, sequence decoding)."""
