"""Pluggable model/data storage backends.

≙ reference ops IO: ``ModelSaver`` impls (DefaultModelSaver file, HDFS
HdfsModelSaver.java:19, S3 S3ModelSaver) plus the S3/HDFS dataset
iterators (BaseS3DataSetIterator, BaseHdfsDataSetIterator) and AWS
provisioning glue (deeplearning4j-aws, SURVEY §2).

Cloud SDKs are *gated*: the interface always exists, object-store
backends activate only when their client library is importable (this
build environment has zero egress).  EC2-style provisioning is replaced
by a TPU-VM provisioning *command renderer* — cloud CLIs do the work, so
the framework emits the commands rather than shelling out.
"""

from __future__ import annotations

from pathlib import Path
from typing import Protocol


class ModelSaver(Protocol):
    def save(self, blob: bytes, name: str) -> str: ...
    def load(self, name: str) -> bytes: ...


class LocalModelSaver:
    """≙ DefaultModelSaver.java:19."""

    def __init__(self, directory: str | Path):
        self.dir = Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)

    def save(self, blob: bytes, name: str) -> str:
        p = self.dir / name
        tmp = p.with_suffix(p.suffix + ".tmp")
        tmp.write_bytes(blob)
        tmp.replace(p)
        return str(p)

    def load(self, name: str) -> bytes:
        return (self.dir / name).read_bytes()


class S3ModelSaver:
    """≙ S3ModelSaver (deeplearning4j-aws). Requires boto3 — or an
    injected ``client`` implementing put_object/get_object (boto3's S3
    surface), which also makes the saver logic exercisable offline."""

    def __init__(self, bucket: str, prefix: str = "", client=None):
        if client is None:
            try:
                import boto3
            except ImportError as e:
                raise RuntimeError("S3ModelSaver requires boto3") from e
            client = boto3.client("s3")
        self.client = client
        self.bucket = bucket
        self.prefix = prefix.rstrip("/")

    def _key(self, name: str) -> str:
        return f"{self.prefix}/{name}" if self.prefix else name

    def save(self, blob: bytes, name: str) -> str:
        self.client.put_object(Bucket=self.bucket, Key=self._key(name), Body=blob)
        return f"s3://{self.bucket}/{self._key(name)}"

    def load(self, name: str) -> bytes:
        return self.client.get_object(Bucket=self.bucket, Key=self._key(name))[
            "Body"
        ].read()


class GCSModelSaver:
    """GCS twin of S3ModelSaver (the TPU-native object store). Requires
    google-cloud-storage."""

    def __init__(self, bucket: str, prefix: str = "", bucket_client=None):
        if bucket_client is None:
            try:
                from google.cloud import storage
            except ImportError as e:
                raise RuntimeError(
                    "GCSModelSaver requires google-cloud-storage"
                ) from e
            bucket_client = storage.Client().bucket(bucket)
        self.bucket = bucket_client
        self.prefix = prefix.rstrip("/")

    def _key(self, name: str) -> str:
        return f"{self.prefix}/{name}" if self.prefix else name

    def save(self, blob: bytes, name: str) -> str:
        self.bucket.blob(self._key(name)).upload_from_string(blob)
        return f"gs://{self.bucket.name}/{self._key(name)}"

    def load(self, name: str) -> bytes:
        return self.bucket.blob(self._key(name)).download_as_bytes()


def get_saver(uri: str) -> ModelSaver:
    """Scheme-dispatch: s3://bucket/prefix, gs://bucket/prefix, or a path."""
    if uri.startswith("s3://"):
        bucket, _, prefix = uri[5:].partition("/")
        return S3ModelSaver(bucket, prefix)
    if uri.startswith("gs://"):
        bucket, _, prefix = uri[5:].partition("/")
        return GCSModelSaver(bucket, prefix)
    return LocalModelSaver(uri)


def render_tpu_vm_provision(
    name: str,
    accelerator_type: str = "v5litepod-8",
    zone: str = "us-central1-a",
    version: str = "tpu-ubuntu2204-base",
    startup_script: str | None = None,
) -> list[str]:
    """TPU-VM provisioning commands (≙ Ec2BoxCreator/ClusterSetup.java:24
    spinning up EC2 workers — here rendered as gcloud invocations for the
    operator or an orchestrator to run)."""
    cmd = [
        "gcloud", "compute", "tpus", "tpu-vm", "create", name,
        f"--zone={zone}", f"--accelerator-type={accelerator_type}",
        f"--version={version}",
    ]
    if startup_script:
        cmd.append(f"--metadata=startup-script={startup_script}")
    return cmd
