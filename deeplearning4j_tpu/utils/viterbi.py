"""Viterbi sequence decoding.

≙ reference util/Viterbi.java:176 (pure-Java decoder; the vendored
CRFSuite binaries were dead resources — SURVEY §2).  Implemented as a
jittable ``lax.scan`` over log-domain transition/emission scores.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def viterbi_decode(log_emissions: jax.Array, log_transitions: jax.Array, log_start: jax.Array):
    """Most likely state path.

    log_emissions: (T, S); log_transitions: (S, S) [from, to]; log_start: (S,)
    Returns (path (T,), score).
    """
    t0 = log_start + log_emissions[0]

    def step(delta, emit):
        scores = delta[:, None] + log_transitions  # (S_from, S_to)
        best_prev = jnp.argmax(scores, axis=0)
        delta_next = jnp.max(scores, axis=0) + emit
        return delta_next, best_prev

    delta, backptrs = jax.lax.scan(step, t0, log_emissions[1:])
    last = jnp.argmax(delta)
    score = delta[last]

    def backtrack(state, ptrs):
        prev = ptrs[state]
        return prev, state

    first, rest = jax.lax.scan(backtrack, last, backptrs, reverse=True)
    path = jnp.concatenate([jnp.array([first]), rest])
    return path, score


class Viterbi:
    """Stateful wrapper with probability-space inputs (≙ util/Viterbi.java)."""

    def __init__(self, transitions: np.ndarray, start: np.ndarray | None = None):
        self.log_transitions = jnp.log(jnp.asarray(transitions) + 1e-12)
        s = transitions.shape[0]
        start = start if start is not None else np.full(s, 1.0 / s)
        self.log_start = jnp.log(jnp.asarray(start) + 1e-12)

    def decode(self, emissions: np.ndarray) -> tuple[np.ndarray, float]:
        path, score = viterbi_decode(
            jnp.log(jnp.asarray(emissions) + 1e-12),
            self.log_transitions,
            self.log_start,
        )
        return np.asarray(path), float(score)
