"""Visualization: t-SNE (exact, jitted) + Barnes-Hut t-SNE, weight/activation
plotting, render endpoint."""

from deeplearning4j_tpu.plot.tsne import Tsne  # noqa: F401
