"""t-SNE.

≙ reference plot/Tsne.java:261 (gains + momentum gradient loop, exact
pairwise affinities) and plot/BarnesHutTsne.java:333 (quadtree
approximation for large N).

TPU re-design: the exact O(N^2) variant is the accelerator fast path —
the pairwise-distance and affinity computations are dense matmuls that
map straight onto the MXU, and the whole gradient loop (gains, momentum,
re-centering) runs as one ``lax.fori_loop`` inside jit.  P-matrix
construction (perplexity binary search) happens once, host-side.
The Barnes-Hut variant (host, quadtree) is in
:mod:`deeplearning4j_tpu.plot.barnes_hut`.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def _hbeta(d_row: np.ndarray, beta: float) -> tuple[float, np.ndarray]:
    p = np.exp(-d_row * beta)
    s = p.sum() + 1e-12
    h = np.log(s) + beta * (d_row * p).sum() / s
    return h, p / s


def p_affinities(x: np.ndarray, perplexity: float = 30.0, tol: float = 1e-5) -> np.ndarray:
    """Conditional -> joint affinities with per-point beta binary search
    (≙ Tsne's x2p)."""
    n = x.shape[0]
    d2 = np.square(x[:, None, :] - x[None, :, :]).sum(-1)
    p = np.zeros((n, n))
    log_u = np.log(perplexity)
    for i in range(n):
        idx = np.arange(n) != i
        beta, beta_min, beta_max = 1.0, -np.inf, np.inf
        for _ in range(50):
            h, row = _hbeta(d2[i, idx], beta)
            if abs(h - log_u) < tol:
                break
            if h > log_u:
                beta_min = beta
                beta = beta * 2 if beta_max == np.inf else (beta + beta_max) / 2
            else:
                beta_max = beta
                beta = beta / 2 if beta_min == -np.inf else (beta + beta_min) / 2
        p[i, idx] = row
    p = (p + p.T) / (2 * n)
    return np.maximum(p, 1e-12)


@functools.partial(jax.jit, static_argnames=("n_iter", "stop_lying_iter"))
def _tsne_loop(p, y0, lr, momentum_0, momentum_f, n_iter, stop_lying_iter):
    n = y0.shape[0]
    p_lied = p * 4.0  # early exaggeration (≙ Tsne's lie factor)

    def body(i, carry):
        y, y_inc, gains = carry
        pm = jnp.where(i < stop_lying_iter, p_lied, p)
        d2 = jnp.sum((y[:, None, :] - y[None, :, :]) ** 2, axis=-1)
        num = 1.0 / (1.0 + d2)
        num = num * (1.0 - jnp.eye(n))
        q = jnp.maximum(num / jnp.sum(num), 1e-12)
        pq = (pm - q) * num  # (N, N)
        grad = 4.0 * (jnp.diag(pq.sum(1)) - pq) @ y
        momentum = jnp.where(i < 20, momentum_0, momentum_f)
        same_sign = jnp.sign(grad) == jnp.sign(y_inc)
        gains = jnp.maximum(
            jnp.where(same_sign, gains * 0.8, gains + 0.2), 0.01
        )
        y_inc = momentum * y_inc - lr * gains * grad
        y = y + y_inc
        y = y - jnp.mean(y, axis=0, keepdims=True)
        return (y, y_inc, gains)

    y, _, _ = jax.lax.fori_loop(
        0, n_iter, body, (y0, jnp.zeros_like(y0), jnp.ones_like(y0))
    )
    return y


class Tsne:
    """≙ Tsne.Builder: perplexity, learningRate, maxIter, momentum switch."""

    def __init__(
        self,
        n_components: int = 2,
        perplexity: float = 30.0,
        learning_rate: float = 200.0,
        n_iter: int = 500,
        initial_momentum: float = 0.5,
        final_momentum: float = 0.8,
        stop_lying_iter: int = 100,
        seed: int = 0,
        use_pca: bool = False,
        pca_dims: int = 50,
    ):
        self.use_pca = use_pca
        self.pca_dims = pca_dims
        self.n_components = n_components
        self.perplexity = perplexity
        self.learning_rate = learning_rate
        self.n_iter = n_iter
        self.initial_momentum = initial_momentum
        self.final_momentum = final_momentum
        self.stop_lying_iter = stop_lying_iter
        self.seed = seed

    def calculate(self, x: np.ndarray) -> np.ndarray:
        """(N, D) -> (N, n_components) embedding (≙ Tsne.calculate:261)."""
        x = np.asarray(x, dtype=np.float32)
        if self.use_pca:  # ≙ Tsne.java:262-263: PCA.pca(X, min(50, D), norm)
            from deeplearning4j_tpu.ops.pca import pca

            x = pca(x, min(self.pca_dims, x.shape[1]), normalize=True)
        p = jnp.asarray(p_affinities(x, self.perplexity), jnp.float32)
        key = jax.random.key(self.seed)
        y0 = 1e-4 * jax.random.normal(key, (x.shape[0], self.n_components))
        y = _tsne_loop(
            p, y0, self.learning_rate, self.initial_momentum,
            self.final_momentum, self.n_iter, self.stop_lying_iter,
        )
        return np.asarray(y)

    fit_transform = calculate
