"""Weight/activation plotting + filter rendering.

≙ reference plot/NeuralNetPlotter.java:34 (which shells out to bundled
matplotlib scripts — resources/scripts/plot.py) and FilterRenderer.java.
Python is idiomatic here already, so matplotlib is called directly
(headless Agg backend).  ≙ NeuralNetPlotterIterationListener hooks this
into the optimizer loop.
"""

from __future__ import annotations

import math
from pathlib import Path

import numpy as np

from deeplearning4j_tpu.optimize.api import IterationListener


def _plt():
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    return plt


class NeuralNetPlotter:
    def __init__(self, out_dir: str | Path = "plots"):
        self.out_dir = Path(out_dir)
        self.out_dir.mkdir(parents=True, exist_ok=True)

    def plot_weight_histograms(self, params: dict, name: str = "weights") -> Path:
        """Histogram grid of every param tensor (≙ plotWeights/plot.py)."""
        plt = _plt()
        items = list(params.items())
        cols = min(len(items), 3)
        rows_n = math.ceil(len(items) / cols)
        fig, axes = plt.subplots(rows_n, cols, figsize=(4 * cols, 3 * rows_n), squeeze=False)
        for ax in axes.flat:
            ax.axis("off")
        for ax, (key, w) in zip(axes.flat, items):
            ax.axis("on")
            ax.hist(np.asarray(w).ravel(), bins=50)
            ax.set_title(key)
        out = self.out_dir / f"{name}.png"
        fig.tight_layout()
        fig.savefig(out)
        plt.close(fig)
        return out

    def render_filters(
        self, w: np.ndarray, name: str = "filters", patch_shape: tuple[int, int] | None = None
    ) -> Path:
        """Grid image of learned filters (≙ FilterRenderer.java:541).

        w: (n_in, n_out) dense weights (columns become patches) or
        (kh, kw, c_in, c_out) conv kernels.
        """
        plt = _plt()
        w = np.asarray(w)
        if w.ndim == 4:
            patches = [w[:, :, 0, i] for i in range(w.shape[-1])]
        else:
            side = patch_shape or (
                int(math.isqrt(w.shape[0])), int(math.isqrt(w.shape[0]))
            )
            patches = [w[: side[0] * side[1], i].reshape(side) for i in range(w.shape[1])]
        n = len(patches)
        cols = math.ceil(math.sqrt(n))
        rows_n = math.ceil(n / cols)
        fig, axes = plt.subplots(rows_n, cols, figsize=(cols, rows_n), squeeze=False)
        for ax in axes.flat:
            ax.axis("off")
        for ax, p in zip(axes.flat, patches):
            ax.imshow(p, cmap="gray")
        out = self.out_dir / f"{name}.png"
        fig.tight_layout()
        fig.savefig(out, dpi=120)
        plt.close(fig)
        return out

    def plot_activations(self, activations: np.ndarray, name: str = "activations") -> Path:
        plt = _plt()
        fig, ax = plt.subplots(figsize=(6, 4))
        ax.imshow(np.asarray(activations), aspect="auto", cmap="viridis")
        ax.set_xlabel("unit")
        ax.set_ylabel("example")
        out = self.out_dir / f"{name}.png"
        fig.savefig(out)
        plt.close(fig)
        return out


class PlotterIterationListener(IterationListener):
    """≙ NeuralNetPlotterIterationListener.java:70 — render every N iters."""

    def __init__(self, get_params, out_dir="plots", every: int = 50):
        self.get_params = get_params
        self.plotter = NeuralNetPlotter(out_dir)
        self.every = every

    def iteration_done(self, info: dict) -> None:
        i = info["iteration"]
        if i % self.every == 0:
            self.plotter.plot_weight_histograms(
                self.get_params(), name=f"weights_iter{i}"
            )


def serve_tsne(words: list[str], coords: np.ndarray, port: int = 0) -> int:
    """Tiny render endpoint serving t-SNE coords as JSON
    (≙ plot/dropwizard RenderApplication.java:53 + ApiResource.java:65)."""
    import json
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    payload = json.dumps(
        [
            {"word": w, "x": float(x), "y": float(y)}
            for w, (x, y) in zip(words, np.asarray(coords))
        ]
    ).encode()

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.end_headers()
            self.wfile.write(payload)

        def log_message(self, *a):
            pass

    server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server.server_address[1]
