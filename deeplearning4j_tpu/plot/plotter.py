"""Weight/activation plotting + filter rendering.

≙ reference plot/NeuralNetPlotter.java:34 (which shells out to bundled
matplotlib scripts — resources/scripts/plot.py) and FilterRenderer.java.
Python is idiomatic here already, so matplotlib is called directly
(headless Agg backend).  ≙ NeuralNetPlotterIterationListener hooks this
into the optimizer loop.
"""

from __future__ import annotations

import math
from pathlib import Path

import numpy as np

from deeplearning4j_tpu.optimize.api import IterationListener


def _plt():
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    return plt


class NeuralNetPlotter:
    def __init__(self, out_dir: str | Path = "plots"):
        self.out_dir = Path(out_dir)
        self.out_dir.mkdir(parents=True, exist_ok=True)

    def plot_weight_histograms(self, params: dict, name: str = "weights") -> Path:
        """Histogram grid of every param tensor (≙ plotWeights/plot.py)."""
        plt = _plt()
        items = list(params.items())
        cols = min(len(items), 3)
        rows_n = math.ceil(len(items) / cols)
        fig, axes = plt.subplots(rows_n, cols, figsize=(4 * cols, 3 * rows_n), squeeze=False)
        for ax in axes.flat:
            ax.axis("off")
        for ax, (key, w) in zip(axes.flat, items):
            ax.axis("on")
            ax.hist(np.asarray(w).ravel(), bins=50)
            ax.set_title(key)
        out = self.out_dir / f"{name}.png"
        fig.tight_layout()
        fig.savefig(out)
        plt.close(fig)
        return out

    def render_filters(
        self, w: np.ndarray, name: str = "filters", patch_shape: tuple[int, int] | None = None
    ) -> Path:
        """Grid image of learned filters (≙ FilterRenderer.java:541).

        w: (n_in, n_out) dense weights (columns become patches) or
        (kh, kw, c_in, c_out) conv kernels.
        """
        plt = _plt()
        w = np.asarray(w)
        if w.ndim == 4:
            patches = [w[:, :, 0, i] for i in range(w.shape[-1])]
        else:
            side = patch_shape or (
                int(math.isqrt(w.shape[0])), int(math.isqrt(w.shape[0]))
            )
            patches = [w[: side[0] * side[1], i].reshape(side) for i in range(w.shape[1])]
        n = len(patches)
        cols = math.ceil(math.sqrt(n))
        rows_n = math.ceil(n / cols)
        fig, axes = plt.subplots(rows_n, cols, figsize=(cols, rows_n), squeeze=False)
        for ax in axes.flat:
            ax.axis("off")
        for ax, p in zip(axes.flat, patches):
            ax.imshow(p, cmap="gray")
        out = self.out_dir / f"{name}.png"
        fig.tight_layout()
        fig.savefig(out, dpi=120)
        plt.close(fig)
        return out

    def plot_activations(self, activations: np.ndarray, name: str = "activations") -> Path:
        plt = _plt()
        fig, ax = plt.subplots(figsize=(6, 4))
        ax.imshow(np.asarray(activations), aspect="auto", cmap="viridis")
        ax.set_xlabel("unit")
        ax.set_ylabel("example")
        out = self.out_dir / f"{name}.png"
        fig.savefig(out)
        plt.close(fig)
        return out


class PlotterIterationListener(IterationListener):
    """≙ NeuralNetPlotterIterationListener.java:70 — render every N iters."""

    def __init__(self, get_params, out_dir="plots", every: int = 50):
        self.get_params = get_params
        self.plotter = NeuralNetPlotter(out_dir)
        self.every = every

    def iteration_done(self, info: dict) -> None:
        i = info["iteration"]
        if i % self.every == 0:
            self.plotter.plot_weight_histograms(
                self.get_params(), name=f"weights_iter{i}"
            )


# self-contained scatter page: pan/zoom canvas, hover labels, no external
# assets (the reference ships a jquery+highcharts bundle under
# deeplearning4j-nlp/src/main/resources/assets; offline here, so inline JS)
_TSNE_PAGE = """<!doctype html>
<html><head><meta charset="utf-8"><title>t-SNE — deeplearning4j_tpu</title>
<style>
 body{margin:0;font:13px system-ui,sans-serif;background:#111;color:#ddd}
 #hud{position:fixed;top:8px;left:12px;pointer-events:none}
 canvas{display:block;cursor:grab}
</style></head><body>
<div id="hud">t-SNE render — drag to pan, wheel to zoom, hover for words</div>
<canvas id="c"></canvas>
<script>
const cv=document.getElementById('c'),cx=cv.getContext('2d');
let pts=[],tx=0,ty=0,scale=1,drag=null,hover=-1;
function resize(){cv.width=innerWidth;cv.height=innerHeight;draw()}
function sx(p){return (p.x-mid.x)*base*scale+cv.width/2+tx}
function sy(p){return (p.y-mid.y)*base*scale+cv.height/2+ty}
let mid={x:0,y:0},base=1;
function fit(){
 if(!pts.length)return;
 const xs=pts.map(p=>p.x),ys=pts.map(p=>p.y);
 const w=Math.max(...xs)-Math.min(...xs)||1,h=Math.max(...ys)-Math.min(...ys)||1;
 mid={x:(Math.max(...xs)+Math.min(...xs))/2,y:(Math.max(...ys)+Math.min(...ys))/2};
 base=0.9*Math.min(cv.width/w,cv.height/h);
}
function draw(){
 cx.clearRect(0,0,cv.width,cv.height);
 pts.forEach((p,i)=>{
  cx.fillStyle=i===hover?'#ff5':'#6cf';
  cx.beginPath();cx.arc(sx(p),sy(p),i===hover?5:2.5,0,7);cx.fill();
  if(scale>2.5||i===hover){cx.fillStyle=i===hover?'#ff5':'#9ab';
   cx.fillText(p.word,sx(p)+6,sy(p)+4);}
 });
}
cv.onmousedown=e=>{drag={x:e.clientX-tx,y:e.clientY-ty};cv.style.cursor='grabbing'};
onmouseup=()=>{drag=null;cv.style.cursor='grab'};
onmousemove=e=>{
 if(drag){tx=e.clientX-drag.x;ty=e.clientY-drag.y;draw();return}
 let best=-1,bd=144;
 pts.forEach((p,i)=>{const d=(sx(p)-e.clientX)**2+(sy(p)-e.clientY)**2;
  if(d<bd){bd=d;best=i}});
 if(best!==hover){hover=best;draw()}
};
onwheel=e=>{scale*=e.deltaY<0?1.15:1/1.15;draw()};
onresize=resize;
fetch('/coords').then(r=>r.json()).then(d=>{pts=d;resize();fit();draw()});
resize();
</script></body></html>"""


def serve_tsne(words: list[str], coords: np.ndarray, port: int = 0) -> int:
    """Browsable t-SNE render server.

    ≙ the reference's dropwizard render webapp (plot/dropwizard/
    RenderApplication.java:53 serving ApiResource coords + a JS scatter
    under nlp resources/assets): ``GET /`` returns a self-contained
    HTML/canvas scatter page (pan/zoom/hover), ``GET /coords`` the
    [{word, x, y}] JSON the page fetches."""
    import json
    import threading
    from http.server import ThreadingHTTPServer

    from deeplearning4j_tpu.utils.httpjson import QuietHandler, send_body

    payload = json.dumps(
        [
            {"word": w, "x": float(x), "y": float(y)}
            for w, (x, y) in zip(words, np.asarray(coords))
        ]
    ).encode()
    page = _TSNE_PAGE.encode()

    class Handler(QuietHandler):
        def do_GET(self):  # noqa: N802
            if self.path in ("/", "/index.html"):
                send_body(self, 200, page, "text/html; charset=utf-8")
            elif self.path in ("/coords", "/api/coords"):
                # /api/coords matches the reference's dropwizard
                # ApiResource path; /coords is what the bundled page uses
                send_body(self, 200, payload, "application/json")
            else:
                # unknown paths (favicon.ico, typos) must not ship the
                # whole coords payload
                send_body(self, 404, b"{}", "application/json")

    server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server.server_address[1]
