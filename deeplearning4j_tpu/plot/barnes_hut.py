"""Barnes-Hut t-SNE (host-side, quadtree-approximated).

≙ reference plot/BarnesHutTsne.java:42-333: attractive forces over a
sparse kNN affinity graph, repulsive forces via quadtree pole expansion.
The exact jitted t-SNE (:mod:`deeplearning4j_tpu.plot.tsne`) is the
accelerator fast path; this variant trades exactness for O(N log N) on
large N where the dense N^2 no longer fits.
"""

from __future__ import annotations

import numpy as np

from deeplearning4j_tpu.clustering.quadtree import QuadTree
from deeplearning4j_tpu.clustering.vptree import VPTree
from deeplearning4j_tpu.plot.tsne import _hbeta


def knn_affinities(x: np.ndarray, perplexity: float) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Sparse symmetric P over 3*perplexity nearest neighbours
    (≙ BarnesHutTsne's VPTree-based input similarity)."""
    n = x.shape[0]
    k = min(int(3 * perplexity), n - 1)
    tree = VPTree(x)
    rows, cols, vals = [], [], []
    log_u = np.log(perplexity)
    for i in range(n):
        nbrs = tree.nearest(x[i], k + 1)
        nbrs = [(d, j) for d, j in nbrs if j != i][:k]
        d2 = np.array([d * d for d, _ in nbrs])
        beta, lo, hi = 1.0, -np.inf, np.inf
        for _ in range(50):
            h, row = _hbeta(d2, beta)
            if abs(h - log_u) < 1e-5:
                break
            if h > log_u:
                lo, beta = beta, beta * 2 if hi == np.inf else (beta + hi) / 2
            else:
                hi, beta = beta, beta / 2 if lo == -np.inf else (beta + lo) / 2
        for (d, j), p in zip(nbrs, row):
            rows.append(i)
            cols.append(j)
            vals.append(p)
    # symmetrize
    p = {}
    for r, c, v in zip(rows, cols, vals):
        p[(r, c)] = p.get((r, c), 0.0) + v / (2 * n)
        p[(c, r)] = p.get((c, r), 0.0) + v / (2 * n)
    out_r = np.array([k[0] for k in p], dtype=np.int64)
    out_c = np.array([k[1] for k in p], dtype=np.int64)
    out_v = np.array(list(p.values()))
    return out_r, out_c, np.maximum(out_v, 1e-12)


class BarnesHutTsne:
    def __init__(
        self,
        n_components: int = 2,
        perplexity: float = 30.0,
        theta: float = 0.5,
        learning_rate: float = 200.0,
        n_iter: int = 300,
        seed: int = 0,
    ):
        assert n_components == 2, "quadtree variant is 2-D"
        self.perplexity = perplexity
        self.theta = theta
        self.learning_rate = learning_rate
        self.n_iter = n_iter
        self.seed = seed

    def fit_transform(self, x: np.ndarray) -> np.ndarray:
        x = np.asarray(x, dtype=np.float64)
        n = x.shape[0]
        rows, cols, vals = knn_affinities(x, self.perplexity)
        rng = np.random.default_rng(self.seed)
        y = 1e-4 * rng.normal(size=(n, 2))
        y_inc = np.zeros_like(y)
        gains = np.ones_like(y)
        for it in range(self.n_iter):
            lie = 12.0 if it < 100 else 1.0
            tree = QuadTree.build(y)
            # repulsive via quadtree
            neg = np.zeros_like(y)
            sum_q = 0.0
            for i in range(n):
                f = np.zeros(2)
                sum_q += tree.compute_non_edge_forces(y[i], self.theta, f)
                neg[i] = f
            # attractive over sparse edges
            diff = y[rows] - y[cols]
            q = 1.0 / (1.0 + (diff**2).sum(1))
            coeff = (lie * vals) * q
            pos = np.zeros_like(y)
            np.add.at(pos, rows, coeff[:, None] * diff)
            grad = pos - neg / max(sum_q, 1e-12)
            momentum = 0.5 if it < 20 else 0.8
            same = np.sign(grad) == np.sign(y_inc)
            gains = np.maximum(np.where(same, gains * 0.8, gains + 0.2), 0.01)
            y_inc = momentum * y_inc - self.learning_rate * gains * grad
            y = y + y_inc
            y -= y.mean(0, keepdims=True)
        return y
