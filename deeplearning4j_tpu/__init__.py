"""deeplearning4j_tpu — a TPU-native deep-learning framework.

A ground-up JAX/XLA/pallas re-design of the capabilities of early
DeepLearning4j (reference: everpeace/deeplearning4j): configurable
multi-layer networks (dense, RBM/DBN with CD-k pretraining, denoising
autoencoders, conv+pool, LSTM), a full convex-optimizer family
(SGD / conjugate gradient / L-BFGS / stochastic Hessian-free with
backtracking line search), data pipelines, evaluation, an NLP stack
(Word2Vec / GloVe / ParagraphVectors / RNTN), t-SNE + clustering, and —
in place of the reference's Akka/Hazelcast/Spark/YARN parameter-averaging
runtimes — idiomatic SPMD data parallelism over a `jax.sharding.Mesh`
with XLA collectives.

Design principles (vs the Java reference):
- Mutable ``Model``/``Layer`` object graphs become pure functions over
  pytree parameter dicts; ``Layer.paramTable()`` maps onto named-array
  pytrees and ``Gradient``'s keyed table is simply the cotangent pytree.
- Everything on the compute path is jit-compatible: static shapes,
  ``lax.scan``/``lax.while_loop`` control flow, threaded PRNG keys.
- Distribution is in-graph: the reference's parameter-averaging master/
  worker machinery collapses into a pjit'd train step with ``psum`` over
  ICI; local-SGD-with-averaging is kept as a faithful compatibility mode.
"""

__version__ = "0.1.0"

from deeplearning4j_tpu import dtypes  # noqa: F401
