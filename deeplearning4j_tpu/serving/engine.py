"""Continuous-batching decode engine — pipelined, multi-step hot path.

Iteration-level scheduling (Orca, OSDI '22) composed with multi-step
scheduling (vLLM): instead of batching whole requests, the engine
batches DECODE STEPS — and instead of paying one dispatch + one host
sync per step, it fuses ``decode_horizon`` (K) steps into ONE jitted
program and overlaps the host side of horizon n with the device side
of horizon n+1. It owns a fixed-shape batch of ``n_slots`` KV-cache
slots (one pooled ``init_caches`` allocation, see :mod:`cache_pool`);
every ``step()``:

1. sweeps occupied slots for cancelled/deadline-expired requests and
   retires them (slot freed within one horizon boundary);
2. admits queued requests into freed slots: a per-BUCKET jitted
   prefill runs at batch 1 (the prompt right-padded to a power-of-two
   length bucket) and its cache rows are inserted into the pooled
   buffers at the slot index; prompts longer than the largest bucket
   are chunked through ``forward_chunk`` at the same bucket sizes, so
   ``_prefill_fns`` holds O(log max_len) programs no matter how many
   distinct prompt lengths traffic brings;
3. DISPATCHES one fused K-substep decode program for all slots and
   only then
4. SYNCS the PREVIOUS horizon's (slots, K) token block, doing finish
   detection / retirement / metrics while the device is already
   computing the next horizon (async double-buffered readback — the
   ``np.asarray`` sync is the one blocking host sync per horizon).

Everything the per-substep decode logic needs lives ON DEVICE and is
threaded through the programs — positions, active mask, remaining
token budget, per-slot EOS id, pending logits — so EOS/max-len
deactivation happens in-program via the active mask: a slot that
finishes mid-horizon stops advancing (its position freezes, its
sampled tokens are masked to 0) without any host round trip. The host
replays the same stopping rule when the block arrives, so host
bookkeeping and the device mask can never disagree. Host <-> device
state only meets at admission (prefill writes the slot's state) and at
crash recovery (state is rebuilt from host records).

Slot-reuse slack: because horizon n's block is synced AFTER horizon
n+1 is dispatched, a slot retired at sync time may already appear in
the in-flight horizon. Each dispatch snapshots (slot, occupant,
pool generation); a sync discards blocks whose slot has since been
retired or re-acquired (the dummy tokens a finished slot decodes are
dead by construction — the next admission's prefill insert rewrites
the whole Tpad slab).

jit stability: exactly one compiled step program per engine, one
prefill program per power-of-two bucket, one chunk program per bucket
on the long-prompt path, plus two tiny state-edit programs.

Greedy determinism: at ``temperature=0`` the engine samples via the
same ``_top_k_filter`` + argmax the plain ``transformer_generate``
path uses; the decode math is row-/padding-invariant (masked cache
rows contribute exact zeros), and a right-padded bucket prefill is
bitwise identical to an exact-length prefill at the true last row
(causal masking — pinned empirically by the parity tests), so token
streams are byte-identical to running each request alone for every
horizon K — ``tests/test_serving.py`` asserts K in {1, 2, 4, 8}.

Sampled determinism: at ``temperature > 0`` each slot gets its own
sampling key at admission (split from the engine master key in
admission order) and token ``i`` is drawn with ``fold_in(slot_key,
position_i)`` — the key stream is a pure function of (slot key,
position), independent of batch composition, horizon K, and crashes.
Persisting the key data per slot makes crash-recovery replay exact for
sampled requests too: replay teacher-forces the recorded tokens, then
sampling resumes at the next position with the next key the
uninterrupted run would have used (``tests/test_serving_faults.py``
pins byte-parity for a sampled run crashed mid-decode).

Fault tolerance (the DL4J lineage: the reference runtime supervised
its workers via Akka and rebuilt them from ZooKeeper state; here the
unit of supervision is the horizon dispatch and the durable state is
host-side). The engine consults an optional
:class:`~.faults.FaultInjector` at its two host boundaries — "step"
before each horizon dispatch, "prefill" before each admission — and
supervises itself:

- a ``TransientFault`` at a boundary retries with capped exponential
  backoff (``max_retries``/``retry_backoff_s``/``max_backoff_s``);
- a fault that PERSISTS past the retry budget, or a ``PermanentFault``,
  quarantines only the implicated request — slot freed, ``done`` set,
  status ``FAILED`` — and the batch keeps decoding;
- an ``EngineCrash`` (or any fault with no implicated request)
  abandons the device state entirely (including any un-synced
  horizon: its tokens were never recorded, so replay simply
  regenerates them); :meth:`recover` rebuilds state by DETERMINISTIC
  REPLAY. Two replay modes:

  * **stepwise** (the conservative default): re-prefill every live
    slot's original prompt through the same bucketed program as its
    admission, then TEACHER-FORCE the recorded tokens one fused step
    at a time — exactly re-tracing the crashed run's op sequence, so
    at ``temperature=0`` the resumed stream is byte-identical to an
    uninterrupted one (chaos parity tests pin this);
  * **chunked** (O(prompt/bucket + tokens/bucket) device calls per
    slot instead of O(tokens)): re-prefill ``prompt + tokens_so_far``
    in one pass through the bucketed/chunked prefill path. The
    prefill-path logits can differ from the decode-path logits in the
    last float bit (different XLA schedules), so ``chunked_replay=
    "auto"`` runs a one-time parity probe at first recovery —
    full-sequence prefill vs prefill+teacher-forcing on a synthetic
    sequence — and only enables chunked replay when they agree
    bitwise; otherwise it falls back to stepwise. ``True``/``False``
    force a mode (``tests/test_serving_faults.py`` covers both).

Request lifecycle: ``Request.deadline_s`` and ``Request.cancel()`` are
checked at every horizon boundary; a timed-out or cancelled request is
retired (status EXPIRED/CANCELLED, partial stream in ``results``, KV
slot freed) instead of decoding to ``max_new``. :meth:`preempt_all`
cancels every live and queued request — the drain-deadline hook
``ServingServer.stop`` uses to converge instead of waiting out
stragglers. ``last_dispatch_t`` is a monotonic heartbeat for the
server's hung-engine watchdog.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
import time
from collections import deque

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from deeplearning4j_tpu.models.transformer import (
    TransformerConfig,
    _chunk_builder,
    _decode_builder,
    _top_k_filter,
    make_paged_fwd1,
    paged_block_copy,
    paged_slot_gather,
    paged_slot_scatter,
    place_serving_tp_params,
    serving_tp_cache_sharding,
)
from deeplearning4j_tpu.parallel.mesh import model_parallel_mesh
from deeplearning4j_tpu.obs.flight import FlightRecorder
from deeplearning4j_tpu.obs.logs import log_event
from deeplearning4j_tpu.obs.profiler import ProfileTrigger
from deeplearning4j_tpu.obs.trace import (
    ENGINE_TRACK,
    SCHEDULER_TRACK,
    Tracer,
    new_span_id,
    slot_track,
)
from deeplearning4j_tpu.serving.cache_pool import KVSlotPool, PagedKVPool
from deeplearning4j_tpu.serving.disagg import (
    WireError,
    decode_segment,
    encode_segment,
    model_config_hash,
    slab_to_blocks,
)
from deeplearning4j_tpu.serving.faults import (
    EngineCrash,
    FaultInjector,
    PermanentFault,
    TransientFault,
)
from deeplearning4j_tpu.analysis.sanitizers import note_access, wrap_lock
from deeplearning4j_tpu.serving.grammar import (
    MAX_LOGIT_BIAS,
    MAX_TOP_LOGPROBS,
    GrammarCache,
    GrammarError,
    GrammarTable,
    StopMatcher,
    default_token_bytes,
    parse_response_format,
)
from deeplearning4j_tpu.serving.metrics import ServingMetrics
from deeplearning4j_tpu.serving.prefix_cache import PrefixCache, Segment
from deeplearning4j_tpu.serving.probe_cache import ProbeCache, probe_key
from deeplearning4j_tpu.serving.scheduler import (
    AdmissionError,
    Backpressure,
    Request,
    RequestScheduler,
    RequestStatus,
)
from deeplearning4j_tpu.serving.tenancy import QuotaExceeded

#: device EOS id for requests without one (never equals a sampled token)
_NO_EOS = -1

_log = logging.getLogger(__name__)


#: Declared donation intent per program family: the argnums each
#: family donates ON TPU (CPU jit cannot alias donated buffers, so the
#: engine passes () there — same programs, no aliasing). This table IS
#: the contract the static donation audit (analysis/audit.py) checks
#: against each family's traced avals: every donated argument must be
#: consumable by an output of matching shape/dtype, or the donation is
#: dead weight ("donation not used") and the cache stops updating in
#: place.
PROGRAM_DONATION: dict[str, tuple[int, ...]] = {
    # step/replay thread the pooled caches + per-slot device state
    "step": (1, 2, 3, 4, 5),          # caches, logits, pos, active, budget
    "replay": (1, 2),                 # caches, logits
    "deactivate": (0,),               # active mask
    # admission programs donate the pool state sextuple
    "prefill": (0, 1, 2, 3, 4, 5),
    "insert": (0, 1, 2, 3, 4, 5),
    "hit_insert": (0, 1, 2, 3, 4, 5),
    "batch_prefill": (0, 1, 2, 3, 4, 5),
    "batch_hit": (0, 1, 2, 3, 4, 5),
    # segment store replaces the region functionally
    "seg_store": (0,),
    # wire-segment import lands a host-uploaded slab the same way
    "seg_import": (0,),
    # pure reads
    "chunk": (),
    "seg_fetch": (),
    "logit_row": (),
    # paged families: the caches argument is the {"blocks", "tables"}
    # dict; donating it donates both leaves — the tables leaf is
    # consumed by the identity pass-through output, blocks by the
    # scattered blocks output
    "paged_step": (1, 2, 3, 4, 5),
    "paged_replay": (1, 2),
    "paged_prefill": (0, 1, 2, 3, 4, 5),
    "paged_insert": (0, 1, 2, 3, 4, 5),
    "block_copy": (0,),
    "paged_seg_fetch": (),
    "paged_seg_import": (0,),
    # piggyback: decode state donated exactly as "step" (argnums
    # 1..5), plus the admitting slot's chunk scratch slab (argnum 9),
    # consumed by the fused program's updated-scratch output
    "piggyback_step": (1, 2, 3, 4, 5, 9),
    "paged_piggyback_step": (1, 2, 3, 4, 5, 9),
    # masked step (grammar-constrained decoding + per-request sampling
    # surface): decode state donated as "step" (argnums 1..5) plus the
    # per-slot grammar FSM state vector (argnum 7), consumed by the
    # program's advanced-state output. The mask/transition tables are
    # NOT donated — they are reused across dispatches and shared with
    # the host mirror.
    "masked_step": (1, 2, 3, 4, 5, 7),
    "paged_masked_step": (1, 2, 3, 4, 5, 7),
    # masked piggyback adds the admitting slot's chunk scratch slab
    # (argnum 17), as "piggyback_step" donates its argnum 9
    "masked_piggyback_step": (1, 2, 3, 4, 5, 7, 17),
    "paged_masked_piggyback_step": (1, 2, 3, 4, 5, 7, 17),
    # single-slot grammar-state seat (admission), like "deactivate"
    "gstate_set": (0,),
}


# -- program-family factories ----------------------------------------------
#
# Every compiled program the engine can emit is built by one of these
# module-level factories. The engine's jit caches call them with its
# own closures; the program-surface registry (analysis/programs.py)
# calls the SAME factories with abstract avals — so the audited
# programs are the live programs by construction, not by transcription.


def build_step_program(fwd1, horizon: int, temperature: float,
                       top_k: int | None, approx_top_k: bool):
    """K fused decode substeps in one program. The carry — caches,
    pending logits, positions, active mask, remaining budget — lives
    entirely on device; ``eos`` is per-slot data. The chain is unrolled
    (not ``lax.scan``) so XLA keeps in-place cache updates; the layer
    loop inside ``fwd1`` is already unrolled for the same reason."""

    def step(params, caches, logits, pos, active, budget, eos,
             slot_keys_raw, adapters):
        # per-slot keys (raw uint32 rows, host-persisted): token i
        # of slot s is sampled with fold_in(key_s, position) — a
        # pure function of the slot's admission key and its stream
        # position, so the key stream is invariant to batch
        # composition, horizon K, and crash-recovery replay
        keys = (
            jax.random.wrap_key_data(slot_keys_raw)
            if temperature != 0 else None
        )
        toks_all = []
        for k in range(horizon):
            filt = _top_k_filter(logits, top_k, approx_top_k)
            if temperature == 0:
                toks = jnp.argmax(filt, axis=-1).astype(jnp.int32)
            else:
                tok_keys = jax.vmap(jax.random.fold_in)(keys, pos)
                toks = jax.vmap(
                    lambda kk, lg: jax.random.categorical(kk, lg)
                )(tok_keys, filt / temperature).astype(jnp.int32)
            # inactive slots decode token 0 at their frozen
            # position — shape stability; the garbage row they
            # write stays inside their own slab and is wiped by the
            # next admission's prefill insert
            toks = jnp.where(active, toks, 0)
            new_logits, caches = fwd1(
                params, caches, toks, pos, adapter=adapters
            )
            # advance only live slots, then deactivate in-program:
            # a slot that just emitted EOS or spent its budget
            # stops mutating for the rest of the horizon
            pos = jnp.where(active, pos + 1, pos)
            budget = jnp.where(active, budget - 1, budget)
            active = active & (toks != eos) & (budget > 0)
            logits = new_logits
            toks_all.append(toks)
        return (caches, logits, pos, active, budget,
                jnp.stack(toks_all, axis=1))

    return step


def build_replay_program(fwd1):
    """Teacher-forced decode step for stepwise crash recovery: feed
    RECORDED tokens (no sampling) and freeze the pending-logits rows of
    slots whose recording is already exhausted — those rows must stay
    exactly what the slot's last real step produced."""

    def rstep(params, caches, logits, toks, pos, replaying, adapters):
        new_logits, caches = fwd1(
            params, caches, toks, pos, adapter=adapters
        )
        logits = jnp.where(replaying[:, None], new_logits, logits)
        return caches, logits

    return rstep


def build_deact_program():
    """Single-slot deactivation: flip one row of the device-resident
    active mask (retirement between horizons)."""
    return lambda active, slot: active.at[slot].set(False)


def build_prefill_program(do_prefill, init_caches, max_total: int):
    """Fused admission program for one prompt bucket: prefill-at-
    batch-1 over the padded prompt, slab insert at the slot index, and
    the slot's device state (pos/active/budget/eos + pending logits)
    set in the same dispatch."""

    def prefill(caches, logits, pos, active, budget, eos, params,
                prompt, last_idx, slot, pos0, max_new, eos_tok,
                adapter):
        # batch-1 prefill into a scratch single-slot cache of the
        # SAME Tpad as the pool, then insert the slab at the slot
        # index. The slab copy includes the zero rows beyond the
        # prompt — that wipes the previous occupant's rows, so no
        # stale state survives reuse. ``last_idx`` points at the true
        # last prompt row; the padded rows are causally invisible to
        # it, so the logits are bitwise those of an exact-length
        # prefill.
        tmp, lg = do_prefill(
            params, init_caches(1, max_total), prompt,
            last_idx=last_idx, adapter=adapter,
        )
        caches = jax.tree.map(
            lambda c, t: lax.dynamic_update_slice(
                c, t, (0, 0, slot, 0, 0)
            ),
            caches, tmp,
        )
        logits = lax.dynamic_update_slice(logits, lg, (slot, 0))
        pos = pos.at[slot].set(pos0)
        active = active.at[slot].set(True)
        budget = budget.at[slot].set(max_new)
        eos = eos.at[slot].set(eos_tok)
        return caches, logits, pos, active, budget, eos

    return prefill


def build_chunk_program(fwd_chunk):
    """Chunk-at-offset program for the long-prompt path: one
    ``forward_chunk`` pass over the bucket's rows of a batch-1 scratch
    cache, returning the (1, V) logits at ``last_idx``."""

    def chunk(params, tmp, toks, pos0, last_idx, adapter):
        lg, tmp = fwd_chunk(
            params, tmp, toks, pos0, last_idx=last_idx,
            adapter=adapter,
        )
        return tmp, lg

    return chunk


def build_piggyback_program(fwd1, fwd_chunk, horizon: int,
                            temperature: float, top_k: int | None,
                            approx_top_k: bool):
    """Chunked-prefill piggyback (Sarathi-style): K fused decode
    substeps for the active slots AND one bounded prefill chunk for an
    admitting slot, in a single dispatch. The decode leg is the
    ``build_step_program`` body verbatim; the chunk leg is the
    ``build_chunk_program`` body verbatim, over the admitting slot's
    OWN batch-1 scratch cache — the two legs share no buffers, so
    fusing them cannot perturb either side's numerics (the
    construction-time piggyback parity probe proves it bitwise)."""

    def pstep(params, caches, logits, pos, active, budget, eos,
              slot_keys_raw, adapters, tmp, ctoks, cpos0, clast,
              cadapter):
        keys = (
            jax.random.wrap_key_data(slot_keys_raw)
            if temperature != 0 else None
        )
        toks_all = []
        for k in range(horizon):
            filt = _top_k_filter(logits, top_k, approx_top_k)
            if temperature == 0:
                toks = jnp.argmax(filt, axis=-1).astype(jnp.int32)
            else:
                tok_keys = jax.vmap(jax.random.fold_in)(keys, pos)
                toks = jax.vmap(
                    lambda kk, lg: jax.random.categorical(kk, lg)
                )(tok_keys, filt / temperature).astype(jnp.int32)
            toks = jnp.where(active, toks, 0)
            new_logits, caches = fwd1(
                params, caches, toks, pos, adapter=adapters
            )
            pos = jnp.where(active, pos + 1, pos)
            budget = jnp.where(active, budget - 1, budget)
            active = active & (toks != eos) & (budget > 0)
            logits = new_logits
            toks_all.append(toks)
        clg, tmp = fwd_chunk(
            params, tmp, ctoks, cpos0, last_idx=clast,
            adapter=cadapter,
        )
        return (caches, logits, pos, active, budget,
                jnp.stack(toks_all, axis=1), tmp, clg)

    return pstep


def _dyn_top_k_filter(logits, top_ks):
    """Per-slot top-k filter with a TRACED k vector. ``_top_k_filter``
    thresholds at ``lax.top_k(logits, k)[0][..., -1]`` — the kth order
    statistic — and an ascending full sort gathered at ``V - k`` yields
    the same float value, so the subsequent ``where(logits < kth)``
    keeps bitwise-identical rows. ``k == 0`` is the no-filter sentinel
    (engine-wide ``top_k=None``), folded out so those slots keep the
    raw logits object untouched."""
    vs = logits.shape[-1]
    srt = jnp.sort(logits, axis=-1)
    idx = jnp.clip(vs - top_ks, 0, vs - 1).astype(jnp.int32)
    kth = jnp.take_along_axis(srt, idx[:, None], axis=-1)
    filt = jnp.where(logits < kth, -jnp.inf, logits)
    return jnp.where((top_ks > 0)[:, None], filt, logits)


def _top_p_filter(scaled, top_ps):
    """Per-slot nucleus filter on the temperature-scaled logits: keep
    the smallest descending-probability prefix whose mass reaches
    top_p (the token crossing the threshold is kept, standard nucleus
    semantics). ``top_p == 1`` is the no-filter sentinel, folded out
    so unfiltered slots keep ``scaled`` bitwise."""
    srt = -jnp.sort(-scaled, axis=-1)
    probs = jax.nn.softmax(srt, axis=-1)
    csum = jnp.cumsum(probs, axis=-1)
    keep = (csum - probs) < top_ps[:, None]
    cut = jnp.min(
        jnp.where(keep, srt, jnp.inf), axis=-1, keepdims=True
    )
    out = jnp.where(scaled < cut, -jnp.inf, scaled)
    return jnp.where((top_ps < 1.0)[:, None], out, scaled)


def _masked_draw(logits, pos, active, gstate, keys, temps, top_ks,
                 top_ps, bias_idx, bias_val, mask_words, n_logprobs):
    """One masked substep's draw: grammar mask → logit bias → logprob
    rows → per-slot top-k → temperature → top-p → greedy/sampled
    select. Every per-request control sits behind a ``jnp.where`` at
    its neutral value (state 0, no bias rows, k=0, p=1, engine
    temperature) so a slot using none of them reproduces the base
    step program's token stream bitwise — the construction-time
    masked-parity probe gates exactly that.

    Returns ``(toks, aux)`` where ``aux`` is the packed int32 per-slot
    row ``[tok, bitcast(chosen logprob), top ids..., bitcast(top
    logprobs)...]`` — logprobs ride the one existing readback instead
    of syncing the (slots, V) logits."""
    vs = logits.shape[-1]
    # grammar mask: gather each slot's packed row for its current FSM
    # state and unpack 32 bits/word in-program. Row 0 is the
    # all-permitted unconstrained sentinel, and the gstate>0 fold
    # keeps unconstrained rows as the untouched logits object.
    rows = mask_words[gstate]
    bits = (
        rows[:, :, None] >> jnp.arange(32, dtype=jnp.uint32)
    ) & jnp.uint32(1)
    allowed = bits.reshape(rows.shape[0], -1)[:, :vs] != 0
    constrained = (gstate > 0)[:, None]
    base = jnp.where(constrained & ~allowed, -jnp.inf, logits)
    # sparse per-slot logit bias: idx<0 rows are padding. The has_bias
    # fold is load-bearing for parity — ``base + 0.0`` flips -0.0
    # logits to +0.0.
    has_bias = jnp.any(bias_idx >= 0, axis=-1)[:, None]
    idx = jnp.clip(bias_idx, 0, vs - 1)
    val = jnp.where(bias_idx >= 0, bias_val, 0.0)
    delta = jax.vmap(
        lambda i, v: jnp.zeros((vs,), logits.dtype).at[i].add(v)
    )(idx, val)
    base = jnp.where(has_bias, base + delta, base)
    # logprob source: the masked+biased distribution BEFORE
    # top-k/temperature/top-p shaping — API logprobs describe the
    # model's constrained distribution, not the sampler's
    lp = jax.nn.log_softmax(base, axis=-1)
    filt = _dyn_top_k_filter(base, top_ks)
    greedy = jnp.argmax(filt, axis=-1).astype(jnp.int32)
    safe_t = jnp.where(temps > 0, temps, 1.0)
    scaled = filt / safe_t[:, None]
    final = _top_p_filter(scaled, top_ps)
    tok_keys = jax.vmap(jax.random.fold_in)(keys, pos)
    sampled = jax.vmap(
        lambda kk, lg: jax.random.categorical(kk, lg)
    )(tok_keys, final).astype(jnp.int32)
    toks = jnp.where(temps > 0, sampled, greedy)
    toks = jnp.where(active, toks, 0)
    lp_cho = jnp.take_along_axis(lp, toks[:, None], axis=-1)[:, 0]
    tv, ti = lax.top_k(lp, n_logprobs)
    aux = jnp.concatenate(
        [
            toks[:, None],
            lax.bitcast_convert_type(lp_cho, jnp.int32)[:, None],
            ti.astype(jnp.int32),
            lax.bitcast_convert_type(tv, jnp.int32),
        ],
        axis=1,
    )
    return toks, aux


def build_masked_step_program(fwd1, horizon: int, n_logprobs: int):
    """Grammar-constrained + per-request-sampling variant of
    ``build_step_program``: the same unrolled K-substep chain, with a
    per-slot FSM state vector threaded through it. Each substep masks
    disallowed tokens BEFORE the draw and advances the state
    in-program off the chosen token, so K>1 horizons stay constrained
    without a host round-trip. The token output is replaced by the
    packed aux tensor (slots, K, 2+2*n_logprobs) whose [:, :, 0] slice
    is the token stream."""

    def mstep(params, caches, logits, pos, active, budget, eos,
              gstate, slot_keys_raw, adapters, temps, top_ks, top_ps,
              bias_idx, bias_val, mask_words, trans_tab):
        keys = jax.random.wrap_key_data(slot_keys_raw)
        aux_all = []
        for k in range(horizon):
            toks, aux = _masked_draw(  # lint: prng-ok _masked_draw folds pos into the key; pos advances every substep
                logits, pos, active, gstate, keys, temps, top_ks,
                top_ps, bias_idx, bias_val, mask_words, n_logprobs,
            )
            # advance the FSM off the chosen token; disallowed
            # transitions are stored as 0 in the table so the gather
            # never indexes negatively. Inactive and unconstrained
            # slots hold their state.
            nxt = trans_tab[gstate, toks]
            gstate = jnp.where(active & (gstate > 0), nxt, gstate)
            new_logits, caches = fwd1(
                params, caches, toks, pos, adapter=adapters
            )
            pos = jnp.where(active, pos + 1, pos)
            budget = jnp.where(active, budget - 1, budget)
            active = active & (toks != eos) & (budget > 0)
            logits = new_logits
            aux_all.append(aux)
        return (caches, logits, pos, active, budget, gstate,
                jnp.stack(aux_all, axis=1))

    return mstep


def build_masked_piggyback_program(fwd1, fwd_chunk, horizon: int,
                                   n_logprobs: int):
    """Masked decode leg + one bounded prefill chunk in a single
    dispatch — ``build_masked_step_program`` body verbatim plus the
    ``build_chunk_program`` leg, mirroring how ``piggyback_step``
    extends ``step``."""

    def mpstep(params, caches, logits, pos, active, budget, eos,
               gstate, slot_keys_raw, adapters, temps, top_ks,
               top_ps, bias_idx, bias_val, mask_words, trans_tab,
               tmp, ctoks, cpos0, clast, cadapter):
        keys = jax.random.wrap_key_data(slot_keys_raw)
        aux_all = []
        for k in range(horizon):
            toks, aux = _masked_draw(  # lint: prng-ok _masked_draw folds pos into the key; pos advances every substep
                logits, pos, active, gstate, keys, temps, top_ks,
                top_ps, bias_idx, bias_val, mask_words, n_logprobs,
            )
            nxt = trans_tab[gstate, toks]
            gstate = jnp.where(active & (gstate > 0), nxt, gstate)
            new_logits, caches = fwd1(
                params, caches, toks, pos, adapter=adapters
            )
            pos = jnp.where(active, pos + 1, pos)
            budget = jnp.where(active, budget - 1, budget)
            active = active & (toks != eos) & (budget > 0)
            logits = new_logits
            aux_all.append(aux)
        clg, tmp = fwd_chunk(
            params, tmp, ctoks, cpos0, last_idx=clast,
            adapter=cadapter,
        )
        return (caches, logits, pos, active, budget, gstate,
                jnp.stack(aux_all, axis=1), tmp, clg)

    return mpstep


def build_gstate_set_program():
    """Single-slot grammar-state seat: write one row of the
    device-resident FSM state vector at admission (and zero it at
    retirement), like ``build_deact_program``."""
    return lambda gstate, slot, val: gstate.at[slot].set(val)


def build_insert_program():
    """Slab insert + state set (no prefill): lands a scratch cache
    built by the chunked path — or zeros, for an empty prompt — into
    the pool at the slot index."""

    def insert(caches, logits, pos, active, budget, eos, tmp, lg,
               slot, pos0, max_new, eos_tok):
        caches = jax.tree.map(
            lambda c, t: lax.dynamic_update_slice(
                c, t, (0, 0, slot, 0, 0)
            ),
            caches, tmp,
        )
        logits = lax.dynamic_update_slice(logits, lg, (slot, 0))
        pos = pos.at[slot].set(pos0)
        active = active.at[slot].set(True)
        budget = budget.at[slot].set(max_new)
        eos = eos.at[slot].set(eos_tok)
        return caches, logits, pos, active, budget, eos

    return insert


def build_hit_insert_program():
    """FULL-hit admission: one gather/dynamic-update program that
    copies a segment's whole slab from the region into the pool at the
    slot index, lands the segment's stored last-row logits, and sets
    the slot's device state — zero prompt rows computed, zero prefill
    dispatches."""

    def hit(caches, logits, pos, active, budget, eos, region, seg_lg,
            seg, slot, pos0, max_new, eos_tok):
        slab = jax.tree.map(
            lambda r: lax.dynamic_slice(
                r, (0, 0, seg, 0, 0),
                (r.shape[0], r.shape[1], 1, r.shape[3], r.shape[4]),
            ),
            region,
        )
        caches = jax.tree.map(
            lambda c, t: lax.dynamic_update_slice(
                c, t, (0, 0, slot, 0, 0)
            ),
            caches, slab,
        )
        logits = lax.dynamic_update_slice(logits, seg_lg, (slot, 0))
        pos = pos.at[slot].set(pos0)
        active = active.at[slot].set(True)
        budget = budget.at[slot].set(max_new)
        eos = eos.at[slot].set(eos_tok)
        return caches, logits, pos, active, budget, eos

    return hit


def build_seg_fetch_program():
    """Segment fetch: one region slot's slab as a batch-1 scratch
    cache (the partial-hit path chunk-computes the suffix on top)."""

    def fetch(region, seg):
        return jax.tree.map(
            lambda r: lax.dynamic_slice(
                r, (0, 0, seg, 0, 0),
                (r.shape[0], r.shape[1], 1, r.shape[3], r.shape[4]),
            ),
            region,
        )

    return fetch


def build_seg_store_program():
    """Segment store: copy a pool slot's slab into the region at the
    segment index (insert-on-completion). Pool caches are read, not
    donated; the region is replaced functionally."""

    def store(region, caches, seg, slot):
        slab = jax.tree.map(
            lambda c: lax.dynamic_slice(
                c, (0, 0, slot, 0, 0),
                (c.shape[0], c.shape[1], 1, c.shape[3], c.shape[4]),
            ),
            caches,
        )
        return jax.tree.map(
            lambda r, t: lax.dynamic_update_slice(
                r, t, (0, 0, seg, 0, 0)
            ),
            region, slab,
        )

    return store


def build_seg_import_program():
    """Wire-segment import: land a batch-1 slab (a remote replica's
    ``_seg_fetch``-layout segment, uploaded from host bytes) into the
    region at the segment index — the disaggregated-ingest mirror of
    the segment store, with the pool slot slice replaced by the slab
    that arrived over the wire."""

    def imp(region, slab, seg):
        return jax.tree.map(
            lambda r, t: lax.dynamic_update_slice(
                r, t, (0, 0, seg, 0, 0)
            ),
            region, slab,
        )

    return imp


def build_logit_row_program():
    """(1, V) row slice of the pending logits — captured at insert
    time so a later FULL hit replays the exact prefill logits without
    recomputing anything."""
    return lambda lg, slot: lax.dynamic_slice(
        lg, (slot, 0), (1, lg.shape[1])
    )


def build_batch_prefill_program(do_prefill, init_caches,
                                max_total: int, nb: int):
    """BATCHED admission prefill: ``nb`` same-bucket prompts prefilled
    in one dispatched program (vector per-row last_idx), each row's
    slab + logits + device state landed at its slot. Group sizes are
    padded to powers of two (pad rows repeat row 0, re-writing
    identical values), so the program count stays
    O(buckets x log n_slots)."""

    def bprefill(caches, logits, pos, active, budget, eos, params,
                 prompts, last_idx, slots, pos0, max_new, eos_toks,
                 adapters):
        tmp, lg = do_prefill(
            params, init_caches(nb, max_total), prompts,
            last_idx=last_idx, adapter=adapters,
        )
        for r in range(nb):
            slab = jax.tree.map(
                lambda t, r=r: t[:, :, r:r + 1], tmp
            )
            caches = jax.tree.map(
                lambda c, t, r=r: lax.dynamic_update_slice(
                    c, t, (0, 0, slots[r], 0, 0)
                ),
                caches, slab,
            )
            logits = lax.dynamic_update_slice(
                logits, lg[r:r + 1], (slots[r], 0)
            )
            pos = pos.at[slots[r]].set(pos0[r])
            active = active.at[slots[r]].set(True)
            budget = budget.at[slots[r]].set(max_new[r])
            eos = eos.at[slots[r]].set(eos_toks[r])
        return caches, logits, pos, active, budget, eos

    return bprefill


def build_batch_hit_program(fwd_chunk, nb: int):
    """BATCHED partial-hit admission for ``nb`` requests sharing the
    same cached-prefix length L and suffix bucket: one gather pulls
    each row's segment slab from the region, one ``forward_chunk`` at
    scalar pos0=L (vector per-row last_idx) computes all the uncached
    suffixes, and each row lands at its slot. The common case — many
    requests behind one system prompt — gathers the SAME segment nb
    times."""

    def bhit(caches, logits, pos, active, budget, eos, params, region,
             seg_idx, toks, p0, last_idx, slots, posf, max_new,
             eos_toks, adapters):
        tmp = jax.tree.map(
            lambda r_: jnp.take(r_, seg_idx, axis=2), region
        )
        lg, tmp = fwd_chunk(
            params, tmp, toks, p0, last_idx=last_idx,
            adapter=adapters,
        )
        for r in range(nb):
            slab = jax.tree.map(
                lambda t, r=r: t[:, :, r:r + 1], tmp
            )
            caches = jax.tree.map(
                lambda c, t, r=r: lax.dynamic_update_slice(
                    c, t, (0, 0, slots[r], 0, 0)
                ),
                caches, slab,
            )
            logits = lax.dynamic_update_slice(
                logits, lg[r:r + 1], (slots[r], 0)
            )
            pos = pos.at[slots[r]].set(posf[r])
            active = active.at[slots[r]].set(True)
            budget = budget.at[slots[r]].set(max_new[r])
            eos = eos.at[slots[r]].set(eos_toks[r])
        return caches, logits, pos, active, budget, eos

    return bhit


# -- paged program factories -----------------------------------------------
#
# Paged-mode analogues over the {"blocks", "tables"} caches dict. The
# compute is IDENTICAL to the slab programs — same do_prefill, same
# fwd1 via make_paged_fwd1's gather/compute/scatter wrapper — only the
# landing changes: instead of a dynamic-update at the slot's slab, rows
# scatter into the pool blocks the slot's table row names. Rows past
# the row's allocated coverage scatter into the zero sentinel (block 0,
# re-zeroed in-program), so a slot only ever writes blocks it owns.


def build_paged_prefill_program(do_prefill, init_caches, max_total: int):
    """Paged admission prefill: batch-1 prefill into a scratch slab
    (same as the slab program), then scatter the slab's rows into the
    slot's table-row blocks. Fresh private blocks get the scratch
    cache's zero rows beyond the prompt, so no stale bytes from a
    previous block owner survive reuse."""

    def prefill(caches, logits, pos, active, budget, eos, params,
                prompt, last_idx, slot, pos0, max_new, eos_tok,
                adapter):
        tmp, lg = do_prefill(
            params, init_caches(1, max_total), prompt,
            last_idx=last_idx, adapter=adapter,
        )
        row = caches["tables"][slot]
        caches = {
            "blocks": paged_slot_scatter(caches["blocks"], row, tmp),
            "tables": caches["tables"],
        }
        logits = lax.dynamic_update_slice(logits, lg, (slot, 0))
        pos = pos.at[slot].set(pos0)
        active = active.at[slot].set(True)
        budget = budget.at[slot].set(max_new)
        eos = eos.at[slot].set(eos_tok)
        return caches, logits, pos, active, budget, eos

    return prefill


def build_paged_insert_program():
    """Paged insert + state set (no prefill): scatter a batch-1 scratch
    slab — built by the chunked path or a segment gather — into the
    slot's table-row blocks and land the pending logits row."""

    def insert(caches, logits, pos, active, budget, eos, tmp, lg,
               slot, pos0, max_new, eos_tok):
        row = caches["tables"][slot]
        caches = {
            "blocks": paged_slot_scatter(caches["blocks"], row, tmp),
            "tables": caches["tables"],
        }
        logits = lax.dynamic_update_slice(logits, lg, (slot, 0))
        pos = pos.at[slot].set(pos0)
        active = active.at[slot].set(True)
        budget = budget.at[slot].set(max_new)
        eos = eos.at[slot].set(eos_tok)
        return caches, logits, pos, active, budget, eos

    return insert


def build_paged_seg_fetch_program():
    """Paged segment fetch: gather a segment's block list (sentinel-
    padded to full table width, so uncovered rows come back zero) into
    a batch-1 scratch slab the chunk programs accept unchanged."""

    def fetch(blocks, seg_row):
        return paged_slot_gather(blocks, seg_row)

    return fetch


def build_paged_seg_import_program():
    """Paged wire-segment import: scatter a host-uploaded batch-1 slab
    into the segment's freshly allocated blocks through a sentinel-
    padded table row (rows past the segment's block span land in the
    sentinel block and vanish, as everywhere else in the paged
    layout)."""

    def imp(blocks, seg_row, slab):
        return paged_slot_scatter(blocks, seg_row, slab)

    return imp


def build_block_copy_program():
    """Copy one block's rows to another block across every layer/leaf —
    the paged segment store's tail privatization (a donor slot keeps
    writing its tail block past the cached length, so the cache copies
    that one block instead of aliasing it)."""

    def copy(blocks, src, dst):
        return paged_block_copy(blocks, src, dst)

    return copy


class _SlotState:
    """Host-side record for one occupied slot."""

    __slots__ = ("req", "tokens", "t_first_token", "gen", "key_data",
                 "adapter", "segs", "gkey", "gstate0", "stop_matcher",
                 "lp_out", "n_stripped")

    def __init__(self, req: Request, gen: int, key_data,
                 adapter: int = 0):
        self.req = req
        self.tokens: list[int] = []
        self.t_first_token: float | None = None
        self.gen = gen  # pool generation at admission (reuse detection)
        # raw uint32 data of the slot's sampling key (host-persisted so
        # crash-recovery replay resumes the exact key stream)
        self.key_data = key_data
        # LoRA bank row (host-persisted so recovery replays through the
        # same adapter weights)
        self.adapter = adapter
        # prefix-cache segments this request pins (the one its
        # admission read + the one its prompt inserted); unpinned at
        # retirement so LRU eviction can reclaim them
        self.segs: list[Segment] = []
        # sampling-surface state (engines with sampling_surface=True):
        # gkey/gstate0 pin the seated grammar's table rows + start
        # state so crash recovery can re-walk the transition table
        # over st.tokens; the stop matcher holds back a rolling
        # suffix; lp_out collects per-token logprob records
        self.gkey = None
        self.gstate0 = 0
        self.stop_matcher: StopMatcher | None = None
        self.lp_out: list | None = None
        self.n_stripped = 0


class _AdmitPlan:
    """One admission being planned: the popped request, its acquired
    slot, and the prefix-cache classification (``kind`` in
    miss/partial/full, ``seg`` the pinned source segment, ``matched``
    the usable grain-aligned cached-token count)."""

    __slots__ = ("req", "slot", "kind", "seg", "matched", "admitted",
                 "prefill_s", "t_pf")

    def __init__(self, req: Request, slot: int):
        self.req = req
        self.slot = slot
        self.kind = "miss"
        self.seg: Segment | None = None
        self.matched = 0
        self.admitted = False  # slot state seated (crash requeue guard)
        self.prefill_s = 0.0
        self.t_pf = 0.0


class _PendingPrefill:
    """One deferred admission (chunked-prefill piggyback): the plan
    holds the acquired slot + pinned prefix segment; ``chunks`` is the
    remaining pow2 chunk schedule over the uncached suffix; ``tmp`` /
    ``lg`` carry the batch-1 scratch cache and last chunk's (1, V)
    logits across horizons until the completion insert seats the
    slot."""

    __slots__ = ("plan", "chunks", "tmp", "lg", "t_start")

    def __init__(self, plan: _AdmitPlan, chunks, tmp, t_start: float):
        self.plan = plan
        self.chunks = chunks
        self.tmp = tmp
        self.lg = None
        self.t_start = t_start


# Process-level compiled-program sharing.  The callable a family jits
# is fully determined by (cfg, tp, paged geometry, max_total, the
# family's own statics): two engines with the same key — replica
# fleets, supervised restarts, parity-test pairs — reuse ONE jitted
# callable instead of recompiling identical programs.  Safe because
# every program is pure (all state rides in the arguments) and
# jax.jit retraces per input aval, so shape differences (n_slots,
# prompt buckets) never alias.  The executables themselves live in
# jax's own caches, so jax.clear_caches() still frees them; this dict
# only pins the small wrapper objects.
_SHARED_PROGRAMS: dict = {}


def _shared_program(key, thunk):
    fn = _SHARED_PROGRAMS.get(key)
    if fn is None:
        fn = _SHARED_PROGRAMS[key] = thunk()
    return fn


class _Inflight:
    """One dispatched-but-unsynced horizon: the device future holding
    the (slots, K) token block plus a snapshot of who occupied each
    slot at dispatch time."""

    __slots__ = ("toks", "snaps", "t_dispatch")

    def __init__(self, toks, snaps, t_dispatch):
        self.toks = toks
        self.snaps = snaps  # [(slot, _SlotState)] occupied at dispatch
        self.t_dispatch = t_dispatch


class ServingEngine:
    """Fixed-shape pipelined continuous-batching decode loop.

    ``params`` may be float or ``quantize_decode_params`` output (pair
    with ``cfg.decode_int8=True`` for the int8 KV cache). Sampling
    settings are engine-wide (they are baked into the compiled step);
    ``temperature=0`` decodes greedily.

    ``decode_horizon`` (K) is the number of decode steps fused into one
    dispatched program; lifecycle checks, admission and fault injection
    happen at horizon boundaries, so K trades up-to-K-steps extra
    admission/TTFT latency for amortized dispatch + host-sync overhead.
    K=1 reproduces the unpipelined per-step cadence except that token
    readback still lags dispatch by one step (the double buffer).

    ``prefill_max_bucket`` caps the power-of-two prompt padding bucket;
    longer prompts are chunked through the same buckets.
    ``chunked_replay`` picks the crash-replay mode ("auto" probes for
    bitwise prefill/decode parity at first recovery; see module doc).

    Supervision knobs: ``faults`` (an optional
    :class:`~.faults.FaultInjector`), ``max_retries`` transient retries
    per boundary with exponential backoff starting at
    ``retry_backoff_s`` capped at ``max_backoff_s``. ``results_cap``
    bounds the finished-stream dict (oldest evicted first) so sustained
    traffic cannot leak host memory; front ends should prefer
    :meth:`pop_result`, which removes the entry on read.

    Observability: ``tracer`` (an :class:`~deeplearning4j_tpu.obs
    .trace.Tracer`) records the request lifecycle as spans — queued on
    the scheduler track, prefill/decode/first-token/terminal per slot
    track, dispatch/sync/step on the engine track — defaulting to a
    DISABLED tracer (every record call is one attribute check);
    ``profile`` (an :class:`~deeplearning4j_tpu.obs.profiler
    .ProfileTrigger`) brackets engine steps so an armed XLA capture
    starts and stops on step boundaries.
    """

    def __init__(
        self,
        cfg: TransformerConfig,
        params,
        *,
        n_slots: int = 8,
        max_total: int | None = None,
        temperature: float = 0.0,
        top_k: int | None = None,
        approx_top_k: bool = False,
        decode_horizon: int = 1,
        adaptive_horizon: bool = False,
        prefill_max_bucket: int = 128,
        chunked_replay: bool | str = "auto",
        batch_admission: bool | str = "auto",
        prefix_cache: bool = False,
        prefix_cache_tokens: int | None = None,
        prefix_affinity_tokens: int = 0,
        scheduler: RequestScheduler | None = None,
        metrics: ServingMetrics | None = None,
        rng_seed: int = 0,
        faults: FaultInjector | None = None,
        max_retries: int = 3,
        retry_backoff_s: float = 0.01,
        max_backoff_s: float = 0.25,
        results_cap: int = 1024,
        tracer: Tracer | None = None,
        flight: FlightRecorder | None = None,
        attribution: bool = True,
        profile: ProfileTrigger | None = None,
        tp: int = 1,
        tp_parity: bool | str = "auto",
        probe_cache: str | ProbeCache | None = None,
        lora_bank=None,
        lora_parity: bool | str = "auto",
        tenancy=None,
        embedders=None,
        paged: bool = False,
        block_size: int | None = None,
        paged_parity: bool | str = "auto",
        piggyback: bool = False,
        prefill_budget: int | None = None,
        piggyback_parity: bool | str = "auto",
        sampling_surface: bool = False,
        masked_parity: bool | str = "auto",
        grammar_states: int = 256,
        grammar_cache: str | GrammarCache | None = None,
    ):
        self.n_slots = n_slots
        self.max_total = int(min(max_total or cfg.max_len, cfg.max_len))
        # per-program-family device-time attribution (see _attr /
        # _flush_attr): armed at the END of __init__ so construction
        # probes never count, same "probes don't count" contract as
        # prefill_dispatches. _attr_suspend re-suspends during runtime
        # probes and recovery replay.
        self._attr_enabled = False
        self._attr_suspend = 0
        self._pending_attr: list[tuple[str, float]] = []
        # crash flight recorder: enabled by default (one deque.append
        # per horizon/admission — postmortems must exist BEFORE the
        # incident, so this is not opt-in like the tracer)
        self.flight = flight if flight is not None else FlightRecorder()
        # parity-probe verdict persistence (per config x backend x
        # program geometry): repeated engine instances — replica
        # fleets, restarts, tests — skip the cold-start probe
        # dispatches entirely. probes_run / probes_from_cache record
        # which probes actually dispatched this instance.
        # DL4J_TPU_PROBE_CACHE supplies a default path for library
        # construction sites that don't thread the kwarg (the CLI
        # passes its own --probe-cache); an explicit kwarg wins.
        if probe_cache is None:
            probe_cache = os.environ.get("DL4J_TPU_PROBE_CACHE") or None
        self._probe_cache = (
            probe_cache if isinstance(probe_cache, ProbeCache)
            else ProbeCache(probe_cache) if probe_cache else None
        )
        self.probes_run: list[str] = []
        self.probes_from_cache: list[str] = []
        # batched LoRA: the adapter bank (init_lora_bank pytree) rides
        # inside params under the "lora" key; each slot carries an
        # adapter INDEX as traced data, so one compiled step serves
        # every adapter mix (no per-adapter program families). Row 0 is
        # the zero adapter — the forward SELECTS the untouched base
        # activations for it (jnp.where, not +0.0), so adapter-0 output
        # is bitwise the base model; lora_parity "auto" probes exactly
        # that once (verdict persisted via probe_cache) and drops the
        # bank on mismatch, as tp_parity falls back to tp=1.
        self.lora_bank = None
        self.n_adapters = 0
        if lora_bank is not None and lora_parity is not False:
            self.lora_bank = lora_bank
            self.n_adapters = int(
                jax.tree.leaves(lora_bank)[0].shape[1]
            )
            if cfg.decode_kernel:
                # the Pallas decode kernel has no adapter-gather path;
                # the dense fallback is the same numerics (see
                # block_decode)
                cfg = dataclasses.replace(cfg, decode_kernel=False)
        # multi-tenant serving config (see serving.tenancy): resolves
        # per-tenant slot caps at admission; quota charging happens in
        # the scheduler's submit
        self.tenancy = tenancy
        # host-side embedding tables (name -> object with
        # embedding(word)) served at admission boundaries without a KV
        # slot — the scheduler/metrics/drain machinery is model-agnostic
        self.embedders = dict(embedders or {})
        # tensor parallelism: resolve the mesh BEFORE anything compiles.
        # tp > 1 shards the whole hot path — params per
        # serving_tp_shardings (exact head/column layout), the KV pool
        # and prefix region per serving_tp_cache_sharding — behind the
        # standing byte-parity bar: tp_parity "auto" probes the sharded
        # programs bitwise against the single-chip ones once (verdict
        # persisted via probe_cache) and falls back to tp=1 on
        # mismatch, exactly as chunked_replay "auto" falls back to
        # stepwise. True trusts the layout (skips the probe — the
        # escape hatch when the model doesn't FIT on one chip, which is
        # the point of TP); False forces single-chip.
        self.tp = max(1, int(tp))
        self.tp_mesh = None
        if self.tp > 1:
            if tp_parity is False:
                self.tp = 1
            else:
                if cfg.decode_kernel:
                    # the Pallas decode kernel is a custom call GSPMD
                    # cannot partition; the dense fallback is the same
                    # numerics (see block_decode)
                    cfg = dataclasses.replace(cfg, decode_kernel=False)
                mesh = model_parallel_mesh(self.tp)
                ok = True if tp_parity is True else self._probe_verdict(
                    "tp_parity",
                    lambda: self._probe_tp_parity(cfg, params, mesh),
                    cfg=cfg, tp=self.tp, max_total=self.max_total,
                )
                if ok:
                    self.tp_mesh = mesh
                else:
                    log_event(_log, "tp_parity_probe_failed", tp=self.tp)
                    self.tp = 1
        self.cfg = cfg
        self.temperature = temperature
        self.top_k = top_k
        self.approx_top_k = approx_top_k
        self.decode_horizon = max(1, int(decode_horizon))
        # adaptive horizon: shrink K to 1 while requests wait in the
        # queue (admissions happen at horizon boundaries, so a hot
        # queue wants short horizons), restore the configured K when it
        # drains. The device stopping rule is per-substep, so horizon
        # partitioning never changes token streams (K-parity tests).
        self.adaptive_horizon = bool(adaptive_horizon)
        self.decode_horizon_current = self.decode_horizon
        self.chunked_replay = chunked_replay
        self.batch_admission = batch_admission
        self.faults = faults
        self.max_retries = max_retries
        self.retry_backoff_s = retry_backoff_s
        self.max_backoff_s = max_backoff_s
        self.results_cap = results_cap
        # disabled-by-default tracer: every record call is one attribute
        # check, so leaving it wired costs nothing (see obs.trace)
        self.tracer = tracer if tracer is not None else Tracer(enabled=False)
        self.profile = profile

        fwd1, init_caches, do_prefill, cast_params = _decode_builder(
            cfg, tp_mesh=self.tp_mesh
        )
        self._fwd1 = fwd1
        self._init_caches = init_caches
        self._do_prefill = do_prefill
        self._fwd_chunk = _chunk_builder(cfg, tp_mesh=self.tp_mesh)
        if self.lora_bank is not None:
            # the bank travels inside params: place_serving_tp_params
            # shards it with the column layout (A replicated, B sharded
            # on the output dim) and cast_params passes it through —
            # _lora_delta casts at use, so the bank stays f32 at rest
            params = dict(params)
            params["lora"] = self.lora_bank
        if self.tp_mesh is not None:
            # shard the weights over the mesh (exact head/column
            # layout) before the cast — the cast is elementwise, so it
            # preserves placement and runs shard-local
            params = place_serving_tp_params(self.tp_mesh, params, cfg)
        # one-time weight cast (generate does this inside its jitted
        # program; hoisting it out of the per-step program keeps every
        # step from re-casting — same values, cast is deterministic)
        self._cfg_key = cfg.to_json()
        # the model-config identity KV segments are keyed by on the
        # wire and in the prefix cache: a segment computed under a
        # different config hash must never be seated here
        self.config_hash = model_config_hash(cfg)
        self.params = _shared_program(
            (self._cfg_key, self.tp, "cast_params"),
            lambda: jax.jit(cast_params),
        )(params)
        if self.lora_bank is not None and lora_parity is not True:
            ok = self._probe_verdict(
                "lora_zero", self._probe_lora_zero,
                n_adapters=self.n_adapters, tp=self.tp,
                max_total=self.max_total,
            )
            if not ok:
                # serve base-only rather than risk perturbing adapter-0
                # traffic (cfg.decode_kernel stays off — same numerics,
                # see block_decode)
                log_event(_log, "lora_parity_probe_failed",
                          n_adapters=self.n_adapters)
                self.params = {
                    k: v for k, v in self.params.items() if k != "lora"
                }
                self.lora_bank = None
                self.n_adapters = 0

        # block-paged KV: the pool becomes a shared store of fixed-size
        # blocks with per-slot int32 block tables (vLLM-style), so
        # long-prompt traffic allocates ceil((prompt+max_new)/bs)
        # blocks instead of a full Tpad slab and cached prefixes are
        # byte-SHARED by table aliasing. Behind the standing parity
        # bar: paged_parity "auto" probes the paged step bitwise
        # against the slab step once (verdict persisted via
        # probe_cache, like tp_parity) and falls back to the slab
        # layout on mismatch; True trusts the layout, False disables.
        self._paged = False
        self._block_size = int(block_size or 8)
        if paged and paged_parity is not False:
            tpad = jax.tree.leaves(jax.eval_shape(
                lambda: self._init_caches(1, self.max_total)
            ))[0].shape[3]
            if tpad % self._block_size:
                log_event(_log, "paged_disabled_bad_block_size",
                          block_size=self._block_size, tpad=tpad)
            else:
                ok = True if paged_parity is True else self._probe_verdict(
                    "paged_parity",
                    lambda: self._probe_paged_parity(self._block_size),
                    cfg=cfg, block_size=self._block_size,
                    n_slots=n_slots, max_total=self.max_total,
                    tpad=tpad, tp=self.tp,
                )
                if ok:
                    self._paged = True
                else:
                    log_event(_log, "paged_parity_probe_failed",
                              block_size=self._block_size)

        pool_sharding = (serving_tp_cache_sharding(self.tp_mesh, cfg)
                         if self.tp_mesh is not None else None)
        if self._paged:
            self.pool = PagedKVPool(
                cfg, n_slots, self.max_total, sharding=pool_sharding,
                block_size=self._block_size,
            )
        else:
            self.pool = KVSlotPool(
                cfg, n_slots, self.max_total, sharding=pool_sharding,
            )
        # NOT `scheduler or ...`: RequestScheduler defines __len__, so
        # a caller's (normally empty) scheduler would be falsy and
        # silently swapped for a default one, dropping its knobs
        self.scheduler = scheduler if scheduler is not None else (
            RequestScheduler(
                max_total_tokens=self.max_total,
                prefix_affinity_tokens=prefix_affinity_tokens,
                tenancy=tenancy,
            )
        )
        if self.scheduler.max_total_tokens is None:
            self.scheduler.max_total_tokens = self.max_total
        self.metrics = metrics or ServingMetrics()
        self.metrics.decode_horizon = self.decode_horizon

        # power-of-two prompt buckets: the largest must respect the
        # positional table (prefill embeds rows 0..bucket-1) and the
        # pooled slab row count (the insert window must fit Tpad)
        limit = min(int(prefill_max_bucket), cfg.max_len, self.pool.tpad)
        mb = 1
        while mb * 2 <= limit:
            mb *= 2
        self._max_bucket = mb
        self._min_bucket = min(8, mb)
        # partial-hit rounding grain: block-aligned in paged mode so
        # every partial hit is pure block aliasing (no sub-block copy),
        # the bucket grain otherwise
        self._hit_grain = (
            max(self._min_bucket, self._block_size) if self._paged
            else self._min_bucket
        )

        # chunked-prefill piggyback (Sarathi-style): long-prompt
        # admissions defer their uncached suffix to a FIFO of pending
        # records that the dispatch loop drains under a per-horizon
        # token budget, fusing the last budgeted chunk into the decode
        # dispatch itself. Default budget 2x the largest bucket: one
        # standalone chunk + one fused chunk per horizon, so a
        # deferred prompt always makes >= _max_bucket progress while
        # decode keeps stepping. The path arms only after the
        # construction-time parity probe below proves the fused
        # program bitwise-identical to step + chunk run separately.
        self._piggyback_requested = bool(piggyback)
        self._piggyback = False
        self.prefill_budget = max(1, int(
            prefill_budget if prefill_budget is not None
            else 2 * self._max_bucket
        ))
        self._pending_prefills: deque[_PendingPrefill] = deque()
        self._presplit_keys: dict[str, np.ndarray] = {}
        self._pb_did_work = False

        # prefix cache: radix tree over a bounded segment region with
        # the pool's slab layout (see serving.prefix_cache). Partial
        # hits are rounded DOWN to the bucket grain (_min_bucket) so
        # every suffix chunk window starts sublane-aligned and provably
        # fits Tpad. Hit-path reuse is gated by a one-time bitwise
        # parity probe (_prefix_reuse_ok), mirroring chunked_replay
        # "auto": when the probe fails, every lookup is treated as a
        # miss and admission falls back to the full prefill path.
        self.prefix_cache: PrefixCache | None = None
        if prefix_cache:
            self.prefix_cache = PrefixCache(
                self.pool,
                (prefix_cache_tokens if prefix_cache_tokens is not None
                 else n_slots * self.pool.tpad),
                on_evict=self._on_prefix_evict,
                # branch-point segments shorter than the hit grain
                # can never serve a hit (partial matches round down;
                # block-aligned under paging)
                min_seg_len=self._hit_grain,
                config_hash=self.config_hash,
            )
        self._register_gauges()

        # per-slot decode state, DEVICE-resident (threaded through the
        # fused step so pipelined dispatch never reads stale host state)
        self._logits = jnp.zeros((n_slots, cfg.vocab_size), jnp.float32)
        self._dpos = jnp.zeros((n_slots,), jnp.int32)
        self._dactive = jnp.zeros((n_slots,), bool)
        self._dbudget = jnp.zeros((n_slots,), jnp.int32)
        self._deos = jnp.full((n_slots,), _NO_EOS, jnp.int32)

        self._slots: list[_SlotState | None] = [None] * n_slots
        self._inflight: _Inflight | None = None
        # terminal streams are written by the engine thread and read by
        # HTTP handler threads (GET /v1/result pops them), so every
        # access goes through the lock
        self._results_lock = wrap_lock(threading.Lock(), "engine.results")
        self._results: dict[str, np.ndarray] = {}  # guarded-by: _results_lock
        # attached opt-in SyncSanitizer (None in production: the hot
        # path pays one attribute-is-None check per phase)
        self._san = None
        self._key = jax.random.key(rng_seed)
        # per-slot sampling keys, split from the master key at
        # admission (deterministic by admission order). The step
        # program derives each sampled token's key as
        # fold_in(slot_key, position) — a pure function of slot key and
        # position, independent of batch composition or horizon K, so
        # crash-recovery replay (teacher-force recorded tokens, re-seat
        # positions and keys) resumes the EXACT key stream an
        # uninterrupted run would have used. _slot_keys is the raw
        # uint32 key data, host-side; each _SlotState keeps its row.
        _kd0 = np.asarray(jax.random.key_data(self._key))
        self._slot_keys = np.zeros(
            (n_slots,) + _kd0.shape, _kd0.dtype
        )
        # per-slot LoRA adapter indices, host-side mirror of
        # _slot_keys: written at admission, snapshotted (copied) per
        # dispatch, re-seated from _SlotState records at recovery.
        # Always threaded into the compiled programs — with no bank the
        # traced vector is unused and folds out of the graph, so the
        # program count and numerics are unchanged.
        self._slot_adapters = np.zeros((n_slots,), np.int32)
        self._steps = 0
        self._admitting = 0  # requests between scheduler pop and slot
        self.last_dispatch_t: float | None = None  # watchdog heartbeat
        self._chunked_ok: bool | None = None  # replay parity probe memo
        self._prefix_ok_memo: bool | None = None  # hit-path parity memo
        self._batch_ok_memo: bool | None = None   # batched-path memo
        self._disagg_ok_memo: bool | None = None  # wire seat-path memo
        self.last_recover_mode: str | None = None
        # programs that COMPUTE prompt rows (bucketed prefill, chunk
        # windows, batched prefill groups) — a pure-copy admission
        # (full prefix hit: segment slab + stored logits) dispatches
        # none, which tests assert on. Probes do not count.
        self.prefill_dispatches = 0

        # donating the cache + per-slot state lets XLA update them in
        # place (the cache is the dominant allocation); CPU jit can't
        # alias donated buffers and would warn every call. The donated
        # argnums per family are DECLARED in PROGRAM_DONATION — the
        # static donation audit checks that table against the traced
        # programs, so drift between intent and program shape fails CI.
        self._tpu = jax.devices()[0].platform == "tpu"
        self._state_donate = self._donate(
            "paged_step" if self._paged else "step"
        )
        # every program below is shared process-wide through
        # _shared_program keyed on this tuple + the family's own
        # statics (platform is constant within a process, so the
        # _donate() results are a function of the family name and
        # need not be keyed)
        self._prog_key = (
            self._cfg_key, self.tp, self._paged, self._block_size,
            self.max_total,
        )
        # one compiled step program per horizon ACTUALLY used: just
        # {K} static, {1, K} with the adaptive horizon
        self._step_fns: dict[int, object] = {}
        self._replay_fn = _shared_program(
            self._prog_key + ("replay",),
            lambda: jax.jit(
                build_replay_program(
                    make_paged_fwd1(self._fwd1) if self._paged
                    else self._fwd1
                ),
                donate_argnums=self._donate(
                    "paged_replay" if self._paged else "replay"
                ),
            ),
        )
        self._deact_fn = _shared_program(
            self._prog_key + ("deactivate",),
            lambda: jax.jit(
                build_deact_program(),
                donate_argnums=self._donate("deactivate"),
            ),
        )
        self._prefill_fns: dict[int, object] = {}
        self._chunk_fns: dict[int, object] = {}
        self._batch_prefill_fns: dict[tuple[int, int], object] = {}
        self._batch_hit_fns: dict[tuple[int, int], object] = {}
        self._insert_fn = None
        self._hit_insert_fn = None
        self._seg_store_fn = None
        self._seg_fetch_fn = None
        self._seg_import_fn = None
        self._logit_row_fn = None
        self._admit_donate = self._donate("prefill")
        # paged program caches. The SLAB prefill/insert/chunk caches
        # above stay live in paged mode too: the parity probes run the
        # slab programs on scratch state, and the chunked partial-hit
        # path computes suffix windows on batch-1 slab scratch in both
        # modes.
        self._paged_prefill_fns: dict[int, object] = {}
        self._paged_insert_fn = None
        self._paged_seg_fetch_fn = None
        self._paged_seg_import_fn = None
        self._block_copy_fn = None
        self._paged_admit_donate = self._donate("paged_prefill")
        # chunked-prefill piggyback: one fused program per (bucket, K)
        # actually used, gated by a construction-time bitwise parity
        # probe (ProbeCache'd) — probe failure falls back to blocking
        # admission prefill, never to wrong bytes
        self._piggyback_fns: dict[tuple[int, int], object] = {}
        if self._piggyback_requested and piggyback_parity is not False:
            ok = (
                True if piggyback_parity is True
                else self._probe_verdict(
                    "piggyback_parity",
                    self._probe_piggyback_parity,
                    n_slots=self.n_slots,
                    max_total=self.max_total,
                    max_bucket=self._max_bucket,
                    tp=self.tp,
                    paged=self._paged,
                    temperature=self.temperature,
                    top_k=self.top_k,
                    horizon=self.decode_horizon,
                )
            )
            if ok:
                self._piggyback = True
            else:
                log_event(
                    _log, "piggyback_parity_probe_failed",
                    fallback="blocking admission prefill",
                )

        # grammar-constrained decoding + per-request sampling surface:
        # per-slot FSM state / temperature / top-k / top-p / logit-bias
        # vectors threaded through masked step variants as traced data
        # (the adapter-id idiom, one compiled family for every mix),
        # behind the standing bitwise bar — masked_parity "auto" probes
        # the masked program against the base step on neutral surface
        # state once (ProbeCache'd) and leaves the surface off on
        # mismatch, so base traffic can never be perturbed.
        self._surface_requested = bool(sampling_surface)
        self._surface = False
        self._gtable: GrammarTable | None = None
        self.grammar_cache: GrammarCache | None = None
        self._masked_step_fns: dict[int, object] = {}
        self._masked_piggyback_fns: dict[tuple[int, int], object] = {}
        self._gstate_set_fn = None
        self._n_logprobs = min(MAX_TOP_LOGPROBS, cfg.vocab_size)
        # device copies of the grammar table, refreshed when the host
        # table's version moves (seat/evict between horizons only)
        self._gtab_version = -1
        self._dmask_tab = None
        self._dtrans_tab = None
        # host mirrors of the per-slot surface vectors: written at
        # admission, snapshotted per dispatch, re-seated at recovery
        # (the _slot_adapters contract). _slot_gstate holds each
        # slot's ABSOLUTE seat state for recovery re-walks — the live
        # value is the DEVICE-resident _dgstate carry.
        self._slot_gstate = np.zeros((n_slots,), np.int32)
        self._slot_temps = np.full(
            (n_slots,), self.temperature, np.float32
        )
        self._slot_topks = np.full(
            (n_slots,), int(self.top_k or 0), np.int32
        )
        self._slot_topps = np.ones((n_slots,), np.float32)
        self._slot_bias_idx = np.full(
            (n_slots, MAX_LOGIT_BIAS), -1, np.int32
        )
        self._slot_bias_val = np.zeros(
            (n_slots, MAX_LOGIT_BIAS), np.float32
        )
        self._dgstate = jnp.zeros((n_slots,), jnp.int32)
        if self._surface_requested and masked_parity is not False:
            if self.approx_top_k:
                # approx_max_k has no traced-k variant with identical
                # tie semantics, so the parity bar is unmeetable;
                # surface requests are rejected at submit instead
                log_event(_log, "sampling_surface_disabled",
                          reason="approx_top_k")
            else:
                self._gtable = GrammarTable(
                    max(2, int(grammar_states)), cfg.vocab_size
                )
                ok = (
                    True if masked_parity is True
                    else self._probe_verdict(
                        "masked_parity",
                        self._probe_masked_parity,
                        n_slots=self.n_slots,
                        max_total=self.max_total,
                        max_bucket=self._max_bucket,
                        tp=self.tp,
                        paged=self._paged,
                        piggyback=self._piggyback,
                        temperature=self.temperature,
                        top_k=self.top_k,
                        horizon=self.decode_horizon,
                        grammar_states=self._gtable.capacity,
                        n_logprobs=self._n_logprobs,
                    )
                )
                if ok:
                    self._surface = True
                    self.grammar_cache = (
                        grammar_cache
                        if isinstance(grammar_cache, GrammarCache)
                        else GrammarCache(grammar_cache)
                    )
                    self.metrics.registry.gauge(
                        "serve_grammar_table_rows",
                        "Grammar DFA table rows in use (incl. the "
                        "unconstrained sentinel row).",
                    ).set_function(lambda: self._gtable.rows_used)
                else:
                    self._gtable = None
                    log_event(
                        _log, "masked_parity_probe_failed",
                        fallback="sampling surface disabled",
                    )
        # arm attribution last: everything dispatched above was a probe
        self._attr_enabled = bool(attribution)

    # -- per-program-family attribution ------------------------------------

    def _attr(self, family: str, t0: float | None = None) -> None:
        """Mark one dispatched program for device-time attribution:
        ``(family, dispatch timestamp)`` joins the pending list, and
        ``_flush_attr`` prices it at the next horizon readback that
        PROVES it complete (device stream ordering). One attribute
        check + one list append on the hot path; nothing at all when
        disabled, and never a device sync either way."""
        if self._attr_enabled and not self._attr_suspend:
            self._pending_attr.append(
                (family, t0 if t0 is not None else time.perf_counter())
            )

    def _flush_attr(self, t_horizon: float, now: float) -> None:
        """Attribute every pending program dispatched no later than
        the just-synced horizon (``t_horizon`` is its dispatch stamp):
        the designated readback proved all of them complete, so each
        gets ``now - t0`` seconds — dispatch call to proven-complete,
        an honest upper bound that includes async overlap with host
        work rather than pretending per-program device intervals are
        observable without extra syncs. Entries are time-ordered
        (single engine thread), so this is a prefix flush."""
        n = 0
        for family, t0 in self._pending_attr:
            if t0 > t_horizon:
                break
            self.metrics.record_program(family, now - t0)
            n += 1
        if n:
            del self._pending_attr[:n]

    def _register_gauges(self) -> None:
        """Live-state gauges on the metrics registry: scrapes read
        engine state through callbacks, so the hot path never updates
        them."""
        reg = self.metrics.registry
        reg.gauge(
            "serve_kv_slots", "KV slot pool size (decode batch width).",
        ).set_function(lambda: self.n_slots)
        reg.gauge(
            "serve_kv_slots_active", "KV slots currently occupied.",
        ).set_function(lambda: self.pool.n_active)
        reg.gauge(
            "serve_kv_occupancy", "Occupied fraction of the slot pool.",
        ).set_function(lambda: self.pool.occupancy)
        reg.gauge(
            "serve_kv_slot_generations",
            "Total slot acquire count (slot-reuse churn).",
        ).set_function(
            lambda: sum(
                self.pool.generation(s) for s in range(self.n_slots)
            )
        )
        reg.gauge(
            "serve_kv_cache_bytes",
            "Device bytes of the pooled KV cache (global logical bytes "
            "under TP; precomputed host metadata, no device sync).",
        ).set_function(lambda: self.pool.nbytes())
        if self.pool.is_paged:
            reg.gauge(
                "serve_kv_blocks",
                "Allocatable KV blocks in the paged pool (sentinel "
                "excluded).",
            ).set_function(lambda: self.pool.n_blocks - 1)
            reg.gauge(
                "serve_kv_blocks_free",
                "KV blocks on the paged pool's free heap.",
            ).set_function(lambda: self.pool.n_free_blocks)
            reg.gauge(
                "serve_kv_blocks_in_use",
                "KV blocks held by slot tables or cached segments.",
            ).set_function(lambda: self.pool.n_blocks_in_use)
            reg.gauge(
                "serve_kv_block_size",
                "Rows per KV block (paged layout granule).",
            ).set_function(lambda: self.pool.block_size)
        reg.gauge(
            "serve_tp_degree",
            "Tensor-parallel width the engine is serving at (1 = "
            "single chip).",
        ).set_function(lambda: self.tp)
        reg.gauge(
            "serve_queue_depth", "Requests queued, not yet admitted.",
        ).set_function(lambda: len(self.scheduler))
        reg.gauge(
            "serve_lora_adapters",
            "Rows in the batched-LoRA adapter bank (0 = base only; "
            "row 0 is always the zero adapter).",
        ).set_function(lambda: self.n_adapters)
        if self.tenancy is not None:
            reg.gauge(
                "serve_tenants", "Configured tenants in the registry.",
            ).set_function(lambda: len(self.tenancy))
            # declare per-tenant SLOs so every /metrics render derives
            # serve_tenant_slo_burn{tenant} from the observed p99s
            for tid in self.tenancy.tenant_ids():
                t = self.tenancy.get(tid)
                if t.slo_p99_tpot_s is not None:
                    self.metrics.set_tenant_slo(tid, t.slo_p99_tpot_s)
        reg.gauge(
            "serve_decode_horizon_current",
            "Decode substeps fused into the next horizon dispatch "
            "(shrinks to 1 under adaptive_horizon while the queue is "
            "non-empty).",
        ).set_function(lambda: self.decode_horizon_current)
        if self._piggyback_requested:
            reg.gauge(
                "serve_prefill_budget_tokens",
                "Chunk tokens the piggyback scheduler may spend per "
                "decode horizon (--prefill-budget).",
            ).set_function(lambda: self.prefill_budget)
            reg.gauge(
                "serve_prefill_pending",
                "Admissions whose prefill is deferred across horizons "
                "(piggyback records holding a slot, not yet seated).",
            ).set_function(lambda: len(self._pending_prefills))
        if self.prefix_cache is not None:
            reg.gauge(
                "serve_prefix_segments", "Cached prefix segments.",
            ).set_function(lambda: self.prefix_cache.n_segments)
            reg.gauge(
                "serve_prefix_segments_pinned",
                "Segments pinned by in-flight requests (not evictable).",
            ).set_function(lambda: self.prefix_cache.n_pinned)
            reg.gauge(
                "serve_prefix_tokens_cached",
                "Prompt tokens held in cached segments.",
            ).set_function(lambda: self.prefix_cache.tokens_cached)
            reg.gauge(
                "serve_prefix_capacity_tokens",
                "Prefix-cache capacity in tokens (whole region slots).",
            ).set_function(lambda: self.prefix_cache.capacity_tokens)
            reg.gauge(
                "serve_prefix_region_bytes",
                "Device bytes of the prefix-cache segment region.",
            ).set_function(lambda: self.prefix_cache.nbytes())

    def _on_prefix_evict(self, seg) -> None:
        self.metrics.record_prefix_eviction()
        self.tracer.instant(
            ENGINE_TRACK, "prefix_evict", length=seg.length,
        )

    # -- compiled programs -------------------------------------------------
    #
    # Program BODIES live in the module-level build_*_program factories
    # so the static auditor traces the exact functions the engine jits;
    # these methods only cache the jitted callables per family key.

    def _donate(self, family: str) -> tuple[int, ...]:
        """Declared donation for one program family — active on TPU,
        () on CPU (jit can't alias donated buffers there)."""
        return PROGRAM_DONATION[family] if self._tpu else ()

    def _step_fn_for(self, horizon: int):
        """The compiled fused-step program for ``horizon`` substeps
        (cached per K — the adaptive horizon alternates between the
        configured K and 1)."""
        fn = self._step_fns.get(horizon)
        if fn is None:
            fn = _shared_program(
                self._prog_key + ("step", horizon, self.temperature,
                                  self.top_k, self.approx_top_k),
                lambda: jax.jit(
                    build_step_program(
                        make_paged_fwd1(self._fwd1) if self._paged
                        else self._fwd1,
                        horizon, self.temperature, self.top_k,
                        self.approx_top_k,
                    ),
                    donate_argnums=self._state_donate,
                ),
            )
            self._step_fns[horizon] = fn
        return fn

    def _prefill_fn(self, bucket: int):
        """Jitted fused admission program for one prompt bucket (see
        :func:`build_prefill_program`)."""
        fn = self._prefill_fns.get(bucket)
        if fn is None:
            # bucket only changes input shapes, so every bucket shares
            # ONE callable (jit traces per aval under the hood; the
            # per-bucket dict keys still express the compile surface)
            fn = _shared_program(
                self._prog_key + ("prefill",),
                lambda: jax.jit(
                    build_prefill_program(
                        self._do_prefill, self._init_caches,
                        self.max_total,
                    ),
                    donate_argnums=self._admit_donate,
                ),
            )
            self._prefill_fns[bucket] = fn
        return fn

    def _chunk_fn(self, bucket: int):
        """Jitted chunk-at-offset program for the long-prompt path
        (see :func:`build_chunk_program`)."""
        fn = self._chunk_fns.get(bucket)
        if fn is None:
            fn = _shared_program(
                self._prog_key + ("chunk",),
                lambda: jax.jit(build_chunk_program(self._fwd_chunk)),
            )
            self._chunk_fns[bucket] = fn
        return fn

    def _piggyback_fn(self, bucket: int, horizon: int):
        """Jitted fused chunk+decode piggyback program (see
        :func:`build_piggyback_program`). Like ``_chunk_fn``, one
        callable serves every bucket (jit retraces per chunk aval);
        the per-(bucket, K) dict keys express the compile surface the
        audit fences."""
        fn = self._piggyback_fns.get((bucket, horizon))
        if fn is None:
            fn = _shared_program(
                self._prog_key + (
                    "piggyback_step", horizon, self.temperature,
                    self.top_k, self.approx_top_k,
                ),
                lambda: jax.jit(
                    build_piggyback_program(
                        make_paged_fwd1(self._fwd1) if self._paged
                        else self._fwd1,
                        self._fwd_chunk, horizon, self.temperature,
                        self.top_k, self.approx_top_k,
                    ),
                    donate_argnums=self._donate(
                        "paged_piggyback_step" if self._paged
                        else "piggyback_step"
                    ),
                ),
            )
            self._piggyback_fns[(bucket, horizon)] = fn
        return fn

    def _masked_step_fn_for(self, horizon: int):
        """The compiled masked (grammar + sampling surface) step for
        ``horizon`` substeps. Engine-wide temperature/top_k are NOT in
        the shared-program key: they ride as per-slot traced vectors,
        so one compiled family serves every sampling mix."""
        fn = self._masked_step_fns.get(horizon)
        if fn is None:
            fn = _shared_program(
                self._prog_key + (
                    "masked_step", horizon, self._n_logprobs,
                ),
                lambda: jax.jit(
                    build_masked_step_program(
                        make_paged_fwd1(self._fwd1) if self._paged
                        else self._fwd1,
                        horizon, self._n_logprobs,
                    ),
                    donate_argnums=self._donate(
                        "paged_masked_step" if self._paged
                        else "masked_step"
                    ),
                ),
            )
            self._masked_step_fns[horizon] = fn
        return fn

    def _masked_piggyback_fn(self, bucket: int, horizon: int):
        """Jitted masked chunk+decode piggyback program (see
        :func:`build_masked_piggyback_program`); per-(bucket, K) dict
        keys express the compile surface the audit fences."""
        fn = self._masked_piggyback_fns.get((bucket, horizon))
        if fn is None:
            fn = _shared_program(
                self._prog_key + (
                    "masked_piggyback_step", horizon, self._n_logprobs,
                ),
                lambda: jax.jit(
                    build_masked_piggyback_program(
                        make_paged_fwd1(self._fwd1) if self._paged
                        else self._fwd1,
                        self._fwd_chunk, horizon, self._n_logprobs,
                    ),
                    donate_argnums=self._donate(
                        "paged_masked_piggyback_step" if self._paged
                        else "masked_piggyback_step"
                    ),
                ),
            )
            self._masked_piggyback_fns[(bucket, horizon)] = fn
        return fn

    def _gstate_set(self):
        """Jitted single-slot grammar-state write (see
        :func:`build_gstate_set_program`)."""
        if self._gstate_set_fn is None:
            self._gstate_set_fn = _shared_program(
                self._prog_key + ("gstate_set",),
                lambda: jax.jit(
                    build_gstate_set_program(),
                    donate_argnums=self._donate("gstate_set"),
                ),
            )
        return self._gstate_set_fn

    def _grammar_device_tables(self):
        """Device copies of the combined grammar mask/transition
        tables, refreshed exactly when the host table's version moved
        (seats and evictions happen between horizons, admission-side,
        so a dispatch never races this)."""
        gt = self._gtable
        if self._gtab_version != gt.version:
            self._dmask_tab = jnp.asarray(gt.mask_words)
            self._dtrans_tab = jnp.asarray(gt.trans)
            self._gtab_version = gt.version
        return self._dmask_tab, self._dtrans_tab

    def _insert(self):
        """Jitted slab insert + state set (see
        :func:`build_insert_program`)."""
        if self._insert_fn is None:
            self._insert_fn = _shared_program(
                self._prog_key + ("insert",),
                lambda: jax.jit(
                    build_insert_program(),
                    donate_argnums=self._donate("insert"),
                ),
            )
        return self._insert_fn

    def _hit_insert(self):
        """Jitted FULL-hit admission (see
        :func:`build_hit_insert_program`)."""
        if self._hit_insert_fn is None:
            # donates the pool state only — the region must survive
            self._hit_insert_fn = _shared_program(
                self._prog_key + ("hit_insert",),
                lambda: jax.jit(
                    build_hit_insert_program(),
                    donate_argnums=self._donate("hit_insert"),
                ),
            )
        return self._hit_insert_fn

    def _seg_fetch(self):
        """Jitted segment fetch (see
        :func:`build_seg_fetch_program`)."""
        if self._seg_fetch_fn is None:
            self._seg_fetch_fn = _shared_program(
                self._prog_key + ("seg_fetch",),
                lambda: jax.jit(build_seg_fetch_program()),
            )
        return self._seg_fetch_fn

    def _seg_store(self):
        """Jitted segment store (see
        :func:`build_seg_store_program`)."""
        if self._seg_store_fn is None:
            self._seg_store_fn = _shared_program(
                self._prog_key + ("seg_store",),
                lambda: jax.jit(
                    build_seg_store_program(),
                    donate_argnums=self._donate("seg_store"),
                ),
            )
        return self._seg_store_fn

    def _seg_import(self):
        """Jitted wire-segment import (see
        :func:`build_seg_import_program`)."""
        if self._seg_import_fn is None:
            self._seg_import_fn = _shared_program(
                self._prog_key + ("seg_import",),
                lambda: jax.jit(
                    build_seg_import_program(),
                    donate_argnums=self._donate("seg_import"),
                ),
            )
        return self._seg_import_fn

    def _logit_row(self):
        """Jitted (1, V) pending-logits row slice (see
        :func:`build_logit_row_program`)."""
        if self._logit_row_fn is None:
            self._logit_row_fn = _shared_program(
                self._prog_key + ("logit_row",),
                lambda: jax.jit(build_logit_row_program()),
            )
        return self._logit_row_fn

    def _paged_prefill_fn(self, bucket: int):
        """Jitted paged admission program for one prompt bucket (see
        :func:`build_paged_prefill_program`)."""
        fn = self._paged_prefill_fns.get(bucket)
        if fn is None:
            fn = _shared_program(
                self._prog_key + ("paged_prefill",),
                lambda: jax.jit(
                    build_paged_prefill_program(
                        self._do_prefill, self._init_caches,
                        self.max_total,
                    ),
                    donate_argnums=self._paged_admit_donate,
                ),
            )
            self._paged_prefill_fns[bucket] = fn
        return fn

    def _paged_insert(self):
        """Jitted paged insert + state set (see
        :func:`build_paged_insert_program`)."""
        if self._paged_insert_fn is None:
            self._paged_insert_fn = _shared_program(
                self._prog_key + ("paged_insert",),
                lambda: jax.jit(
                    build_paged_insert_program(),
                    donate_argnums=self._donate("paged_insert"),
                ),
            )
        return self._paged_insert_fn

    def _paged_seg_fetch(self):
        """Jitted paged segment fetch (see
        :func:`build_paged_seg_fetch_program`)."""
        if self._paged_seg_fetch_fn is None:
            self._paged_seg_fetch_fn = _shared_program(
                self._prog_key + ("paged_seg_fetch",),
                lambda: jax.jit(build_paged_seg_fetch_program()),
            )
        return self._paged_seg_fetch_fn

    def _paged_seg_import(self):
        """Jitted paged wire-segment import (see
        :func:`build_paged_seg_import_program`)."""
        if self._paged_seg_import_fn is None:
            self._paged_seg_import_fn = _shared_program(
                self._prog_key + ("paged_seg_import",),
                lambda: jax.jit(
                    build_paged_seg_import_program(),
                    donate_argnums=self._donate("paged_seg_import"),
                ),
            )
        return self._paged_seg_import_fn

    def _block_copy(self):
        """Jitted single-block copy (see
        :func:`build_block_copy_program`)."""
        if self._block_copy_fn is None:
            self._block_copy_fn = _shared_program(
                self._prog_key + ("block_copy",),
                lambda: jax.jit(
                    build_block_copy_program(),
                    donate_argnums=self._donate("block_copy"),
                ),
            )
        return self._block_copy_fn

    def _batch_prefill_fn(self, bucket: int, nb: int):
        """Jitted BATCHED admission prefill (see
        :func:`build_batch_prefill_program`)."""
        fn = self._batch_prefill_fns.get((bucket, nb))
        if fn is None:
            fn = _shared_program(
                self._prog_key + ("batch_prefill", nb),
                lambda: jax.jit(
                    build_batch_prefill_program(
                        self._do_prefill, self._init_caches,
                        self.max_total, nb,
                    ),
                    donate_argnums=self._admit_donate,
                ),
            )
            self._batch_prefill_fns[(bucket, nb)] = fn
        return fn

    def _batch_hit_fn(self, bucket: int, nb: int):
        """Jitted BATCHED partial-hit admission (see
        :func:`build_batch_hit_program`)."""
        fn = self._batch_hit_fns.get((bucket, nb))
        if fn is None:
            fn = _shared_program(
                self._prog_key + ("batch_hit", nb),
                lambda: jax.jit(
                    build_batch_hit_program(self._fwd_chunk, nb),
                    donate_argnums=self._admit_donate,
                ),
            )
            self._batch_hit_fns[(bucket, nb)] = fn
        return fn

    # -- bucketing ---------------------------------------------------------

    def _bucket_for(self, n: int) -> int:
        """Smallest power-of-two bucket >= n (caller ensures
        ``n <= self._max_bucket``)."""
        b = self._min_bucket
        while b < n:
            b *= 2
        return b

    def _chunk_schedule(self, n: int, start: int = 0
                        ) -> list[tuple[int, int, int]]:
        """(offset, real_len, bucket) chunks covering a prompt's rows
        start..n-1 through the power-of-two bucket programs. Every
        write window [offset, offset+bucket) must fit the pooled Tpad
        (a clamped ``dynamic_update_slice`` would SHIFT over real
        rows); when the padded tail would spill, the remainder is
        decomposed into exact power-of-two pieces plus one minimal
        padded tail, which always fits (pieces are sublane multiples,
        Tpad is a sublane multiple). ``start`` > 0 is the partial-hit
        suffix path — the first ``start`` rows came from a cached
        segment; the caller grain-aligns it (start % _min_bucket == 0)
        so the window-fit invariant carries over unchanged."""
        if start % self._min_bucket:
            raise AssertionError(
                f"chunk start {start} not {self._min_bucket}-aligned"
            )
        tpad = self.pool.tpad
        sched, t0, rem = [], start, n - start
        while rem > self._max_bucket:
            sched.append((t0, self._max_bucket, self._max_bucket))
            t0 += self._max_bucket
            rem -= self._max_bucket
        if rem:
            b = self._bucket_for(rem)
            if t0 + b <= tpad:
                sched.append((t0, rem, b))
            else:
                while rem:
                    if rem >= b:
                        sched.append((t0, b, b))
                        t0 += b
                        rem -= b
                    elif b > self._min_bucket:
                        b //= 2
                    else:
                        sched.append((t0, rem, b))
                        rem = 0
        for t0, _, b in sched:  # invariant: no clamped insert, ever
            if t0 + b > tpad:
                raise AssertionError(
                    f"chunk window [{t0}, {t0 + b}) spills Tpad {tpad}"
                )
        return sched

    # -- host-side loop ----------------------------------------------------

    def submit(self, req: Request) -> str:
        """Queue a request (see ``RequestScheduler.submit`` for the
        backpressure/admission contract). Rejections are labelled per
        tenant and per reason (quota vs queue depth) in the metrics."""
        if req.adapter >= max(1, self.n_adapters):
            raise AdmissionError(
                f"request {req.id}: adapter {req.adapter} outside the "
                f"loaded bank ({self.n_adapters} adapters)"
            )
        if getattr(req, "uses_sampling_surface", False):
            if not self._surface:
                raise AdmissionError(
                    f"request {req.id}: sampling-surface fields "
                    "(temperature/top_k/top_p/stop/logit_bias/"
                    "logprobs/response_format) need an engine built "
                    "with sampling_surface=True"
                )
            if req.response_format is not None:
                if req.eos_token is None:
                    raise AdmissionError(
                        f"request {req.id}: response_format requires "
                        "eos_token (grammars terminate by permitting "
                        "EOS in accepting states)"
                    )
                kind, spec = parse_response_format(req.response_format)
                try:
                    cg, how = self.grammar_cache.get_or_compile(
                        kind, spec,
                        default_token_bytes(self.cfg.vocab_size),
                        req.eos_token,
                        max_states=self._gtable.capacity - 1,
                    )
                except GrammarError as e:
                    self.metrics.record_grammar_compile("error")
                    raise AdmissionError(
                        f"request {req.id}: {e}"
                    ) from None
                self.metrics.record_grammar_compile(how)
                req._grammar = cg
        try:
            rid = self.scheduler.submit(req)
        except Backpressure as e:
            reason = ("quota" if isinstance(e, QuotaExceeded)
                      else "backpressure")
            self.metrics.record_backpressure()
            self.metrics.record_rejection(reason, tenant=req.tenant_id)
            self.tracer.instant(
                SCHEDULER_TRACK, "backpressure", req_id=req.id
            )
            log_event(_log, "request_rejected", level=logging.DEBUG,
                      req_id=req.id, reason=reason,
                      tenant=req.tenant_id or None)
            raise
        self.tracer.instant(SCHEDULER_TRACK, "submit", req_id=rid)
        log_event(_log, "request_submitted", level=logging.DEBUG,
                  req_id=rid, prompt_len=len(req.prompt),
                  max_new=req.max_new, tenant=req.tenant_id or None,
                  trace_id=req.trace_id or None)
        return rid

    @property
    def results(self) -> dict[str, np.ndarray]:
        """Terminal streams by request id: prompt + generated tokens
        (partial for CANCELLED/EXPIRED/FAILED-while-running). Bounded
        to ``results_cap`` entries, oldest evicted; ``pop_result``
        consumes an entry. Returns a snapshot — the live dict is shared
        with the engine thread."""
        with self._results_lock:
            return dict(self._results)

    def pop_result(self, req_id: str, default=None):
        """Remove and return a terminal stream (front-end consumption:
        read-once keeps the results dict from growing with traffic)."""
        with self._results_lock:
            return self._results.pop(req_id, default)

    @property
    def idle(self) -> bool:
        """True when no request is queued, mid-admission, decoding, or
        awaiting readback. ``pool.n_active`` (not the device mask) is
        what covers the admission window — the slot is acquired before
        the prefill runs, and a concurrent drain must not mistake that
        window for idleness; ``_admitting`` covers the few instructions
        between the scheduler pop and the acquire; ``_inflight`` covers
        the pipelined horizon whose tokens are still on device."""
        return (self.pool.n_active == 0 and self._admitting == 0
                and len(self.scheduler) == 0 and self._inflight is None)

    def cancel(self, req_id: str) -> bool:
        """Cancel by id: flags the request whether it is queued or
        decoding; the engine honors the flag within one horizon.
        Returns False when the id is unknown (already retired or never
        seen)."""
        for st in self._slots:
            if st is not None and st.req.id == req_id:
                st.req.cancel()
                return True
        return self.scheduler.cancel(req_id)

    def preempt_all(self) -> int:
        """Cancel every live and queued request (drain-deadline
        preemption: ``ServingServer.stop`` calls this when ``drain_s``
        elapses, so shutdown converges within one horizon instead of
        waiting out stragglers). Returns the number newly cancelled."""
        n = 0
        for st in self._slots:
            if st is not None and not st.req.cancelled:
                st.req.cancel()
                n += 1
        for rec in self._pending_prefills:
            if not rec.plan.req.cancelled:
                rec.plan.req.cancel()
                n += 1
        return n + self.scheduler.cancel_all()

    # -- live session migration --------------------------------------------
    #
    # Instead of preempting in-flight requests at the drain deadline,
    # the server can EXPORT each active slot as a KVSG frame extended
    # with generation state (tokens so far, remaining budget, the
    # slot's sampling-key words) and re-seat it on another replica
    # mid-generation. Byte parity holds by construction: the exported
    # slab covers rows [0, prompt+generated) — exactly the state a
    # crash-recovery replay of prompt+tokens rebuilds — the pending
    # logits row is the next token's sampling input, and fold_in(key,
    # position) sampling only needs the key words and the position to
    # continue the identical stream, greedy and sampled alike. The
    # receiving engine even recovers migrated sessions through its own
    # crashes: replay uses req.prompt + st.tokens + st.key_data, all
    # of which the seat installs.

    def export_sessions(self) -> list[dict]:
        """Snapshot every live slot for migration and free it WITHOUT
        a terminal status — each request stays RUNNING ("parked"), its
        waiting handler blocked until :meth:`complete_migrated` /
        :meth:`fail_migrated` settles it with the destination's
        outcome. ENGINE-LOOP THREAD ONLY (touches device state and
        slot bookkeeping); the server services it between steps. A
        slot whose snapshot fails is skipped and left live — it falls
        back to the ordinary preempt/recovery path."""
        if self._inflight is not None:
            # sync the pipelined horizon first so tokens-so-far and the
            # device logits row agree on the export position
            inflight, self._inflight = self._inflight, None
            self._process(inflight)
        now = time.perf_counter()
        out: list[dict] = []
        for slot, st in enumerate(self._slots):
            if st is None or st.req.kind not in ("generate", "kv_session"):
                continue
            if st.req.cancelled or st.req.expired(now):
                continue  # the lifecycle sweep owns these
            if getattr(st.req, "uses_sampling_surface", False):
                # sampling-surface state (grammar FSM position, stop
                # hold-back, bias rows) does not travel on the KVSG
                # wire; these slots drain locally via preempt/recovery
                continue
            t0 = time.perf_counter()
            req = st.req
            try:
                seq = np.concatenate(
                    [req.prompt, np.asarray(st.tokens, np.int32)]
                )
                if self._paged:
                    slab = self._paged_seg_fetch()(
                        self.pool.caches,
                        jnp.asarray(self.pool.table(slot)),
                    )
                else:
                    slab = self._seg_store()(
                        self.pool.alloc_region(1), self.pool.caches,
                        jnp.int32(0), jnp.int32(slot),
                    )
                leaves = [
                    np.asarray(leaf)  # lint: sync-ok migration export copies the live segment to host by design
                    for leaf in jax.tree.leaves(slab)
                ]
                lg = np.asarray(  # lint: sync-ok pending logits row rides the migration frame
                    self._logit_row()(self._logits, jnp.int32(slot))
                )
            except Exception as e:  # noqa: BLE001 — skip slot, keep exporting
                self.flight.record(
                    "migrate_export_failed", req_id=req.id, slot=slot,
                    error=str(e),
                )
                continue
            kd = np.asarray(st.key_data).reshape(-1)
            out.append({
                "req": req,
                "n_streamed": len(st.tokens),
                "config_hash": self.config_hash,
                "tokens": seq,
                "leaves": (slab_to_blocks(leaves, self._block_size)
                           if self._paged else leaves),
                "logits": lg,
                "layout": "paged" if self._paged else "slab",
                "block_size": self._block_size if self._paged else 0,
                "gen": {
                    "n_prompt": int(len(req.prompt)),
                    "tokens": [int(t) for t in st.tokens],
                    "max_new": int(req.max_new),
                    "eos_token": (None if req.eos_token is None
                                  else int(req.eos_token)),
                    "adapter": int(st.adapter),
                    "key_data": [int(x) for x in kd.tolist()],
                    "req_id": req.id,
                },
            })
            # park the request: free the slot with NO terminal status —
            # the destination's decode finishes it, complete_migrated
            # stores the result and wakes the handler
            self.pool.release(slot)
            if self.prefix_cache is not None:
                for seg in st.segs:
                    self.prefix_cache.unpin(seg)
            st.segs = []
            self._slots[slot] = None
            self._dactive = self._deact_fn(self._dactive, jnp.int32(slot))
            self.metrics.record_migration_out(
                len(st.tokens), time.perf_counter() - t0,
                tenant=req.tenant_id,
            )
            self.tracer.instant(
                slot_track(slot), "migrate_out", req_id=req.id,
                n_tokens=len(st.tokens),
            )
            self.flight.record(
                "migrate_out", req_id=req.id, slot=slot,
                n_generated=len(st.tokens),
                tenant=req.tenant_id or None,
            )
            log_event(_log, "session_exported", req_id=req.id, slot=slot,
                      n_generated=len(st.tokens),
                      tenant=req.tenant_id or None)
        return out

    def complete_migrated(self, req: Request, tokens,
                          n_streamed: int = 0) -> None:
        """Settle a parked (exported) request with the DESTINATION
        replica's finished token stream (full sequence: prompt +
        every generated token). Any HTTP/stop thread may call this —
        the slot is long freed, so only results/metrics/stream state
        is touched, all of it lock-guarded or thread-safe."""
        toks = np.asarray(tokens, np.int32).reshape(-1)
        new = [int(t) for t in toks[len(req.prompt):]]
        req.status = RequestStatus.FINISHED
        req.error = None
        self._store_result(req, new)
        self.metrics.record_migration_settled(ok=True,
                                              tenant=req.tenant_id)
        self.flight.record(
            "migrate_settled", req_id=req.id, ok=True,
            n_generated=len(new),
        )
        log_event(_log, "request_retired", req_id=req.id, slot=None,
                  status=req.status.value, n_tokens=len(new),
                  error=None, tenant=req.tenant_id or None,
                  kind="migrated")
        if req.stream is not None:
            for t in new[int(n_streamed):]:
                req.stream.put(t)
            req.stream.put(None)  # end-of-stream sentinel
        if req.done is not None:
            req.done.set()

    def fail_migrated(self, req: Request, error: str,
                      partial=None) -> None:
        """Settle a parked request whose migration did NOT land: the
        soft fallback to the pre-migration drain behavior (preempted →
        CANCELLED), with whatever tokens were generated before export
        preserved as the partial result."""
        req.status = RequestStatus.CANCELLED
        req.error = error
        self._store_result(
            req, [int(t) for t in (partial if partial is not None else ())]
        )
        self.metrics.record_migration_settled(ok=False,
                                              tenant=req.tenant_id)
        self.flight.record(
            "migrate_settled", req_id=req.id, ok=False, error=error,
        )
        log_event(_log, "request_retired", req_id=req.id, slot=None,
                  status=req.status.value, n_tokens=0, error=error,
                  tenant=req.tenant_id or None, kind="migrated")
        if req.stream is not None:
            req.stream.put(None)  # end-of-stream sentinel
        if req.done is not None:
            req.done.set()

    # -- retirement --------------------------------------------------------

    def _store_result(self, req: Request, tokens: list[int]) -> None:
        stream = np.concatenate([req.prompt, np.asarray(tokens, np.int32)])  # lint: sync-ok host token list, no device buffer involved
        with self._results_lock:
            note_access("engine.results", write=True)
            self._results[req.id] = stream
            while len(self._results) > self.results_cap:
                self._results.pop(next(iter(self._results)))

    def _retire(self, slot: int, status: RequestStatus, now: float,
                error: str | None = None, *,
                deactivate: bool = False) -> None:
        """Free a slot and move its request to a terminal status.
        ``deactivate`` also clears the slot's DEVICE active bit — needed
        when the device mask may still be live (cancel/expiry/
        quarantine); a FINISHED slot already deactivated in-program."""
        st = self._slots[slot]
        req = st.req
        req.status = status
        req.error = error
        self._store_result(req, st.tokens)
        if status is RequestStatus.FINISHED:
            decode_s = now - (st.t_first_token or now)
            self.metrics.record_finished(
                req.id, len(st.tokens), decode_s, tenant=req.tenant_id,
            )
            if (req.kind == "generate" and st.t_first_token is not None
                    and req.arrival_time is not None):
                # engine-measured request timing, surfaced in the HTTP
                # response: ttft_s is engine-local (scheduler arrival to
                # first token — excludes any upstream prefill/transfer
                # leg), decode_s is the wall time after the first token,
                # which lets a client recover true end-to-end TTFT as
                # (request wall - decode_s) without streaming
                req.timing = {
                    "ttft_s": st.t_first_token - req.arrival_time,
                    "decode_s": decode_s,
                }
        else:
            self.metrics.record_outcome(status, tenant=req.tenant_id)
        self.pool.release(slot)
        if self.prefix_cache is not None:
            for seg in st.segs:
                self.prefix_cache.unpin(seg)
        st.segs = []
        if self._surface:
            if st.stop_matcher is not None and req.stream is not None:
                # release the hold-back before the end-of-stream
                # sentinel (empty when a stop match consumed it)
                for t in st.stop_matcher.flush():
                    req.stream.put(t)
            self._clear_surface(slot, st)
        self._slots[slot] = None
        if deactivate:
            self._dactive = self._deact_fn(self._dactive, jnp.int32(slot))
        self.tracer.instant(
            slot_track(slot), status.value, ts=now, req_id=req.id,
            n_tokens=len(st.tokens),
        )
        log_event(_log, "request_retired", req_id=req.id, slot=slot,
                  status=status.value, n_tokens=len(st.tokens),
                  error=error, tenant=req.tenant_id or None,
                  trace_id=req.trace_id or None)
        if req.stream is not None:
            req.stream.put(None)  # end-of-stream sentinel
        if req.done is not None:
            req.done.set()

    def _retire_unadmitted(self, req: Request, status: RequestStatus,
                           error: str | None = None) -> None:
        """Terminal status for a request that never got a slot."""
        self._presplit_keys.pop(req.id, None)
        req.status = status
        req.error = error
        self.metrics.record_outcome(status, tenant=req.tenant_id)
        self.tracer.instant(
            SCHEDULER_TRACK, status.value, req_id=req.id
        )
        log_event(_log, "request_retired", req_id=req.id, slot=None,
                  status=status.value, n_tokens=0, error=error,
                  tenant=req.tenant_id or None,
                  trace_id=req.trace_id or None)
        if req.stream is not None:
            req.stream.put(None)  # end-of-stream sentinel
        if req.done is not None:
            req.done.set()

    def _finish(self, slot: int, now: float) -> None:
        self._retire(slot, RequestStatus.FINISHED, now)

    def _serve_embedding(self, req, now: float) -> None:
        """Serve an :class:`EmbeddingRequest` host-side at the
        admission boundary: no KV slot, no device dispatch — a zoo
        embedding model's table lookup — but the full request
        lifecycle (scheduler pop, per-tenant metrics, logs, ``done``),
        proving the serving machinery is model-agnostic."""
        t0 = time.perf_counter()
        emb = self.embedders.get(req.model)
        if emb is None:
            req.status = RequestStatus.FAILED
            req.error = (
                f"unknown embedding model {req.model!r} "
                f"(loaded: {sorted(self.embedders) or 'none'})"
            )
            self.metrics.record_outcome(RequestStatus.FAILED)
        else:
            vectors = {}
            for w in req.words:
                v = emb.get_word_vector(w)
                vectors[w] = None if v is None else np.asarray(v)  # lint: sync-ok host embedding table row, no device buffer
            req.result = vectors
            req.status = RequestStatus.FINISHED
            self.metrics.record_embedding(
                req.model, len(req.words),
                time.perf_counter() - t0, tenant=req.tenant_id,
            )
        self.tracer.instant(
            SCHEDULER_TRACK, "embedding", req_id=req.id,
            model=req.model, n_words=len(req.words),
        )
        log_event(_log, "request_retired", req_id=req.id, slot=None,
                  status=req.status.value, n_tokens=0,
                  error=req.error, tenant=req.tenant_id or None,
                  kind="embedding")
        if req.done is not None:
            req.done.set()

    # -- disaggregated prefill/decode --------------------------------------
    #
    # A PREFILL replica serves KVExportRequests: prefill the prompt
    # into a transiently held pool slot through the SAME bucketed
    # admission programs a monolithic admission dispatches — which is
    # what makes the transfer byte-exact by construction — snapshot the
    # segment slab plus the pending logits row to host, and release the
    # slot without decoding. A DECODE replica serves KVIngestRequests:
    # validate the wire-decoded slab against its own cache geometry,
    # land it in the prefix cache (region import in slab mode, private
    # block scatter in paged mode), and let the follow-up generate
    # full-hit — zero prefill dispatched for the covered prompt. Both
    # paths are gated by the disagg parity probe (_disagg_ok), and
    # every ingest decline is SOFT: the sender falls back to local
    # prefill, which is byte-identical anyway.

    def _serve_kv_export(self, req, now: float) -> None:
        """Serve a :class:`KVExportRequest` at the admission boundary.
        ``req.result`` gets the raw segment material (host arrays +
        layout metadata) ready for
        :func:`~deeplearning4j_tpu.serving.disagg.encode_segment` —
        framing happens on the HTTP thread, off the engine loop."""
        t0 = time.perf_counter()
        seq = np.asarray(req.prompt, np.int32)
        n = int(len(seq))
        if not self._disagg_ok():
            self._retire_unadmitted(
                req, RequestStatus.FAILED,
                "disagg wire parity probe failed on this backend",
            )
            return
        if n + 1 > self.max_total or n > self.pool.tpad:
            self._retire_unadmitted(
                req, RequestStatus.FAILED,
                f"prompt of {n} tokens cannot be exported "
                f"(max_total={self.max_total}, tpad={self.pool.tpad})",
            )
            return
        slot = self.pool.acquire()
        try:
            self._prefill_seq_into_slot(seq, slot, 1, _NO_EOS,
                                        adapter=req.adapter)
            if self._paged:
                slab = self._paged_seg_fetch()(
                    self.pool.caches,
                    jnp.asarray(self.pool.table(slot)),
                )
            else:
                # a 1-slot region IS the batch-1 slab every seat path
                # consumes; seg_store copies the pool slot into it
                slab = self._seg_store()(
                    self.pool.alloc_region(1), self.pool.caches,
                    jnp.int32(0), jnp.int32(slot),
                )
            leaves = [
                np.asarray(leaf)  # lint: sync-ok wire export copies the segment to host by design
                for leaf in jax.tree.leaves(slab)
            ]
            lg = np.asarray(  # lint: sync-ok pending logits row rides the wire frame
                self._logit_row()(self._logits, jnp.int32(slot))
            )
        except BaseException:
            # EngineCrash (or anything unexpected): the popped request
            # must not be dropped — requeue it before the supervisor
            # rebuilds state, exactly like an unseated admission plan.
            self.pool.release(slot)
            self.scheduler.requeue(req)
            raise
        # the slot was only a prefill staging area: clear its device
        # active bit (prefill armed it with budget 1) and free it
        self._dactive = self._deact_fn(self._dactive, jnp.int32(slot))
        self.pool.release(slot)
        req.result = {
            "config_hash": self.config_hash,
            "tokens": seq,
            "leaves": (slab_to_blocks(leaves, self._block_size)
                       if self._paged else leaves),
            "logits": lg,
            "layout": "paged" if self._paged else "slab",
            "block_size": self._block_size if self._paged else 0,
        }
        req.status = RequestStatus.FINISHED
        nbytes = sum(a.nbytes for a in leaves) + lg.nbytes
        self.metrics.record_kv_export(
            n, nbytes, time.perf_counter() - t0, tenant=req.tenant_id,
        )
        # a real admission span (named "prefill", prefix="export") so
        # the merged fleet trace chains controller dispatch -> export
        # prefill -> transfer -> decode ingest; the span id rides the
        # result so the HTTP layer parents its transfer span on it
        tctx = {}
        if self.tracer.enabled and req.trace_id:
            tctx = {"trace_id": req.trace_id, "span_id": new_span_id()}
            if req.parent_span_id:
                tctx["parent_span_id"] = req.parent_span_id
            req.result["span_id"] = tctx["span_id"]
        self.tracer.span(
            SCHEDULER_TRACK, "prefill", t0, time.perf_counter() - t0,
            req_id=req.id, prompt_len=n, prefix="export",
            nbytes=nbytes, **tctx,
        )
        log_event(_log, "request_retired", req_id=req.id, slot=None,
                  status=req.status.value, n_tokens=n, error=None,
                  tenant=req.tenant_id or None, kind="kv_export")
        if req.done is not None:
            req.done.set()

    def _serve_kv_ingest(self, req, now: float) -> None:
        """Seat a wire-delivered KV segment (req.segment: a
        :func:`~deeplearning4j_tpu.serving.disagg.decode_segment`
        dict) in the prefix cache so the follow-up generate request
        full-hits. Slotless and SOFT-failing: every decline reports
        ``{"stored": False, "reason": ...}`` and the sender falls back
        to local prefill — byte-identical by the parity bar, so a
        decline costs latency, never correctness."""
        t0 = time.perf_counter()
        seg_data = req.segment
        tokens = np.asarray(seg_data["tokens"], np.int32)
        n = int(len(tokens))
        cache = self.prefix_cache
        reason = None
        if cache is None:
            reason = "no prefix cache on this replica"
        elif seg_data.get("config_hash") != self.config_hash:
            reason = "model config hash mismatch"
        elif n < self._hit_grain or n > self.pool.tpad:
            reason = (f"segment of {n} tokens not seatable "
                      f"(grain={self._hit_grain}, tpad={self.pool.tpad})")
        elif not (self._prefix_reuse_ok() and self._disagg_ok()):
            reason = "parity probes reject wire seating on this backend"
        stored = False
        if reason is None:
            try:
                slab = self._wire_slab(seg_data)
            except WireError as e:
                reason = str(e)
            else:
                stored, reason = self._seat_wire_segment(
                    tokens, slab, seg_data["logits"]
                )
        req.result = {"stored": stored, "reason": reason, "n_tokens": n}
        req.status = RequestStatus.FINISHED
        self.metrics.record_kv_ingest(
            n, int(seg_data.get("nbytes", 0)),
            time.perf_counter() - t0, stored=stored,
            tenant=req.tenant_id,
        )
        tctx = {}
        if self.tracer.enabled and req.trace_id:
            tctx = {"trace_id": req.trace_id, "span_id": new_span_id()}
            if req.parent_span_id:
                tctx["parent_span_id"] = req.parent_span_id
        self.tracer.span(
            SCHEDULER_TRACK, "kv_ingest", t0,
            time.perf_counter() - t0, req_id=req.id,
            n_tokens=n, stored=stored, **tctx,
        )
        log_event(_log, "request_retired", req_id=req.id, slot=None,
                  status=req.status.value, n_tokens=n,
                  error=None if stored else reason,
                  tenant=req.tenant_id or None, kind="kv_ingest")
        if req.done is not None:
            req.done.set()

    def _wire_slab(self, seg_data: dict):
        """Validate a decoded frame's slab leaves against this
        engine's cache geometry and upload them as the batch-1 device
        pytree every seat path consumes. Raises :class:`WireError`
        (status 400) on any disagreement — geometry is derived from
        the config, so after the hash check a mismatch here means a
        corrupt or hand-rolled frame, not version skew."""
        shapes = jax.eval_shape(
            lambda: self._init_caches(1, self.max_total)
        )
        specs = jax.tree.leaves(shapes)
        leaves = seg_data["leaves"]
        if len(leaves) != len(specs):
            raise WireError(
                f"frame has {len(leaves)} cache leaves, engine "
                f"expects {len(specs)}"
            )
        up = []
        for i, (arr, spec) in enumerate(zip(leaves, specs)):
            if (tuple(arr.shape) != tuple(spec.shape)
                    or arr.dtype != spec.dtype):
                raise WireError(
                    f"leaf {i} is {arr.dtype.name}{tuple(arr.shape)}, "
                    f"engine expects "
                    f"{np.dtype(spec.dtype).name}{tuple(spec.shape)}"
                )
            up.append(jnp.asarray(arr))
        lg = seg_data["logits"]
        if (tuple(lg.shape) != (1, self.cfg.vocab_size)
                or lg.dtype != np.float32):
            raise WireError(
                f"logits are {lg.dtype.name}{tuple(lg.shape)}, engine "
                f"expects float32(1, {self.cfg.vocab_size})"
            )
        return jax.tree.unflatten(jax.tree.structure(shapes), up)

    def _seat_wire_segment(self, tokens: np.ndarray, slab,
                           logits_row) -> tuple[bool, str | None]:
        """Insert ``tokens`` in the prefix cache and back every new
        segment with the wire slab's rows. Returns ``(stored,
        reason)`` where ``stored`` means the follow-up generate will
        FULL-hit (full-length segment seated with its logits row)."""
        cache = self.prefix_cache
        n = int(len(tokens))
        seg, matched = cache.lookup(tokens)
        if seg is not None and matched == n and seg.logits is not None:
            return True, "already cached"
        segs = cache.insert(tokens)
        if not segs:
            return False, "cache declined (all segments pinned)"
        stored = False
        for seg in segs:
            if self._paged:
                if not self._back_paged_wire_segment(seg, slab):
                    # block allocation lost to admission pressure:
                    # un-cache rather than leave an unbacked segment
                    cache.drop(seg)
                    continue
            else:
                cache.region = self._seg_import()(
                    cache.region, slab, jnp.int32(seg.slot)
                )
            if seg.length == n:
                seg.logits = jnp.asarray(logits_row)
                stored = True
            self.metrics.record_prefix_insert()
            self.tracer.instant(
                ENGINE_TRACK, "prefix_insert", source="wire",
                length=seg.length,
            )
            cache.unpin(seg)
        return stored, None if stored else "segment backing failed"

    def _back_paged_wire_segment(self, seg, slab) -> bool:
        """Back one paged wire segment with freshly allocated private
        blocks holding the slab's rows — there is no donor slot to
        alias; the prefill happened on another replica. Rows past the
        segment's block span scatter to the sentinel block and vanish.
        False when the allocation loses to admission pressure."""
        need = self.pool.blocks_needed(seg.length)
        try:
            ids = self.pool.alloc_blocks(need)
        except RuntimeError:
            return False
        row = np.zeros((self.pool.blocks_per_slot,), np.int32)
        row[:need] = ids
        self.pool.caches = self._paged_seg_import()(
            self.pool.caches, jnp.asarray(row), slab
        )
        seg.block_ids = ids
        return True

    def _serve_kv_session(self, req, now: float) -> None:
        """Seat a LIVE migrated session (:class:`KVSessionRequest`) in
        a fresh slot mid-generation. The wire slab covers rows
        [0, prompt+generated); seating it with pos0 = that length and
        budget = remaining is EXACTLY the full-hit insert of a
        seq-so-far segment — an existing, parity-probed program family
        — after which the ordinary decode loop continues the stream.
        The migrated sampling-key words are installed verbatim so
        fold_in(key, position) draws the same randomness the source
        would have: byte-identical continuation, greedy AND sampled.
        Every decline is SOFT (``result["seated"] is False`` → the
        sender keeps its existing fail path for that session)."""
        t0 = time.perf_counter()
        seg_data = req.segment
        n0 = int(len(req.prompt))
        g = len(req.gen_tokens)
        m = n0 + g
        budget = int(req.max_new) - g
        kd = np.asarray(
            () if req.key_data is None else req.key_data,
            self._slot_keys.dtype,
        ).reshape(-1)
        reason = None
        if seg_data.get("config_hash") != self.config_hash:
            reason = "model config hash mismatch"
        elif not self._disagg_ok():
            reason = "disagg wire parity probe failed on this backend"
        elif int(len(seg_data["tokens"])) != m:
            reason = (f"frame covers {len(seg_data['tokens'])} tokens, "
                      f"session claims prompt {n0} + generated {g}")
        elif n0 + int(req.max_new) > self.max_total or m > self.pool.tpad:
            reason = (f"session of {m} tokens / budget {req.max_new} "
                      f"does not fit (max_total={self.max_total}, "
                      f"tpad={self.pool.tpad})")
        elif budget < 1:
            reason = "session has no remaining budget"
        elif kd.shape != self._slot_keys.shape[1:]:
            reason = (f"sampling key has {kd.shape} words, engine "
                      f"uses {self._slot_keys.shape[1:]}")
        if reason is None:
            try:
                slab = self._wire_slab(seg_data)
            except WireError as e:
                reason = str(e)
        if reason is not None:
            req.result = {"seated": False, "reason": reason}
            self.metrics.record_migration_in(
                g, time.perf_counter() - t0, seated=False,
                tenant=req.tenant_id,
            )
            self.flight.record(
                "migrate_declined", req_id=req.id, reason=reason,
            )
            self._retire_unadmitted(req, RequestStatus.FAILED, reason)
            return
        eos_tok = _NO_EOS if req.eos_token is None else int(req.eos_token)
        slot = self.pool.acquire()
        try:
            if self._paged:
                self._paged_ensure_blocks(slot, m + budget)
            insert = self._paged_insert() if self._paged else self._insert()
            self._set_state(insert(
                *self._state(), slab, jnp.asarray(seg_data["logits"]),
                jnp.int32(slot), jnp.int32(m), jnp.int32(budget),
                jnp.int32(eos_tok),
            ))
        except BaseException:
            # EngineCrash (or anything unexpected): the popped request
            # must not be dropped — requeue it before the supervisor
            # rebuilds state, exactly like an unseated admission plan.
            self.pool.release(slot)
            self.scheduler.requeue(req)
            raise
        # NO key split here: the slot continues the SOURCE's stream, so
        # the migrated key words are installed verbatim and this
        # engine's own key chain is untouched (its replay determinism
        # for locally admitted requests is unaffected).
        self._slot_keys[slot] = kd
        self._slot_adapters[slot] = req.adapter
        st = _SlotState(req, self.pool.generation(slot), kd, req.adapter)
        st.tokens = list(req.gen_tokens)
        st.t_first_token = now if g else None
        self._slots[slot] = st
        req.status = RequestStatus.RUNNING
        req.result = {"seated": True, "n_tokens": m}
        self.metrics.record_migration_in(
            g, time.perf_counter() - t0, seated=True,
            tenant=req.tenant_id,
        )
        tctx = {}
        if self.tracer.enabled and req.trace_id:
            tctx = {"trace_id": req.trace_id, "span_id": new_span_id()}
            if req.parent_span_id:
                tctx["parent_span_id"] = req.parent_span_id
        self.tracer.span(
            slot_track(slot), "migrate_in", t0,
            time.perf_counter() - t0, req_id=req.id,
            n_tokens=m, **tctx,
        )
        self.flight.record(
            "migrate_seated", req_id=req.id, slot=slot,
            n_generated=g, budget=budget,
            tenant=req.tenant_id or None,
        )
        log_event(_log, "session_seated", req_id=req.id, slot=slot,
                  prompt_len=n0, n_generated=g, budget=budget,
                  tenant=req.tenant_id or None)

    def _slot_of(self, req_id: str | None) -> int | None:
        if req_id is None:
            return None
        for slot, st in enumerate(self._slots):
            if st is not None and st.req.id == req_id:
                return slot
        return None

    # lint: hot-path
    def _sweep_lifecycle(self, now: float) -> None:
        """Retire cancelled / deadline-expired occupied slots (this is
        what bounds slot occupation to one horizon past cancel/expiry).
        Tokens still in flight for a swept slot are discarded at sync
        by the snapshot identity check."""
        for slot, st in enumerate(self._slots):
            if st is None:
                continue
            if st.req.cancelled:
                self._retire(slot, RequestStatus.CANCELLED, now,
                             deactivate=True)
            elif st.req.expired(now):
                self._retire(slot, RequestStatus.EXPIRED, now,
                             deactivate=True)
        # piggyback records hold a slot before seating — sweep them on
        # the same cadence so a cancelled/expired deferred admission
        # frees its slot (and pinned segment) within one horizon too
        if self._pending_prefills:
            kept: deque[_PendingPrefill] = deque()
            while self._pending_prefills:
                rec = self._pending_prefills.popleft()
                req = rec.plan.req
                if req.cancelled or req.expired(now):
                    self._drop_pending(
                        rec,
                        RequestStatus.CANCELLED if req.cancelled
                        else RequestStatus.EXPIRED,
                    )
                else:
                    kept.append(rec)
            self._pending_prefills = kept

    def _drop_pending(self, rec: _PendingPrefill,
                      status: RequestStatus,
                      error: str | None = None) -> None:
        """Release a deferred admission's slot + pinned segment and
        retire its request without seating. Executed chunks stay
        charged to the tenant's DRR deficit (the device time was
        spent); the un-executed remainder was already credited back at
        defer time."""
        pl = rec.plan
        if pl.seg is not None and self.prefix_cache is not None:
            self.prefix_cache.unpin(pl.seg)
            pl.seg = None
        self.pool.release(pl.slot)
        self._retire_unadmitted(pl.req, status, error)

    # -- admission ---------------------------------------------------------

    def _prefill_into_state(self, state, seq: np.ndarray, slot: int,
                            budget: int, eos_tok: int,
                            adapter: int = 0, paged: bool = False):
        """Land ``seq`` in ``slot`` of a pool-shaped ``state`` tuple
        through the bucketed prefill path and return the new state
        (pure w.r.t. engine attributes — the parity probes run it on
        scratch state). Dispatches O(1) programs for bucket-sized
        sequences and O(len/bucket) on the chunked long-prompt path.
        ``adapter`` selects the LoRA bank row (traced data, so every
        adapter shares the bucket's one compiled program). With
        ``paged`` the state's caches are the {"blocks", "tables"} dict
        and the two landing dispatches switch to the paged programs —
        everything else (bucketing, chunk windows, the batch-1 scratch
        compute) is byte-for-byte the slab path, which is what keeps
        the slab parity probes valid in a paged engine."""
        n = int(len(seq))
        ad = jnp.asarray([adapter], jnp.int32)
        insert = self._paged_insert() if paged else self._insert()
        if n == 0:
            # empty prompt: decode starts from uniform logits over a
            # zeroed slab, as the unbucketed prefill did
            tmp = self._init_caches(1, self.max_total)
            lg = jnp.zeros((1, self.cfg.vocab_size), jnp.float32)
            return insert(
                *state, tmp, lg, jnp.int32(slot), jnp.int32(0),
                jnp.int32(budget), jnp.int32(eos_tok),
            )
        if n <= self._max_bucket:
            b = self._bucket_for(n)
            pad = np.zeros((1, b), np.int32)
            pad[0, :n] = seq
            self.prefill_dispatches += 1
            self._attr("paged_prefill" if paged else "prefill")
            pf = self._paged_prefill_fn(b) if paged else self._prefill_fn(b)
            return pf(
                *state, self.params, jnp.asarray(pad), jnp.int32(n - 1),
                jnp.int32(slot), jnp.int32(n), jnp.int32(budget),
                jnp.int32(eos_tok), ad,
            )
        # chunked: walk the prompt through forward_chunk at bucket
        # sizes over a batch-1 scratch cache, then one slab insert —
        # a long admission compiles nothing new and never stalls
        # the decode loop on a monster program
        tmp = self._init_caches(1, self.max_total)
        lg = None
        for t0, ln, b in self._chunk_schedule(n):
            pad = np.zeros((1, b), np.int32)
            pad[0, :ln] = seq[t0:t0 + ln]
            self._attr("chunk")
            tmp, lg = self._chunk_fn(b)(
                self.params, tmp, jnp.asarray(pad), jnp.int32(t0),
                jnp.int32(ln - 1), ad,
            )
            self.prefill_dispatches += 1
        return insert(
            *state, tmp, lg, jnp.int32(slot), jnp.int32(n),
            jnp.int32(budget), jnp.int32(eos_tok),
        )

    def _caches_in(self):
        """The caches operand for the next dispatch. Paged mode
        rebuilds the {"blocks", "tables"} dict with a FRESH device
        mirror of the host block tables EVERY call — a stale mirror
        from before a release/re-admit would scatter a dead slot's
        decode rows into blocks the pool has since handed to someone
        else, so never cache this across pool mutations."""
        if self._paged:
            return {
                "blocks": self.pool.caches,
                "tables": jnp.asarray(self.pool.tables()),
            }
        return self.pool.caches

    def _caches_out(self, caches) -> None:
        """Re-own the caches a dispatch returned (the table mirror is
        discarded — the host tables are the source of truth)."""
        self.pool.caches = caches["blocks"] if self._paged else caches

    def _state(self):
        return (self._caches_in(), self._logits, self._dpos,
                self._dactive, self._dbudget, self._deos)

    def _set_state(self, out) -> None:
        (caches, self._logits, self._dpos, self._dactive,
         self._dbudget, self._deos) = out
        self._caches_out(caches)

    def _paged_ensure_blocks(self, slot: int, n_tokens: int) -> None:
        """Grow ``slot``'s block coverage to ``n_tokens`` rows with
        fresh private blocks (no-op when already covered — the aliased
        prefix-hit entries stay untouched). Clamped to the slab row
        bound: rows past Tpad cannot exist in either layout."""
        n_tokens = min(int(n_tokens), self.pool.tpad)
        need = self.pool.blocks_needed(n_tokens)
        have = int(np.count_nonzero(self.pool.table(slot)))
        if need > have:
            self.pool.alloc_slot_blocks(slot, n_tokens, start=have)

    def _prefill_seq_into_slot(self, seq: np.ndarray, slot: int,
                               budget: int, eos_tok: int,
                               adapter: int = 0) -> None:
        """Land ``seq`` (prompt, or prompt+replayed tokens) in ``slot``
        through the bucketed prefill path and set the slot's device
        state: position len(seq), active, ``budget`` tokens
        remaining."""
        if self._paged:
            # cover every row the slot can ever write BEFORE building
            # the state tuple, so the fresh table mirror includes the
            # allocation (rows past coverage scatter to the sentinel
            # and vanish)
            self._paged_ensure_blocks(slot, len(seq) + budget)
        self._set_state(self._prefill_into_state(
            self._state(), seq, slot, budget, eos_tok, adapter,
            paged=self._paged,
        ))

    def _check_prefill_faults(self, req: Request) -> bool:
        """The admission fault boundary under transient-retry
        supervision — one check per ADMISSION (not per chunk or per
        batch), so scripted chaos plans stay request-aligned. Returns
        False when the request is poisoned (caller fails it);
        ``EngineCrash`` propagates to the supervisor."""
        if self.faults is None:
            return True
        attempt, backoff = 0, self.retry_backoff_s
        while True:
            try:
                self.faults.check("prefill", req_id=req.id)
                return True
            except TransientFault as e:
                self.metrics.record_retry()
                attempt += 1
                if attempt > self.max_retries:
                    req.error = (
                        f"transient prefill fault persisted past "
                        f"{self.max_retries} retries: {e}"
                    )
                    return False
                time.sleep(backoff)
                backoff = min(backoff * 2, self.max_backoff_s)
            except PermanentFault as e:
                req.error = str(e)
                return False

    # -- admission parity probes -------------------------------------------

    def _scratch_state(self):
        """A pool-shaped device state tuple over freshly zeroed scratch
        buffers. The parity probes run the PRODUCTION compiled programs
        on it — so probing never touches live pool state (unlike the
        recovery-time chunked-replay probe, which runs on abandoned
        buffers) and compiles nothing the serving path won't reuse."""
        return (
            self._init_caches(self.n_slots, self.max_total),
            jnp.zeros((self.n_slots, self.cfg.vocab_size), jnp.float32),
            jnp.zeros((self.n_slots,), jnp.int32),
            jnp.zeros((self.n_slots,), bool),
            jnp.zeros((self.n_slots,), jnp.int32),
            jnp.full((self.n_slots,), _NO_EOS, jnp.int32),
        )

    @staticmethod
    def _states_equal(x, y) -> bool:
        return all(
            np.array_equal(np.asarray(a), np.asarray(b))
            for a, b in zip(jax.tree.leaves(x), jax.tree.leaves(y))
        )

    @staticmethod
    def _slot_rows(caches, slot: int, n: int):
        return [np.asarray(leaf[:, :, slot, :n])
                for leaf in jax.tree.leaves(caches)]

    def _probe_prefix_parity(self) -> bool:
        """One-time probe gating hit-path reuse (the admission-side
        mirror of ``chunked_replay="auto"``): is copy-cached-prefix-
        rows + chunk-computed suffix bitwise identical — KV rows AND
        logits — to the full bucketed prefill? On backends where the
        differently-scheduled programs agree only to float-
        reassociation level, every lookup is treated as a miss and
        admission falls back to full prefill."""
        L = self._min_bucket
        n = min(L + 3, self.max_total, self.pool.tpad)
        if n <= L:
            return False
        _disp = self.prefill_dispatches  # probes don't count
        self._attr_suspend += 1  # nor toward device-time attribution
        try:
            seq = ((1 + np.arange(n)) % self.cfg.vocab_size).astype(
                np.int32
            )
            # miss path: the full bucketed prefill
            sa = self._prefill_into_state(
                self._scratch_state(), seq, 0, 1, _NO_EOS
            )
            rows_a = self._slot_rows(sa[0], 0, n)
            lg_a = np.asarray(sa[1][0])
            # build the segment exactly as insert-on-completion does
            sb = self._prefill_into_state(
                self._scratch_state(), seq[:L], 0, 1, _NO_EOS
            )
            region = self.pool.alloc_region(1)
            region = self._seg_store()(
                region, sb[0], jnp.int32(0), jnp.int32(0)
            )
            # hit path: fetch + suffix chunks + insert
            tmp = self._seg_fetch()(region, jnp.int32(0))
            lg = None
            for t0, ln, b in self._chunk_schedule(n, start=L):
                pad = np.zeros((1, b), np.int32)
                pad[0, :ln] = seq[t0:t0 + ln]
                tmp, lg = self._chunk_fn(b)(
                    self.params, tmp, jnp.asarray(pad), jnp.int32(t0),
                    jnp.int32(ln - 1), jnp.zeros((1,), jnp.int32),
                )
            sc = self._insert()(
                *self._scratch_state(), tmp, lg, jnp.int32(0),
                jnp.int32(n), jnp.int32(1), jnp.int32(_NO_EOS),
            )
            rows_c = self._slot_rows(sc[0], 0, n)
            lg_c = np.asarray(sc[1][0])
            return bool(
                np.array_equal(lg_a, lg_c)
                and all(np.array_equal(a, c)
                        for a, c in zip(rows_a, rows_c))
            )
        finally:
            self.prefill_dispatches = _disp
            self._attr_suspend -= 1

    def _probe_piggyback_parity(self) -> bool:
        """One-time probe gating the piggyback path: does the FUSED
        chunk+decode program reproduce, bitwise, what the production
        step program and chunk program produce when run separately
        over identical inputs — every decode-state leaf, the sampled
        token matrix, the scratch slab, and the chunk logits row? The
        legs share no buffers, so this holds by construction unless
        the backend schedules the fused graph differently; when it
        does not hold bitwise, piggyback stays off and admission
        prefill keeps blocking (slow, never wrong)."""
        b = self._max_bucket
        k = self.decode_horizon
        n = self.n_slots
        vs = self.cfg.vocab_size
        _disp = self.prefill_dispatches  # probes don't count
        self._attr_suspend += 1  # nor toward device-time attribution
        try:
            def caches0():
                if self._paged:
                    # sentinel-only tables: same avals as the live
                    # operand (no new compile surface), every row
                    # scatters to block 0 identically on both sides
                    return {
                        "blocks": jax.tree.map(
                            jnp.zeros_like, self.pool.caches
                        ),
                        "tables": jnp.zeros(
                            (n, self.pool.blocks_per_slot), jnp.int32
                        ),
                    }
                return self._init_caches(n, self.max_total)

            def decode_state():
                # donation safety: each side gets fresh buffers
                lg = (
                    jnp.arange(n * vs, dtype=jnp.float32)
                    .reshape(n, vs) % 7.0
                )
                return (
                    caches0(), lg,
                    jnp.arange(n, dtype=jnp.int32) % 3,
                    jnp.ones((n,), bool),
                    jnp.full((n,), 5, jnp.int32),
                    jnp.full((n,), _NO_EOS, jnp.int32),
                )

            keys = np.arange(
                self._slot_keys.size, dtype=self._slot_keys.dtype
            ).reshape(self._slot_keys.shape)
            ad = jnp.zeros((n,), jnp.int32)
            ctoks = jnp.asarray(
                ((1 + np.arange(b)) % vs).astype(np.int32)[None, :]
            )
            cad = jnp.zeros((1,), jnp.int32)
            # separate: the production step + chunk programs
            out_a = self._step_fn_for(k)(
                self.params, *decode_state(), jnp.asarray(keys), ad
            )
            tmp_a, lg_a = self._chunk_fn(b)(
                self.params, self._init_caches(1, self.max_total),
                ctoks, jnp.int32(0), jnp.int32(b - 1), cad,
            )
            # fused: one piggyback dispatch over identical inputs
            out_b = self._piggyback_fn(b, k)(
                self.params, *decode_state(), jnp.asarray(keys), ad,
                self._init_caches(1, self.max_total), ctoks,
                jnp.int32(0), jnp.int32(b - 1), cad,
            )
            return bool(
                self._states_equal(out_a, out_b[:6])
                and self._states_equal(tmp_a, out_b[6])
                and np.array_equal(np.asarray(lg_a),
                                   np.asarray(out_b[7]))
            )
        finally:
            self.prefill_dispatches = _disp
            self._attr_suspend -= 1

    def _probe_masked_parity(self) -> bool:
        """One-time probe gating the sampling surface: does the MASKED
        step program — grammar mask, logit bias, per-slot temperature/
        top-k/top-p, logprob gathers all folded behind jnp.where at
        their neutral values — reproduce, bitwise, the production step
        program over identical inputs? Every decode-state leaf and the
        token matrix must match, and the FSM state vector must hold at
        the unconstrained sentinel. When piggyback is armed the masked
        piggyback variant is held to the same bar against the plain
        one. Failure leaves the surface off: base traffic keeps its
        exact bytes and surface requests 400 at submit (never wrong,
        just absent)."""
        k = self.decode_horizon
        n = self.n_slots
        vs = self.cfg.vocab_size
        _disp = self.prefill_dispatches  # probes don't count
        self._attr_suspend += 1  # nor toward device-time attribution
        try:
            def caches0():
                if self._paged:
                    return {
                        "blocks": jax.tree.map(
                            jnp.zeros_like, self.pool.caches
                        ),
                        "tables": jnp.zeros(
                            (n, self.pool.blocks_per_slot), jnp.int32
                        ),
                    }
                return self._init_caches(n, self.max_total)

            def decode_state():
                # donation safety: each side gets fresh buffers
                lg = (
                    jnp.arange(n * vs, dtype=jnp.float32)
                    .reshape(n, vs) % 7.0
                )
                return (
                    caches0(), lg,
                    jnp.arange(n, dtype=jnp.int32) % 3,
                    jnp.ones((n,), bool),
                    jnp.full((n,), 5, jnp.int32),
                    jnp.full((n,), _NO_EOS, jnp.int32),
                )

            keys = np.arange(
                self._slot_keys.size, dtype=self._slot_keys.dtype
            ).reshape(self._slot_keys.shape)
            ad = jnp.zeros((n,), jnp.int32)
            # neutral surface vectors: the exact values _seat_surface
            # writes for a request that sets nothing
            temps = jnp.full((n,), self.temperature, jnp.float32)
            topks = jnp.full((n,), int(self.top_k or 0), jnp.int32)
            topps = jnp.ones((n,), jnp.float32)
            bidx = jnp.full((n, MAX_LOGIT_BIAS), -1, jnp.int32)
            bval = jnp.zeros((n, MAX_LOGIT_BIAS), jnp.float32)
            gstate = jnp.zeros((n,), jnp.int32)
            mask_tab, trans_tab = self._grammar_device_tables()
            out_a = self._step_fn_for(k)(
                self.params, *decode_state(), jnp.asarray(keys), ad
            )
            out_b = self._masked_step_fn_for(k)(
                self.params, *decode_state(), gstate,
                jnp.asarray(keys), ad, temps, topks, topps, bidx,
                bval, mask_tab, trans_tab,
            )
            ok = bool(
                self._states_equal(out_a[:5], out_b[:5])
                and np.array_equal(np.asarray(out_a[5]),
                                   np.asarray(out_b[6][:, :, 0]))
                and np.array_equal(np.asarray(out_b[5]),
                                   np.zeros((n,), np.int32))
            )
            if ok and self._piggyback:
                b = self._max_bucket
                ctoks = jnp.asarray(
                    ((1 + np.arange(b)) % vs).astype(np.int32)[None, :]
                )
                cad = jnp.zeros((1,), jnp.int32)
                out_c = self._piggyback_fn(b, k)(
                    self.params, *decode_state(), jnp.asarray(keys),
                    ad, self._init_caches(1, self.max_total), ctoks,
                    jnp.int32(0), jnp.int32(b - 1), cad,
                )
                out_d = self._masked_piggyback_fn(b, k)(
                    self.params, *decode_state(), gstate,
                    jnp.asarray(keys), ad, temps, topks, topps, bidx,
                    bval, mask_tab, trans_tab,
                    self._init_caches(1, self.max_total), ctoks,
                    jnp.int32(0), jnp.int32(b - 1), cad,
                )
                ok = bool(
                    self._states_equal(out_c[:5], out_d[:5])
                    and np.array_equal(np.asarray(out_c[5]),
                                       np.asarray(out_d[6][:, :, 0]))
                    and self._states_equal(out_c[6], out_d[7])
                    and np.array_equal(np.asarray(out_c[7]),
                                       np.asarray(out_d[8]))
                )
            return ok
        finally:
            self.prefill_dispatches = _disp
            self._attr_suspend -= 1

    def _probe_batch_parity(self) -> bool:
        """One-time probe gating batched admission: do the batched
        same-bucket prefill program (vector last_idx) and — when the
        prefix cache reuses — the batched partial-hit program
        reproduce, bitwise, the full device state the serial
        per-request paths produce?"""
        if self.n_slots < 2:
            return False
        n0 = min(self._min_bucket, self.max_total)
        if n0 < 2:
            return False
        n1 = n0 - 1
        b = self._bucket_for(n0)
        _disp = self.prefill_dispatches  # probes don't count
        self._attr_suspend += 1  # nor toward device-time attribution
        try:
            vs = self.cfg.vocab_size
            seq0 = ((1 + np.arange(n0)) % vs).astype(np.int32)
            seq1 = ((2 + np.arange(n1)) % vs).astype(np.int32)
            sa = self._prefill_into_state(
                self._scratch_state(), seq0, 0, 3, _NO_EOS
            )
            sa = self._prefill_into_state(sa, seq1, 1, 2, _NO_EOS)
            prompts = np.zeros((2, b), np.int32)
            prompts[0, :n0] = seq0
            prompts[1, :n1] = seq1
            sb = self._batch_prefill_fn(b, 2)(
                *self._scratch_state(), self.params,
                jnp.asarray(prompts),
                jnp.asarray([n0 - 1, n1 - 1], np.int32),
                jnp.asarray([0, 1], np.int32),
                jnp.asarray([n0, n1], np.int32),
                jnp.asarray([3, 2], np.int32),
                jnp.asarray([_NO_EOS, _NO_EOS], np.int32),
                jnp.zeros((2,), jnp.int32),
            )
            if not self._states_equal(sa, sb):
                return False
            if self.prefix_cache is None or not self._prefix_reuse_ok():
                return True
            # batched partial hits: two suffixes behind one cached
            # prefix, serial fetch+chunk+insert vs one batched program
            L = self._min_bucket
            lns = (2, 1)
            bs = self._bucket_for(max(lns))
            if (L + max(lns) > self.max_total
                    or L + bs > self.pool.tpad):
                return True  # geometry can't form hit groups anyway
            prefix = ((3 + np.arange(L)) % vs).astype(np.int32)
            sfx = [((5 + r + np.arange(ln)) % vs).astype(np.int32)
                   for r, ln in enumerate(lns)]
            sp = self._prefill_into_state(
                self._scratch_state(), prefix, 0, 1, _NO_EOS
            )
            region = self.pool.alloc_region(1)
            region = self._seg_store()(
                region, sp[0], jnp.int32(0), jnp.int32(0)
            )
            sh = self._scratch_state()
            for r, ln in enumerate(lns):
                tmp = self._seg_fetch()(region, jnp.int32(0))
                pad = np.zeros((1, bs), np.int32)
                pad[0, :ln] = sfx[r]
                tmp, lg = self._chunk_fn(bs)(
                    self.params, tmp, jnp.asarray(pad), jnp.int32(L),
                    jnp.int32(ln - 1), jnp.zeros((1,), jnp.int32),
                )
                sh = self._insert()(
                    *sh, tmp, lg, jnp.int32(r), jnp.int32(L + ln),
                    jnp.int32(2), jnp.int32(_NO_EOS),
                )
            toks = np.zeros((2, bs), np.int32)
            for r, ln in enumerate(lns):
                toks[r, :ln] = sfx[r]
            sbh = self._batch_hit_fn(bs, 2)(
                *self._scratch_state(), self.params, region,
                jnp.asarray([0, 0], np.int32), jnp.asarray(toks),
                jnp.int32(L),
                jnp.asarray([ln - 1 for ln in lns], np.int32),
                jnp.asarray([0, 1], np.int32),
                jnp.asarray([L + ln for ln in lns], np.int32),
                jnp.asarray([2, 2], np.int32),
                jnp.asarray([_NO_EOS, _NO_EOS], np.int32),
                jnp.zeros((2,), jnp.int32),
            )
            return self._states_equal(sh, sbh)
        finally:
            self.prefill_dispatches = _disp
            self._attr_suspend -= 1

    def _probe_verdict(self, name: str, compute, cfg=None,
                       **geometry) -> bool:
        """Gate one parity probe through the on-disk verdict cache
        (when configured): a persisted verdict for the same (probe,
        config, backend, geometry) skips the probe's device dispatches
        entirely — verdicts are pure functions of those inputs, so a
        second engine instance constructs probe-free. A fresh verdict
        is computed and persisted. ``probes_run`` /
        ``probes_from_cache`` record which path each probe took."""
        cfg_json = (cfg if cfg is not None else self.cfg).to_json()
        key = None
        if self._probe_cache is not None:
            key = probe_key(name, cfg_json, **geometry)
            v = self._probe_cache.get(key)
            if v is not None:
                self.probes_from_cache.append(name)
                log_event(_log, "parity_probe_cached", probe=name, ok=v)
                return v
        v = bool(compute())
        self.probes_run.append(name)
        if self._probe_cache is not None:
            self._probe_cache.put(key, v)
        return v

    def _probe_tp_parity(self, cfg, params, mesh) -> bool:
        """One-time probe gating tensor-parallel serving — the
        construction-time mirror of ``chunked_replay="auto"``: do the
        SHARDED prefill and decode programs reproduce, bitwise, the
        single-chip logits on scratch state? The exact-TP layout
        preserves every reduction's flop order by construction (see
        ``serving_tp_shardings``), so this should pass on any backend —
        the probe is the standing bar that proves it on THIS one.
        Bitwise-equal logits at every step make greedy AND sampled
        streams identical (sampling is a replicated pure function of
        logits, slot key and position)."""
        total = int(min(self.max_total, 32))
        n = min(8, total - 4)
        if n < 1:
            return False

        seq = ((1 + np.arange(n)) % cfg.vocab_size).astype(np.int32)
        prompt = jnp.asarray(seq[None])

        def stream(tp_mesh):
            fwd1, init_caches, do_prefill, cast_params = _decode_builder(
                cfg, tp_mesh=tp_mesh
            )
            p = params if tp_mesh is None else place_serving_tp_params(
                tp_mesh, params, cfg
            )
            p = jax.jit(cast_params)(p)  # lint: retrace-ok one-shot parity probe
            caches, logits = jax.jit(do_prefill)(  # lint: retrace-ok one-shot probe
                p, init_caches(1, total), prompt
            )
            out = [np.asarray(logits)]
            pos = jnp.full((1,), n, jnp.int32)
            step = jax.jit(
                lambda pp, c, lg, po: fwd1(
                    pp, c, jnp.argmax(lg, axis=-1).astype(jnp.int32), po
                )
            )
            for _ in range(3):
                logits, caches = step(p, caches, logits, pos)
                pos = pos + 1
                out.append(np.asarray(logits))
            return out

        try:
            ref = stream(None)
            tpo = stream(mesh)
        except Exception as e:  # pragma: no cover - backend-specific
            # conservative: a backend that cannot even run the probe
            # (e.g. the single-chip reference does not fit) serves
            # unsharded unless tp_parity=True overrides
            log_event(_log, "tp_parity_probe_error", error=repr(e))
            return False
        return all(np.array_equal(a, b) for a, b in zip(ref, tpo))

    def _probe_lora_zero(self) -> bool:
        """One-time probe gating batched LoRA — the bank-attach mirror
        of ``tp_parity``: with the bank riding in params, does adapter
        index 0 reproduce, bitwise, the bank-free base model through
        prefill + greedy decode? The forward SELECTS the base
        activations for adapter-0 rows (``jnp.where``, never ``+ 0.0``
        — adding a zero delta could flip ``-0.0`` sign bits), so this
        should pass on any backend; the probe is the standing bar that
        proves it on THIS one. Bitwise-equal logits make greedy AND
        sampled adapter-0 streams identical to base (sampling is a pure
        function of logits, slot key and position)."""
        total = int(min(self.max_total, 32))
        n = min(8, total - 4)
        if n < 1:
            return False
        seq = ((1 + np.arange(n)) % self.cfg.vocab_size).astype(np.int32)
        prompt = jnp.asarray(seq[None])
        base = {k: v for k, v in self.params.items() if k != "lora"}
        ad = jnp.zeros((1,), jnp.int32)

        def stream(p):
            caches, logits = jax.jit(self._do_prefill)(  # lint: retrace-ok one-shot parity probe
                p, self._init_caches(1, total), prompt, adapter=ad
            )
            out = [np.asarray(logits)]
            pos = jnp.full((1,), n, jnp.int32)
            step = jax.jit(  # lint: retrace-ok one-shot parity probe
                lambda pp, c, lg, po: self._fwd1(
                    pp, c, jnp.argmax(lg, axis=-1).astype(jnp.int32),
                    po, adapter=ad,
                )
            )
            for _ in range(3):
                logits, caches = step(p, caches, logits, pos)
                pos = pos + 1
                out.append(np.asarray(logits))
            return out

        try:
            ref = stream(base)
            lz = stream(self.params)
        except Exception as e:  # pragma: no cover - backend-specific
            log_event(_log, "lora_parity_probe_error", error=repr(e))
            return False
        return all(np.array_equal(a, b) for a, b in zip(ref, lz))

    def _probe_paged_parity(self, block_size: int) -> bool:
        """One-time probe gating the paged KV layout — the block-table
        mirror of ``tp_parity``: does the paged step (block gather,
        IDENTICAL fwd1 compute, block scatter) reproduce, bitwise, the
        slab step's logits on scratch state? Both legs run batch-2 over
        the same prefilled rows, with the paged tables SHUFFLED (blocks
        land scattered through the pool, as after churn) and one block
        ALIASED between the rows (the shared-prefix shape — both rows
        write identical bytes into it, since their inputs are
        identical). Bitwise-equal logits at every step make greedy AND
        sampled streams identical (sampling is a pure function of
        logits, slot key and position). Runs before the pool exists, on
        self-built scratch blocks."""
        total = int(min(self.max_total, 32))
        n = min(8, total - 4)
        if n < 1:
            return False
        seq = ((1 + np.arange(n)) % self.cfg.vocab_size).astype(np.int32)
        prompt = jnp.asarray(seq[None])
        try:
            shapes = jax.eval_shape(
                lambda: self._init_caches(1, total)
            )
            tpad = jax.tree.leaves(shapes)[0].shape[3]
            if tpad % block_size:
                return False
            bps = tpad // block_size
            tmp, lg = jax.jit(self._do_prefill)(  # lint: retrace-ok one-shot parity probe
                self.params, self._init_caches(1, total), prompt
            )
            # slab leg: the prefilled slab landed in both rows of a
            # 2-slot pool
            slab = self._init_caches(2, total)
            place = jax.jit(  # lint: retrace-ok one-shot parity probe
                lambda c, t, s: jax.tree.map(
                    lambda cc, tt: lax.dynamic_update_slice(
                        cc, tt, (0, 0, s, 0, 0)
                    ),
                    c, t,
                )
            )
            for s in (0, 1):
                slab = place(slab, tmp, jnp.int32(s))
            # paged leg: the same rows scattered through shuffled
            # tables, rows 0 and 1 aliasing one shared block
            perm = np.random.default_rng(0).permutation(2 * bps) + 1
            tables = perm.reshape(2, bps).astype(np.int32)
            tables[1, 0] = tables[0, 0]
            blocks = jax.tree.map(
                lambda sh: jnp.zeros(
                    (sh.shape[0], sh.shape[1], 2 * bps + 1,
                     block_size, sh.shape[4]),
                    sh.dtype,
                ),
                shapes,
            )
            dtab = jnp.asarray(tables)
            scatter = jax.jit(paged_slot_scatter)  # lint: retrace-ok one-shot parity probe
            for s in (0, 1):
                blocks = scatter(blocks, dtab[s], tmp)
            pcaches = {"blocks": blocks, "tables": dtab}

            sstep = jax.jit(  # lint: retrace-ok one-shot parity probe
                lambda c, l, p: self._fwd1(
                    self.params, c,
                    jnp.argmax(l, axis=-1).astype(jnp.int32), p,
                )
            )
            pfwd1 = make_paged_fwd1(self._fwd1)
            pstep = jax.jit(  # lint: retrace-ok one-shot parity probe
                lambda c, l, p: pfwd1(
                    self.params, c,
                    jnp.argmax(l, axis=-1).astype(jnp.int32), p,
                )
            )
            lg2 = jnp.concatenate([lg, lg], axis=0)
            slg, plg = lg2, lg2
            pos = jnp.full((2,), n, jnp.int32)
            for _ in range(3):
                slg, slab = sstep(slab, slg, pos)
                plg, pcaches = pstep(pcaches, plg, pos)
                pos = pos + 1
                if not np.array_equal(np.asarray(slg), np.asarray(plg)):
                    return False
            return True
        except Exception as e:  # pragma: no cover - backend-specific
            log_event(_log, "paged_parity_probe_error", error=repr(e))
            return False

    def _prefix_reuse_ok(self) -> bool:
        if self.prefix_cache is None:
            return False
        if self._prefix_ok_memo is None:
            self._prefix_ok_memo = self._probe_verdict(
                "prefix_reuse", self._probe_prefix_parity,
                n_slots=self.n_slots, max_total=self.max_total,
                min_bucket=self._min_bucket, tpad=self.pool.tpad,
                tp=self.tp,
            )
            log_event(_log, "prefix_parity_probe",
                      ok=self._prefix_ok_memo)
            self.tracer.instant(ENGINE_TRACK, "prefix_parity_probe",
                                ok=self._prefix_ok_memo)
        return self._prefix_ok_memo

    def _probe_disagg_parity(self) -> bool:
        """One-time probe gating the disaggregated wire path: does a
        segment moved prefill -> seg_store -> host wire frame (a real
        ``encode_segment``/``decode_segment`` byte round-trip) ->
        device import -> zero-prefill hit insert reproduce, bitwise,
        the KV rows AND logits of the direct prefill? Paged engines
        additionally push the slab through the block scatter/gather
        pair ingest uses. On refusal both export and ingest decline
        and the fleet falls back to local prefill everywhere."""
        n = min(self._min_bucket + 3, self.max_total - 1,
                self.pool.tpad)
        if n < 1:
            return False
        _disp = self.prefill_dispatches  # probes don't count
        self._attr_suspend += 1  # nor toward device-time attribution
        try:
            seq = ((1 + np.arange(n)) % self.cfg.vocab_size).astype(
                np.int32
            )
            sa = self._prefill_into_state(
                self._scratch_state(), seq, 0, 1, _NO_EOS
            )
            rows_a = self._slot_rows(sa[0], 0, n)
            lg_a = np.asarray(sa[1][0])
            # export side: slab snapshot + pending logits row, to host
            region = self._seg_store()(
                self.pool.alloc_region(1), sa[0],
                jnp.int32(0), jnp.int32(0),
            )
            leaves = [
                np.asarray(leaf)  # lint: sync-ok probe round-trips through host bytes by design
                for leaf in jax.tree.leaves(region)
            ]
            lg = np.asarray(  # lint: sync-ok probe round-trips through host bytes by design
                self._logit_row()(sa[1], jnp.int32(0))
            )
            # the actual wire: frame the bytes and re-decode them
            if self._paged:
                wire_leaves = slab_to_blocks(leaves, self._block_size)
                layout, bs = "paged", self._block_size
            else:
                wire_leaves, layout, bs = leaves, "slab", 0
            frame = encode_segment(
                config_hash=self.config_hash, tokens=seq,
                leaves=wire_leaves, logits=lg,
                layout=layout, block_size=bs,
            )
            try:
                dec = decode_segment(frame, expect_hash=self.config_hash)
                slab = self._wire_slab(dec)
            except WireError:
                return False
            if self._paged:
                # land and re-fetch through a scratch block store, as
                # ingest will (rows past n scatter to the sentinel)
                bps = self.pool.tpad // self._block_size
                blocks = jax.tree.map(
                    lambda sh: jnp.zeros(
                        (sh.shape[0], sh.shape[1], bps + 1,
                         self._block_size, sh.shape[4]),
                        sh.dtype,
                    ),
                    jax.eval_shape(
                        lambda: self._init_caches(1, self.max_total)
                    ),
                )
                row = jnp.asarray(np.arange(1, bps + 1, dtype=np.int32))
                blocks = self._paged_seg_import()(blocks, row, slab)
                slab = self._paged_seg_fetch()(blocks, row)
            region2 = self._seg_import()(
                self.pool.alloc_region(1), slab, jnp.int32(0)
            )
            # decode-side seat: the ordinary zero-prefill full hit
            sc = self._hit_insert()(
                *self._scratch_state(), region2,
                jnp.asarray(dec["logits"]), jnp.int32(0), jnp.int32(0),
                jnp.int32(n), jnp.int32(1), jnp.int32(_NO_EOS),
            )
            rows_c = self._slot_rows(sc[0], 0, n)
            lg_c = np.asarray(sc[1][0])
            return bool(
                np.array_equal(lg_a, lg_c)
                and all(np.array_equal(a, c)
                        for a, c in zip(rows_a, rows_c))
            )
        finally:
            self.prefill_dispatches = _disp
            self._attr_suspend -= 1

    def _disagg_ok(self) -> bool:
        if self._disagg_ok_memo is None:
            self._disagg_ok_memo = self._probe_verdict(
                "disagg_wire", self._probe_disagg_parity,
                n_slots=self.n_slots, max_total=self.max_total,
                min_bucket=self._min_bucket, tpad=self.pool.tpad,
                paged=self._paged, block_size=self._block_size,
                tp=self.tp,
            )
            log_event(_log, "disagg_parity_probe",
                      ok=self._disagg_ok_memo)
            self.tracer.instant(ENGINE_TRACK, "disagg_parity_probe",
                                ok=self._disagg_ok_memo)
        return self._disagg_ok_memo

    def _batch_admission_ok(self) -> bool:
        if self._paged:
            # the batched admission programs are slab-landing (whole
            # groups dynamic-update into pool slabs); paged admissions
            # go serial through the paged prefill/insert programs
            return False
        if self.batch_admission is True:
            return True
        if self.batch_admission is False:
            return False
        if self._batch_ok_memo is None:
            self._batch_ok_memo = self._probe_verdict(
                "batch_admission", self._probe_batch_parity,
                n_slots=self.n_slots, max_total=self.max_total,
                min_bucket=self._min_bucket, tpad=self.pool.tpad,
                prefix=self.prefix_cache is not None, tp=self.tp,
            )
            log_event(_log, "batch_parity_probe",
                      ok=self._batch_ok_memo)
            self.tracer.instant(ENGINE_TRACK, "batch_parity_probe",
                                ok=self._batch_ok_memo)
        return self._batch_ok_memo

    def _classify_plan(self, pl: _AdmitPlan) -> None:
        """Prefix-cache lookup for one planned admission. A FULL hit
        (whole prompt cached, stored logits present) admits by pure
        copy; a PARTIAL hit reuses the longest cached prefix rounded
        DOWN to the bucket grain (suffix chunk windows must start
        sublane-aligned to provably fit Tpad) and chunk-computes only
        the suffix. The source segment is pinned here and unpinned at
        retirement, so eviction can never drop a segment an active
        slot's admission read."""
        cache = self.prefix_cache
        n = len(pl.req.prompt)
        # adapter != 0 prompts are NOT cacheable or reusable: the MLP
        # delta makes every later layer's KV rows adapter-dependent, so
        # segments are base-model-only and nonzero adapters always take
        # the full prefill path
        if (cache is None or n == 0 or pl.req.adapter != 0
                or not self._prefix_reuse_ok()):
            return
        seg, m = cache.lookup(pl.req.prompt)
        if seg is None:
            self.metrics.record_prefix_lookup("miss", 0)
            return
        if m == n and seg.logits is not None:
            pl.kind, pl.seg, pl.matched = "full", seg, n
        else:
            L = min(m, n - 1)
            L -= L % self._hit_grain
            if L <= 0:
                self.metrics.record_prefix_lookup("miss", 0)
                return
            pl.kind, pl.seg, pl.matched = "partial", seg, L
        cache.pin(seg)
        self.metrics.record_prefix_lookup(
            "hit_full" if pl.kind == "full" else "hit_partial",
            pl.matched,
        )
        self.tracer.instant(
            slot_track(pl.slot), "prefix_hit", req_id=pl.req.id,
            kind=pl.kind, cached_tokens=pl.matched, prompt_len=n,
        )

    def _paged_seg_tmp(self, seg):
        """Gather a cached segment's blocks into a batch-1 scratch slab
        (sentinel-padded table row, so rows past the segment's block
        span come back zero). The chunked suffix programs and the paged
        insert consume it exactly like a slab-mode segment fetch."""
        row = np.zeros((self.pool.blocks_per_slot,), np.int32)
        row[:len(seg.block_ids)] = seg.block_ids
        return self._paged_seg_fetch()(
            self.pool.caches, jnp.asarray(row)
        )

    def _alias_hit_blocks(self, pl: _AdmitPlan, covered: int) -> None:
        """Land a prefix hit's cached rows by table aliasing: share the
        segment's FULL blocks over rows [0, covered) into the slot
        (refcount bump, zero device work), then cover the rest of the
        slot's writable range with fresh private blocks. The segment's
        copied tail block (when its length is not block-aligned) is
        never aliased — rows the slot itself writes, hit-suffix or
        decode, must land in private blocks."""
        full = covered // self.pool.block_size
        if full:
            self.pool.alias_into_slot(pl.slot, pl.seg.block_ids[:full])
        self._paged_ensure_blocks(
            pl.slot, len(pl.req.prompt) + pl.req.max_new
        )

    def _admit_full_hit(self, pl: _AdmitPlan) -> None:
        """Admission by pure device copy: segment slab + stored logits.
        Dispatches ZERO prefill programs for the cached portion — which
        is all of it. Paged mode goes further: the segment's full
        blocks are byte-SHARED into the slot's table (aliasing, no
        copy); one gather + one insert land the tail rows and re-zero
        the fresh private blocks."""
        req = pl.req
        n = len(req.prompt)
        eos_tok = _NO_EOS if req.eos_token is None else int(req.eos_token)
        if self._paged:
            self._alias_hit_blocks(pl, n)
            tmp = self._paged_seg_tmp(pl.seg)
            self._set_state(self._paged_insert()(
                *self._state(), tmp, pl.seg.logits, jnp.int32(pl.slot),
                jnp.int32(n), jnp.int32(req.max_new),
                jnp.int32(eos_tok),
            ))
            return
        self._set_state(self._hit_insert()(
            *self._state(), self.prefix_cache.region, pl.seg.logits,
            jnp.int32(pl.seg.slot), jnp.int32(pl.slot),
            jnp.int32(n), jnp.int32(req.max_new),
            jnp.int32(eos_tok),
        ))

    def _admit_partial_hit(self, pl: _AdmitPlan) -> None:
        """Serial partial-hit assembly: fetch the segment slab as the
        scratch cache, chunk-compute rows [matched, n) through the same
        bucket programs the long-prompt path uses, then one slab
        insert. Only the uncached suffix costs prefill dispatches. In
        paged mode the matched rows additionally land in the slot by
        block ALIASING (the hit grain is block-aligned, so the matched
        range is whole shared blocks) and the insert scatters through
        the slot's table."""
        req = pl.req
        seq, n, L = req.prompt, len(req.prompt), pl.matched
        eos_tok = _NO_EOS if req.eos_token is None else int(req.eos_token)
        if self._paged:
            self._alias_hit_blocks(pl, L)
            tmp = self._paged_seg_tmp(pl.seg)
        else:
            tmp = self._seg_fetch()(
                self.prefix_cache.region, jnp.int32(pl.seg.slot)
            )
        lg = None
        for t0, ln, b in self._chunk_schedule(n, start=L):
            pad = np.zeros((1, b), np.int32)
            pad[0, :ln] = seq[t0:t0 + ln]
            self._attr("chunk")
            tmp, lg = self._chunk_fn(b)(
                self.params, tmp, jnp.asarray(pad), jnp.int32(t0),
                jnp.int32(ln - 1),
                jnp.asarray([req.adapter], jnp.int32),
            )
            self.prefill_dispatches += 1
        insert = self._paged_insert() if self._paged else self._insert()
        self._set_state(insert(
            *self._state(), tmp, lg, jnp.int32(pl.slot), jnp.int32(n),
            jnp.int32(req.max_new), jnp.int32(eos_tok),
        ))

    @staticmethod
    def _pad_group(group: list, nb: int) -> list:
        """Pad a batched-admission group to ``nb`` rows by repeating
        the first plan — the duplicate rows recompute identical values
        and re-write them to the same slot, so the result is unchanged
        while the compiled-program count stays at powers of two."""
        return group + [group[0]] * (nb - len(group))

    def _batch_prefill_group(self, bucket: int,
                             group: list[_AdmitPlan]) -> None:
        """One dispatched program admits every plan in ``group`` (all
        misses padding to the same bucket)."""
        nb = 1
        while nb < len(group):
            nb *= 2
        rows = self._pad_group(group, nb)
        prompts = np.zeros((nb, bucket), np.int32)
        last_idx = np.zeros((nb,), np.int32)
        slots = np.zeros((nb,), np.int32)
        pos0 = np.zeros((nb,), np.int32)
        max_new = np.zeros((nb,), np.int32)
        eos_toks = np.full((nb,), _NO_EOS, np.int32)
        adapters = np.zeros((nb,), np.int32)
        for r, pl in enumerate(rows):
            n = len(pl.req.prompt)
            prompts[r, :n] = pl.req.prompt
            last_idx[r] = n - 1
            slots[r] = pl.slot
            pos0[r] = n
            max_new[r] = pl.req.max_new
            if pl.req.eos_token is not None:
                eos_toks[r] = int(pl.req.eos_token)
            adapters[r] = pl.req.adapter
        self.prefill_dispatches += 1
        self._attr("batch_prefill")
        self._set_state(self._batch_prefill_fn(bucket, nb)(
            *self._state(), self.params, jnp.asarray(prompts),
            jnp.asarray(last_idx), jnp.asarray(slots),
            jnp.asarray(pos0), jnp.asarray(max_new),
            jnp.asarray(eos_toks), jnp.asarray(adapters),
        ))
        self.metrics.record_batched_admissions(len(group))

    def _batch_hit_group(self, bucket: int, L: int,
                         group: list[_AdmitPlan]) -> None:
        """One dispatched program admits every plan in ``group`` (all
        partial hits with cached length L and a single suffix window of
        the same bucket)."""
        nb = 1
        while nb < len(group):
            nb *= 2
        rows = self._pad_group(group, nb)
        seg_idx = np.zeros((nb,), np.int32)
        toks = np.zeros((nb, bucket), np.int32)
        last_idx = np.zeros((nb,), np.int32)
        slots = np.zeros((nb,), np.int32)
        posf = np.zeros((nb,), np.int32)
        max_new = np.zeros((nb,), np.int32)
        eos_toks = np.full((nb,), _NO_EOS, np.int32)
        adapters = np.zeros((nb,), np.int32)
        for r, pl in enumerate(rows):
            n = len(pl.req.prompt)
            ln = n - L
            seg_idx[r] = pl.seg.slot
            toks[r, :ln] = pl.req.prompt[L:]
            last_idx[r] = ln - 1
            slots[r] = pl.slot
            posf[r] = n
            max_new[r] = pl.req.max_new
            if pl.req.eos_token is not None:
                eos_toks[r] = int(pl.req.eos_token)
            adapters[r] = pl.req.adapter
        self.prefill_dispatches += 1
        self._attr("batch_hit")
        self._set_state(self._batch_hit_fn(bucket, nb)(
            *self._state(), self.params, self.prefix_cache.region,
            jnp.asarray(seg_idx), jnp.asarray(toks), jnp.int32(L),
            jnp.asarray(last_idx), jnp.asarray(slots),
            jnp.asarray(posf), jnp.asarray(max_new),
            jnp.asarray(eos_toks), jnp.asarray(adapters),
        ))
        self.metrics.record_batched_admissions(len(group))

    # lint: hot-path
    def _seat_plan(self, pl: _AdmitPlan, now: float) -> None:
        """Host bookkeeping that makes an executed plan a live slot:
        sampling key split (in admission order — the order replay
        reproduces), slot state, metrics, spans."""
        req, slot = pl.req, pl.slot
        # piggyback engines pre-split at plan execution (same order)
        # so a prefill deferred across horizons cannot reorder the
        # master key chain; everyone else splits here, at seating
        kd = self._presplit_keys.pop(req.id, None)
        if kd is None:
            self._key, sub = jax.random.split(self._key)
            kd = np.asarray(jax.random.key_data(sub))  # lint: sync-ok per-admission key snapshot (tiny, off the decode critical section)
        self._slot_keys[slot] = kd
        self._slot_adapters[slot] = req.adapter
        st = _SlotState(req, self.pool.generation(slot), kd,
                        req.adapter)
        if pl.seg is not None:
            st.segs.append(pl.seg)
        if self._surface:
            self._seat_surface(slot, st, req)
        self._slots[slot] = st
        pl.admitted = True
        req.status = RequestStatus.RUNNING
        self.metrics.record_prefill(req.id, pl.prefill_s)
        delay = (time.perf_counter() - req.arrival_time
                 if req.arrival_time is not None else None)
        if delay is not None:
            self.metrics.record_admitted(req.id, delay,
                                         tenant=req.tenant_id)
            self.tracer.span(
                SCHEDULER_TRACK, "queued", req.arrival_time,
                delay, req_id=req.id,
            )
        # the ADMISSION span: when the request carries distributed-
        # trace context (router/server resolved a traceparent), the
        # span joins the fleet trace — parent_span_id is the upstream
        # dispatch span, so trace-merge draws the cross-process arrow
        # into this span
        tctx = {}
        if self.tracer.enabled and req.trace_id:
            tctx = {"trace_id": req.trace_id, "span_id": new_span_id()}
            if req.parent_span_id:
                tctx["parent_span_id"] = req.parent_span_id
        self.tracer.span(
            slot_track(slot), "prefill", pl.t_pf, pl.prefill_s,
            req_id=req.id, prompt_len=len(req.prompt),
            prefix=pl.kind, cached_tokens=pl.matched, **tctx,
        )
        self.flight.record(
            "admit", req_id=req.id, slot=slot,
            prompt_len=len(req.prompt), prefix=pl.kind,
            tenant=req.tenant_id or None,
            trace_id=req.trace_id or None,
        )
        log_event(_log, "request_admitted", req_id=req.id,
                  slot=slot, prompt_len=len(req.prompt),
                  queue_delay_s=delay,
                  prefill_s=round(pl.prefill_s, 6),
                  prefix=pl.kind, cached_tokens=pl.matched,
                  tenant=req.tenant_id or None,
                  adapter=req.adapter or None,
                  trace_id=req.trace_id or None)

    def _seat_surface(self, slot: int, st: _SlotState,
                      req: Request) -> None:
        """Seat the slot's sampling-surface rows: host mirror vectors
        (snapshotted per dispatch, re-seated at recovery — the
        _slot_adapters contract), the compiled grammar in the combined
        table, and the DEVICE-resident FSM state row. Defaults
        reproduce the engine-wide sampler bitwise (temperature/top_k
        engine values, p=1, no bias, state 0)."""
        t = (req.temperature if req.temperature is not None
             else self.temperature)
        k = req.top_k if req.top_k is not None else (self.top_k or 0)
        p = req.top_p if req.top_p is not None else 1.0
        self._slot_temps[slot] = np.float32(t)
        self._slot_topks[slot] = np.int32(k)
        self._slot_topps[slot] = np.float32(p)
        self._slot_bias_idx[slot] = -1
        self._slot_bias_val[slot] = 0.0
        if req.logit_bias:
            for j, (ti, tv) in enumerate(sorted(req.logit_bias.items())):
                self._slot_bias_idx[slot, j] = ti
                self._slot_bias_val[slot, j] = tv
        start = 0
        if req._grammar is not None:
            try:
                start = self._gtable.seat(req._grammar)
                st.gkey = req._grammar.key
            except GrammarError as e:
                # seat-time pressure (table rows pinned by live
                # requests): submit's budget check passed, so this is
                # a transient-capacity edge. Never decode this slot
                # unconstrained — cancel before its first step.
                req.error = str(e)
                req.cancel()
                log_event(_log, "grammar_seat_failed", req_id=req.id,
                          error=str(e))
        st.gstate0 = int(start)
        self._slot_gstate[slot] = start
        self._dgstate = self._gstate_set()(
            self._dgstate, jnp.int32(slot), jnp.int32(start)
        )
        st.stop_matcher = StopMatcher(req.stop) if req.stop else None
        st.lp_out = [] if req.logprobs else None

    def _clear_surface(self, slot: int, st: _SlotState) -> None:
        """Retire-side inverse of ``_seat_surface``: drop the grammar
        refcount and reset the host mirror rows to engine defaults.
        The device FSM row is NOT rewritten — a stale state on an
        inactive slot is inert (draws forced to 0, advance gated on
        active) and the next occupant's seat overwrites it."""
        if st.gkey is not None:
            self._gtable.release(st.gkey)
            st.gkey = None
        self._slot_gstate[slot] = 0
        self._slot_temps[slot] = self.temperature
        self._slot_topks[slot] = int(self.top_k or 0)
        self._slot_topps[slot] = 1.0
        self._slot_bias_idx[slot] = -1
        self._slot_bias_val[slot] = 0.0
        if st.lp_out is not None:
            st.req.logprobs_out = st.lp_out

    def _maybe_insert_prefix(self, pl: _AdmitPlan) -> None:
        """Insert-on-completion (of the prefill): cache the admitted
        prompt's full KV as a new segment — one slab copy into the
        region plus the (1, V) logits row, both captured before any
        decode step touches the slot. ``insert`` may return a second
        segment at a newly observed branch point (two prompts seen
        diverging there — the system-prompt sharing signal); it gets
        the same slab copy but NO logits row (no request ended at that
        length, so it only ever serves partial hits). The creating
        request pins every segment until retirement.

        Paged storage inverts the copy direction of the slab region:
        instead of copying the slot's slab OUT, the segment takes
        cache-owned REFERENCES on the slot's own full blocks (incref —
        the slot never rewrites rows below its prompt length) plus one
        privately copied tail block when the length is not
        block-aligned (the slot keeps writing that block's remaining
        rows). One block copy at most, usually zero device work."""
        cache = self.prefix_cache
        n = len(pl.req.prompt)
        if (cache is None or pl.kind == "full" or pl.req.adapter != 0
                or n < self._min_bucket or not self._prefix_reuse_ok()):
            return
        for seg in cache.insert(pl.req.prompt):
            if self._paged:
                if not self._paged_store_segment(seg, pl.slot):
                    # tail-block allocation lost to admission pressure:
                    # un-cache rather than leave an unbacked segment
                    cache.drop(seg)
                    continue
            else:
                cache.region = self._seg_store()(
                    cache.region, self.pool.caches, jnp.int32(seg.slot),
                    jnp.int32(pl.slot),
                )
            if seg.length == n:
                seg.logits = self._logit_row()(
                    self._logits, jnp.int32(pl.slot))
            self._slots[pl.slot].segs.append(seg)
            self.metrics.record_prefix_insert()
            self.tracer.instant(
                ENGINE_TRACK, "prefix_insert", req_id=pl.req.id,
                length=seg.length,
            )

    def _paged_store_segment(self, seg, slot: int) -> bool:
        """Back a new segment with block references off donor ``slot``:
        incref the donor's full blocks (aliased, zero device work —
        the donor only ever writes rows >= seg.length, which live in
        later blocks) and COPY the partial tail block, if any, into a
        cache-private block (the donor keeps writing that block's
        remaining rows). Returns False — no references taken — when
        the tail block cannot be allocated."""
        bs = self.pool.block_size
        row = self.pool.table(slot)
        full = seg.length // bs
        tail = seg.length % bs
        try:
            tail_ids = self.pool.alloc_blocks(1) if tail else []
        except RuntimeError:
            return False
        ids = [int(b) for b in row[:full]]
        self.pool.incref(ids)
        if tail:
            self.pool.caches = self._block_copy()(
                self.pool.caches, jnp.int32(int(row[full])),
                jnp.int32(tail_ids[0]),
            )
        seg.block_ids = ids + tail_ids
        return True

    # lint: hot-path
    def _admit(self, now: float) -> None:
        """Admission at a horizon boundary: pop every admissible
        request (one per free slot), classify each against the prefix
        cache, then execute — misses that pad to the same bucket
        coalesce into ONE dispatched prefill program, partial hits
        sharing (bucket, cached length) coalesce the same way, full
        hits admit by pure copy — and finally seat slot states in
        admission order. A crash mid-batch requeues every plan that was
        not yet seated (front of its class, original order) and
        releases its slot/segment pins before the supervisor rebuilds
        state."""
        if not len(self.scheduler):
            return
        if not (self.pool.n_free or self.scheduler.has_kind("embedding")
                or self.scheduler.has_kind("kv_ingest")):
            return
        self._admitting += 1
        plans: list[_AdmitPlan] = []
        # per-tenant slot caps: live occupancy plus this batch's plans
        # (so one admission round cannot overshoot a cap)
        used: dict[str, int] = {}
        if self.tenancy is not None:
            for st in self._slots:
                if st is not None:
                    tid = st.req.tenant_id
                    used[tid] = used.get(tid, 0) + 1

        # paged: blocks this admission round has already promised to
        # plans not yet executed — two plans must not both pass the
        # free-heap check against the same blocks. Conservative (a
        # prefix hit will alias part of its need), so execution-time
        # allocation can never fail.
        reserved = [0]

        def admissible(r):
            if r.kind in ("embedding", "kv_ingest"):
                return True  # served host-side at admission, slotless
            # generate AND kv_export take the slot checks below
            # (an export transiently holds a pool slot for its prefill)
            if self.pool.n_free == 0:
                return False
            if self._paged:
                need = self.pool.blocks_needed(
                    len(r.prompt) + r.max_new
                )
                while need + reserved[0] > self.pool.n_free_blocks:
                    # hand cached blocks back to the free heap before
                    # declining — live traffic outranks cached prefixes
                    if (self.prefix_cache is None
                            or not self.prefix_cache.reclaim()):
                        return False
            if self.tenancy is not None:
                t = self.tenancy.get(r.tenant_id)
                if (t is not None and t.max_slots is not None
                        and used.get(r.tenant_id, 0) >= t.max_slots):
                    return False
            return True

        try:
            hint = None
            while len(self.scheduler):
                req = self.scheduler.pop(
                    affinity_hint=hint, admissible=admissible
                )
                if req is None:
                    break
                if req.cancelled:
                    self._retire_unadmitted(req, RequestStatus.CANCELLED)
                    continue
                if req.expired(now):
                    self._retire_unadmitted(req, RequestStatus.EXPIRED)
                    continue
                if req.kind == "embedding":
                    self._serve_embedding(req, now)
                    continue
                if req.kind == "kv_ingest":
                    self._serve_kv_ingest(req, now)  # lint: sync-ok wire seat must land before decode admits
                    continue
                if req.kind == "kv_export":
                    self._serve_kv_export(req, now)  # lint: sync-ok export materializes the wire frame bytes
                    continue
                if req.kind == "kv_session":
                    # seats synchronously (pool/block state updates
                    # before the next admissible() check); count the
                    # held slot against its tenant's cap like a plan
                    self._serve_kv_session(req, now)  # lint: sync-ok migrated session must seat before decode admits
                    if req.status is RequestStatus.RUNNING:
                        used[req.tenant_id] = used.get(req.tenant_id, 0) + 1
                    continue
                plans.append(_AdmitPlan(req, self.pool.acquire()))
                used[req.tenant_id] = used.get(req.tenant_id, 0) + 1
                if self._paged:
                    reserved[0] += self.pool.blocks_needed(
                        len(req.prompt) + req.max_new
                    )
                # prefix affinity only helps adapter-0 traffic (nonzero
                # adapters never reuse cached segments)
                hint = req.prompt if req.adapter == 0 else None
            if not plans:
                return
            for pl in plans:
                self._classify_plan(pl)
            self._execute_plans(plans, now)
        except BaseException:
            # EngineCrash (or anything unexpected) mid-batch: no popped
            # request may be dropped — requeue every unseated plan at
            # the front of its class (reversed, so original order is
            # restored) before the supervisor rebuilds state.
            for pl in reversed(plans):
                if not pl.admitted:
                    if pl.seg is not None:
                        self.prefix_cache.unpin(pl.seg)
                    self.pool.release(pl.slot)
                    self.scheduler.requeue(pl.req)
            raise
        finally:
            self._admitting -= 1

    # lint: hot-path
    def _execute_plans(self, plans: list[_AdmitPlan],
                       now: float) -> None:
        # fault boundary first, in admission order, so scripted chaos
        # fires at the same per-request check counts as serial
        # admission did
        live: list[_AdmitPlan] = []
        for pl in plans:
            if self._check_prefill_faults(pl.req):
                live.append(pl)
            else:
                if pl.seg is not None:
                    self.prefix_cache.unpin(pl.seg)
                    pl.seg = None
                self.pool.release(pl.slot)
                pl.admitted = True  # handled: excluded from requeue
                self._retire_unadmitted(
                    pl.req, RequestStatus.FAILED, pl.req.error
                )
        deferred: set[int] = set()
        if self._piggyback:
            # pre-split sampling keys for EVERY surviving plan now, in
            # admission order — the exact split sequence non-piggyback
            # seating produces — so deferring a prefill across
            # horizons cannot reorder the master key chain (sampled
            # byte parity). A crash before seating keeps the stash;
            # re-admission reuses it without advancing the chain,
            # matching the blocking path (which never split either).
            for pl in live:
                if pl.req.id not in self._presplit_keys:
                    self._key, sub = jax.random.split(self._key)
                    self._presplit_keys[pl.req.id] = np.asarray(
                        jax.random.key_data(sub)
                    )  # lint: sync-ok per-admission key snapshot (tiny, off the decode critical section)
            # defer only prompts whose uncached suffix exceeds one
            # bucket — everything the blocking path serves in a single
            # prefill dispatch stays on the blocking path, bitwise
            for pl in live:
                n = len(pl.req.prompt)
                cached = pl.matched if pl.kind == "partial" else 0
                if pl.kind != "full" and n - cached > self._max_bucket:
                    self._enqueue_piggyback(pl, now)
                    deferred.add(id(pl))
        occupied = any(st is not None for st in self._slots)
        t_exec = time.perf_counter()
        # group what can share a dispatch
        batch_ok = len(live) > 1 and self._batch_admission_ok()
        miss_groups: dict[int, list[_AdmitPlan]] = {}
        hit_groups: dict[tuple[int, int], list[_AdmitPlan]] = {}
        if batch_ok:
            for pl in live:
                n = len(pl.req.prompt)
                if pl.kind == "miss" and 0 < n <= self._max_bucket:
                    miss_groups.setdefault(
                        self._bucket_for(n), []
                    ).append(pl)
                elif pl.kind == "partial":
                    sfx = n - pl.matched
                    if sfx <= self._max_bucket:
                        b = self._bucket_for(sfx)
                        if pl.matched + b <= self.pool.tpad:
                            hit_groups.setdefault(
                                (b, pl.matched), []
                            ).append(pl)
        batched: set[int] = set()
        for bucket, group in sorted(miss_groups.items()):
            if len(group) >= 2:
                t0 = time.perf_counter()
                self._batch_prefill_group(bucket, group)
                dt = (time.perf_counter() - t0) / len(group)
                for pl in group:
                    pl.t_pf, pl.prefill_s = t0, dt
                    batched.add(id(pl))
        for (bucket, length), group in sorted(hit_groups.items()):
            if len(group) >= 2:
                t0 = time.perf_counter()
                self._batch_hit_group(bucket, length, group)
                dt = (time.perf_counter() - t0) / len(group)
                for pl in group:
                    pl.t_pf, pl.prefill_s = t0, dt
                    batched.add(id(pl))
        # serial remainder, in admission order
        for pl in live:
            if id(pl) in batched or id(pl) in deferred:
                continue
            t0 = time.perf_counter()
            if pl.kind == "full":
                self._admit_full_hit(pl)
            elif pl.kind == "partial":
                self._admit_partial_hit(pl)
            else:
                eos_tok = (_NO_EOS if pl.req.eos_token is None
                           else int(pl.req.eos_token))
                self._prefill_seq_into_slot(
                    pl.req.prompt, pl.slot, pl.req.max_new, eos_tok,
                    adapter=pl.req.adapter,
                )
            pl.t_pf, pl.prefill_s = t0, time.perf_counter() - t0
        # decode-stall accounting: admission prefill executed while
        # decode slots sat occupied is exactly the stall piggyback
        # exists to bound — measured identically on and off so the
        # bench comparison is honest
        if occupied:
            self.metrics.record_decode_stall(
                time.perf_counter() - t_exec
            )
        # seat states in admission order (sampling-key split order is
        # part of the determinism contract), then cache new prefixes
        for pl in live:
            if id(pl) not in deferred:
                self._seat_plan(pl, now)
        for pl in live:
            if id(pl) not in deferred:
                self._maybe_insert_prefix(pl)

    # -- chunked-prefill piggyback -----------------------------------------
    #
    # A deferred admission keeps its acquired slot and pinned prefix
    # segment but is NOT seated: its uncached suffix sits as a pow2
    # chunk schedule in a _PendingPrefill record, and every dispatch
    # horizon spends up to `prefill_budget` chunk tokens advancing the
    # FIFO — middles standalone, the last budgeted chunk FUSED into
    # the decode dispatch itself (the piggyback_step program). The
    # final chunk always runs standalone so the completion insert
    # consumes a well-defined logits row, then the record completes —
    # insert + seat — in the same horizon a blocking admission would
    # have joined. Byte parity with the blocking path holds because
    # the chunk programs, schedule, and scratch slab are IDENTICAL;
    # only the horizon at which each dispatch happens moves.

    def _enqueue_piggyback(self, pl: _AdmitPlan, now: float) -> None:
        """Turn an executed-plan candidate into a pending record: set
        up its scratch slab (segment fetch for partial hits — only the
        uncached suffix is piggybacked), its chunk schedule, and, in
        paged mode, its private block coverage."""
        req = pl.req
        L = pl.matched if pl.kind == "partial" else 0
        if self._paged:
            # private blocks for every row the slot will write; rows
            # [0, L) stay sentinel-mapped until completion (decode
            # steps run while this record is pending, and an inactive
            # slot's frozen-position garbage write must never land in
            # a SHARED prefix block — aliasing is deferred to
            # _complete_pending, a refcount bump that cannot fail)
            full = L // self.pool.block_size
            self.pool.alloc_slot_blocks(
                pl.slot, min(len(req.prompt) + req.max_new,
                             self.pool.tpad),
                start=full,
            )
            tmp = (self._paged_seg_tmp(pl.seg) if pl.kind == "partial"
                   else self._init_caches(1, self.max_total))
        elif pl.kind == "partial":
            tmp = self._seg_fetch()(
                self.prefix_cache.region, jnp.int32(pl.seg.slot)
            )
        else:
            tmp = self._init_caches(1, self.max_total)
        rec = _PendingPrefill(
            pl, deque(self._chunk_schedule(len(req.prompt), start=L)),
            tmp, now,
        )
        self._pending_prefills.append(rec)
        # the scheduler pop charged the whole prompt to the tenant's
        # DRR deficit up front; credit the deferred suffix back here
        # and re-charge it chunk by chunk as the work executes, so
        # fairness meters the device time when it is actually spent
        self.scheduler.adjust_deficit(req, float(len(req.prompt) - L))
        self.flight.record(
            "piggyback", phase="defer", req_id=req.id, slot=pl.slot,
            suffix_tokens=len(req.prompt) - L,
            n_chunks=len(rec.chunks),
        )
        self.tracer.instant(
            slot_track(pl.slot), "piggyback_defer", req_id=req.id,
            suffix_tokens=len(req.prompt) - L,
        )
        log_event(_log, "piggyback_defer", req_id=req.id, slot=pl.slot,
                  prompt_len=len(req.prompt), cached_tokens=L,
                  n_chunks=len(rec.chunks),
                  tenant=req.tenant_id or None)

    def _account_chunk(self, rec: _PendingPrefill, ln: int,
                       fused: bool) -> None:
        """Bookkeeping for one executed piggyback chunk (standalone or
        fused): pop it from the schedule, charge the tenant, count."""
        rec.chunks.popleft()
        pl = rec.plan
        self.prefill_dispatches += 1
        self.metrics.record_prefill_chunk(ln)
        self.scheduler.adjust_deficit(pl.req, -float(ln))
        self.flight.record(
            "piggyback", phase="chunk", req_id=pl.req.id, slot=pl.slot,
            chunk_tokens=ln, fused=fused, remaining=len(rec.chunks),
        )

    def _run_pending_chunk(self, rec: _PendingPrefill) -> int:
        """Run the head chunk of ``rec`` standalone — the non-fused
        path: budget middles, final chunks, and horizons with no
        active decode slot to piggyback on. Returns real tokens."""
        pl = rec.plan
        t0, ln, b = rec.chunks[0]
        pad = np.zeros((1, b), np.int32)
        pad[0, :ln] = pl.req.prompt[t0:t0 + ln]
        self._attr("chunk")
        rec.tmp, rec.lg = self._chunk_fn(b)(
            self.params, rec.tmp, jnp.asarray(pad), jnp.int32(t0),
            jnp.int32(ln - 1), jnp.asarray([pl.req.adapter], jnp.int32),
        )
        self._account_chunk(rec, ln, fused=False)
        return ln

    def _complete_pending(self, rec: _PendingPrefill) -> None:
        """All chunks executed: land the scratch slab with the SAME
        insert program the blocking path uses, seat the slot, and
        cache the new prefix — the deferred admission is now
        indistinguishable from a blocking one."""
        pl = rec.plan
        req = pl.req
        now = time.perf_counter()
        n = len(req.prompt)
        eos_tok = _NO_EOS if req.eos_token is None else int(req.eos_token)
        if self._paged and pl.kind == "partial":
            # alias the cached prefix blocks in now (refcount bump,
            # no allocation — the private coverage was reserved at
            # defer time); the insert scatter then rewrites the
            # aliased rows with the identical bytes the segment holds,
            # exactly like the blocking partial-hit path
            full = pl.matched // self.pool.block_size
            if full:
                self.pool.alias_into_slot(
                    pl.slot, pl.seg.block_ids[:full]
                )
        insert = self._paged_insert() if self._paged else self._insert()
        self._set_state(insert(
            *self._state(), rec.tmp, rec.lg, jnp.int32(pl.slot),
            jnp.int32(n), jnp.int32(req.max_new), jnp.int32(eos_tok),
        ))
        pl.t_pf = rec.t_start
        pl.prefill_s = now - rec.t_start
        self._seat_plan(pl, now)
        self._maybe_insert_prefix(pl)
        self.flight.record(
            "piggyback", phase="seated", req_id=req.id, slot=pl.slot,
            prefill_s=round(pl.prefill_s, 6),
        )

    def _advance_piggyback(self, can_fuse: bool
                           ) -> _PendingPrefill | None:
        """Spend up to ``prefill_budget`` chunk tokens advancing the
        pending FIFO (oldest first — Sarathi-style per-iteration token
        budget). Returns the record whose head chunk should be FUSED
        into this horizon's decode dispatch (never a record's final
        chunk), or None. The first chunk always runs even over budget,
        so every pending admission makes progress each horizon."""
        budget = self.prefill_budget
        spent = 0
        fused = None
        t_wall = time.perf_counter()
        while self._pending_prefills and spent < budget:
            rec = self._pending_prefills[0]
            ln = rec.chunks[0][1]
            final = len(rec.chunks) == 1
            if not final and can_fuse and spent + ln >= budget:
                fused = rec
                self._pb_did_work = True
                break
            spent += self._run_pending_chunk(rec)
            self._pb_did_work = True
            if final:
                self._complete_pending(rec)
                self._pending_prefills.popleft()
        if can_fuse and spent:
            # standalone chunks executed ahead of an occupied-slot
            # dispatch are residual decode stall (the fused chunk is
            # the part that isn't)
            self.metrics.record_decode_stall(
                time.perf_counter() - t_wall
            )
        return fused

    # -- supervised dispatch + pipelined readback --------------------------

    # lint: hot-path
    def _dispatch(self) -> _Inflight | None:
        """Dispatch one fused K-substep horizon for every occupied slot
        under transient-retry supervision; returns the in-flight record
        WITHOUT syncing its tokens. Persistent faults quarantine the
        implicated request when one is named, otherwise escalate to
        ``EngineCrash`` (replay recovery). Returns None when there is
        nothing to dispatch (or quarantining emptied the batch)."""
        self._pb_did_work = False
        fused = None
        if self._pending_prefills:
            # advance deferred prefills under the token budget FIRST:
            # completions seat their slot pre-dispatch (joining this
            # horizon exactly as a blocking admission would), and the
            # returned record's head chunk rides the decode dispatch
            # below. With no occupied slot there is nothing to fuse
            # with — chunks run standalone and this horizon may
            # dispatch no step at all.
            fused = self._advance_piggyback(  # lint: sync-ok host-int chunk accounting, no device readback
                can_fuse=any(st is not None for st in self._slots)
            )
        if not any(st is not None for st in self._slots):
            return None
        # adaptive horizon: when requests are waiting for a slot, drop
        # to K=1 so the next admission boundary arrives one substep
        # away; restore the configured K once the queue drains. Byte-
        # safe — the device stopping rule is applied per-substep, so
        # the emitted stream is invariant to K. Piggyback pendings are
        # NOT queue pressure (their slot is already taken): K stays
        # configured, the budget bounds their prefill instead.
        k = (1 if (self.adaptive_horizon and len(self.scheduler) > 0)
             else self.decode_horizon)
        self.decode_horizon_current = k
        surface = self._surface
        step_fn = (self._masked_step_fn_for(k) if surface
                   else self._step_fn_for(k))
        if fused is not None:
            fp = fused.plan
            ct0, cln, cb = fused.chunks[0]
            cpad = np.zeros((1, cb), np.int32)
            cpad[0, :cln] = fp.req.prompt[ct0:ct0 + cln]
            pb_fn = (self._masked_piggyback_fn(cb, k) if surface
                     else self._piggyback_fn(cb, k))
        attempt, backoff = 0, self.retry_backoff_s
        t_call = time.perf_counter()
        # .copy(): jnp.asarray can zero-copy alias the mutable host key
        # buffer on CPU, and dispatch is async — a later admission
        # writing a slot key must not race the in-flight step. The
        # snapshot is what gets dispatched, and (under the sanitizer)
        # what gets integrity-tracked until the readback.
        keys_host = self._slot_keys.copy()
        ad_host = self._slot_adapters.copy()
        if surface:
            # per-slot sampling-surface vectors, snapshotted for the
            # same async-alias reason as the keys above
            temps_host = self._slot_temps.copy()
            topks_host = self._slot_topks.copy()
            topps_host = self._slot_topps.copy()
            bidx_host = self._slot_bias_idx.copy()
            bval_host = self._slot_bias_val.copy()
            mask_tab, trans_tab = self._grammar_device_tables()
        while True:
            try:
                if self.faults is not None:
                    self.faults.check("step")
                # _caches_in INSIDE the retry loop: a quarantining
                # retire below releases the slot and rewrites its table
                # row, so the paged table mirror must be rebuilt before
                # every (re)dispatch
                if fused is None and not surface:
                    (caches, self._logits, self._dpos,
                     self._dactive, self._dbudget, toks) = step_fn(
                        self.params, self._caches_in(), self._logits,
                        self._dpos, self._dactive, self._dbudget,
                        self._deos, jnp.asarray(keys_host),
                        jnp.asarray(ad_host),
                    )
                elif fused is None:
                    # masked step: grammar FSM state threaded through
                    # the substeps; ``toks`` is the packed aux block
                    # (slots, K, 2+2*n_logprobs), token ids in [:,:,0]
                    (caches, self._logits, self._dpos,
                     self._dactive, self._dbudget, self._dgstate,
                     toks) = step_fn(
                        self.params, self._caches_in(), self._logits,
                        self._dpos, self._dactive, self._dbudget,
                        self._deos, self._dgstate,
                        jnp.asarray(keys_host), jnp.asarray(ad_host),
                        jnp.asarray(temps_host),
                        jnp.asarray(topks_host),
                        jnp.asarray(topps_host),
                        jnp.asarray(bidx_host),
                        jnp.asarray(bval_host),
                        mask_tab, trans_tab,
                    )
                elif not surface:
                    # piggyback: K decode substeps + one bounded
                    # prefill chunk for the admitting slot, fused
                    (caches, self._logits, self._dpos,
                     self._dactive, self._dbudget, toks,
                     fused.tmp, fused.lg) = pb_fn(
                        self.params, self._caches_in(), self._logits,
                        self._dpos, self._dactive, self._dbudget,
                        self._deos, jnp.asarray(keys_host),
                        jnp.asarray(ad_host), fused.tmp,
                        jnp.asarray(cpad), jnp.int32(ct0),
                        jnp.int32(cln - 1),
                        jnp.asarray([fp.req.adapter], jnp.int32),
                    )
                else:
                    (caches, self._logits, self._dpos,
                     self._dactive, self._dbudget, self._dgstate,
                     toks, fused.tmp, fused.lg) = pb_fn(
                        self.params, self._caches_in(), self._logits,
                        self._dpos, self._dactive, self._dbudget,
                        self._deos, self._dgstate,
                        jnp.asarray(keys_host), jnp.asarray(ad_host),
                        jnp.asarray(temps_host),
                        jnp.asarray(topks_host),
                        jnp.asarray(topps_host),
                        jnp.asarray(bidx_host),
                        jnp.asarray(bval_host),
                        mask_tab, trans_tab, fused.tmp,
                        jnp.asarray(cpad), jnp.int32(ct0),
                        jnp.int32(cln - 1),
                        jnp.asarray([fp.req.adapter], jnp.int32),
                    )
                self._caches_out(caches)
                if fused is not None:
                    self._account_chunk(fused, cln, fused=True)  # lint: sync-ok host-int chunk accounting
                break
            except TransientFault as e:
                self.metrics.record_retry()
                self.tracer.instant(
                    ENGINE_TRACK, "retry", site="step", error=str(e)
                )
                self.flight.record("fault", fault="transient",
                                   site="step", error=str(e),
                                   attempt=attempt + 1)
                attempt += 1
                if attempt <= self.max_retries:
                    time.sleep(backoff)
                    backoff = min(backoff * 2, self.max_backoff_s)
                    continue
                slot = self._slot_of(e.req_id)
                if slot is None:
                    raise EngineCrash(
                        f"transient step fault persisted past "
                        f"{self.max_retries} retries: {e}"
                    ) from e
                self._retire(slot, RequestStatus.FAILED,
                             time.perf_counter(), error=str(e),
                             deactivate=True)
                if not any(st is not None for st in self._slots):
                    return None
                attempt, backoff = 0, self.retry_backoff_s
            except PermanentFault as e:
                self.flight.record("fault", fault="permanent",
                                   site="step", error=str(e))
                slot = self._slot_of(e.req_id)
                if slot is None:
                    raise EngineCrash(
                        f"permanent step fault names no live request: {e}"
                    ) from e
                self._retire(slot, RequestStatus.FAILED,
                             time.perf_counter(), error=str(e),
                             deactivate=True)
                if not any(st is not None for st in self._slots):
                    return None
            except EngineCrash as e:
                # injected whole-engine crash: the last flight event
                # before the supervisor's postmortem dump names it
                self.flight.record("fault", fault="crash", site="step",
                                   error=str(e))
                raise
        now = time.perf_counter()
        self.last_dispatch_t = now
        if self._san is not None:
            self._san.track("dispatch.slot_keys", keys_host)
        snaps = [(s, st) for s, st in enumerate(self._slots)
                 if st is not None]
        self.metrics.record_step(
            len(snaps), self.n_slots, len(self.scheduler)
        )
        self.tracer.span(
            ENGINE_TRACK, "dispatch", t_call, now - t_call,
            n_active=len(snaps),
        )
        fam = "step" if fused is None else "piggyback_step"
        if surface:
            fam = "masked_" + fam
        self._attr(("paged_" + fam) if self._paged else fam, t_call)
        if self.flight.enabled:
            self.flight.record(
                "dispatch", k=k, n_active=len(snaps),
                queue_depth=len(self.scheduler),
                **({"piggyback_chunk": cln} if fused is not None
                   else {}),
                **({"blocks_in_use": self.pool.n_blocks_in_use,
                    "blocks_free": self.pool.n_free_blocks}
                   if self._paged else {}),
            )
        return _Inflight(toks, snaps, now)

    # lint: hot-path
    def _process(self, horizon: _Inflight) -> None:
        """Sync a horizon's (slots, K) token block and do the host-side
        bookkeeping: append tokens (replaying the same EOS/budget
        stopping rule the device mask applied in-program), stamp first
        tokens, retire finished slots. Blocks whose slot was retired or
        re-acquired since dispatch are discarded."""
        t_sync = time.perf_counter()
        toks_host = np.asarray(horizon.toks)  # lint: sync-ok THE designated readback, 1/horizon
        aux_host = None
        if toks_host.ndim == 3:
            # masked-step horizons read back the packed aux block:
            # [:, :, 0] is the token stream, the rest carries bitcast
            # logprob rows — still ONE readback per horizon
            aux_host = toks_host
            toks_host = aux_host[:, :, 0]
        if self._san is not None:
            # the program that read the dispatch-tracked buffers has
            # completed: verify nothing mutated them while in flight
            self._san.check("dispatch.slot_keys")
        now = time.perf_counter()
        self.metrics.record_readback(
            sync_wait_s=now - t_sync,
            overlap_s=max(0.0, t_sync - horizon.t_dispatch),
        )
        self.tracer.span(ENGINE_TRACK, "sync", t_sync, now - t_sync)
        # the sync above proved every program dispatched at or before
        # this horizon complete — price the pending attribution entries
        if self._pending_attr:
            self._flush_attr(horizon.t_dispatch, now)
        # per-slot decode span for this horizon: dispatch → block
        # arrival, clipped at the NEXT horizon's dispatch (which already
        # happened — pipelining) so consecutive decode spans on one slot
        # track stay disjoint in the trace viewer
        t_span_end = now
        if (self._inflight is not None
                and self._inflight.t_dispatch > horizon.t_dispatch):
            t_span_end = min(now, self._inflight.t_dispatch)
        for slot, st in horizon.snaps:
            if (self._slots[slot] is not st
                    or st.gen != self.pool.generation(slot)):
                continue  # retired/reused since dispatch: tokens dead
            req = st.req
            self.tracer.span(
                slot_track(slot), "decode", horizon.t_dispatch,
                t_span_end - horizon.t_dispatch, req_id=req.id,
                k=int(toks_host.shape[1]),
            )
            finished = False
            for k in range(toks_host.shape[1]):
                tok = int(toks_host[slot, k])
                if st.t_first_token is None:
                    st.t_first_token = now
                    self.tracer.instant(
                        slot_track(slot), "first_token", ts=now,
                        req_id=req.id,
                    )
                    if req.arrival_time is not None:
                        self.metrics.record_first_token(
                            req.id, now - req.arrival_time
                        )
                st.tokens.append(tok)
                if st.lp_out is not None and aux_host is not None:
                    row = aux_host[slot, k]
                    nl = self._n_logprobs
                    rec = {
                        "token": tok,
                        # contiguous row slice: bitcast back to f32
                        "logprob": float(row[1:2].view(np.float32)[0]),  # lint: sync-ok row is a host numpy slice of aux_host, no device buffer
                    }
                    if req.top_logprobs:
                        ids = row[2:2 + nl][:req.top_logprobs]
                        vals = row[2 + nl:2 + 2 * nl].view(
                            np.float32
                        )[:req.top_logprobs]
                        rec["top_logprobs"] = [
                            {"token": int(i), "logprob": float(v)}  # lint: sync-ok host numpy scalars from aux_host
                            for i, v in zip(ids, vals)
                        ]
                    st.lp_out.append(rec)
                stopped = False
                if st.stop_matcher is not None:
                    emitted, stripped = st.stop_matcher.push(tok)
                    if req.stream is not None:
                        for et in emitted:
                            req.stream.put(et)
                    if stripped:
                        # the matched stop sequence is NOT part of the
                        # output: truncate the record (the held tokens
                        # were never streamed)
                        del st.tokens[-stripped:]
                        if st.lp_out is not None:
                            del st.lp_out[-stripped:]
                        self.metrics.record_stop_hit()
                        stopped = True
                elif req.stream is not None:
                    # host-side fan-out for SSE: tokens already arrived
                    # with this horizon's one readback, so streaming
                    # costs zero extra device syncs
                    req.stream.put(tok)
                if stopped:
                    finished = True
                    # the device mask did NOT freeze this slot (stops
                    # are host-side): retire with deactivate below
                    break
                if (tok == req.eos_token
                        or len(st.tokens) >= req.max_new):
                    finished = True
                    break  # device mask froze this slot here too
            if finished:
                if stopped:
                    self._retire(slot, RequestStatus.FINISHED, now,
                                 deactivate=True)
                else:
                    self._finish(slot, now)

    def attach_sanitizer(self, san) -> None:
        """Attach an opt-in :class:`SyncSanitizer`: the engine stamps
        its phase (sweep/admit/dispatch/process) onto the sanitizer's
        thread-local so blocking syncs are attributed and budgeted, and
        registers each dispatch's host key snapshot for in-flight
        mutation checks. Detach with ``attach_sanitizer(None)``."""
        self._san = san

    def _set_phase(self, phase: str | None) -> None:
        san = self._san
        if san is not None:
            san.set_phase(phase)

    # lint: hot-path
    def step(self) -> bool:
        """One horizon boundary: sweep lifecycle, admit waiting
        requests, dispatch the next K-substep horizon, then sync and
        process the PREVIOUS horizon's tokens (so host bookkeeping
        overlaps device compute). Returns False when there was nothing
        to do. Raises ``EngineCrash`` when the dispatch loop cannot
        make progress (callers recover via :meth:`recover`)."""
        prof = self.profile
        if prof is not None:
            prof.step_start()
        now = time.perf_counter()
        try:
            self._set_phase("sweep")
            self._sweep_lifecycle(now)
            self._set_phase("admit")
            self._admit(now)
            self._set_phase("dispatch")
            prev, self._inflight = self._inflight, self._dispatch()
            if self._inflight is not None:
                self._steps += 1
            self._set_phase("process")
            if prev is not None:
                self._process(prev)
        finally:
            self._set_phase(None)
            if prof is not None:
                prof.step_end()
        progressed = (prev is not None or self._inflight is not None
                      or self._pb_did_work)
        if self.tracer.enabled and progressed:
            t_end = time.perf_counter()
            self.tracer.span(
                ENGINE_TRACK, "step", now, t_end - now, n=self._steps
            )
            self.tracer.counter(
                SCHEDULER_TRACK, "queue_depth", len(self.scheduler),
                ts=t_end,
            )
            self.tracer.counter(
                ENGINE_TRACK, "kv_slots_active", self.pool.n_active,
                ts=t_end,
            )
        return progressed

    # -- crash recovery ----------------------------------------------------

    def _reset_device_state(self) -> None:
        self._logits = jnp.zeros(
            (self.n_slots, self.cfg.vocab_size), jnp.float32
        )
        self._dpos = jnp.zeros((self.n_slots,), jnp.int32)
        self._dactive = jnp.zeros((self.n_slots,), bool)
        self._dbudget = jnp.zeros((self.n_slots,), jnp.int32)
        self._deos = jnp.full((self.n_slots,), _NO_EOS, jnp.int32)
        self._dgstate = jnp.zeros((self.n_slots,), jnp.int32)

    def _probe_chunked_parity(self) -> bool:
        """One-time probe for ``chunked_replay="auto"``: does a
        full-sequence bucketed prefill reproduce, bitwise, the logits
        of a shorter prefill + teacher-forced decode? (They are
        differently-scheduled XLA programs; on some backends they agree
        only to float-reassociation level, in which case chunked replay
        would break greedy byte-parity and stepwise replay is used.)
        Runs on abandoned pre-recovery state and leaves state zeroed."""
        length = int(min(self._max_bucket + 1, self.max_total))
        k = length - 2
        if k < 1:
            return False
        _disp = self.prefill_dispatches  # probes don't count
        self._attr_suspend += 1  # nor toward device-time attribution
        try:
            return self._probe_chunked_parity_inner(length, k)
        finally:
            self.prefill_dispatches = _disp
            self._attr_suspend -= 1

    def _probe_chunked_parity_inner(self, length: int, k: int) -> bool:
        seq = ((1 + np.arange(length)) % self.cfg.vocab_size).astype(
            np.int32
        )
        self.pool.reinit()
        self._reset_device_state()
        self._prefill_seq_into_slot(seq, 0, budget=1, eos_tok=_NO_EOS)
        la = np.asarray(self._logits[0])
        self.pool.reinit()
        self._reset_device_state()
        # budget length-k, not 1: in paged mode the prefill's block
        # coverage is len(seq)+budget, and the teacher-forced rows
        # [k, length) must land in allocated blocks (rows past coverage
        # scatter to the sentinel and vanish). Budget never feeds the
        # compared logits, so the slab verdict is unchanged.
        self._prefill_seq_into_slot(
            seq[:k], 0, budget=max(1, length - k), eos_tok=_NO_EOS
        )
        pos = np.zeros((self.n_slots,), np.int32)
        replaying = np.zeros((self.n_slots,), bool)
        replaying[0] = True
        for j in range(k, length):
            toks = np.zeros((self.n_slots,), np.int32)
            toks[0] = seq[j]
            pos[0] = j
            caches, self._logits = self._replay_fn(
                self.params, self._caches_in(), self._logits,
                jnp.asarray(toks), jnp.asarray(pos.copy()),
                jnp.asarray(replaying),
                jnp.zeros((self.n_slots,), jnp.int32),
            )
            self._caches_out(caches)
        lb = np.asarray(self._logits[0])
        self.pool.reinit()
        self._reset_device_state()
        return bool(np.array_equal(la, lb))

    def _use_chunked_replay(self) -> bool:
        if self.chunked_replay is True:
            return True
        if self.chunked_replay is False:
            return False
        if self._chunked_ok is None:
            self._chunked_ok = self._probe_verdict(
                "chunked_replay", self._probe_chunked_parity,
                n_slots=self.n_slots, max_total=self.max_total,
                max_bucket=self._max_bucket, tp=self.tp,
                paged=self._paged,
            )
        return self._chunked_ok

    def recover(self) -> int:
        """Rebuild engine/device state by deterministic replay after an
        engine-loop crash. The device buffers are abandoned (assumed
        corrupt — with donation they may already be invalidated
        mid-dispatch) and re-created zeroed; any un-synced horizon is
        dropped (its tokens were never recorded, so the replayed run
        regenerates them). Each live slot is then rebuilt either by
        CHUNKED replay — one bucketed prefill pass over
        ``prompt + tokens_so_far``, O(len/bucket) device calls — or by
        STEPWISE replay — re-prefill the original prompt, then
        teacher-force the recorded tokens one fused step at a time —
        per ``chunked_replay`` (see class docstring; "auto" probes for
        bitwise parity and falls back to stepwise). Queued requests are
        untouched. Returns the number of live requests replayed."""
        t_rec = time.perf_counter()
        self.metrics.record_restart()
        self.tracer.instant(ENGINE_TRACK, "crash", ts=t_rec)
        self.flight.record(
            "restart", n_live=sum(
                1 for st in self._slots if st is not None
            ), queue_depth=len(self.scheduler),
            restarts=self.metrics.n_restarts,
        )
        # pending attribution entries lost their completion proof with
        # the abandoned device state; replay dispatches don't count
        # (recovery wall time is not serving device time)
        self._pending_attr.clear()
        self._attr_suspend += 1
        try:
            return self._recover_inner(t_rec)
        finally:
            self._attr_suspend -= 1

    def _recover_inner(self, t_rec: float) -> int:
        self._inflight = None
        # deferred admissions lose their device-side chunk progress
        # with the abandoned buffers: hand them back to the scheduler
        # (reversed, so front-requeue restores admission order) before
        # the pool reinit and replay only seated slots. Their
        # pre-split sampling keys stay stashed — re-admission reuses
        # them without advancing the master chain, exactly the key an
        # uninterrupted blocking run would have assigned.
        if self._pending_prefills:
            for rec in reversed(self._pending_prefills):
                pl = rec.plan
                if pl.seg is not None and self.prefix_cache is not None:
                    self.prefix_cache.unpin(pl.seg)
                    pl.seg = None
                self.pool.release(pl.slot)
                self.scheduler.requeue(pl.req)
            self._pending_prefills.clear()
        live = [(s, st) for s, st in enumerate(self._slots)
                if st is not None]
        chunked = bool(live) and self._use_chunked_replay()
        self.pool.reinit()
        self._reset_device_state()
        if self.prefix_cache is not None:
            # the region shares the crash's blast radius (donated
            # programs may have invalidated it mid-flight): drop every
            # segment and re-create it zeroed. Replay then misses on
            # every lookup — i.e. it replays through the same lookup
            # path and takes the cold branch, byte-identical to a
            # cold-start replay.
            self.prefix_cache.reinit()
            for st in self._slots:
                if st is not None:
                    st.segs = []
        # re-seat each live slot's sampling key from its host record —
        # with position-indexed fold_in sampling this is all it takes
        # for a temperature>0 stream to resume exactly where it left off
        self._slot_keys[:] = 0
        self._slot_adapters[:] = 0
        if self._surface:
            # sampling-surface mirrors share the keys' re-seat
            # contract; the device grammar-table copies share the
            # crash's blast radius, so force a refresh from the host
            # table (which survived — it is plain numpy)
            self._slot_gstate[:] = 0
            self._slot_temps[:] = self.temperature
            self._slot_topks[:] = int(self.top_k or 0)
            self._slot_topps[:] = 1.0
            self._slot_bias_idx[:] = -1
            self._slot_bias_val[:] = 0.0
            self._gtab_version = -1
        for slot, st in live:
            self._slot_keys[slot] = st.key_data
            self._slot_adapters[slot] = st.adapter
            if self._surface:
                self._reseat_surface(slot, st)
        if self._surface and live:
            self._dgstate = jnp.asarray(self._slot_gstate.copy())
        self.last_recover_mode = (
            None if not live else ("chunked" if chunked else "stepwise")
        )
        if not live:
            log_event(_log, "engine_recovered", mode=None, n_replayed=0,
                      restarts=self.metrics.n_restarts)
            return 0
        if chunked:
            for slot, st in live:
                req = st.req
                seq = np.concatenate(
                    [req.prompt, np.asarray(st.tokens, np.int32)]
                )
                eos_tok = (_NO_EOS if req.eos_token is None
                           else int(req.eos_token))
                self._prefill_seq_into_slot(
                    seq, slot, req.max_new - len(st.tokens), eos_tok,
                    adapter=st.adapter,
                )
            self._log_recovered(t_rec, len(live))
            return len(live)
        pos = np.zeros((self.n_slots,), np.int32)
        for slot, st in live:
            req = st.req
            eos_tok = (_NO_EOS if req.eos_token is None
                       else int(req.eos_token))
            self._prefill_seq_into_slot(
                req.prompt, slot, req.max_new, eos_tok,
                adapter=st.adapter,
            )
            pos[slot] = len(req.prompt)
        for j in range(max((len(st.tokens) for _, st in live), default=0)):
            toks = np.zeros((self.n_slots,), np.int32)
            replaying = np.zeros((self.n_slots,), bool)
            for slot, st in live:
                if j < len(st.tokens):
                    toks[slot] = st.tokens[j]
                    replaying[slot] = True
            # pos must be snapshotted: jnp.asarray can zero-copy alias
            # a numpy buffer on CPU and dispatch is async, so mutating
            # pos below would race the in-flight replay step
            caches, self._logits = self._replay_fn(
                self.params, self._caches_in(), self._logits,
                jnp.asarray(toks), jnp.asarray(pos.copy()),
                jnp.asarray(replaying),
                jnp.asarray(self._slot_adapters.copy()),
            )
            self._caches_out(caches)
            for slot, st in live:
                if j < len(st.tokens):
                    pos[slot] += 1
        # stepwise replay drove positions through host arrays; re-seat
        # the device state to match the rebuilt trajectory
        active = np.zeros((self.n_slots,), bool)
        budget = np.zeros((self.n_slots,), np.int32)
        eos = np.full((self.n_slots,), _NO_EOS, np.int32)
        for slot, st in live:
            active[slot] = True
            budget[slot] = st.req.max_new - len(st.tokens)
            if st.req.eos_token is not None:
                eos[slot] = int(st.req.eos_token)
        self._dpos = jnp.asarray(pos)
        self._dactive = jnp.asarray(active)
        self._dbudget = jnp.asarray(budget)
        self._deos = jnp.asarray(eos)
        self._log_recovered(t_rec, len(live))
        return len(live)

    def _reseat_surface(self, slot: int, st: _SlotState) -> None:
        """Crash-recovery re-seat of one live slot's sampling-surface
        state (mirrors the adapter/key re-seat): per-slot sampler
        vectors from the request, the grammar FSM state re-walked over
        the recorded tokens from the seat state, and the stop-sequence
        hold-back rebuilt by re-pushing the stream (a live slot's
        record cannot contain a completed stop match, so the rebuild
        emits nothing we'd have to suppress — emissions are simply
        discarded, they already streamed before the crash)."""
        req = st.req
        self._slot_temps[slot] = np.float32(
            req.temperature if req.temperature is not None
            else self.temperature
        )
        self._slot_topks[slot] = np.int32(
            req.top_k if req.top_k is not None else (self.top_k or 0)
        )
        self._slot_topps[slot] = np.float32(
            req.top_p if req.top_p is not None else 1.0
        )
        self._slot_bias_idx[slot] = -1
        self._slot_bias_val[slot] = 0.0
        if req.logit_bias:
            for j, (ti, tv) in enumerate(sorted(req.logit_bias.items())):
                self._slot_bias_idx[slot, j] = ti
                self._slot_bias_val[slot, j] = tv
        g = int(st.gstate0)
        for t in st.tokens:
            g = self._gtable.advance(g, int(t))
        self._slot_gstate[slot] = g
        if st.stop_matcher is not None:
            st.stop_matcher = StopMatcher(req.stop)
            for t in st.tokens:
                st.stop_matcher.push(int(t))

    def _log_recovered(self, t_rec: float, n_replayed: int) -> None:
        now = time.perf_counter()
        self.tracer.span(
            ENGINE_TRACK, "recover", t_rec, now - t_rec,
            mode=self.last_recover_mode, n_replayed=n_replayed,
        )
        log_event(_log, "engine_recovered", mode=self.last_recover_mode,
                  n_replayed=n_replayed,
                  restarts=self.metrics.n_restarts,
                  recover_s=round(now - t_rec, 6))

    def fail_all(self, error: str) -> None:
        """Terminal supervision failure: fail every live and queued
        request (slot freed, ``done`` set) so no caller blocks on an
        engine that will never step again. Device state is left as-is
        (possibly corrupt — nothing will dispatch to it again)."""
        now = time.perf_counter()
        self._inflight = None
        while self._pending_prefills:
            self._drop_pending(
                self._pending_prefills.popleft(),
                RequestStatus.FAILED, error,
            )
        for slot, st in enumerate(self._slots):
            if st is not None:
                self._retire(slot, RequestStatus.FAILED, now, error=error)
        while True:
            req = self.scheduler.pop()
            if req is None:
                break
            self._retire_unadmitted(req, RequestStatus.FAILED, error)

    def run(self, max_steps: int | None = None, *,
            max_restarts: int = 5) -> dict[str, np.ndarray]:
        """Step until every queued/active request reaches a terminal
        status, supervising crashes: up to ``max_restarts`` replay
        recoveries before the crash propagates."""
        steps = 0
        restarts = 0
        while not self.idle:
            try:
                self.step()
            except EngineCrash:
                if restarts >= max_restarts:
                    raise
                restarts += 1
                self.recover()
                continue
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return self.results


def run_request_trace(
    engine: ServingEngine,
    trace: list[tuple[float, Request]],
    *,
    time_scale: float = 1.0,
    max_restarts: int = 5,
) -> dict[str, np.ndarray]:
    """Replay an arrival trace against a live engine.

    ``trace``: (arrival_offset_seconds, request) pairs; offsets are
    relative to the replay start and scaled by ``time_scale`` (0 floods
    every request instantly — useful for deterministic tests). The
    engine keeps stepping while waiting, exactly as a serving loop
    would, so admissions interleave with in-flight decodes. A submit
    rejected with ``Backpressure`` is retried on the next loop
    iteration (a decode step frees queue space) instead of killing the
    replay, and engine crashes recover by replay up to
    ``max_restarts`` times.
    """
    from collections import deque

    order = sorted(range(len(trace)), key=lambda j: trace[j][0])
    t0 = time.perf_counter()
    i = 0
    pending: deque[Request] = deque()
    restarts = 0
    while i < len(order) or pending or not engine.idle:
        now = time.perf_counter() - t0
        while i < len(order) and trace[order[i]][0] * time_scale <= now:
            pending.append(trace[order[i]][1])
            i += 1
        while pending:
            try:
                engine.submit(pending[0])
            except Backpressure:
                break  # queue full — a step below frees space, retry then
            pending.popleft()
        try:
            progressed = engine.step()
        except EngineCrash:
            if restarts >= max_restarts:
                raise
            restarts += 1
            engine.recover()
            continue
        if not progressed and not pending and i < len(order):
            # idle engine, next arrival still in the future
            time.sleep(
                min(0.001, max(0.0, trace[order[i]][0] * time_scale - now))
            )
    return engine.results
