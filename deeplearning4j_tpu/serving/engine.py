"""Continuous-batching decode engine — pipelined, multi-step hot path.

Iteration-level scheduling (Orca, OSDI '22) composed with multi-step
scheduling (vLLM): instead of batching whole requests, the engine
batches DECODE STEPS — and instead of paying one dispatch + one host
sync per step, it fuses ``decode_horizon`` (K) steps into ONE jitted
program and overlaps the host side of horizon n with the device side
of horizon n+1. It owns a fixed-shape batch of ``n_slots`` KV-cache
slots (one pooled ``init_caches`` allocation, see :mod:`cache_pool`);
every ``step()``:

1. sweeps occupied slots for cancelled/deadline-expired requests and
   retires them (slot freed within one horizon boundary);
2. admits queued requests into freed slots: a per-BUCKET jitted
   prefill runs at batch 1 (the prompt right-padded to a power-of-two
   length bucket) and its cache rows are inserted into the pooled
   buffers at the slot index; prompts longer than the largest bucket
   are chunked through ``forward_chunk`` at the same bucket sizes, so
   ``_prefill_fns`` holds O(log max_len) programs no matter how many
   distinct prompt lengths traffic brings;
3. DISPATCHES one fused K-substep decode program for all slots and
   only then
4. SYNCS the PREVIOUS horizon's (slots, K) token block, doing finish
   detection / retirement / metrics while the device is already
   computing the next horizon (async double-buffered readback — the
   ``np.asarray`` sync is the one blocking host sync per horizon).

Everything the per-substep decode logic needs lives ON DEVICE and is
threaded through the programs — positions, active mask, remaining
token budget, per-slot EOS id, pending logits — so EOS/max-len
deactivation happens in-program via the active mask: a slot that
finishes mid-horizon stops advancing (its position freezes, its
sampled tokens are masked to 0) without any host round trip. The host
replays the same stopping rule when the block arrives, so host
bookkeeping and the device mask can never disagree. Host <-> device
state only meets at admission (prefill writes the slot's state) and at
crash recovery (state is rebuilt from host records).

Slot-reuse slack: because horizon n's block is synced AFTER horizon
n+1 is dispatched, a slot retired at sync time may already appear in
the in-flight horizon. Each dispatch snapshots (slot, occupant,
pool generation); a sync discards blocks whose slot has since been
retired or re-acquired (the dummy tokens a finished slot decodes are
dead by construction — the next admission's prefill insert rewrites
the whole Tpad slab).

jit stability: exactly one compiled step program per engine, one
prefill program per power-of-two bucket, one chunk program per bucket
on the long-prompt path, plus two tiny state-edit programs.

Greedy determinism: at ``temperature=0`` the engine samples via the
same ``_top_k_filter`` + argmax the plain ``transformer_generate``
path uses; the decode math is row-/padding-invariant (masked cache
rows contribute exact zeros), and a right-padded bucket prefill is
bitwise identical to an exact-length prefill at the true last row
(causal masking — pinned empirically by the parity tests), so token
streams are byte-identical to running each request alone for every
horizon K — ``tests/test_serving.py`` asserts K in {1, 2, 4, 8}.

Sampled determinism: at ``temperature > 0`` each slot gets its own
sampling key at admission (split from the engine master key in
admission order) and token ``i`` is drawn with ``fold_in(slot_key,
position_i)`` — the key stream is a pure function of (slot key,
position), independent of batch composition, horizon K, and crashes.
Persisting the key data per slot makes crash-recovery replay exact for
sampled requests too: replay teacher-forces the recorded tokens, then
sampling resumes at the next position with the next key the
uninterrupted run would have used (``tests/test_serving_faults.py``
pins byte-parity for a sampled run crashed mid-decode).

Fault tolerance (the DL4J lineage: the reference runtime supervised
its workers via Akka and rebuilt them from ZooKeeper state; here the
unit of supervision is the horizon dispatch and the durable state is
host-side). The engine consults an optional
:class:`~.faults.FaultInjector` at its two host boundaries — "step"
before each horizon dispatch, "prefill" before each admission — and
supervises itself:

- a ``TransientFault`` at a boundary retries with capped exponential
  backoff (``max_retries``/``retry_backoff_s``/``max_backoff_s``);
- a fault that PERSISTS past the retry budget, or a ``PermanentFault``,
  quarantines only the implicated request — slot freed, ``done`` set,
  status ``FAILED`` — and the batch keeps decoding;
- an ``EngineCrash`` (or any fault with no implicated request)
  abandons the device state entirely (including any un-synced
  horizon: its tokens were never recorded, so replay simply
  regenerates them); :meth:`recover` rebuilds state by DETERMINISTIC
  REPLAY. Two replay modes:

  * **stepwise** (the conservative default): re-prefill every live
    slot's original prompt through the same bucketed program as its
    admission, then TEACHER-FORCE the recorded tokens one fused step
    at a time — exactly re-tracing the crashed run's op sequence, so
    at ``temperature=0`` the resumed stream is byte-identical to an
    uninterrupted one (chaos parity tests pin this);
  * **chunked** (O(prompt/bucket + tokens/bucket) device calls per
    slot instead of O(tokens)): re-prefill ``prompt + tokens_so_far``
    in one pass through the bucketed/chunked prefill path. The
    prefill-path logits can differ from the decode-path logits in the
    last float bit (different XLA schedules), so ``chunked_replay=
    "auto"`` runs a one-time parity probe at first recovery —
    full-sequence prefill vs prefill+teacher-forcing on a synthetic
    sequence — and only enables chunked replay when they agree
    bitwise; otherwise it falls back to stepwise. ``True``/``False``
    force a mode (``tests/test_serving_faults.py`` covers both).

Request lifecycle: ``Request.deadline_s`` and ``Request.cancel()`` are
checked at every horizon boundary; a timed-out or cancelled request is
retired (status EXPIRED/CANCELLED, partial stream in ``results``, KV
slot freed) instead of decoding to ``max_new``. :meth:`preempt_all`
cancels every live and queued request — the drain-deadline hook
``ServingServer.stop`` uses to converge instead of waiting out
stragglers. ``last_dispatch_t`` is a monotonic heartbeat for the
server's hung-engine watchdog.
"""

from __future__ import annotations

import logging
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from deeplearning4j_tpu.models.transformer import (
    TransformerConfig,
    _chunk_builder,
    _decode_builder,
    _top_k_filter,
)
from deeplearning4j_tpu.obs.logs import log_event
from deeplearning4j_tpu.obs.profiler import ProfileTrigger
from deeplearning4j_tpu.obs.trace import (
    ENGINE_TRACK,
    SCHEDULER_TRACK,
    Tracer,
    slot_track,
)
from deeplearning4j_tpu.serving.cache_pool import KVSlotPool
from deeplearning4j_tpu.serving.faults import (
    EngineCrash,
    FaultInjector,
    PermanentFault,
    TransientFault,
)
from deeplearning4j_tpu.serving.metrics import ServingMetrics
from deeplearning4j_tpu.serving.scheduler import (
    Backpressure,
    Request,
    RequestScheduler,
    RequestStatus,
)

#: device EOS id for requests without one (never equals a sampled token)
_NO_EOS = -1

_log = logging.getLogger(__name__)


class _SlotState:
    """Host-side record for one occupied slot."""

    __slots__ = ("req", "tokens", "t_first_token", "gen", "key_data")

    def __init__(self, req: Request, gen: int, key_data):
        self.req = req
        self.tokens: list[int] = []
        self.t_first_token: float | None = None
        self.gen = gen  # pool generation at admission (reuse detection)
        # raw uint32 data of the slot's sampling key (host-persisted so
        # crash-recovery replay resumes the exact key stream)
        self.key_data = key_data


class _Inflight:
    """One dispatched-but-unsynced horizon: the device future holding
    the (slots, K) token block plus a snapshot of who occupied each
    slot at dispatch time."""

    __slots__ = ("toks", "snaps", "t_dispatch")

    def __init__(self, toks, snaps, t_dispatch):
        self.toks = toks
        self.snaps = snaps  # [(slot, _SlotState)] occupied at dispatch
        self.t_dispatch = t_dispatch


class ServingEngine:
    """Fixed-shape pipelined continuous-batching decode loop.

    ``params`` may be float or ``quantize_decode_params`` output (pair
    with ``cfg.decode_int8=True`` for the int8 KV cache). Sampling
    settings are engine-wide (they are baked into the compiled step);
    ``temperature=0`` decodes greedily.

    ``decode_horizon`` (K) is the number of decode steps fused into one
    dispatched program; lifecycle checks, admission and fault injection
    happen at horizon boundaries, so K trades up-to-K-steps extra
    admission/TTFT latency for amortized dispatch + host-sync overhead.
    K=1 reproduces the unpipelined per-step cadence except that token
    readback still lags dispatch by one step (the double buffer).

    ``prefill_max_bucket`` caps the power-of-two prompt padding bucket;
    longer prompts are chunked through the same buckets.
    ``chunked_replay`` picks the crash-replay mode ("auto" probes for
    bitwise prefill/decode parity at first recovery; see module doc).

    Supervision knobs: ``faults`` (an optional
    :class:`~.faults.FaultInjector`), ``max_retries`` transient retries
    per boundary with exponential backoff starting at
    ``retry_backoff_s`` capped at ``max_backoff_s``. ``results_cap``
    bounds the finished-stream dict (oldest evicted first) so sustained
    traffic cannot leak host memory; front ends should prefer
    :meth:`pop_result`, which removes the entry on read.

    Observability: ``tracer`` (an :class:`~deeplearning4j_tpu.obs
    .trace.Tracer`) records the request lifecycle as spans — queued on
    the scheduler track, prefill/decode/first-token/terminal per slot
    track, dispatch/sync/step on the engine track — defaulting to a
    DISABLED tracer (every record call is one attribute check);
    ``profile`` (an :class:`~deeplearning4j_tpu.obs.profiler
    .ProfileTrigger`) brackets engine steps so an armed XLA capture
    starts and stops on step boundaries.
    """

    def __init__(
        self,
        cfg: TransformerConfig,
        params,
        *,
        n_slots: int = 8,
        max_total: int | None = None,
        temperature: float = 0.0,
        top_k: int | None = None,
        approx_top_k: bool = False,
        decode_horizon: int = 1,
        prefill_max_bucket: int = 128,
        chunked_replay: bool | str = "auto",
        scheduler: RequestScheduler | None = None,
        metrics: ServingMetrics | None = None,
        rng_seed: int = 0,
        faults: FaultInjector | None = None,
        max_retries: int = 3,
        retry_backoff_s: float = 0.01,
        max_backoff_s: float = 0.25,
        results_cap: int = 1024,
        tracer: Tracer | None = None,
        profile: ProfileTrigger | None = None,
    ):
        self.cfg = cfg
        self.n_slots = n_slots
        self.max_total = int(min(max_total or cfg.max_len, cfg.max_len))
        self.temperature = temperature
        self.top_k = top_k
        self.approx_top_k = approx_top_k
        self.decode_horizon = max(1, int(decode_horizon))
        self.chunked_replay = chunked_replay
        self.faults = faults
        self.max_retries = max_retries
        self.retry_backoff_s = retry_backoff_s
        self.max_backoff_s = max_backoff_s
        self.results_cap = results_cap
        # disabled-by-default tracer: every record call is one attribute
        # check, so leaving it wired costs nothing (see obs.trace)
        self.tracer = tracer if tracer is not None else Tracer(enabled=False)
        self.profile = profile

        fwd1, init_caches, do_prefill, cast_params = _decode_builder(cfg)
        self._fwd1 = fwd1
        self._init_caches = init_caches
        self._do_prefill = do_prefill
        self._fwd_chunk = _chunk_builder(cfg)
        # one-time weight cast (generate does this inside its jitted
        # program; hoisting it out of the per-step program keeps every
        # step from re-casting — same values, cast is deterministic)
        self.params = jax.jit(cast_params)(params)

        self.pool = KVSlotPool(cfg, n_slots, self.max_total)
        self.scheduler = scheduler or RequestScheduler(
            max_total_tokens=self.max_total
        )
        if self.scheduler.max_total_tokens is None:
            self.scheduler.max_total_tokens = self.max_total
        self.metrics = metrics or ServingMetrics()
        self.metrics.decode_horizon = self.decode_horizon
        self._register_gauges()

        # power-of-two prompt buckets: the largest must respect the
        # positional table (prefill embeds rows 0..bucket-1) and the
        # pooled slab row count (the insert window must fit Tpad)
        limit = min(int(prefill_max_bucket), cfg.max_len, self.pool.tpad)
        mb = 1
        while mb * 2 <= limit:
            mb *= 2
        self._max_bucket = mb
        self._min_bucket = min(8, mb)

        # per-slot decode state, DEVICE-resident (threaded through the
        # fused step so pipelined dispatch never reads stale host state)
        self._logits = jnp.zeros((n_slots, cfg.vocab_size), jnp.float32)
        self._dpos = jnp.zeros((n_slots,), jnp.int32)
        self._dactive = jnp.zeros((n_slots,), bool)
        self._dbudget = jnp.zeros((n_slots,), jnp.int32)
        self._deos = jnp.full((n_slots,), _NO_EOS, jnp.int32)

        self._slots: list[_SlotState | None] = [None] * n_slots
        self._inflight: _Inflight | None = None
        self._results: dict[str, np.ndarray] = {}
        self._key = jax.random.key(rng_seed)
        # per-slot sampling keys, split from the master key at
        # admission (deterministic by admission order). The step
        # program derives each sampled token's key as
        # fold_in(slot_key, position) — a pure function of slot key and
        # position, independent of batch composition or horizon K, so
        # crash-recovery replay (teacher-force recorded tokens, re-seat
        # positions and keys) resumes the EXACT key stream an
        # uninterrupted run would have used. _slot_keys is the raw
        # uint32 key data, host-side; each _SlotState keeps its row.
        _kd0 = np.asarray(jax.random.key_data(self._key))
        self._slot_keys = np.zeros(
            (n_slots,) + _kd0.shape, _kd0.dtype
        )
        self._steps = 0
        self._admitting = 0  # requests between scheduler pop and slot
        self.last_dispatch_t: float | None = None  # watchdog heartbeat
        self._chunked_ok: bool | None = None  # replay parity probe memo
        self.last_recover_mode: str | None = None

        # donating the cache + per-slot state lets XLA update them in
        # place (the cache is the dominant allocation); CPU jit can't
        # alias donated buffers and would warn every call
        tpu = jax.devices()[0].platform == "tpu"
        self._state_donate = (1, 2, 3, 4, 5) if tpu else ()
        self._step_fn = jax.jit(
            self._build_step(), donate_argnums=self._state_donate
        )
        self._replay_fn = jax.jit(
            self._build_replay_step(),
            donate_argnums=(1, 2) if tpu else (),
        )
        self._deact_fn = jax.jit(
            lambda active, slot: active.at[slot].set(False),
            donate_argnums=(0,) if tpu else (),
        )
        self._prefill_fns: dict[int, object] = {}
        self._chunk_fns: dict[int, object] = {}
        self._insert_fn = None
        self._admit_donate = (0, 1, 2, 3, 4, 5) if tpu else ()

    def _register_gauges(self) -> None:
        """Live-state gauges on the metrics registry: scrapes read
        engine state through callbacks, so the hot path never updates
        them."""
        reg = self.metrics.registry
        reg.gauge(
            "serve_kv_slots", "KV slot pool size (decode batch width).",
        ).set_function(lambda: self.n_slots)
        reg.gauge(
            "serve_kv_slots_active", "KV slots currently occupied.",
        ).set_function(lambda: self.pool.n_active)
        reg.gauge(
            "serve_kv_occupancy", "Occupied fraction of the slot pool.",
        ).set_function(lambda: self.pool.occupancy)
        reg.gauge(
            "serve_kv_slot_generations",
            "Total slot acquire count (slot-reuse churn).",
        ).set_function(
            lambda: sum(
                self.pool.generation(s) for s in range(self.n_slots)
            )
        )
        reg.gauge(
            "serve_kv_cache_bytes", "Device bytes of the pooled KV cache.",
        ).set_function(lambda: self.pool.nbytes())
        reg.gauge(
            "serve_queue_depth", "Requests queued, not yet admitted.",
        ).set_function(lambda: len(self.scheduler))

    # -- compiled programs -------------------------------------------------

    def _build_step(self):
        """K fused decode substeps in one program. The carry —
        caches, pending logits, positions, active mask, remaining
        budget — lives entirely on device; ``eos`` is per-slot data.
        The chain is unrolled (not ``lax.scan``) so XLA keeps in-place
        cache updates; the layer loop inside ``fwd1`` is already
        unrolled for the same reason."""
        fwd1 = self._fwd1
        temperature, top_k = self.temperature, self.top_k
        approx_top_k = self.approx_top_k
        horizon = self.decode_horizon

        def step(params, caches, logits, pos, active, budget, eos,
                 slot_keys_raw):
            # per-slot keys (raw uint32 rows, host-persisted): token i
            # of slot s is sampled with fold_in(key_s, position) — a
            # pure function of the slot's admission key and its stream
            # position, so the key stream is invariant to batch
            # composition, horizon K, and crash-recovery replay
            keys = (
                jax.random.wrap_key_data(slot_keys_raw)
                if temperature != 0 else None
            )
            toks_all = []
            for k in range(horizon):
                filt = _top_k_filter(logits, top_k, approx_top_k)
                if temperature == 0:
                    toks = jnp.argmax(filt, axis=-1).astype(jnp.int32)
                else:
                    tok_keys = jax.vmap(jax.random.fold_in)(keys, pos)
                    toks = jax.vmap(
                        lambda kk, lg: jax.random.categorical(kk, lg)
                    )(tok_keys, filt / temperature).astype(jnp.int32)
                # inactive slots decode token 0 at their frozen
                # position — shape stability; the garbage row they
                # write stays inside their own slab and is wiped by the
                # next admission's prefill insert
                toks = jnp.where(active, toks, 0)
                new_logits, caches = fwd1(params, caches, toks, pos)
                # advance only live slots, then deactivate in-program:
                # a slot that just emitted EOS or spent its budget
                # stops mutating for the rest of the horizon
                pos = jnp.where(active, pos + 1, pos)
                budget = jnp.where(active, budget - 1, budget)
                active = active & (toks != eos) & (budget > 0)
                logits = new_logits
                toks_all.append(toks)
            return (caches, logits, pos, active, budget,
                    jnp.stack(toks_all, axis=1))

        return step

    def _build_replay_step(self):
        """Teacher-forced decode step for stepwise crash recovery: feed
        RECORDED tokens (no sampling) and freeze the pending-logits
        rows of slots whose recording is already exhausted — those rows
        must stay exactly what the slot's last real step produced."""
        fwd1 = self._fwd1

        def rstep(params, caches, logits, toks, pos, replaying):
            new_logits, caches = fwd1(params, caches, toks, pos)
            logits = jnp.where(replaying[:, None], new_logits, logits)
            return caches, logits

        return rstep

    def _prefill_fn(self, bucket: int):
        """Jitted fused admission program for one prompt bucket:
        prefill-at-batch-1 over the padded prompt, slab insert at the
        slot index, and the slot's device state (pos/active/budget/eos
        + pending logits) set in the same dispatch."""
        fn = self._prefill_fns.get(bucket)
        if fn is None:
            do_prefill = self._do_prefill
            init_caches = self._init_caches
            max_total = self.max_total

            def prefill(caches, logits, pos, active, budget, eos,
                        params, prompt, last_idx, slot, pos0, max_new,
                        eos_tok):
                # batch-1 prefill into a scratch single-slot cache of
                # the SAME Tpad as the pool, then insert the slab at
                # the slot index. The slab copy includes the zero rows
                # beyond the prompt — that wipes the previous
                # occupant's rows, so no stale state survives reuse.
                # ``last_idx`` points at the true last prompt row; the
                # padded rows are causally invisible to it, so the
                # logits are bitwise those of an exact-length prefill.
                tmp, lg = do_prefill(
                    params, init_caches(1, max_total), prompt,
                    last_idx=last_idx,
                )
                caches = jax.tree.map(
                    lambda c, t: lax.dynamic_update_slice(
                        c, t, (0, 0, slot, 0, 0)
                    ),
                    caches, tmp,
                )
                logits = lax.dynamic_update_slice(logits, lg, (slot, 0))
                pos = pos.at[slot].set(pos0)
                active = active.at[slot].set(True)
                budget = budget.at[slot].set(max_new)
                eos = eos.at[slot].set(eos_tok)
                return caches, logits, pos, active, budget, eos

            fn = jax.jit(prefill, donate_argnums=self._admit_donate)
            self._prefill_fns[bucket] = fn
        return fn

    def _chunk_fn(self, bucket: int):
        """Jitted chunk-at-offset program for the long-prompt path: one
        ``forward_chunk`` pass over ``bucket`` rows of a batch-1
        scratch cache, returning the (1, V) logits at ``last_idx``."""
        fn = self._chunk_fns.get(bucket)
        if fn is None:
            fwd_chunk = self._fwd_chunk

            def chunk(params, tmp, toks, pos0, last_idx):
                lg, tmp = fwd_chunk(
                    params, tmp, toks, pos0, last_idx=last_idx
                )
                return tmp, lg

            fn = jax.jit(chunk)
            self._chunk_fns[bucket] = fn
        return fn

    def _insert(self):
        """Jitted slab insert + state set (no prefill): lands a scratch
        cache built by the chunked path — or zeros, for an empty
        prompt — into the pool at the slot index."""
        if self._insert_fn is None:

            def insert(caches, logits, pos, active, budget, eos, tmp,
                       lg, slot, pos0, max_new, eos_tok):
                caches = jax.tree.map(
                    lambda c, t: lax.dynamic_update_slice(
                        c, t, (0, 0, slot, 0, 0)
                    ),
                    caches, tmp,
                )
                logits = lax.dynamic_update_slice(logits, lg, (slot, 0))
                pos = pos.at[slot].set(pos0)
                active = active.at[slot].set(True)
                budget = budget.at[slot].set(max_new)
                eos = eos.at[slot].set(eos_tok)
                return caches, logits, pos, active, budget, eos

            self._insert_fn = jax.jit(
                insert, donate_argnums=self._admit_donate
            )
        return self._insert_fn

    # -- bucketing ---------------------------------------------------------

    def _bucket_for(self, n: int) -> int:
        """Smallest power-of-two bucket >= n (caller ensures
        ``n <= self._max_bucket``)."""
        b = self._min_bucket
        while b < n:
            b *= 2
        return b

    def _chunk_schedule(self, n: int) -> list[tuple[int, int, int]]:
        """(offset, real_len, bucket) chunks covering a long prompt's
        rows 0..n-1 through the power-of-two bucket programs. Every
        write window [offset, offset+bucket) must fit the pooled Tpad
        (a clamped ``dynamic_update_slice`` would SHIFT over real
        rows); when the padded tail would spill, the remainder is
        decomposed into exact power-of-two pieces plus one minimal
        padded tail, which always fits (pieces are sublane multiples,
        Tpad is a sublane multiple)."""
        tpad = self.pool.tpad
        sched, t0, rem = [], 0, n
        while rem > self._max_bucket:
            sched.append((t0, self._max_bucket, self._max_bucket))
            t0 += self._max_bucket
            rem -= self._max_bucket
        if rem:
            b = self._bucket_for(rem)
            if t0 + b <= tpad:
                sched.append((t0, rem, b))
            else:
                while rem:
                    if rem >= b:
                        sched.append((t0, b, b))
                        t0 += b
                        rem -= b
                    elif b > self._min_bucket:
                        b //= 2
                    else:
                        sched.append((t0, rem, b))
                        rem = 0
        for t0, _, b in sched:  # invariant: no clamped insert, ever
            if t0 + b > tpad:
                raise AssertionError(
                    f"chunk window [{t0}, {t0 + b}) spills Tpad {tpad}"
                )
        return sched

    # -- host-side loop ----------------------------------------------------

    def submit(self, req: Request) -> str:
        """Queue a request (see ``RequestScheduler.submit`` for the
        backpressure/admission contract)."""
        try:
            rid = self.scheduler.submit(req)
        except Backpressure:
            self.metrics.record_backpressure()
            self.tracer.instant(
                SCHEDULER_TRACK, "backpressure", req_id=req.id
            )
            raise
        self.tracer.instant(SCHEDULER_TRACK, "submit", req_id=rid)
        log_event(_log, "request_submitted", level=logging.DEBUG,
                  req_id=rid, prompt_len=len(req.prompt),
                  max_new=req.max_new)
        return rid

    @property
    def results(self) -> dict[str, np.ndarray]:
        """Terminal streams by request id: prompt + generated tokens
        (partial for CANCELLED/EXPIRED/FAILED-while-running). Bounded
        to ``results_cap`` entries, oldest evicted; ``pop_result``
        consumes an entry."""
        return self._results

    def pop_result(self, req_id: str, default=None):
        """Remove and return a terminal stream (front-end consumption:
        read-once keeps the results dict from growing with traffic)."""
        return self._results.pop(req_id, default)

    @property
    def idle(self) -> bool:
        """True when no request is queued, mid-admission, decoding, or
        awaiting readback. ``pool.n_active`` (not the device mask) is
        what covers the admission window — the slot is acquired before
        the prefill runs, and a concurrent drain must not mistake that
        window for idleness; ``_admitting`` covers the few instructions
        between the scheduler pop and the acquire; ``_inflight`` covers
        the pipelined horizon whose tokens are still on device."""
        return (self.pool.n_active == 0 and self._admitting == 0
                and len(self.scheduler) == 0 and self._inflight is None)

    def cancel(self, req_id: str) -> bool:
        """Cancel by id: flags the request whether it is queued or
        decoding; the engine honors the flag within one horizon.
        Returns False when the id is unknown (already retired or never
        seen)."""
        for st in self._slots:
            if st is not None and st.req.id == req_id:
                st.req.cancel()
                return True
        return self.scheduler.cancel(req_id)

    def preempt_all(self) -> int:
        """Cancel every live and queued request (drain-deadline
        preemption: ``ServingServer.stop`` calls this when ``drain_s``
        elapses, so shutdown converges within one horizon instead of
        waiting out stragglers). Returns the number newly cancelled."""
        n = 0
        for st in self._slots:
            if st is not None and not st.req.cancelled:
                st.req.cancel()
                n += 1
        return n + self.scheduler.cancel_all()

    # -- retirement --------------------------------------------------------

    def _store_result(self, req: Request, tokens: list[int]) -> None:
        self._results[req.id] = np.concatenate(
            [req.prompt, np.asarray(tokens, np.int32)]
        )
        while len(self._results) > self.results_cap:
            self._results.pop(next(iter(self._results)))

    def _retire(self, slot: int, status: RequestStatus, now: float,
                error: str | None = None, *,
                deactivate: bool = False) -> None:
        """Free a slot and move its request to a terminal status.
        ``deactivate`` also clears the slot's DEVICE active bit — needed
        when the device mask may still be live (cancel/expiry/
        quarantine); a FINISHED slot already deactivated in-program."""
        st = self._slots[slot]
        req = st.req
        req.status = status
        req.error = error
        self._store_result(req, st.tokens)
        if status is RequestStatus.FINISHED:
            self.metrics.record_finished(
                req.id, len(st.tokens),
                now - (st.t_first_token or now),
            )
        else:
            self.metrics.record_outcome(status)
        self.pool.release(slot)
        self._slots[slot] = None
        if deactivate:
            self._dactive = self._deact_fn(self._dactive, jnp.int32(slot))
        self.tracer.instant(
            slot_track(slot), status.value, ts=now, req_id=req.id,
            n_tokens=len(st.tokens),
        )
        log_event(_log, "request_retired", req_id=req.id, slot=slot,
                  status=status.value, n_tokens=len(st.tokens),
                  error=error)
        if req.done is not None:
            req.done.set()

    def _retire_unadmitted(self, req: Request, status: RequestStatus,
                           error: str | None = None) -> None:
        """Terminal status for a request that never got a slot."""
        req.status = status
        req.error = error
        self.metrics.record_outcome(status)
        self.tracer.instant(
            SCHEDULER_TRACK, status.value, req_id=req.id
        )
        log_event(_log, "request_retired", req_id=req.id, slot=None,
                  status=status.value, n_tokens=0, error=error)
        if req.done is not None:
            req.done.set()

    def _finish(self, slot: int, now: float) -> None:
        self._retire(slot, RequestStatus.FINISHED, now)

    def _slot_of(self, req_id: str | None) -> int | None:
        if req_id is None:
            return None
        for slot, st in enumerate(self._slots):
            if st is not None and st.req.id == req_id:
                return slot
        return None

    def _sweep_lifecycle(self, now: float) -> None:
        """Retire cancelled / deadline-expired occupied slots (this is
        what bounds slot occupation to one horizon past cancel/expiry).
        Tokens still in flight for a swept slot are discarded at sync
        by the snapshot identity check."""
        for slot, st in enumerate(self._slots):
            if st is None:
                continue
            if st.req.cancelled:
                self._retire(slot, RequestStatus.CANCELLED, now,
                             deactivate=True)
            elif st.req.expired(now):
                self._retire(slot, RequestStatus.EXPIRED, now,
                             deactivate=True)

    # -- admission ---------------------------------------------------------

    def _prefill_seq_into_slot(self, seq: np.ndarray, slot: int,
                               budget: int, eos_tok: int) -> None:
        """Land ``seq`` (prompt, or prompt+replayed tokens) in ``slot``
        through the bucketed prefill path and set the slot's device
        state: position len(seq), active, ``budget`` tokens remaining.
        Dispatches O(1) programs for bucket-sized sequences and
        O(len/bucket) on the chunked long-prompt path."""
        n = int(len(seq))
        state = (self.pool.caches, self._logits, self._dpos,
                 self._dactive, self._dbudget, self._deos)
        if n == 0:
            # empty prompt: decode starts from uniform logits over a
            # zeroed slab, as the unbucketed prefill did
            tmp = self._init_caches(1, self.max_total)
            lg = jnp.zeros((1, self.cfg.vocab_size), jnp.float32)
            out = self._insert()(
                *state, tmp, lg, jnp.int32(slot), jnp.int32(0),
                jnp.int32(budget), jnp.int32(eos_tok),
            )
        elif n <= self._max_bucket:
            b = self._bucket_for(n)
            pad = np.zeros((1, b), np.int32)
            pad[0, :n] = seq
            out = self._prefill_fn(b)(
                *state, self.params, jnp.asarray(pad), jnp.int32(n - 1),
                jnp.int32(slot), jnp.int32(n), jnp.int32(budget),
                jnp.int32(eos_tok),
            )
        else:
            # chunked: walk the prompt through forward_chunk at bucket
            # sizes over a batch-1 scratch cache, then one slab insert —
            # a long admission compiles nothing new and never stalls
            # the decode loop on a monster program
            tmp = self._init_caches(1, self.max_total)
            lg = None
            for t0, ln, b in self._chunk_schedule(n):
                pad = np.zeros((1, b), np.int32)
                pad[0, :ln] = seq[t0:t0 + ln]
                tmp, lg = self._chunk_fn(b)(
                    self.params, tmp, jnp.asarray(pad), jnp.int32(t0),
                    jnp.int32(ln - 1),
                )
            out = self._insert()(
                *state, tmp, lg, jnp.int32(slot), jnp.int32(n),
                jnp.int32(budget), jnp.int32(eos_tok),
            )
        (self.pool.caches, self._logits, self._dpos, self._dactive,
         self._dbudget, self._deos) = out

    def _prefill_with_retries(self, req: Request, slot: int) -> bool:
        """Run the admission prefill under transient-retry supervision.
        Returns False when the request is poisoned (caller fails it).
        One fault check per ADMISSION (not per chunk), so scripted
        chaos plans stay request-aligned."""
        attempt, backoff = 0, self.retry_backoff_s
        eos_tok = _NO_EOS if req.eos_token is None else int(req.eos_token)
        while True:
            try:
                if self.faults is not None:
                    self.faults.check("prefill", req_id=req.id)
                self._prefill_seq_into_slot(
                    req.prompt, slot, req.max_new, eos_tok
                )
                return True
            except TransientFault as e:
                self.metrics.record_retry()
                attempt += 1
                if attempt > self.max_retries:
                    req.error = (
                        f"transient prefill fault persisted past "
                        f"{self.max_retries} retries: {e}"
                    )
                    return False
                time.sleep(backoff)
                backoff = min(backoff * 2, self.max_backoff_s)
            except PermanentFault as e:
                req.error = str(e)
                return False

    def _admit(self, now: float) -> None:
        while self.pool.n_free and len(self.scheduler):
            self._admitting += 1
            try:
                req = self.scheduler.pop()
                if req is None:
                    break
                if req.cancelled:
                    self._retire_unadmitted(req, RequestStatus.CANCELLED)
                    continue
                if req.expired(now):
                    self._retire_unadmitted(req, RequestStatus.EXPIRED)
                    continue
                slot = self.pool.acquire()
                t_pf = time.perf_counter()
                try:
                    ok = self._prefill_with_retries(req, slot)
                except BaseException:
                    # EngineCrash (or anything unexpected) between pop
                    # and admission: the request must not be dropped —
                    # put it back at the front of its class before the
                    # supervisor rebuilds state.
                    self.pool.release(slot)
                    self.scheduler.requeue(req)
                    raise
                t_adm = time.perf_counter()
                self.metrics.record_prefill(req.id, t_adm - t_pf)
                if not ok:
                    self.pool.release(slot)
                    self._retire_unadmitted(
                        req, RequestStatus.FAILED, req.error
                    )
                    continue
                # split the slot's sampling key here (deterministic by
                # admission order — the same order replay reproduces)
                self._key, sub = jax.random.split(self._key)
                kd = np.asarray(jax.random.key_data(sub))
                self._slot_keys[slot] = kd
                self._slots[slot] = _SlotState(
                    req, self.pool.generation(slot), kd
                )
                req.status = RequestStatus.RUNNING
                delay = (time.perf_counter() - req.arrival_time
                         if req.arrival_time is not None else None)
                if delay is not None:
                    self.metrics.record_admitted(req.id, delay)
                    self.tracer.span(
                        SCHEDULER_TRACK, "queued", req.arrival_time,
                        delay, req_id=req.id,
                    )
                self.tracer.span(
                    slot_track(slot), "prefill", t_pf, t_adm - t_pf,
                    req_id=req.id, prompt_len=len(req.prompt),
                )
                log_event(_log, "request_admitted", req_id=req.id,
                          slot=slot, prompt_len=len(req.prompt),
                          queue_delay_s=delay,
                          prefill_s=round(t_adm - t_pf, 6))
            finally:
                self._admitting -= 1

    # -- supervised dispatch + pipelined readback --------------------------

    def _dispatch(self) -> _Inflight | None:
        """Dispatch one fused K-substep horizon for every occupied slot
        under transient-retry supervision; returns the in-flight record
        WITHOUT syncing its tokens. Persistent faults quarantine the
        implicated request when one is named, otherwise escalate to
        ``EngineCrash`` (replay recovery). Returns None when there is
        nothing to dispatch (or quarantining emptied the batch)."""
        if not any(st is not None for st in self._slots):
            return None
        attempt, backoff = 0, self.retry_backoff_s
        t_call = time.perf_counter()
        while True:
            try:
                if self.faults is not None:
                    self.faults.check("step")
                # .copy(): jnp.asarray can zero-copy alias the mutable
                # host key buffer on CPU, and dispatch is async — a
                # concurrent admission writing a slot key must not race
                # the in-flight step
                (self.pool.caches, self._logits, self._dpos,
                 self._dactive, self._dbudget, toks) = self._step_fn(
                    self.params, self.pool.caches, self._logits,
                    self._dpos, self._dactive, self._dbudget,
                    self._deos, jnp.asarray(self._slot_keys.copy()),
                )
                break
            except TransientFault as e:
                self.metrics.record_retry()
                self.tracer.instant(
                    ENGINE_TRACK, "retry", site="step", error=str(e)
                )
                attempt += 1
                if attempt <= self.max_retries:
                    time.sleep(backoff)
                    backoff = min(backoff * 2, self.max_backoff_s)
                    continue
                slot = self._slot_of(e.req_id)
                if slot is None:
                    raise EngineCrash(
                        f"transient step fault persisted past "
                        f"{self.max_retries} retries: {e}"
                    ) from e
                self._retire(slot, RequestStatus.FAILED,
                             time.perf_counter(), error=str(e),
                             deactivate=True)
                if not any(st is not None for st in self._slots):
                    return None
                attempt, backoff = 0, self.retry_backoff_s
            except PermanentFault as e:
                slot = self._slot_of(e.req_id)
                if slot is None:
                    raise EngineCrash(
                        f"permanent step fault names no live request: {e}"
                    ) from e
                self._retire(slot, RequestStatus.FAILED,
                             time.perf_counter(), error=str(e),
                             deactivate=True)
                if not any(st is not None for st in self._slots):
                    return None
        now = time.perf_counter()
        self.last_dispatch_t = now
        snaps = [(s, st) for s, st in enumerate(self._slots)
                 if st is not None]
        self.metrics.record_step(
            len(snaps), self.n_slots, len(self.scheduler)
        )
        self.tracer.span(
            ENGINE_TRACK, "dispatch", t_call, now - t_call,
            n_active=len(snaps),
        )
        return _Inflight(toks, snaps, now)

    def _process(self, horizon: _Inflight) -> None:
        """Sync a horizon's (slots, K) token block and do the host-side
        bookkeeping: append tokens (replaying the same EOS/budget
        stopping rule the device mask applied in-program), stamp first
        tokens, retire finished slots. Blocks whose slot was retired or
        re-acquired since dispatch are discarded."""
        t_sync = time.perf_counter()
        toks_host = np.asarray(horizon.toks)  # THE host sync, 1/horizon
        now = time.perf_counter()
        self.metrics.record_readback(
            sync_wait_s=now - t_sync,
            overlap_s=max(0.0, t_sync - horizon.t_dispatch),
        )
        self.tracer.span(ENGINE_TRACK, "sync", t_sync, now - t_sync)
        # per-slot decode span for this horizon: dispatch → block
        # arrival, clipped at the NEXT horizon's dispatch (which already
        # happened — pipelining) so consecutive decode spans on one slot
        # track stay disjoint in the trace viewer
        t_span_end = now
        if (self._inflight is not None
                and self._inflight.t_dispatch > horizon.t_dispatch):
            t_span_end = min(now, self._inflight.t_dispatch)
        for slot, st in horizon.snaps:
            if (self._slots[slot] is not st
                    or st.gen != self.pool.generation(slot)):
                continue  # retired/reused since dispatch: tokens dead
            req = st.req
            self.tracer.span(
                slot_track(slot), "decode", horizon.t_dispatch,
                t_span_end - horizon.t_dispatch, req_id=req.id,
                k=int(toks_host.shape[1]),
            )
            finished = False
            for k in range(toks_host.shape[1]):
                tok = int(toks_host[slot, k])
                if st.t_first_token is None:
                    st.t_first_token = now
                    self.tracer.instant(
                        slot_track(slot), "first_token", ts=now,
                        req_id=req.id,
                    )
                    if req.arrival_time is not None:
                        self.metrics.record_first_token(
                            req.id, now - req.arrival_time
                        )
                st.tokens.append(tok)
                if (tok == req.eos_token
                        or len(st.tokens) >= req.max_new):
                    finished = True
                    break  # device mask froze this slot here too
            if finished:
                self._finish(slot, now)

    def step(self) -> bool:
        """One horizon boundary: sweep lifecycle, admit waiting
        requests, dispatch the next K-substep horizon, then sync and
        process the PREVIOUS horizon's tokens (so host bookkeeping
        overlaps device compute). Returns False when there was nothing
        to do. Raises ``EngineCrash`` when the dispatch loop cannot
        make progress (callers recover via :meth:`recover`)."""
        prof = self.profile
        if prof is not None:
            prof.step_start()
        now = time.perf_counter()
        try:
            self._sweep_lifecycle(now)
            self._admit(now)
            prev, self._inflight = self._inflight, self._dispatch()
            if self._inflight is not None:
                self._steps += 1
            if prev is not None:
                self._process(prev)
        finally:
            if prof is not None:
                prof.step_end()
        progressed = prev is not None or self._inflight is not None
        if self.tracer.enabled and progressed:
            t_end = time.perf_counter()
            self.tracer.span(
                ENGINE_TRACK, "step", now, t_end - now, n=self._steps
            )
            self.tracer.counter(
                SCHEDULER_TRACK, "queue_depth", len(self.scheduler),
                ts=t_end,
            )
            self.tracer.counter(
                ENGINE_TRACK, "kv_slots_active", self.pool.n_active,
                ts=t_end,
            )
        return progressed

    # -- crash recovery ----------------------------------------------------

    def _reset_device_state(self) -> None:
        self._logits = jnp.zeros(
            (self.n_slots, self.cfg.vocab_size), jnp.float32
        )
        self._dpos = jnp.zeros((self.n_slots,), jnp.int32)
        self._dactive = jnp.zeros((self.n_slots,), bool)
        self._dbudget = jnp.zeros((self.n_slots,), jnp.int32)
        self._deos = jnp.full((self.n_slots,), _NO_EOS, jnp.int32)

    def _probe_chunked_parity(self) -> bool:
        """One-time probe for ``chunked_replay="auto"``: does a
        full-sequence bucketed prefill reproduce, bitwise, the logits
        of a shorter prefill + teacher-forced decode? (They are
        differently-scheduled XLA programs; on some backends they agree
        only to float-reassociation level, in which case chunked replay
        would break greedy byte-parity and stepwise replay is used.)
        Runs on abandoned pre-recovery state and leaves state zeroed."""
        length = int(min(self._max_bucket + 1, self.max_total))
        k = length - 2
        if k < 1:
            return False
        seq = ((1 + np.arange(length)) % self.cfg.vocab_size).astype(
            np.int32
        )
        self.pool.reinit()
        self._reset_device_state()
        self._prefill_seq_into_slot(seq, 0, budget=1, eos_tok=_NO_EOS)
        la = np.asarray(self._logits[0])
        self.pool.reinit()
        self._reset_device_state()
        self._prefill_seq_into_slot(seq[:k], 0, budget=1, eos_tok=_NO_EOS)
        pos = np.zeros((self.n_slots,), np.int32)
        replaying = np.zeros((self.n_slots,), bool)
        replaying[0] = True
        for j in range(k, length):
            toks = np.zeros((self.n_slots,), np.int32)
            toks[0] = seq[j]
            pos[0] = j
            self.pool.caches, self._logits = self._replay_fn(
                self.params, self.pool.caches, self._logits,
                jnp.asarray(toks), jnp.asarray(pos.copy()),
                jnp.asarray(replaying),
            )
        lb = np.asarray(self._logits[0])
        self.pool.reinit()
        self._reset_device_state()
        return bool(np.array_equal(la, lb))

    def _use_chunked_replay(self) -> bool:
        if self.chunked_replay is True:
            return True
        if self.chunked_replay is False:
            return False
        if self._chunked_ok is None:
            self._chunked_ok = self._probe_chunked_parity()
        return self._chunked_ok

    def recover(self) -> int:
        """Rebuild engine/device state by deterministic replay after an
        engine-loop crash. The device buffers are abandoned (assumed
        corrupt — with donation they may already be invalidated
        mid-dispatch) and re-created zeroed; any un-synced horizon is
        dropped (its tokens were never recorded, so the replayed run
        regenerates them). Each live slot is then rebuilt either by
        CHUNKED replay — one bucketed prefill pass over
        ``prompt + tokens_so_far``, O(len/bucket) device calls — or by
        STEPWISE replay — re-prefill the original prompt, then
        teacher-force the recorded tokens one fused step at a time —
        per ``chunked_replay`` (see class docstring; "auto" probes for
        bitwise parity and falls back to stepwise). Queued requests are
        untouched. Returns the number of live requests replayed."""
        t_rec = time.perf_counter()
        self.metrics.record_restart()
        self.tracer.instant(ENGINE_TRACK, "crash", ts=t_rec)
        self._inflight = None
        live = [(s, st) for s, st in enumerate(self._slots)
                if st is not None]
        chunked = bool(live) and self._use_chunked_replay()
        self.pool.reinit()
        self._reset_device_state()
        # re-seat each live slot's sampling key from its host record —
        # with position-indexed fold_in sampling this is all it takes
        # for a temperature>0 stream to resume exactly where it left off
        self._slot_keys[:] = 0
        for slot, st in live:
            self._slot_keys[slot] = st.key_data
        self.last_recover_mode = (
            None if not live else ("chunked" if chunked else "stepwise")
        )
        if not live:
            log_event(_log, "engine_recovered", mode=None, n_replayed=0,
                      restarts=self.metrics.n_restarts)
            return 0
        if chunked:
            for slot, st in live:
                req = st.req
                seq = np.concatenate(
                    [req.prompt, np.asarray(st.tokens, np.int32)]
                )
                eos_tok = (_NO_EOS if req.eos_token is None
                           else int(req.eos_token))
                self._prefill_seq_into_slot(
                    seq, slot, req.max_new - len(st.tokens), eos_tok
                )
            self._log_recovered(t_rec, len(live))
            return len(live)
        pos = np.zeros((self.n_slots,), np.int32)
        for slot, st in live:
            req = st.req
            eos_tok = (_NO_EOS if req.eos_token is None
                       else int(req.eos_token))
            self._prefill_seq_into_slot(
                req.prompt, slot, req.max_new, eos_tok
            )
            pos[slot] = len(req.prompt)
        for j in range(max((len(st.tokens) for _, st in live), default=0)):
            toks = np.zeros((self.n_slots,), np.int32)
            replaying = np.zeros((self.n_slots,), bool)
            for slot, st in live:
                if j < len(st.tokens):
                    toks[slot] = st.tokens[j]
                    replaying[slot] = True
            # pos must be snapshotted: jnp.asarray can zero-copy alias
            # a numpy buffer on CPU and dispatch is async, so mutating
            # pos below would race the in-flight replay step
            self.pool.caches, self._logits = self._replay_fn(
                self.params, self.pool.caches, self._logits,
                jnp.asarray(toks), jnp.asarray(pos.copy()),
                jnp.asarray(replaying),
            )
            for slot, st in live:
                if j < len(st.tokens):
                    pos[slot] += 1
        # stepwise replay drove positions through host arrays; re-seat
        # the device state to match the rebuilt trajectory
        active = np.zeros((self.n_slots,), bool)
        budget = np.zeros((self.n_slots,), np.int32)
        eos = np.full((self.n_slots,), _NO_EOS, np.int32)
        for slot, st in live:
            active[slot] = True
            budget[slot] = st.req.max_new - len(st.tokens)
            if st.req.eos_token is not None:
                eos[slot] = int(st.req.eos_token)
        self._dpos = jnp.asarray(pos)
        self._dactive = jnp.asarray(active)
        self._dbudget = jnp.asarray(budget)
        self._deos = jnp.asarray(eos)
        self._log_recovered(t_rec, len(live))
        return len(live)

    def _log_recovered(self, t_rec: float, n_replayed: int) -> None:
        now = time.perf_counter()
        self.tracer.span(
            ENGINE_TRACK, "recover", t_rec, now - t_rec,
            mode=self.last_recover_mode, n_replayed=n_replayed,
        )
        log_event(_log, "engine_recovered", mode=self.last_recover_mode,
                  n_replayed=n_replayed,
                  restarts=self.metrics.n_restarts,
                  recover_s=round(now - t_rec, 6))

    def fail_all(self, error: str) -> None:
        """Terminal supervision failure: fail every live and queued
        request (slot freed, ``done`` set) so no caller blocks on an
        engine that will never step again. Device state is left as-is
        (possibly corrupt — nothing will dispatch to it again)."""
        now = time.perf_counter()
        self._inflight = None
        for slot, st in enumerate(self._slots):
            if st is not None:
                self._retire(slot, RequestStatus.FAILED, now, error=error)
        while True:
            req = self.scheduler.pop()
            if req is None:
                break
            self._retire_unadmitted(req, RequestStatus.FAILED, error)

    def run(self, max_steps: int | None = None, *,
            max_restarts: int = 5) -> dict[str, np.ndarray]:
        """Step until every queued/active request reaches a terminal
        status, supervising crashes: up to ``max_restarts`` replay
        recoveries before the crash propagates."""
        steps = 0
        restarts = 0
        while not self.idle:
            try:
                self.step()
            except EngineCrash:
                if restarts >= max_restarts:
                    raise
                restarts += 1
                self.recover()
                continue
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return self._results


def run_request_trace(
    engine: ServingEngine,
    trace: list[tuple[float, Request]],
    *,
    time_scale: float = 1.0,
    max_restarts: int = 5,
) -> dict[str, np.ndarray]:
    """Replay an arrival trace against a live engine.

    ``trace``: (arrival_offset_seconds, request) pairs; offsets are
    relative to the replay start and scaled by ``time_scale`` (0 floods
    every request instantly — useful for deterministic tests). The
    engine keeps stepping while waiting, exactly as a serving loop
    would, so admissions interleave with in-flight decodes. A submit
    rejected with ``Backpressure`` is retried on the next loop
    iteration (a decode step frees queue space) instead of killing the
    replay, and engine crashes recover by replay up to
    ``max_restarts`` times.
    """
    from collections import deque

    order = sorted(range(len(trace)), key=lambda j: trace[j][0])
    t0 = time.perf_counter()
    i = 0
    pending: deque[Request] = deque()
    restarts = 0
    while i < len(order) or pending or not engine.idle:
        now = time.perf_counter() - t0
        while i < len(order) and trace[order[i]][0] * time_scale <= now:
            pending.append(trace[order[i]][1])
            i += 1
        while pending:
            try:
                engine.submit(pending[0])
            except Backpressure:
                break  # queue full — a step below frees space, retry then
            pending.popleft()
        try:
            progressed = engine.step()
        except EngineCrash:
            if restarts >= max_restarts:
                raise
            restarts += 1
            engine.recover()
            continue
        if not progressed and not pending and i < len(order):
            # idle engine, next arrival still in the future
            time.sleep(
                min(0.001, max(0.0, trace[order[i]][0] * time_scale - now))
            )
    return engine.results
