"""Continuous-batching decode engine.

Iteration-level scheduling (Orca, OSDI '22): instead of batching whole
requests, the engine batches individual DECODE STEPS. It owns a
fixed-shape batch of ``n_slots`` KV-cache slots (one pooled
``init_caches`` allocation, see :mod:`cache_pool`); every
``step()``:

1. sweeps active slots for cancelled/deadline-expired requests and
   retires them (slot freed within one step boundary);
2. retires slots whose request hit EOS or its ``max_new`` budget
   (host-side bookkeeping only — the slot's rows are simply reused);
3. admits queued requests into freed slots: a per-prompt-length jitted
   prefill runs at batch 1 and its cache rows are inserted into the
   pooled buffers at the slot index (so a long prefill never stalls at
   the batch shape of the decode loop);
4. runs ONE fused decode step for all slots — sampling each slot's next
   token from its pending logits, then ``forward_one`` with a PER-SLOT
   position vector. Inactive slots decode a dummy token at their stale
   position so the program shape never changes (their rows are fully
   overwritten by the next admission's prefill insert, which copies a
   whole Tpad slab).

jit stability: exactly one compiled step program per engine (plus one
prefill program per distinct prompt length). All per-slot state that
the device touches — positions, active mask, pending logits — is
passed as arrays; scheduling decisions happen on host between steps.

Greedy determinism: at ``temperature=0`` the engine samples via the
same ``_top_k_filter`` + argmax the plain ``transformer_generate`` path
uses, and the decode math is row-/padding-invariant (masked cache rows
contribute exact zeros), so token streams are byte-identical to running
each request alone — ``tests/test_serving.py`` asserts this.

Fault tolerance (the DL4J lineage: the reference runtime supervised its
workers via Akka and rebuilt them from ZooKeeper state; here the unit
of supervision is the engine step and the durable state is host-side).
The engine consults an optional :class:`~.faults.FaultInjector` at its
two host boundaries and supervises itself:

- a ``TransientFault`` at a boundary retries with capped exponential
  backoff (``max_retries``/``retry_backoff_s``/``max_backoff_s``);
- a fault that PERSISTS past the retry budget, or a ``PermanentFault``,
  quarantines only the implicated request — slot freed, ``done`` set,
  status ``FAILED`` — and the batch keeps decoding;
- an ``EngineCrash`` (or any fault with no implicated request)
  abandons the device state entirely; :meth:`recover` rebuilds it by
  DETERMINISTIC REPLAY. Because everything the device holds is a pure
  function of host state (each live request's prompt + tokens decoded
  so far), recovery re-prefills every live slot's original prompt and
  then TEACHER-FORCES the recorded tokens through the same fused
  ``forward_one`` step in lockstep (per-slot position vector, logits
  frozen once a slot's recording is exhausted). That re-traces the
  exact op sequence of the original run, so at ``temperature=0`` the
  resumed stream is byte-identical to an uninterrupted one — the chaos
  parity tests in ``tests/test_serving_faults.py`` pin this. (At
  ``temperature>0`` recovery still loses no request, but the sampling
  key has advanced, so post-crash tokens are a different valid sample.)

Request lifecycle: ``Request.deadline_s`` and ``Request.cancel()`` are
checked at admission and at every step boundary; a timed-out or
cancelled request is retired (status EXPIRED/CANCELLED, partial stream
in ``results``, KV slot freed) instead of decoding to ``max_new``.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from deeplearning4j_tpu.models.transformer import (
    TransformerConfig,
    _decode_builder,
    _top_k_filter,
)
from deeplearning4j_tpu.serving.cache_pool import KVSlotPool
from deeplearning4j_tpu.serving.faults import (
    EngineCrash,
    FaultInjector,
    PermanentFault,
    TransientFault,
)
from deeplearning4j_tpu.serving.metrics import ServingMetrics
from deeplearning4j_tpu.serving.scheduler import (
    Backpressure,
    Request,
    RequestScheduler,
    RequestStatus,
)


class _SlotState:
    """Host-side record for one active slot."""

    __slots__ = ("req", "tokens", "t_first_token")

    def __init__(self, req: Request):
        self.req = req
        self.tokens: list[int] = []
        self.t_first_token: float | None = None


class ServingEngine:
    """Fixed-shape continuous-batching decode loop.

    ``params`` may be float or ``quantize_decode_params`` output (pair
    with ``cfg.decode_int8=True`` for the int8 KV cache). Sampling
    settings are engine-wide (they are baked into the compiled step):
    ``temperature=0`` decodes greedily.

    Supervision knobs: ``faults`` (an optional
    :class:`~.faults.FaultInjector`), ``max_retries`` transient retries
    per boundary with exponential backoff starting at
    ``retry_backoff_s`` capped at ``max_backoff_s``. ``results_cap``
    bounds the finished-stream dict (oldest evicted first) so sustained
    traffic cannot leak host memory; front ends should prefer
    :meth:`pop_result`, which removes the entry on read.
    """

    def __init__(
        self,
        cfg: TransformerConfig,
        params,
        *,
        n_slots: int = 8,
        max_total: int | None = None,
        temperature: float = 0.0,
        top_k: int | None = None,
        approx_top_k: bool = False,
        scheduler: RequestScheduler | None = None,
        metrics: ServingMetrics | None = None,
        rng_seed: int = 0,
        faults: FaultInjector | None = None,
        max_retries: int = 3,
        retry_backoff_s: float = 0.01,
        max_backoff_s: float = 0.25,
        results_cap: int = 1024,
    ):
        self.cfg = cfg
        self.n_slots = n_slots
        self.max_total = int(min(max_total or cfg.max_len, cfg.max_len))
        self.temperature = temperature
        self.top_k = top_k
        self.approx_top_k = approx_top_k
        self.faults = faults
        self.max_retries = max_retries
        self.retry_backoff_s = retry_backoff_s
        self.max_backoff_s = max_backoff_s
        self.results_cap = results_cap

        fwd1, init_caches, do_prefill, cast_params = _decode_builder(cfg)
        self._fwd1 = fwd1
        self._init_caches = init_caches
        self._do_prefill = do_prefill
        # one-time weight cast (generate does this inside its jitted
        # program; hoisting it out of the per-step program keeps every
        # step from re-casting — same values, cast is deterministic)
        self.params = jax.jit(cast_params)(params)

        self.pool = KVSlotPool(cfg, n_slots, self.max_total)
        self.scheduler = scheduler or RequestScheduler(
            max_total_tokens=self.max_total
        )
        if self.scheduler.max_total_tokens is None:
            self.scheduler.max_total_tokens = self.max_total
        self.metrics = metrics or ServingMetrics()

        # pending next-token logits per slot (f32, written by prefill
        # on admission and by every decode step)
        self._logits = jnp.zeros((n_slots, cfg.vocab_size), jnp.float32)
        self._pos = np.zeros((n_slots,), np.int32)
        self._active = np.zeros((n_slots,), bool)
        self._slots: list[_SlotState | None] = [None] * n_slots
        self._results: dict[str, np.ndarray] = {}
        self._key = jax.random.key(rng_seed)
        self._steps = 0
        self._admitting = 0  # requests between scheduler pop and slot

        # donating the cache + logits lets XLA update them in place
        # (the cache is the dominant allocation); CPU jit can't alias
        # donated buffers and would warn every call
        donate = (1, 2) if jax.devices()[0].platform == "tpu" else ()
        self._step_fn = jax.jit(self._build_step(), donate_argnums=donate)
        self._replay_fn = jax.jit(
            self._build_replay_step(), donate_argnums=donate
        )
        self._prefill_fns: dict[int, object] = {}
        self._prefill_donate = donate

    # -- compiled programs -------------------------------------------------

    def _build_step(self):
        fwd1 = self._fwd1
        temperature, top_k = self.temperature, self.top_k
        approx_top_k = self.approx_top_k

        def step(params, caches, logits, pos, active, key):
            filt = _top_k_filter(logits, top_k, approx_top_k)
            if temperature == 0:
                toks = jnp.argmax(filt, axis=-1).astype(jnp.int32)
            else:
                toks = jax.random.categorical(
                    key, filt / temperature, axis=-1
                ).astype(jnp.int32)
            # inactive slots decode token 0 at their stale position —
            # shape stability; the garbage rows they write are dead
            # (admission prefill rewrites the whole slot slab)
            toks = jnp.where(active, toks, 0)
            new_logits, caches = fwd1(params, caches, toks, pos)
            return caches, new_logits, toks

        return step

    def _build_replay_step(self):
        """Teacher-forced decode step for crash recovery: feed RECORDED
        tokens (no sampling) and freeze the pending-logits rows of
        slots whose recording is already exhausted — those rows must
        stay exactly what the slot's last real step produced."""
        fwd1 = self._fwd1

        def rstep(params, caches, logits, toks, pos, replaying):
            new_logits, caches = fwd1(params, caches, toks, pos)
            logits = jnp.where(replaying[:, None], new_logits, logits)
            return caches, logits

        return rstep

    def _prefill_into_slot(self, length: int):
        """Jitted prefill-at-batch-1 + row insert, one program per
        distinct prompt length."""
        fn = self._prefill_fns.get(length)
        if fn is None:
            do_prefill = self._do_prefill
            init_caches = self._init_caches
            max_total = self.max_total

            def prefill(params, caches, logits, prompt, slot):
                # batch-1 prefill into a scratch single-slot cache of
                # the SAME Tpad as the pool, then insert the slab at
                # the slot index. The slab copy includes the zero rows
                # beyond the prompt — that wipes the previous
                # occupant's rows, so no stale state survives reuse.
                tmp, lg = do_prefill(params, init_caches(1, max_total), prompt)
                caches = jax.tree.map(
                    lambda c, t: lax.dynamic_update_slice(
                        c, t, (0, 0, slot, 0, 0)
                    ),
                    caches, tmp,
                )
                logits = lax.dynamic_update_slice(logits, lg, (slot, 0))
                return caches, logits

            fn = jax.jit(prefill, donate_argnums=self._prefill_donate)
            self._prefill_fns[length] = fn
        return fn

    # -- host-side loop ----------------------------------------------------

    def submit(self, req: Request) -> str:
        """Queue a request (see ``RequestScheduler.submit`` for the
        backpressure/admission contract)."""
        return self.scheduler.submit(req)

    @property
    def results(self) -> dict[str, np.ndarray]:
        """Terminal streams by request id: prompt + generated tokens
        (partial for CANCELLED/EXPIRED/FAILED-while-running). Bounded
        to ``results_cap`` entries, oldest evicted; ``pop_result``
        consumes an entry."""
        return self._results

    def pop_result(self, req_id: str, default=None):
        """Remove and return a terminal stream (front-end consumption:
        read-once keeps the results dict from growing with traffic)."""
        return self._results.pop(req_id, default)

    @property
    def idle(self) -> bool:
        """True when no request is queued, mid-admission, or decoding.
        ``pool.n_active`` (not ``_active``) is what covers the admission
        window — the slot is acquired before the prefill runs and
        before ``_active`` flips, and a concurrent drain must not
        mistake that window for idleness; ``_admitting`` covers the few
        instructions between the scheduler pop and the acquire."""
        return (self.pool.n_active == 0 and self._admitting == 0
                and len(self.scheduler) == 0)

    def cancel(self, req_id: str) -> bool:
        """Cancel by id: flags the request whether it is queued or
        decoding; the engine honors the flag within one step. Returns
        False when the id is unknown (already retired or never seen)."""
        for st in self._slots:
            if st is not None and st.req.id == req_id:
                st.req.cancel()
                return True
        return self.scheduler.cancel(req_id)

    # -- retirement --------------------------------------------------------

    def _store_result(self, req: Request, tokens: list[int]) -> None:
        self._results[req.id] = np.concatenate(
            [req.prompt, np.asarray(tokens, np.int32)]
        )
        while len(self._results) > self.results_cap:
            self._results.pop(next(iter(self._results)))

    def _retire(self, slot: int, status: RequestStatus, now: float,
                error: str | None = None) -> None:
        """Free a slot and move its request to a terminal status."""
        st = self._slots[slot]
        req = st.req
        req.status = status
        req.error = error
        self._store_result(req, st.tokens)
        if status is RequestStatus.FINISHED:
            self.metrics.record_finished(
                req.id, len(st.tokens),
                now - (st.t_first_token or now),
            )
        else:
            self.metrics.record_outcome(status)
        self.pool.release(slot)
        self._active[slot] = False
        self._slots[slot] = None
        if req.done is not None:
            req.done.set()

    def _retire_unadmitted(self, req: Request, status: RequestStatus,
                           error: str | None = None) -> None:
        """Terminal status for a request that never got a slot."""
        req.status = status
        req.error = error
        self.metrics.record_outcome(status)
        if req.done is not None:
            req.done.set()

    def _finish(self, slot: int, now: float) -> None:
        self._retire(slot, RequestStatus.FINISHED, now)

    def _slot_of(self, req_id: str | None) -> int | None:
        if req_id is None:
            return None
        for slot, st in enumerate(self._slots):
            if st is not None and st.req.id == req_id:
                return slot
        return None

    def _sweep_lifecycle(self, now: float) -> None:
        """Retire cancelled / deadline-expired active slots (this is
        what bounds slot occupation to one step past cancel/expiry)."""
        for slot in np.flatnonzero(self._active):
            req = self._slots[slot].req
            if req.cancelled:
                self._retire(int(slot), RequestStatus.CANCELLED, now)
            elif req.expired(now):
                self._retire(int(slot), RequestStatus.EXPIRED, now)

    # -- admission ---------------------------------------------------------

    def _prefill_with_retries(self, req: Request, slot: int) -> bool:
        """Run the admission prefill under transient-retry supervision.
        Returns False when the request is poisoned (caller fails it)."""
        prompt = jnp.asarray(req.prompt[None, :], jnp.int32)
        fn = self._prefill_into_slot(len(req.prompt))
        attempt, backoff = 0, self.retry_backoff_s
        while True:
            try:
                if self.faults is not None:
                    self.faults.check("prefill", req_id=req.id)
                self.pool.caches, self._logits = fn(
                    self.params, self.pool.caches, self._logits, prompt,
                    jnp.int32(slot),
                )
                return True
            except TransientFault as e:
                self.metrics.record_retry()
                attempt += 1
                if attempt > self.max_retries:
                    req.error = (
                        f"transient prefill fault persisted past "
                        f"{self.max_retries} retries: {e}"
                    )
                    return False
                time.sleep(backoff)
                backoff = min(backoff * 2, self.max_backoff_s)
            except PermanentFault as e:
                req.error = str(e)
                return False

    def _admit(self, now: float) -> None:
        while self.pool.n_free and len(self.scheduler):
            self._admitting += 1
            try:
                req = self.scheduler.pop()
                if req is None:
                    break
                if req.cancelled:
                    self._retire_unadmitted(req, RequestStatus.CANCELLED)
                    continue
                if req.expired(now):
                    self._retire_unadmitted(req, RequestStatus.EXPIRED)
                    continue
                slot = self.pool.acquire()
                try:
                    ok = self._prefill_with_retries(req, slot)
                except BaseException:
                    # EngineCrash (or anything unexpected) between pop
                    # and admission: the request must not be dropped —
                    # put it back at the front of its class before the
                    # supervisor rebuilds state.
                    self.pool.release(slot)
                    self.scheduler.requeue(req)
                    raise
                if not ok:
                    self.pool.release(slot)
                    self._retire_unadmitted(
                        req, RequestStatus.FAILED, req.error
                    )
                    continue
                self._pos[slot] = len(req.prompt)
                self._active[slot] = True
                self._slots[slot] = _SlotState(req)
                req.status = RequestStatus.RUNNING
            finally:
                self._admitting -= 1

    # -- supervised device step --------------------------------------------

    def _step_device(self, sub):
        """One fused decode step under transient-retry supervision.
        Persistent faults quarantine the implicated request when one is
        named, otherwise escalate to ``EngineCrash`` (replay recovery).
        Returns None when quarantining emptied the batch."""
        attempt, backoff = 0, self.retry_backoff_s
        while True:
            try:
                if self.faults is not None:
                    self.faults.check("step")
                # .copy(): jnp.asarray can zero-copy alias numpy buffers
                # on CPU and dispatch is async — the host loop mutates
                # _pos/_active after this call returns
                return self._step_fn(
                    self.params, self.pool.caches, self._logits,
                    jnp.asarray(self._pos.copy()),
                    jnp.asarray(self._active.copy()), sub,
                )
            except TransientFault as e:
                self.metrics.record_retry()
                attempt += 1
                if attempt <= self.max_retries:
                    time.sleep(backoff)
                    backoff = min(backoff * 2, self.max_backoff_s)
                    continue
                slot = self._slot_of(e.req_id)
                if slot is None:
                    raise EngineCrash(
                        f"transient step fault persisted past "
                        f"{self.max_retries} retries: {e}"
                    ) from e
                self._retire(slot, RequestStatus.FAILED, time.perf_counter(),
                             error=str(e))
                if not self._active.any():
                    return None
                attempt, backoff = 0, self.retry_backoff_s
            except PermanentFault as e:
                slot = self._slot_of(e.req_id)
                if slot is None:
                    raise EngineCrash(
                        f"permanent step fault names no live request: {e}"
                    ) from e
                self._retire(slot, RequestStatus.FAILED, time.perf_counter(),
                             error=str(e))
                if not self._active.any():
                    return None

    def step(self) -> bool:
        """Sweep lifecycle, admit waiting requests, run one fused
        decode step, retire finished slots. Returns False when there
        was nothing to do. Raises ``EngineCrash`` when the step loop
        cannot make progress (callers recover via :meth:`recover`)."""
        now = time.perf_counter()
        self._sweep_lifecycle(now)
        self._admit(now)
        if not self._active.any():
            return False
        n_active = int(self._active.sum())
        self._key, sub = jax.random.split(self._key)
        out = self._step_device(sub)
        if out is None:  # quarantine emptied the batch
            return True
        caches, logits, toks = out
        self.pool.caches, self._logits = caches, logits
        toks_host = np.asarray(toks)  # the one host sync per step
        now = time.perf_counter()
        self._steps += 1
        for slot in np.flatnonzero(self._active):
            st = self._slots[slot]
            tok = int(toks_host[slot])
            if st.t_first_token is None:
                st.t_first_token = now
                self.metrics.record_first_token(
                    st.req.id, now - st.req.arrival_time
                )
            st.tokens.append(tok)
            self._pos[slot] += 1
            if (len(st.tokens) >= st.req.max_new
                    or tok == st.req.eos_token):
                self._finish(int(slot), now)
        self.metrics.record_step(
            n_active, self.n_slots, len(self.scheduler)
        )
        return True

    # -- crash recovery ----------------------------------------------------

    def recover(self) -> int:
        """Rebuild engine/device state by deterministic replay after an
        engine-loop crash. The device buffers are abandoned (assumed
        corrupt) and re-created zeroed; every live slot is re-prefilled
        with its ORIGINAL prompt (the same compiled program and inputs
        as its first admission, so the result is byte-identical), then
        the tokens decoded so far are teacher-forced through the fused
        step in lockstep with per-slot positions — exactly re-tracing
        the crashed run's op sequence, so greedy decode resumes
        byte-identically. Queued requests are untouched. Returns the
        number of live requests replayed."""
        self.metrics.record_restart()
        self.pool.reinit()
        self._logits = jnp.zeros(
            (self.n_slots, self.cfg.vocab_size), jnp.float32
        )
        live = [(s, st) for s, st in enumerate(self._slots)
                if st is not None]
        for slot, st in live:
            prompt = jnp.asarray(st.req.prompt[None, :], jnp.int32)
            fn = self._prefill_into_slot(len(st.req.prompt))
            self.pool.caches, self._logits = fn(
                self.params, self.pool.caches, self._logits, prompt,
                jnp.int32(slot),
            )
            self._pos[slot] = len(st.req.prompt)
        for j in range(max((len(st.tokens) for _, st in live), default=0)):
            toks = np.zeros((self.n_slots,), np.int32)
            replaying = np.zeros((self.n_slots,), bool)
            for slot, st in live:
                if j < len(st.tokens):
                    toks[slot] = st.tokens[j]
                    replaying[slot] = True
            # pos must be snapshotted: jnp.asarray can zero-copy alias
            # a numpy buffer on CPU and dispatch is async, so mutating
            # self._pos below would race the in-flight replay step
            self.pool.caches, self._logits = self._replay_fn(
                self.params, self.pool.caches, self._logits,
                jnp.asarray(toks), jnp.asarray(self._pos.copy()),
                jnp.asarray(replaying),
            )
            for slot, st in live:
                if j < len(st.tokens):
                    self._pos[slot] += 1
        return len(live)

    def fail_all(self, error: str) -> None:
        """Terminal supervision failure: fail every live and queued
        request (slot freed, ``done`` set) so no caller blocks on an
        engine that will never step again."""
        now = time.perf_counter()
        for slot in np.flatnonzero(self._active):
            self._retire(int(slot), RequestStatus.FAILED, now, error=error)
        while True:
            req = self.scheduler.pop()
            if req is None:
                break
            self._retire_unadmitted(req, RequestStatus.FAILED, error)

    def run(self, max_steps: int | None = None, *,
            max_restarts: int = 5) -> dict[str, np.ndarray]:
        """Step until every queued/active request reaches a terminal
        status, supervising crashes: up to ``max_restarts`` replay
        recoveries before the crash propagates."""
        steps = 0
        restarts = 0
        while not self.idle:
            try:
                self.step()
            except EngineCrash:
                if restarts >= max_restarts:
                    raise
                restarts += 1
                self.recover()
                continue
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return self._results


def run_request_trace(
    engine: ServingEngine,
    trace: list[tuple[float, Request]],
    *,
    time_scale: float = 1.0,
    max_restarts: int = 5,
) -> dict[str, np.ndarray]:
    """Replay an arrival trace against a live engine.

    ``trace``: (arrival_offset_seconds, request) pairs; offsets are
    relative to the replay start and scaled by ``time_scale`` (0 floods
    every request instantly — useful for deterministic tests). The
    engine keeps stepping while waiting, exactly as a serving loop
    would, so admissions interleave with in-flight decodes. A submit
    rejected with ``Backpressure`` is retried on the next loop
    iteration (a decode step frees queue space) instead of killing the
    replay, and engine crashes recover by replay up to
    ``max_restarts`` times.
    """
    from collections import deque

    order = sorted(range(len(trace)), key=lambda j: trace[j][0])
    t0 = time.perf_counter()
    i = 0
    pending: deque[Request] = deque()
    restarts = 0
    while i < len(order) or pending or not engine.idle:
        now = time.perf_counter() - t0
        while i < len(order) and trace[order[i]][0] * time_scale <= now:
            pending.append(trace[order[i]][1])
            i += 1
        while pending:
            try:
                engine.submit(pending[0])
            except Backpressure:
                break  # queue full — a step below frees space, retry then
            pending.popleft()
        try:
            progressed = engine.step()
        except EngineCrash:
            if restarts >= max_restarts:
                raise
            restarts += 1
            engine.recover()
            continue
        if not progressed and not pending and i < len(order):
            # idle engine, next arrival still in the future
            time.sleep(
                min(0.001, max(0.0, trace[order[i]][0] * time_scale - now))
            )
    return engine.results
