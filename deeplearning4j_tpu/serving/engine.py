"""Continuous-batching decode engine.

Iteration-level scheduling (Orca, OSDI '22): instead of batching whole
requests, the engine batches individual DECODE STEPS. It owns a
fixed-shape batch of ``n_slots`` KV-cache slots (one pooled
``init_caches`` allocation, see :mod:`cache_pool`); every
``step()``:

1. retires slots whose request hit EOS or its ``max_new`` budget
   (host-side bookkeeping only — the slot's rows are simply reused);
2. admits queued requests into freed slots: a per-prompt-length jitted
   prefill runs at batch 1 and its cache rows are inserted into the
   pooled buffers at the slot index (so a long prefill never stalls at
   the batch shape of the decode loop);
3. runs ONE fused decode step for all slots — sampling each slot's next
   token from its pending logits, then ``forward_one`` with a PER-SLOT
   position vector. Inactive slots decode a dummy token at their stale
   position so the program shape never changes (their rows are fully
   overwritten by the next admission's prefill insert, which copies a
   whole Tpad slab).

jit stability: exactly one compiled step program per engine (plus one
prefill program per distinct prompt length). All per-slot state that
the device touches — positions, active mask, pending logits — is
passed as arrays; scheduling decisions happen on host between steps.

Greedy determinism: at ``temperature=0`` the engine samples via the
same ``_top_k_filter`` + argmax the plain ``transformer_generate`` path
uses, and the decode math is row-/padding-invariant (masked cache rows
contribute exact zeros), so token streams are byte-identical to running
each request alone — ``tests/test_serving.py`` asserts this.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from deeplearning4j_tpu.models.transformer import (
    TransformerConfig,
    _decode_builder,
    _top_k_filter,
)
from deeplearning4j_tpu.serving.cache_pool import KVSlotPool
from deeplearning4j_tpu.serving.metrics import ServingMetrics
from deeplearning4j_tpu.serving.scheduler import Request, RequestScheduler


class _SlotState:
    """Host-side record for one active slot."""

    __slots__ = ("req", "tokens", "t_first_token")

    def __init__(self, req: Request):
        self.req = req
        self.tokens: list[int] = []
        self.t_first_token: float | None = None


class ServingEngine:
    """Fixed-shape continuous-batching decode loop.

    ``params`` may be float or ``quantize_decode_params`` output (pair
    with ``cfg.decode_int8=True`` for the int8 KV cache). Sampling
    settings are engine-wide (they are baked into the compiled step):
    ``temperature=0`` decodes greedily.
    """

    def __init__(
        self,
        cfg: TransformerConfig,
        params,
        *,
        n_slots: int = 8,
        max_total: int | None = None,
        temperature: float = 0.0,
        top_k: int | None = None,
        approx_top_k: bool = False,
        scheduler: RequestScheduler | None = None,
        metrics: ServingMetrics | None = None,
        rng_seed: int = 0,
    ):
        self.cfg = cfg
        self.n_slots = n_slots
        self.max_total = int(min(max_total or cfg.max_len, cfg.max_len))
        self.temperature = temperature
        self.top_k = top_k
        self.approx_top_k = approx_top_k

        fwd1, init_caches, do_prefill, cast_params = _decode_builder(cfg)
        self._fwd1 = fwd1
        self._init_caches = init_caches
        self._do_prefill = do_prefill
        # one-time weight cast (generate does this inside its jitted
        # program; hoisting it out of the per-step program keeps every
        # step from re-casting — same values, cast is deterministic)
        self.params = jax.jit(cast_params)(params)

        self.pool = KVSlotPool(cfg, n_slots, self.max_total)
        self.scheduler = scheduler or RequestScheduler(
            max_total_tokens=self.max_total
        )
        if self.scheduler.max_total_tokens is None:
            self.scheduler.max_total_tokens = self.max_total
        self.metrics = metrics or ServingMetrics()

        # pending next-token logits per slot (f32, written by prefill
        # on admission and by every decode step)
        self._logits = jnp.zeros((n_slots, cfg.vocab_size), jnp.float32)
        self._pos = np.zeros((n_slots,), np.int32)
        self._active = np.zeros((n_slots,), bool)
        self._slots: list[_SlotState | None] = [None] * n_slots
        self._results: dict[str, np.ndarray] = {}
        self._key = jax.random.key(rng_seed)
        self._steps = 0

        # donating the cache + logits lets XLA update them in place
        # (the cache is the dominant allocation); CPU jit can't alias
        # donated buffers and would warn every call
        donate = (1, 2) if jax.devices()[0].platform == "tpu" else ()
        self._step_fn = jax.jit(self._build_step(), donate_argnums=donate)
        self._prefill_fns: dict[int, object] = {}
        self._prefill_donate = donate

    # -- compiled programs -------------------------------------------------

    def _build_step(self):
        fwd1 = self._fwd1
        temperature, top_k = self.temperature, self.top_k
        approx_top_k = self.approx_top_k

        def step(params, caches, logits, pos, active, key):
            filt = _top_k_filter(logits, top_k, approx_top_k)
            if temperature == 0:
                toks = jnp.argmax(filt, axis=-1).astype(jnp.int32)
            else:
                toks = jax.random.categorical(
                    key, filt / temperature, axis=-1
                ).astype(jnp.int32)
            # inactive slots decode token 0 at their stale position —
            # shape stability; the garbage rows they write are dead
            # (admission prefill rewrites the whole slot slab)
            toks = jnp.where(active, toks, 0)
            new_logits, caches = fwd1(params, caches, toks, pos)
            return caches, new_logits, toks

        return step

    def _prefill_into_slot(self, length: int):
        """Jitted prefill-at-batch-1 + row insert, one program per
        distinct prompt length."""
        fn = self._prefill_fns.get(length)
        if fn is None:
            do_prefill = self._do_prefill
            init_caches = self._init_caches
            max_total = self.max_total

            def prefill(params, caches, logits, prompt, slot):
                # batch-1 prefill into a scratch single-slot cache of
                # the SAME Tpad as the pool, then insert the slab at
                # the slot index. The slab copy includes the zero rows
                # beyond the prompt — that wipes the previous
                # occupant's rows, so no stale state survives reuse.
                tmp, lg = do_prefill(params, init_caches(1, max_total), prompt)
                caches = jax.tree.map(
                    lambda c, t: lax.dynamic_update_slice(
                        c, t, (0, 0, slot, 0, 0)
                    ),
                    caches, tmp,
                )
                logits = lax.dynamic_update_slice(logits, lg, (slot, 0))
                return caches, logits

            fn = jax.jit(prefill, donate_argnums=self._prefill_donate)
            self._prefill_fns[length] = fn
        return fn

    # -- host-side loop ----------------------------------------------------

    def submit(self, req: Request) -> str:
        """Queue a request (see ``RequestScheduler.submit`` for the
        backpressure/admission contract)."""
        return self.scheduler.submit(req)

    @property
    def results(self) -> dict[str, np.ndarray]:
        """Finished streams by request id: prompt + generated tokens."""
        return self._results

    @property
    def idle(self) -> bool:
        return not self._active.any() and len(self.scheduler) == 0

    def _admit(self) -> None:
        while self.pool.n_free and len(self.scheduler):
            req = self.scheduler.pop()
            slot = self.pool.acquire()
            prompt = jnp.asarray(req.prompt[None, :], jnp.int32)
            fn = self._prefill_into_slot(len(req.prompt))
            self.pool.caches, self._logits = fn(
                self.params, self.pool.caches, self._logits, prompt,
                jnp.int32(slot),
            )
            self._pos[slot] = len(req.prompt)
            self._active[slot] = True
            self._slots[slot] = _SlotState(req)

    def _finish(self, slot: int, now: float) -> None:
        st = self._slots[slot]
        req = st.req
        self._results[req.id] = np.concatenate(
            [req.prompt, np.asarray(st.tokens, np.int32)]
        )
        self.metrics.record_finished(
            req.id, len(st.tokens),
            now - (st.t_first_token or now),
        )
        self.pool.release(slot)
        self._active[slot] = False
        self._slots[slot] = None
        if req.done is not None:
            req.done.set()

    def step(self) -> bool:
        """Admit waiting requests, run one fused decode step, retire
        finished slots. Returns False when there was nothing to do."""
        self._admit()
        if not self._active.any():
            return False
        n_active = int(self._active.sum())
        self._key, sub = jax.random.split(self._key)
        caches, logits, toks = self._step_fn(
            self.params, self.pool.caches, self._logits,
            jnp.asarray(self._pos), jnp.asarray(self._active), sub,
        )
        self.pool.caches, self._logits = caches, logits
        toks_host = np.asarray(toks)  # the one host sync per step
        now = time.perf_counter()
        self._steps += 1
        for slot in np.flatnonzero(self._active):
            st = self._slots[slot]
            tok = int(toks_host[slot])
            if st.t_first_token is None:
                st.t_first_token = now
                self.metrics.record_first_token(
                    st.req.id, now - st.req.arrival_time
                )
            st.tokens.append(tok)
            self._pos[slot] += 1
            if (len(st.tokens) >= st.req.max_new
                    or tok == st.req.eos_token):
                self._finish(int(slot), now)
        self.metrics.record_step(
            n_active, self.n_slots, len(self.scheduler)
        )
        return True

    def run(self, max_steps: int | None = None) -> dict[str, np.ndarray]:
        """Step until every queued/active request finishes."""
        steps = 0
        while not self.idle:
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return self._results


def run_request_trace(
    engine: ServingEngine,
    trace: list[tuple[float, Request]],
    *,
    time_scale: float = 1.0,
) -> dict[str, np.ndarray]:
    """Replay an arrival trace against a live engine.

    ``trace``: (arrival_offset_seconds, request) pairs; offsets are
    relative to the replay start and scaled by ``time_scale`` (0 floods
    every request instantly — useful for deterministic tests). The
    engine keeps stepping while waiting, exactly as a serving loop
    would, so admissions interleave with in-flight decodes.
    """
    order = sorted(range(len(trace)), key=lambda j: trace[j][0])
    t0 = time.perf_counter()
    i = 0
    while i < len(order) or not engine.idle:
        now = time.perf_counter() - t0
        while i < len(order):
            t_arr, req = trace[order[i]]
            if t_arr * time_scale > now:
                break
            engine.submit(req)
            i += 1
        if not engine.step() and i < len(order):
            # idle engine, next arrival still in the future
            time.sleep(
                min(0.001, max(0.0, trace[order[i]][0] * time_scale - now))
            )
    return engine.results
