"""KV-slot pool: slot recycling over ONE pre-allocated decode cache.

The batch axis of ``_decode_builder.init_caches`` IS the slot pool: the
buffers — (n_layers, 2, n_slots, Tpad, Hkv*K), plus the f32 scale
planes in int8 mode — are allocated once at engine start and never
re-allocated. Admitting a request into a freed slot overwrites that
slot's rows (the prefill insert copies a full Tpad slab, zeros beyond
the prompt, so no stale rows from the previous occupant survive);
releasing a slot is pure free-list bookkeeping, no device work. This is
the fixed-slot special case of vLLM's paged pool: one page per request,
sized to the engine's token budget.

Slots are handed out lowest-index-first so admission order is
deterministic — tests (and trace replays) rely on it.

Under tensor-parallel serving the pool carries a ``sharding`` pytree
(:func:`~deeplearning4j_tpu.models.transformer.serving_tp_cache_sharding`):
every allocation this pool hands out — the decode cache, crash-recovery
re-creations, and the prefix-cache segment region from
:meth:`alloc_region` — is placed with it, so pool slabs and region
slabs stay interchangeable under the same dynamic-slice programs.
"""

from __future__ import annotations

import heapq
import math
import threading

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.analysis.sanitizers import note_access, wrap_lock

from deeplearning4j_tpu.models.transformer import (
    TransformerConfig,
    _decode_builder,
)


class KVSlotPool:
    """Free-list of decode-cache slots over one device allocation.

    ``caches`` is the live pytree (an array, or ``{"kv", "scale"}`` in
    int8-cache mode). The engine's jitted steps consume and return it
    functionally; with buffer donation the update is in place.
    """

    is_paged = False  # layout flag consumers branch on (PrefixCache)

    def __init__(self, cfg: TransformerConfig, n_slots: int, max_total: int,
                 sharding=None):
        if n_slots < 1:
            raise ValueError(f"n_slots must be >= 1, got {n_slots}")
        _, init_caches, _, _ = _decode_builder(cfg)
        self._init_caches = init_caches
        self._max_total = max_total
        self._sharding = sharding
        # shape-only pass first: the slab geometry (Tpad row count) is
        # needed before allocation so subclasses can size their own
        # layout from it in ``_alloc_caches`` (PagedKVPool carves the
        # same rows into blocks)
        shapes = jax.eval_shape(
            lambda: init_caches(n_slots, max_total)
        )
        kv = shapes["kv"] if isinstance(shapes, dict) else shapes
        self.n_slots = n_slots
        self.tpad = kv.shape[3]  # rounded-up row count per slot
        self.caches = self._alloc_caches()
        # acquire/release/generation run on the engine thread while
        # n_free/n_active/occupancy feed metrics gauges scraped from
        # the sidecar thread — free-list bookkeeping moves under the
        # lock so a scrape never sees the heap mid-rebalance
        self._lock = wrap_lock(threading.Lock(), "pool._lock")
        self._free = list(range(n_slots))  # already a heap; guarded-by: _lock
        self._in_use: set[int] = set()  # guarded-by: _lock
        # per-slot generation, bumped on acquire: with pipelined
        # readback a token block can arrive for a slot that was retired
        # and re-acquired after its dispatch — the generation lets the
        # engine tell the block belongs to the previous occupant
        self._gen = [0] * n_slots  # guarded-by: _lock
        # byte sizes captured ONCE at allocation time (shape/dtype are
        # host metadata): metrics scrapes must never walk the live
        # device pytree (under donation a buffer can be
        # mid-invalidation, and under TP the per-scrape answer must not
        # depend on which shard you ask) — zero device interaction per
        # scrape
        self._nbytes = sum(
            math.prod(x.shape) * x.dtype.itemsize
            for x in jax.tree.leaves(self.caches)
        )
        self._nbytes_per_slot = self._nbytes // n_slots

    def _place(self, caches):
        """Place a fresh allocation with the pool's sharding (identity
        when unsharded)."""
        if self._sharding is None:
            return caches
        return jax.tree.map(jax.device_put, caches, self._sharding)

    def _alloc_caches(self):
        """Allocate the pool's device cache, zeroed and placed — the
        layout hook ``reinit`` and ``__init__`` share (subclasses
        override it to change the layout without touching the slot
        bookkeeping)."""
        return self._place(
            self._init_caches(self.n_slots, self._max_total)
        )

    @property
    def n_free(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def n_active(self) -> int:
        with self._lock:
            return len(self._in_use)

    @property
    def occupancy(self) -> float:
        """Active fraction of the slot batch this instant, in [0, 1]."""
        with self._lock:
            return len(self._in_use) / self.n_slots

    def acquire(self) -> int:
        """Claim the lowest free slot index."""
        with self._lock:
            note_access("pool.freelist", write=True)
            if not self._free:
                raise RuntimeError("no free KV slots")
            slot = heapq.heappop(self._free)
            self._in_use.add(slot)
            self._gen[slot] += 1
            return slot

    def generation(self, slot: int) -> int:
        """Acquire count for ``slot`` — identifies the current occupant
        across release/re-acquire (see ``_gen`` above)."""
        with self._lock:
            return self._gen[slot]

    def release(self, slot: int) -> None:
        with self._lock:
            note_access("pool.freelist", write=True)
            if slot not in self._in_use:
                raise ValueError(f"slot {slot} is not in use")
            self._in_use.remove(slot)
            heapq.heappush(self._free, slot)

    def alloc_region(self, n_slots: int):
        """A second bounded cache region with the SAME per-slot layout
        as the pool — Tpad row count, dtype, int8 scale planes, and
        (under TP) the same head-axis sharding — so a region slab and a
        pool slab are interchangeable under plain dynamic slices. This
        is how the prefix cache gets its segment store: the pool owns
        the layout, the cache owns the slots."""
        if n_slots < 1:
            raise ValueError(f"n_slots must be >= 1, got {n_slots}")
        return self._place(self._init_caches(n_slots, self._max_total))

    def region_nbytes(self, n_slots: int) -> int:
        """Host-metadata byte size of an ``alloc_region(n_slots)``
        allocation (the prefix cache reports this instead of walking
        its live device pytree on metrics scrapes)."""
        return self._nbytes_per_slot * n_slots

    def reinit(self) -> None:
        """Re-create the pooled cache buffers, zeroed (crash recovery:
        after an engine-loop crash the old buffers must be assumed
        corrupt — and with donation they may already be invalidated
        mid-step). Free-list/occupancy bookkeeping is preserved; the
        engine re-prefills every live slot afterwards (see
        ``ServingEngine.recover``)."""
        self.caches = self._alloc_caches()

    def nbytes(self) -> int:
        """Device bytes of the pooled cache (all slots; global logical
        bytes under TP). Precomputed host metadata — never touches the
        live device arrays, so metrics scrapes cost no device sync."""
        return self._nbytes


class PagedKVPool(KVSlotPool):
    """Block-paged KV pool: one shared device pool of fixed-size blocks
    plus a host-side per-slot int32 block table (vLLM-style paged
    attention). The slot free-list/generation machinery is inherited
    unchanged; what changes is the storage behind a slot:

    - ``caches`` leaves are ``(n_layers, 2, n_blocks, block_size, Hkv*K)``
      (plus the ``(..., 1)`` f32 scale planes in int8 mode) instead of
      per-slot Tpad slabs;
    - slot ``s`` owns the rows named by ``tables()[s]`` — a
      ``blocks_per_slot``-long int32 row where entry ``j`` maps token
      rows ``[j*block_size, (j+1)*block_size)``; unallocated entries
      hold 0, the permanently-zero SENTINEL block (block ids are
      therefore 1-based);
    - admission allocates only ``ceil((prompt+max_new)/block_size)``
      blocks instead of a whole Tpad slab, which is where the capacity
      lift at fixed HBM comes from;
    - blocks are reference-counted: a cached prefix is byte-SHARED by
      aliasing its block ids into a hitting slot's table and bumping
      refcounts (no copy); a block returns to the free heap only when
      its refcount reaches zero.

    Block ids are handed out lowest-id-first (a heap, like the slot
    free list) so allocation order is deterministic — the paged
    extensions of the free-list determinism tests rely on it.

    ``block_size`` must be a power of two dividing Tpad; keeping it a
    multiple of the engine's admission grain (8 rows) makes every
    grain-aligned partial-prefix hit block-aligned, so hits are pure
    aliasing. On TPU the natural size is the flash-decode kernel's time
    tile (512 for the >=1k-context Tpad grain).
    """

    is_paged = True

    def __init__(self, cfg: TransformerConfig, n_slots: int,
                 max_total: int, sharding=None, *, block_size: int = 8,
                 n_blocks: int | None = None):
        bs = int(block_size)
        if bs < 1 or bs & (bs - 1):
            raise ValueError(
                f"block_size must be a power of two, got {block_size}"
            )
        self.block_size = bs
        self._requested_blocks = n_blocks
        super().__init__(cfg, n_slots, max_total, sharding)
        # host-side paging state (same lock as the slot free list —
        # metrics gauges scrape block occupancy from a sidecar thread)
        self._tables = np.zeros(
            (n_slots, self.blocks_per_slot), np.int32
        )  # guarded-by: _lock
        self._refs = np.zeros((self.n_blocks,), np.int32)  # guarded-by: _lock
        self._refs[0] = 1  # zero sentinel: permanently pinned
        self._free_blocks = list(range(1, self.n_blocks))  # heap; guarded-by: _lock

    def _alloc_caches(self):
        if self.block_size > self.tpad or self.tpad % self.block_size:
            raise ValueError(
                f"block_size {self.block_size} does not divide the "
                f"slab row count Tpad={self.tpad}"
            )
        self.blocks_per_slot = self.tpad // self.block_size
        # default capacity matches the slab pool exactly (plus the
        # sentinel), so a paged pool can always hold what the slab pool
        # held; callers oversubscribe by passing a smaller n_blocks or
        # raise n_slots at the same n_blocks
        self.n_blocks = (
            self._requested_blocks if self._requested_blocks is not None
            else self.n_slots * self.blocks_per_slot + 1
        )
        if self.n_blocks < 2:
            raise ValueError(
                f"n_blocks must be >= 2 (sentinel + one allocatable "
                f"block), got {self.n_blocks}"
            )
        shapes = jax.eval_shape(
            lambda: self._init_caches(1, self._max_total)
        )
        return self._place(jax.tree.map(
            lambda s: jnp.zeros(
                (s.shape[0], s.shape[1], self.n_blocks,
                 self.block_size, s.shape[4]),
                s.dtype,
            ),
            shapes,
        ))

    # -- block accounting --------------------------------------------------

    def block_nbytes(self) -> int:
        """Host-metadata byte size of ONE block across all cache leaves
        (the prefix cache reports its footprint from block counts
        instead of walking live device arrays)."""
        return self._nbytes // self.n_blocks

    def blocks_needed(self, n_tokens: int) -> int:
        """Blocks covering ``n_tokens`` rows (every row a request can
        ever write — admission sizes this as prompt + max_new)."""
        return -(-max(0, int(n_tokens)) // self.block_size)

    @property
    def n_free_blocks(self) -> int:
        with self._lock:
            return len(self._free_blocks)

    @property
    def n_blocks_in_use(self) -> int:
        """Allocated blocks (sentinel excluded)."""
        with self._lock:
            return self.n_blocks - 1 - len(self._free_blocks)

    def can_admit(self, n_tokens: int) -> bool:
        """Whether the free heap covers a fresh ``n_tokens``-row
        allocation (the paged admission gate)."""
        return self.blocks_needed(n_tokens) <= self.n_free_blocks

    def table(self, slot: int) -> np.ndarray:
        """Snapshot of one slot's block-table row."""
        with self._lock:
            return self._tables[slot].copy()

    def tables(self) -> np.ndarray:
        """Snapshot of the whole (n_slots, blocks_per_slot) table."""
        with self._lock:
            return self._tables.copy()

    def slot_blocks(self, slot: int) -> list[int]:
        """The non-sentinel block ids a slot's table names, in table
        order."""
        with self._lock:
            return [int(b) for b in self._tables[slot] if b]

    def refcount(self, block_id: int) -> int:
        with self._lock:
            return int(self._refs[block_id])

    # -- allocation / sharing ----------------------------------------------

    def alloc_slot_blocks(self, slot: int, n_tokens: int,
                          start: int = 0) -> list[int]:
        """Allocate private blocks for table entries
        ``[start, blocks_needed(n_tokens))`` of ``slot`` (lowest block
        id first) and return them. ``start`` > 0 is the partial-hit
        path: the first ``start`` entries were aliased from a cached
        segment and stay untouched. Raises ``RuntimeError`` when the
        free heap cannot cover the allocation (callers gate admission
        on :meth:`can_admit`)."""
        k = self.blocks_needed(n_tokens)
        if k > self.blocks_per_slot:
            raise RuntimeError(
                f"{n_tokens} rows need {k} blocks, slot tables hold "
                f"{self.blocks_per_slot}"
            )
        with self._lock:
            note_access("pool.blockmap", write=True)
            need = max(0, k - start)
            if need > len(self._free_blocks):
                raise RuntimeError("no free KV blocks")
            out = []
            for j in range(start, k):
                bid = heapq.heappop(self._free_blocks)
                self._refs[bid] = 1
                self._tables[slot, j] = bid
                out.append(bid)
            return out

    def alias_into_slot(self, slot: int, block_ids, start: int = 0
                        ) -> None:
        """Byte-share existing blocks into ``slot``'s table entries
        ``[start, start+len(block_ids))``: a refcount bump, zero device
        work. This is how a prefix-cache hit lands its cached rows."""
        with self._lock:
            note_access("pool.blockmap", write=True)
            for j, bid in enumerate(block_ids):
                self._refs[bid] += 1
                self._tables[slot, start + j] = bid

    def alloc_blocks(self, k: int) -> list[int]:
        """Allocate ``k`` blocks owned by no slot (refcount 1) — the
        prefix cache's segment storage. Freed via :meth:`decref`."""
        with self._lock:
            note_access("pool.blockmap", write=True)
            if k > len(self._free_blocks):
                raise RuntimeError("no free KV blocks")
            out = [heapq.heappop(self._free_blocks) for _ in range(k)]
            for bid in out:
                self._refs[bid] = 1
            return out

    def incref(self, block_ids) -> None:
        with self._lock:
            note_access("pool.blockmap", write=True)
            for bid in block_ids:
                self._refs[bid] += 1

    def decref(self, block_ids) -> None:
        """Drop one reference per id; blocks reaching zero return to
        the free heap (eviction frees blocks, not slabs)."""
        with self._lock:
            note_access("pool.blockmap", write=True)
            for bid in block_ids:
                self._refs[bid] -= 1
                if self._refs[bid] == 0:
                    heapq.heappush(self._free_blocks, int(bid))

    def release(self, slot: int) -> None:
        """Slot free-list release plus block teardown: every non-
        sentinel table entry drops one reference (shared prefix blocks
        survive under their other holders; private blocks return to the
        heap) and the table row resets to the sentinel."""
        super().release(slot)
        with self._lock:
            note_access("pool.blockmap", write=True)
            for bid in self._tables[slot]:
                if bid:
                    self._refs[bid] -= 1
                    if self._refs[bid] == 0:
                        heapq.heappush(self._free_blocks, int(bid))
            self._tables[slot] = 0

    def reinit(self) -> None:
        """Crash recovery: re-create the block pool zeroed and reset
        ALL paging state — tables, refcounts, free heap. Slot
        free-list/occupancy bookkeeping is preserved (the engine
        re-allocates blocks while re-prefilling each live slot)."""
        super().reinit()
        with self._lock:
            self._tables[:] = 0
            self._refs[:] = 0
            self._refs[0] = 1
            self._free_blocks = list(range(1, self.n_blocks))
