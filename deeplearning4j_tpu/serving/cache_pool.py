"""KV-slot pool: slot recycling over ONE pre-allocated decode cache.

The batch axis of ``_decode_builder.init_caches`` IS the slot pool: the
buffers — (n_layers, 2, n_slots, Tpad, Hkv*K), plus the f32 scale
planes in int8 mode — are allocated once at engine start and never
re-allocated. Admitting a request into a freed slot overwrites that
slot's rows (the prefill insert copies a full Tpad slab, zeros beyond
the prompt, so no stale rows from the previous occupant survive);
releasing a slot is pure free-list bookkeeping, no device work. This is
the fixed-slot special case of vLLM's paged pool: one page per request,
sized to the engine's token budget.

Slots are handed out lowest-index-first so admission order is
deterministic — tests (and trace replays) rely on it.

Under tensor-parallel serving the pool carries a ``sharding`` pytree
(:func:`~deeplearning4j_tpu.models.transformer.serving_tp_cache_sharding`):
every allocation this pool hands out — the decode cache, crash-recovery
re-creations, and the prefix-cache segment region from
:meth:`alloc_region` — is placed with it, so pool slabs and region
slabs stay interchangeable under the same dynamic-slice programs.
"""

from __future__ import annotations

import heapq
import math
import threading

import jax

from deeplearning4j_tpu.analysis.sanitizers import note_access, wrap_lock

from deeplearning4j_tpu.models.transformer import (
    TransformerConfig,
    _decode_builder,
)


class KVSlotPool:
    """Free-list of decode-cache slots over one device allocation.

    ``caches`` is the live pytree (an array, or ``{"kv", "scale"}`` in
    int8-cache mode). The engine's jitted steps consume and return it
    functionally; with buffer donation the update is in place.
    """

    def __init__(self, cfg: TransformerConfig, n_slots: int, max_total: int,
                 sharding=None):
        if n_slots < 1:
            raise ValueError(f"n_slots must be >= 1, got {n_slots}")
        _, init_caches, _, _ = _decode_builder(cfg)
        self._init_caches = init_caches
        self._max_total = max_total
        self._sharding = sharding
        self.caches = self._place(init_caches(n_slots, max_total))
        kv = self.caches["kv"] if isinstance(self.caches, dict) else self.caches
        self.n_slots = n_slots
        self.tpad = kv.shape[3]  # rounded-up row count per slot
        # acquire/release/generation run on the engine thread while
        # n_free/n_active/occupancy feed metrics gauges scraped from
        # the sidecar thread — free-list bookkeeping moves under the
        # lock so a scrape never sees the heap mid-rebalance
        self._lock = wrap_lock(threading.Lock(), "pool._lock")
        self._free = list(range(n_slots))  # already a heap; guarded-by: _lock
        self._in_use: set[int] = set()  # guarded-by: _lock
        # per-slot generation, bumped on acquire: with pipelined
        # readback a token block can arrive for a slot that was retired
        # and re-acquired after its dispatch — the generation lets the
        # engine tell the block belongs to the previous occupant
        self._gen = [0] * n_slots  # guarded-by: _lock
        # byte sizes captured ONCE at allocation time (shape/dtype are
        # host metadata): metrics scrapes must never walk the live
        # device pytree (under donation a buffer can be
        # mid-invalidation, and under TP the per-scrape answer must not
        # depend on which shard you ask) — zero device interaction per
        # scrape
        self._nbytes = sum(
            math.prod(x.shape) * x.dtype.itemsize
            for x in jax.tree.leaves(self.caches)
        )
        self._nbytes_per_slot = self._nbytes // n_slots

    def _place(self, caches):
        """Place a fresh allocation with the pool's sharding (identity
        when unsharded)."""
        if self._sharding is None:
            return caches
        return jax.tree.map(jax.device_put, caches, self._sharding)

    @property
    def n_free(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def n_active(self) -> int:
        with self._lock:
            return len(self._in_use)

    @property
    def occupancy(self) -> float:
        """Active fraction of the slot batch this instant, in [0, 1]."""
        with self._lock:
            return len(self._in_use) / self.n_slots

    def acquire(self) -> int:
        """Claim the lowest free slot index."""
        with self._lock:
            note_access("pool.freelist", write=True)
            if not self._free:
                raise RuntimeError("no free KV slots")
            slot = heapq.heappop(self._free)
            self._in_use.add(slot)
            self._gen[slot] += 1
            return slot

    def generation(self, slot: int) -> int:
        """Acquire count for ``slot`` — identifies the current occupant
        across release/re-acquire (see ``_gen`` above)."""
        with self._lock:
            return self._gen[slot]

    def release(self, slot: int) -> None:
        with self._lock:
            note_access("pool.freelist", write=True)
            if slot not in self._in_use:
                raise ValueError(f"slot {slot} is not in use")
            self._in_use.remove(slot)
            heapq.heappush(self._free, slot)

    def alloc_region(self, n_slots: int):
        """A second bounded cache region with the SAME per-slot layout
        as the pool — Tpad row count, dtype, int8 scale planes, and
        (under TP) the same head-axis sharding — so a region slab and a
        pool slab are interchangeable under plain dynamic slices. This
        is how the prefix cache gets its segment store: the pool owns
        the layout, the cache owns the slots."""
        if n_slots < 1:
            raise ValueError(f"n_slots must be >= 1, got {n_slots}")
        return self._place(self._init_caches(n_slots, self._max_total))

    def region_nbytes(self, n_slots: int) -> int:
        """Host-metadata byte size of an ``alloc_region(n_slots)``
        allocation (the prefix cache reports this instead of walking
        its live device pytree on metrics scrapes)."""
        return self._nbytes_per_slot * n_slots

    def reinit(self) -> None:
        """Re-create the pooled cache buffers, zeroed (crash recovery:
        after an engine-loop crash the old buffers must be assumed
        corrupt — and with donation they may already be invalidated
        mid-step). Free-list/occupancy bookkeeping is preserved; the
        engine re-prefills every live slot afterwards (see
        ``ServingEngine.recover``)."""
        self.caches = self._place(
            self._init_caches(self.n_slots, self._max_total)
        )

    def nbytes(self) -> int:
        """Device bytes of the pooled cache (all slots; global logical
        bytes under TP). Precomputed host metadata — never touches the
        live device arrays, so metrics scrapes cost no device sync."""
        return self._nbytes
