"""KV-segment wire format for disaggregated prefill/decode serving.

Disaggregation (DistServe/Mooncake-style) splits the two phases of a
generate request across replicas: a PREFILL replica computes the
prompt's KV rows, and a DECODE replica seats them and runs the
token loop — so long-prompt prefill bursts stop stealing decode TPOT
at the replica level. The hop between them is this module: one
self-describing binary frame carrying a prefix segment — exactly the
batch-1 slab the engine's ``_seg_fetch`` program produces (or its
paged block-list equivalent) plus the stored last-row logits — such
that decode seats it through the ordinary zero-prefill full-hit path.

The frame is deliberately dumb: a fixed magic + version + JSON header
(model-config hash, token ids, layout, per-leaf dtype/shape specs)
followed by the raw array bytes, concatenated in header order. No
compression, no chunking — dtype/shape round-trip EXACTNESS is the
contract (the engine's disagg parity probe moves a segment through
``encode_segment``/``decode_segment`` and asserts the seated state is
bitwise identical to a local prefill), and raw bytes are the shortest
path to that. int8 segments ship their f32 scale planes as ordinary
leaves; bf16 ships as raw 2-byte words (``ml_dtypes`` round-trips the
dtype by name).

Receivers validate before touching a device: bad magic/version,
truncated or oversized payloads, and malformed headers raise
:class:`WireError` with HTTP status 400; a model-config-hash mismatch
(the segment was computed by a different checkpoint — seating it would
be silent corruption) raises with status 409. The HTTP layer maps
``WireError.status`` straight onto the response code, and senders fall
back to local prefill on any rejection — which is byte-identical
anyway, so a rejected transfer costs latency, never correctness.
"""

from __future__ import annotations

import hashlib
import json
import struct

import numpy as np

#: frame magic — first 4 bytes of every KV-segment frame
WIRE_MAGIC = b"KVSG"

#: wire format version; bumped on ANY header or payload layout change.
#: Receivers reject other versions outright (status 400) — a version
#: skew mid-rolling-restart must fall back to local prefill, never
#: misparse bytes into a cache. OPTIONAL header fields (like the
#: session-migration ``gen`` block) are additive and do NOT bump the
#: version: a v1 receiver that predates them never sees the endpoint
#: that sends them, and JSON headers ignore unknown keys by nature.
WIRE_VERSION = 1

_PREAMBLE = struct.Struct("<4sHI")  # magic, version, header length


class WireError(ValueError):
    """A KV-segment frame the receiver must not seat. ``status`` is
    the HTTP response code: 400 for malformed/truncated frames, 409
    for a model-config-hash mismatch (well-formed, wrong model)."""

    def __init__(self, message: str, status: int = 400):
        super().__init__(message)
        self.status = int(status)


def model_config_hash(cfg) -> str:
    """Stable identity of a model configuration: sha256 over the
    config's canonical JSON (``TransformerConfig.to_json``). Two
    engines agree on this hash iff they run the same architecture,
    dtypes and geometry — the precondition for a KV segment computed
    on one to be seatable on the other. (Weights are NOT hashed; the
    deployment contract is that replicas in one fleet serve one
    checkpoint, and the hash catches the config-level drift a rolling
    restart with the wrong model would introduce.)"""
    return hashlib.sha256(cfg.to_json().encode("utf-8")).hexdigest()


def _np_dtype(name: str) -> np.dtype:
    """dtype by name, including the ml_dtypes extension types (bf16
    etc.) numpy cannot look up by string on every version."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        try:
            return np.dtype(getattr(ml_dtypes, name))
        except AttributeError:
            raise WireError(f"unknown leaf dtype {name!r}") from None


def slab_to_blocks(leaves: list[np.ndarray],
                   block_size: int) -> list[np.ndarray]:
    """Reshape batch-1 slab leaves ``(L, C, 1, Tpad, H)`` into
    block-list leaves ``(L, C, Tpad/bs, bs, H)`` — the paged wire
    layout. Pure view-level reshape (rows are block-contiguous in the
    slab), zero copies beyond what ``tobytes`` does anyway."""
    out = []
    for a in leaves:
        L, C, one, tpad, H = a.shape
        if one != 1 or tpad % block_size:
            raise WireError(
                f"slab leaf {a.shape} not block-alignable at "
                f"block_size={block_size}"
            )
        out.append(a.reshape(L, C, tpad // block_size, block_size, H))
    return out


def blocks_to_slab(leaves: list[np.ndarray]) -> list[np.ndarray]:
    """Inverse of :func:`slab_to_blocks`: reassemble block-list leaves
    into the batch-1 slab form every seat path consumes."""
    out = []
    for a in leaves:
        L, C, nb, bs, H = a.shape
        out.append(a.reshape(L, C, 1, nb * bs, H))
    return out


def encode_segment(*, config_hash: str, tokens, leaves, logits,
                   layout: str = "slab", block_size: int = 0,
                   gen: dict | None = None) -> bytes:
    """Frame one prefix segment for the wire.

    ``leaves`` — the segment's cache arrays: batch-1 slab form
    ``(L, C, 1, Tpad, H)`` for ``layout="slab"``, block-list form
    ``(L, C, n_blocks, block_size, H)`` for ``layout="paged"`` (use
    :func:`slab_to_blocks`). ``logits`` — the stored ``(1, V)``
    last-row logits that make the seated segment full-hit capable.
    Arrays are framed as raw bytes in C order; dtype and shape ride
    the header, so the round-trip is exact for every dtype the engine
    pools (bf16, f32, int8 + f32 scale planes alike).

    ``gen`` — optional LIVE-SESSION state for migration frames: a
    JSON-able dict carrying the generating request's identity and
    mid-generation position (prompt length, tokens emitted so far,
    remaining budget, sampling-key words). Plain-segment frames omit
    it; receivers that don't understand it never see it (additive
    header field, see :data:`WIRE_VERSION`).
    """
    if layout not in ("slab", "paged"):
        raise WireError(f"unknown layout {layout!r}")
    if layout == "paged" and int(block_size) <= 0:
        raise WireError("paged layout requires a positive block_size")
    arrs = [np.ascontiguousarray(a) for a in leaves]
    lg = np.ascontiguousarray(logits)
    header = {
        "version": WIRE_VERSION,
        "config_hash": str(config_hash),
        "layout": layout,
        "block_size": int(block_size),
        "tokens": [int(t) for t in np.asarray(tokens).reshape(-1)],
        "leaves": [
            {"dtype": a.dtype.name, "shape": list(a.shape)} for a in arrs
        ],
        "logits": {"dtype": lg.dtype.name, "shape": list(lg.shape)},
    }
    if gen is not None:
        header["gen"] = dict(gen)
    hjson = json.dumps(header, sort_keys=True).encode("utf-8")
    parts = [_PREAMBLE.pack(WIRE_MAGIC, WIRE_VERSION, len(hjson)), hjson]
    parts += [a.tobytes() for a in arrs]
    parts.append(lg.tobytes())
    return b"".join(parts)


def _read_array(data: bytes, spec: dict, off: int,
                what: str) -> tuple[np.ndarray, int]:
    try:
        dt = _np_dtype(str(spec["dtype"]))
        shape = tuple(int(d) for d in spec["shape"])
    except (KeyError, TypeError, ValueError):
        raise WireError(f"malformed {what} spec {spec!r}") from None
    count = 1
    for d in shape:
        if d < 0:
            raise WireError(f"negative dimension in {what} spec")
        count *= d
    nbytes = count * dt.itemsize
    if off + nbytes > len(data):
        raise WireError(
            f"truncated payload: {what} needs {nbytes} bytes at "
            f"offset {off}, frame has {len(data)}"
        )
    arr = np.frombuffer(data, dt, count=count, offset=off).reshape(shape)
    return arr, off + nbytes


def decode_segment(data: bytes, *,
                   expect_hash: str | None = None) -> dict:
    """Parse and validate one wire frame; the inverse of
    :func:`encode_segment`.

    Returns ``{"config_hash", "layout", "block_size", "tokens"
    (int32 array), "leaves" (batch-1 SLAB-form arrays — paged frames
    are reassembled), "logits", "gen" (the optional live-session
    block, ``None`` for plain segments), "nbytes"}``. Raises
    :class:`WireError`
    (status 400) on bad magic/version, malformed headers, or payloads
    whose byte count disagrees with the declared specs, and (status
    409) when ``expect_hash`` is given and the frame's config hash
    differs — the caller must fall back to local prefill, not seat a
    foreign checkpoint's KV.
    """
    if len(data) < _PREAMBLE.size:
        raise WireError("frame shorter than preamble")
    magic, version, hlen = _PREAMBLE.unpack_from(data)
    if magic != WIRE_MAGIC:
        raise WireError(f"bad magic {magic!r}")
    if version != WIRE_VERSION:
        raise WireError(
            f"unsupported wire version {version} "
            f"(speaking {WIRE_VERSION})"
        )
    if _PREAMBLE.size + hlen > len(data):
        raise WireError("truncated header")
    try:
        header = json.loads(
            data[_PREAMBLE.size:_PREAMBLE.size + hlen].decode("utf-8")
        )
    except (UnicodeDecodeError, json.JSONDecodeError):
        raise WireError("malformed header JSON") from None
    try:
        config_hash = str(header["config_hash"])
        layout = str(header["layout"])
        block_size = int(header["block_size"])
        tokens = np.asarray(
            [int(t) for t in header["tokens"]], np.int32
        )
        leaf_specs = list(header["leaves"])
        logit_spec = dict(header["logits"])
    except (KeyError, TypeError, ValueError):
        raise WireError("header missing required fields") from None
    gen = header.get("gen")
    if gen is not None and not isinstance(gen, dict):
        raise WireError("gen header field must be an object")
    if layout not in ("slab", "paged"):
        raise WireError(f"unknown layout {layout!r}")
    if expect_hash is not None and config_hash != expect_hash:
        raise WireError(
            f"model config hash mismatch: frame {config_hash[:12]}..., "
            f"receiver {expect_hash[:12]}...",
            status=409,
        )
    off = _PREAMBLE.size + hlen
    leaves = []
    for i, spec in enumerate(leaf_specs):
        arr, off = _read_array(data, spec, off, f"leaf {i}")
        leaves.append(arr)
    logits, off = _read_array(data, logit_spec, off, "logits")
    if off != len(data):
        raise WireError(
            f"{len(data) - off} trailing bytes after declared payload"
        )
    if layout == "paged":
        if block_size <= 0:
            raise WireError("paged frame with non-positive block_size")
        leaves = blocks_to_slab(leaves)
    return {
        "config_hash": config_hash,
        "layout": layout,
        "block_size": block_size,
        "tokens": tokens,
        "leaves": leaves,
        "logits": logits,
        "gen": gen,
        "nbytes": len(data),
    }
