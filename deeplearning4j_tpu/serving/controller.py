"""Fleet controller: disaggregated prefill/decode orchestration over
N serving replicas.

The :class:`~deeplearning4j_tpu.serving.router.ReplicaRouter` scales
throughput across interchangeable replicas; this controller adds the
one thing the router deliberately lacks — *roles*. In a disaggregated
fleet (DistServe/Mooncake) some replicas are PREFILL workers (compute
prompt KV, ship it) and some are DECODE workers (seat shipped KV, run
the token loop), so a long-prompt prefill burst stops stealing decode
TPOT at the replica level instead of the batch level.

Routing policy for ``POST /v1/generate`` (in priority order):

1. **Session stickiness.** A request carrying ``"session": <id>``
   lands on the decode replica that served that session last — its
   prefix cache almost certainly still holds the conversation's KV
   run. A dead/draining sticky target falls through to:
2. **Shadow-trie affinity.** Same host-side trie the router keeps: the
   decode-capable replica with the longest shared prompt prefix wins
   when the match reaches ``affinity_min_match`` tokens.
3. **Least loaded** decode-capable replica, round-robin on ties.

Independently of *which* decode replica wins, prompts of
``disagg_threshold`` tokens or more take the TRANSFER path when a
dedicated prefill replica is available: the controller POSTs
``/v1/prefill`` (with ``push_to`` naming the decode target) to the
prefill replica, which computes the KV rows, frames them
(:mod:`.disagg`), and pushes the segment straight to the decode
replica's ``/v1/kv_segment`` — replica-to-replica, the bytes never
transit the controller. The follow-up generate forwarded to the decode
replica then full-hits its prefix cache and goes straight to decoding.
ANY failure along that leg (prefill down, push rejected, segment
declined) just falls back to forwarding the generate as-is — the
decode replica prefills locally, byte-identical, only slower.

Role REBALANCING is hysteretic and observable: the health poller
samples every replica's queue depth (``/healthz``) and worst per-tenant
SLO burn (``/metrics.json``, the PR-9 ``slo_burn`` gauges), and a pure
:class:`RoleBalancer` flips one replica's role only after the pressure
imbalance persists for ``rebalance_windows`` consecutive samples AND
``rebalance_dwell_s`` has passed since the last flip — so a single
bursty window never thrashes the fleet. Pools never drain to zero.

Rolling restarts ride ``POST /fleet/drain`` / ``/fleet/undrain``
(body ``{"replica": "host:port"}``): the controller relays the
replica's own ``/drain`` endpoint and stops dispatching to it
immediately; in-flight work finishes because the replica keeps
stepping. ``/undrain`` restores it to the rotation. With ``{"migrate":
true}`` the drain additionally relays the replica's ``/migrate`` so
live sessions finish on a healthy decode replica (byte-identical —
see ``ServingEngine.export_sessions``) instead of riding out the
drain.

RESILIENCE (PR 17): every outbound leg honors the caller's
``X-Deadline-Ms`` budget (shrunken and re-forwarded per hop, socket
timeouts derived from it); per-replica CIRCUIT BREAKERS
(closed/open/half-open with exponential probe backoff,
:class:`~deeplearning4j_tpu.serving.rpc.CircuitBreaker`) gate dispatch
— a health-poll success alone never closes an open breaker, only a
successful probe request does; the idempotent transfer leg is HEDGED
after the observed p99 transfer latency (the decode replica dedups on
the shared idempotency key; the generate leg is never hedged). The
controller checkpoints roles / sticky sessions / breaker state to a
JOURNAL (atomic rename), and a warm standby (``standby_of=``)
promotes from that journal after ``failover_after`` missed primary
probes, re-verifying against live fleet state.

The controller is the fleet's trace root: every outbound leg (prefill
dispatch, decode dispatch) is a real span carrying a fresh span id
downstream via ``traceparent``, so the merged Perfetto view chains
controller dispatch -> prefill -> transfer -> decode ingest -> decode
generate under one trace id.

Endpoints: ``POST /v1/generate`` (routed passthrough + X-Served-By),
``POST /fleet/drain`` / ``POST /fleet/undrain`` / ``POST /fleet/role``
(manual role override), ``GET /healthz``, ``GET /fleet`` (roles +
per-replica state), ``GET /metrics``, ``GET /debug/dump``.
"""

from __future__ import annotations

import http.client
import json
import logging
import os
import signal
import threading
import time
from collections import OrderedDict
from http.server import ThreadingHTTPServer
from pathlib import Path
from urllib.parse import urlparse

from deeplearning4j_tpu.analysis.sanitizers import note_access, wrap_lock
from deeplearning4j_tpu.obs.flight import FlightRecorder
from deeplearning4j_tpu.obs.logs import log_event
from deeplearning4j_tpu.obs.registry import MetricsRegistry
from deeplearning4j_tpu.obs.trace import (
    Tracer,
    format_traceparent,
    new_span_id,
    new_trace_id,
    parse_traceparent,
)
from deeplearning4j_tpu.serving.router import PrefixShadow, _ReplicaDown
from deeplearning4j_tpu.serving.rpc import (
    CLOSED,
    DEADLINE_HEADER,
    HALF_OPEN,
    CircuitBreaker,
    Deadline,
    LatencyWindow,
    run_hedged,
)
from deeplearning4j_tpu.utils.httpjson import (
    QuietHandler,
    read_json_body,
    send_body,
    send_json,
)

_log = logging.getLogger(__name__)

#: Prometheus text exposition format version served at /metrics
PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

#: the controller's single trace track
CONTROLLER_TRACK = "controller"

#: replica roles the controller understands. "monolithic" serves both
#: phases itself and stays out of the rebalancer's pools.
ROLES = ("prefill", "decode", "monolithic")


class RoleBalancer:
    """Pure, hysteretic role-rebalance policy (no I/O, no clocks of
    its own — fully unit-testable).

    ``observe(now, samples)`` takes one fleet sample — ``{name:
    {"role", "queue_depth", "slo_burn"}}`` — and returns the role
    moves ``[(replica_name, new_role)]`` to apply (at most one per
    call). A move requires the SAME imbalance direction for
    ``windows`` consecutive samples, at least ``dwell_s`` since the
    previous move, and a donor pool of >= 2 (a role is never emptied).

    Pressure model: prefill pressure is queue depth (prefill work is
    admission-bound); decode pressure is queue depth plus
    ``slo_weight`` x the excess SLO burn (burn > 1 means tenants'
    p99 TPOT objective is being violated — the signal disaggregation
    exists to protect). An imbalance counts when one side's mean
    pressure exceeds ``threshold`` x the other's plus an absolute
    epsilon, so two idle pools (0 vs 0.1) never trigger.
    """

    def __init__(self, threshold: float = 2.0, windows: int = 3,
                 dwell_s: float = 30.0, slo_weight: float = 4.0):
        self.threshold = float(threshold)
        self.windows = int(windows)
        self.dwell_s = float(dwell_s)
        self.slo_weight = float(slo_weight)
        self._direction = 0  # +1 decode needs help, -1 prefill does
        self._streak = 0
        self._last_move: float | None = None

    def _pressure(self, s: dict, decode: bool) -> float:
        p = float(s.get("queue_depth") or 0)
        if decode:
            p += self.slo_weight * max(0.0, float(s.get("slo_burn") or 0.0) - 1.0)
        return p

    def observe(self, now: float,
                samples: dict) -> list[tuple[str, str]]:
        pf = {n: s for n, s in samples.items() if s.get("role") == "prefill"}
        dc = {n: s for n, s in samples.items() if s.get("role") == "decode"}
        if not pf or not dc:
            self._streak, self._direction = 0, 0
            return []
        p_pf = sum(self._pressure(s, False) for s in pf.values()) / len(pf)
        p_dc = sum(self._pressure(s, True) for s in dc.values()) / len(dc)
        eps = 0.5
        if p_dc > self.threshold * p_pf + eps:
            direction = 1
        elif p_pf > self.threshold * p_dc + eps:
            direction = -1
        else:
            direction = 0
        if direction == 0:
            self._direction, self._streak = 0, 0
            return []
        if direction != self._direction:
            self._direction, self._streak = direction, 1
        else:
            self._streak += 1
        if self._streak < self.windows:
            return []
        if (self._last_move is not None
                and now - self._last_move < self.dwell_s):
            return []
        donors = pf if direction > 0 else dc
        if len(donors) <= 1:
            return []  # never empty a role
        name = min(
            donors,
            key=lambda n: self._pressure(donors[n], direction < 0),
        )
        self._last_move = now
        self._streak = 0
        return [(name, "decode" if direction > 0 else "prefill")]


class _Member:
    """Controller-side view of one fleet replica."""

    __slots__ = ("host", "port", "role", "role_since", "healthy",
                 "draining", "incompatible", "config_hash", "in_flight",
                 "routed", "queue_depth", "slo_burn", "shadow",
                 "last_health", "breaker")

    def __init__(self, host: str, port: int, role: str = "monolithic"):
        if role not in ROLES:
            raise ValueError(f"unknown role {role!r} (one of {ROLES})")
        self.host = host
        self.port = int(port)
        self.role = role  # guarded-by: _route_lock
        self.role_since = 0.0
        self.healthy = True  # guarded-by: _route_lock
        self.draining = False  # guarded-by: _route_lock
        self.incompatible = False  # guarded-by: _route_lock
        self.config_hash: str | None = None
        self.in_flight = 0  # guarded-by: _route_lock
        self.routed = 0
        self.queue_depth = 0
        self.slo_burn = 0.0
        self.shadow = PrefixShadow()
        self.last_health: dict | None = None
        # per-replica circuit breaker; dispatch gates on it (the binary
        # healthy flag above stays as the liveness VIEW, the breaker is
        # what decides). The controller replaces this with one wired to
        # its transition hooks.
        self.breaker = CircuitBreaker()

    def dispatchable(self) -> bool:  # lint: holds _route_lock
        """Usable AND the breaker is closed — the fast path. Open or
        half-open breakers only admit the explicit probe picked in
        ``_pick_decode``/``_pick_prefills``."""
        return self.usable() and self.breaker.state == CLOSED

    @property
    def name(self) -> str:
        return f"{self.host}:{self.port}"

    def usable(self) -> bool:  # lint: holds _route_lock
        return self.healthy and not self.draining and not self.incompatible

    def decode_capable(self) -> bool:  # lint: holds _route_lock
        return self.role in ("decode", "monolithic")

    def state(self) -> dict:  # lint: holds _route_lock
        return {
            "role": self.role,
            "healthy": self.healthy,
            "draining": self.draining,
            "incompatible": self.incompatible,
            "config_hash": self.config_hash,
            "in_flight": self.in_flight,
            "routed": self.routed,
            "queue_depth": self.queue_depth,
            "slo_burn": self.slo_burn,
            "shadow_nodes": len(self.shadow),
            "breaker": self.breaker.snapshot(),
        }


def _parse_member(spec) -> _Member:
    """Accept ``"host:port"``, ``"host:port=role"``, or
    ``(host, port[, role])`` tuples."""
    if isinstance(spec, str):
        addr, _, role = spec.partition("=")
        host, _, port = addr.rpartition(":")
        if not host or not port.isdigit():
            raise ValueError(f"replica spec {spec!r} is not "
                             "host:port[=role]")
        return _Member(host, int(port), role or "monolithic")
    host, port = spec[0], spec[1]
    role = spec[2] if len(spec) > 2 else "monolithic"
    return _Member(str(host), int(port), str(role))


class FleetController:
    """Role-aware fleet front end; ``start()`` is non-blocking.

    ``disagg_threshold`` — prompt length (tokens) at which a request
    takes the prefill->transfer->decode path instead of prefilling on
    the decode replica. Below it the transfer costs more than the
    prefill it saves (see PERF.md for the heuristic).
    """

    def __init__(self, replicas, host: str = "127.0.0.1", port: int = 0,
                 disagg_threshold: int = 64,
                 affinity_min_match: int = 8,
                 health_interval_s: float = 0.5,
                 request_timeout_s: float = 300.0,
                 rebalance: RoleBalancer | None = None,
                 rebalance_enabled: bool = True,
                 session_cap: int = 65536,
                 tracer: Tracer | None = None,
                 flight: FlightRecorder | None = None,
                 flight_dir: str | None = None,
                 hedge_enabled: bool = True,
                 journal: str | None = None,
                 standby_of: str | None = None,
                 failover_after: int = 3):
        if not replicas:
            raise ValueError("need at least one replica")
        self.members = [_parse_member(spec) for spec in replicas]
        self.disagg_threshold = int(disagg_threshold)
        self.affinity_min_match = int(affinity_min_match)
        self.health_interval_s = float(health_interval_s)
        self.request_timeout_s = float(request_timeout_s)
        self.balancer = rebalance if rebalance is not None else RoleBalancer()
        self.rebalance_enabled = bool(rebalance_enabled)
        self.tracer = tracer if tracer is not None else Tracer(
            enabled=False, process_name="controller")
        self.flight = flight if flight is not None else FlightRecorder()
        self.flight_dir = (flight_dir if flight_dir is not None
                           else os.environ.get("DL4J_TPU_FLIGHT_DIR")
                           or None)
        self._stop = threading.Event()
        self._route_lock = wrap_lock(
            threading.Lock(), "controller._route_lock"
        )
        self._rr = 0  # round-robin tie-break cursor
        # session id -> decode replica name, LRU-bounded; a session
        # whose replica died just falls back to shadow affinity
        self._sessions: OrderedDict[str, str] = OrderedDict()
        self._session_cap = int(session_cap)
        # hedging: the transfer leg is idempotent (the decode replica
        # dedups on the shared idempotency key), so a second attempt
        # fires after the observed p99 transfer latency
        self.hedge_enabled = bool(hedge_enabled)
        self._transfer_lat = LatencyWindow()
        # checkpoint + failover: the journal captures roles, session
        # stickiness (LRU order), breaker state, and config hashes so a
        # warm standby promotes from disk and re-verifies against live
        # /fleet state instead of starting cold
        self.journal_path = Path(journal) if journal else None
        self._journal_seq = 0
        self.standby_of = standby_of or None
        self.failover_after = max(1, int(failover_after))
        self._primary_misses = 0
        # standby controllers route nothing until promoted
        self._active = self.standby_of is None

        reg = self.registry = MetricsRegistry()
        self._m_requests = reg.counter(
            "fleet_requests_total", "Requests accepted by the controller.")
        self._m_routed = reg.counter(
            "fleet_routed_total", "Generates dispatched, per replica.",
            labelnames=("replica",))
        self._m_disagg = reg.counter(
            "fleet_disagg_total",
            "Requests that took the prefill->transfer->decode path.")
        self._m_fallback = reg.counter(
            "fleet_transfer_fallback_total",
            "Disagg-eligible requests that fell back to local prefill "
            "on the decode replica (prefill down / push rejected / "
            "segment declined).")
        self._m_sticky = reg.counter(
            "fleet_sticky_total",
            "Dispatches decided by session stickiness.")
        self._m_affinity = reg.counter(
            "fleet_affinity_total",
            "Dispatches decided by shadow-trie prefix affinity.")
        self._m_retries = reg.counter(
            "fleet_retries_total",
            "Generate forwards retried on another replica.")
        self._m_no_replica = reg.counter(
            "fleet_no_replica_total",
            "Requests failed because no usable decode replica remained.")
        self._m_rebalance = reg.counter(
            "fleet_rebalances_total", "Role flips applied, per new role.",
            labelnames=("role",))
        self._m_role = reg.gauge(
            "fleet_role_replicas", "Usable replicas per role.",
            labelnames=("role",))
        self._m_healthy = reg.gauge(
            "fleet_replica_healthy", "1 while the replica is usable.",
            labelnames=("replica",))
        self._m_breaker = reg.gauge(
            "fleet_breaker_state",
            "Circuit breaker per replica: 0 closed, 0.5 half-open, "
            "1 open.",
            labelnames=("replica",))
        self._m_breaker_transitions = reg.counter(
            "fleet_breaker_transitions_total",
            "Breaker state changes, per replica and new state.",
            labelnames=("replica", "state"))
        self._m_hedges = reg.counter(
            "fleet_hedges_total",
            "Hedged transfer legs, by result (fired = second attempt "
            "launched, won = second attempt answered first).",
            labelnames=("result",))
        self._m_sessions_evicted = reg.counter(
            "fleet_sessions_evicted_total",
            "Sticky sessions dropped by LRU eviction at session_cap.")
        self._m_failovers = reg.counter(
            "fleet_failovers_total",
            "Standby promotions after losing the primary.")
        self._m_migrations = reg.counter(
            "fleet_migrations_total",
            "Live session migrations relayed on drain, by result.",
            labelnames=("result",))
        self._m_standby = reg.gauge(
            "fleet_standby", "1 while this controller is a standby.")
        self._m_standby.set(0.0 if self._active else 1.0)
        for m in self.members:
            self._m_healthy.set(1.0, replica=m.name)
            self._m_breaker.set(0.0, replica=m.name)
            # rewire each member's breaker through the controller's
            # transition hook (flight event + gauge + journal)
            m.breaker = CircuitBreaker(
                on_transition=self._breaker_hook(m.name))
        self._refresh_role_gauges()

        controller = self

        class Handler(QuietHandler):
            def do_GET(self):
                path = urlparse(self.path).path
                if path == "/healthz":
                    payload = controller.health_payload()
                    send_json(self, 200 if payload["ok"] else 503, payload)
                elif path == "/fleet":
                    send_json(self, 200, controller.fleet_state())
                elif path == "/metrics":
                    send_body(self, 200, reg.render().encode(),
                              PROM_CONTENT_TYPE)
                elif path == "/debug/dump":
                    send_json(self, 200,
                              controller.flight_bundle("debug_dump"))
                else:
                    send_json(self, 404, {"error": "not found"})

            def do_POST(self):
                path = urlparse(self.path).path
                if controller._stop.is_set():
                    send_json(self, 503, {"error": "controller stopped"})
                    return
                if not controller._active:
                    # a standby routes nothing until promoted; callers
                    # retry against the primary (or wait for failover)
                    send_json(self, 503, {"error": "standby controller",
                                          "standby": True})
                    return
                if path in ("/fleet/drain", "/fleet/undrain",
                            "/fleet/role"):
                    body = read_json_body(self)
                    if body is None:
                        send_json(self, 400, {"error": "malformed JSON"})
                        return
                    controller._handle_fleet_post(self, path, body)
                    return
                if path != "/v1/generate":
                    send_json(self, 404, {"error": "not found"})
                    return
                body = read_json_body(self)
                if body is None:
                    send_json(self, 400, {"error": "malformed JSON"})
                    return
                code, payload, served_by = controller.route(
                    body, traceparent=self.headers.get("traceparent"),
                    deadline_ms=self.headers.get(DEADLINE_HEADER))
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                if served_by is not None:
                    self.send_header("X-Served-By", served_by)
                self.end_headers()
                self.wfile.write(payload)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="controller-http")
        self._health_thread = threading.Thread(
            target=self._health_loop, daemon=True,
            name="controller-health")

    # ------------------------------------------------------------- #
    # routing                                                        #
    # ------------------------------------------------------------- #

    @staticmethod
    def _prompt_tokens(body: dict) -> list[int]:
        prompt = body.get("prompt")
        if isinstance(prompt, str):
            return list(prompt.encode("latin-1", errors="replace"))
        if isinstance(prompt, list):
            try:
                return [int(t) for t in prompt]
            except (TypeError, ValueError):
                return []
        return []

    def _note_session(self, session, name: str) -> None:
        if not session:
            return
        key = str(session)
        with self._route_lock:
            note_access("controller._sessions", write=True)
            self._sessions[key] = name
            self._sessions.move_to_end(key)
            while len(self._sessions) > self._session_cap:
                evicted, _ = self._sessions.popitem(last=False)
                self._m_sessions_evicted.inc()
                log_event(_log, "fleet_session_evicted",
                          session=evicted, cap=self._session_cap)

    def _pick_decode(self, tokens, session,
                     exclude: set[str]) -> tuple[_Member, str]:
        """Choose the decode-capable target; returns ``(member, how)``
        with ``how`` in sticky/affinity/load. Raises ``_ReplicaDown``
        when no usable candidate remains. Breaker-gated: closed
        breakers are the normal pool; when it is empty, ONE due probe
        through an open breaker is admitted (half-open) so a recovered
        replica can prove itself on real traffic."""
        with self._route_lock:
            avail = [
                m for m in self.members
                if m.usable() and m.decode_capable()
                and m.name not in exclude
            ]
            candidates = [m for m in avail if m.breaker.state == CLOSED]
            if not candidates:
                # allow() consumes the half-open probe, so only ask
                # when no closed-breaker replica remains
                candidates = [m for m in avail if m.breaker.allow()]
            if not candidates:
                raise _ReplicaDown("no usable decode replica")
            chosen, how = None, "load"
            if session:
                note_access("controller._sessions", write=True)
                want = self._sessions.get(str(session))
                if want:
                    for m in candidates:
                        if m.name == want:
                            chosen, how = m, "sticky"
                            # a sticky HIT refreshes LRU recency, so
                            # active sessions outlive idle pins at the
                            # eviction cap
                            self._sessions.move_to_end(str(session))
                            break
            if chosen is None and tokens:
                best, best_match = None, -1
                for m in candidates:
                    match = m.shadow.longest_match(tokens)
                    if match > best_match or (
                        match == best_match
                        and m.in_flight < best.in_flight
                    ):
                        best, best_match = m, match
                if best_match >= self.affinity_min_match:
                    chosen, how = best, "affinity"
            if chosen is None:
                self._rr += 1
                lo = min(m.in_flight for m in candidates)
                tied = [m for m in candidates if m.in_flight == lo]
                chosen = tied[self._rr % len(tied)]
            chosen.in_flight += 1
            chosen.routed += 1
            if tokens:
                chosen.shadow.insert(tokens)
            return chosen, how

    def _pick_prefills(self, decode_name: str) -> list[_Member]:
        """Usable DEDICATED prefill replicas, least-loaded first
        (monolithic replicas prefill for themselves; shipping KV from
        one decode replica to another buys nothing). Entry [0] is the
        primary transfer target; entry [1], when present, is the hedge
        destination. Empty when the fleet has no transfer path — the
        caller falls back to local prefill. Breaker-gated like
        ``_pick_decode``."""
        with self._route_lock:
            avail = [
                m for m in self.members
                if m.usable() and m.role == "prefill"
                and m.name != decode_name
            ]
            candidates = [m for m in avail if m.breaker.state == CLOSED]
            if not candidates:
                candidates = [m for m in avail if m.breaker.allow()]
            ranked = sorted(
                (m.in_flight, i, m) for i, m in enumerate(candidates)
            )
            return [m for _, _, m in ranked]

    def _span(self, name: str, trace_id: str, span_id: str,
              parent_span: str, t0: float, **extra) -> None:
        if not self.tracer.enabled:
            return
        args = {"trace_id": trace_id, "span_id": span_id, **extra}
        if parent_span:
            args["parent_span_id"] = parent_span
        self.tracer.span(CONTROLLER_TRACK, name, t0,
                         time.perf_counter() - t0, **args)

    def _transfer_leg(self, prefills: list[_Member], target: _Member,
                      body: dict, tokens, trace_id: str,
                      parent_span: str,
                      dl: Deadline | None = None) -> bool:
        """The disagg leg: ask a prefill replica to compute the
        prompt's KV and push the segment to ``target``. True only when
        the segment was pushed AND seated — anything else means the
        forwarded generate will prefill locally (same bytes, just
        slower).

        Idempotent end to end (the decode replica dedups the push on
        the shared ``idem_key``), so with a second prefill candidate
        the leg is HEDGED: if the first attempt hasn't answered within
        the observed p99 transfer latency, a second fires at the
        alternate replica and the first completion wins. The loser's
        push is declined by the dedup (409) — which counts as success
        here, since the segment IS seated — at the price of one wasted
        prefill."""
        idem_key = "tx-" + new_span_id()
        req = {"prompt": tokens, "push_to": target.name,
               "idem_key": idem_key}
        for k in ("priority", "adapter"):
            if k in body:
                req[k] = body[k]
        raw = json.dumps(req).encode()

        def attempt(leg: int):
            prefill = prefills[leg % len(prefills)]
            span_id = new_span_id()
            t0 = time.perf_counter()
            ok, info, err = False, {}, None
            with self._route_lock:
                prefill.in_flight += 1
            try:
                conn = http.client.HTTPConnection(
                    prefill.host, prefill.port,
                    timeout=(dl.timeout(self.request_timeout_s)
                             if dl is not None
                             else self.request_timeout_s))
                try:
                    headers = {
                        "Content-Type": "application/json",
                        "traceparent": format_traceparent(
                            trace_id, span_id),
                        "X-Served-By": prefill.name,
                    }
                    if dl is not None:
                        headers[DEADLINE_HEADER] = dl.header_value()
                    conn.request("POST", "/v1/prefill", body=raw,
                                 headers=headers)
                    resp = conn.getresponse()
                    payload = resp.read()
                    try:
                        info = json.loads(payload.decode("utf-8"))
                    except (ValueError, UnicodeDecodeError):
                        info = {}
                    if resp.status == 503:
                        raise _ReplicaDown(f"{prefill.name} answered 503")
                    ok = resp.status == 200 and bool(info.get("pushed"))
                    if (not ok and isinstance(info.get("ingest"), dict)
                            and info["ingest"].get("duplicate")):
                        # the other hedge leg's copy seated first —
                        # same bytes are in the decode replica's cache
                        ok = True
                    prefill.breaker.record_success()
                    if not ok:
                        err = "http %d pushed=%s" % (
                            resp.status, info.get("pushed"))
                except (OSError, http.client.HTTPException,
                        _ReplicaDown) as e:
                    err = str(e)
                    self._mark_unhealthy(prefill, err)
                    raise
                finally:
                    conn.close()
            finally:
                with self._route_lock:
                    prefill.in_flight -= 1
                dt = time.perf_counter() - t0
                self._transfer_lat.record(dt)
                self._span("dispatch", trace_id, span_id, parent_span,
                           t0, leg="prefill", replica=prefill.name,
                           ok=ok)
            return ok, err, prefill.name

        hedge = (self.hedge_enabled and len(prefills) >= 2
                 and (dl is None or not dl.expired()))
        ok, err, via = False, None, prefills[0].name

        def on_hedge():
            self._m_hedges.inc(result="fired")
            self.flight.record("hedge_fired", leg="transfer",
                               trace_id=trace_id,
                               primary=prefills[0].name,
                               hedge=prefills[1].name)

        try:
            if hedge:
                result, legs, winner = run_hedged(
                    attempt, delay_s=self._transfer_lat.quantile(0.99),
                    deadline=dl, on_hedge=on_hedge)
                ok, err, via = result
                if legs > 1 and winner == 1:
                    self._m_hedges.inc(result="won")
                    self.flight.record("hedge_won", leg="transfer",
                                       trace_id=trace_id, replica=via)
            else:
                ok, err, via = attempt(0)
        except (_ReplicaDown, OSError, http.client.HTTPException) as e:
            err = str(e)
        if ok:
            self._m_disagg.inc()
        else:
            self._m_fallback.inc()
            log_event(_log, "fleet_transfer_fallback",
                      prefill=via, decode=target.name,
                      error=err, trace_id=trace_id)
        self.flight.record("transfer", prefill=via,
                           decode=target.name, ok=ok,
                           trace_id=trace_id)
        return ok

    def _forward(self, member: _Member, raw: bytes, headers: dict,
                 dl: Deadline | None = None) -> tuple[int, bytes]:
        conn = http.client.HTTPConnection(
            member.host, member.port,
            timeout=(dl.timeout(self.request_timeout_s)
                     if dl is not None else self.request_timeout_s))
        try:
            conn.request("POST", "/v1/generate", body=raw,
                         headers=headers)
            resp = conn.getresponse()
            payload = resp.read()
            if resp.status == 503:
                raise _ReplicaDown(f"{member.name} answered 503")
            member.breaker.record_success()
            return resp.status, payload
        except (OSError, http.client.HTTPException) as e:
            raise _ReplicaDown(f"{member.name}: {e}") from e
        finally:
            conn.close()

    def route(self, body: dict,
              traceparent: str | None = None,
              deadline_ms: str | None = None
              ) -> tuple[int, bytes, str | None]:
        """Route one generate request; returns
        ``(status, payload_bytes, replica_name | None)``.

        The transfer leg runs at most once (on the first decode pick):
        if the decode replica then dies before accepting the generate,
        the retry on a survivor skips re-transfer — the survivor
        prefills locally, which is the universal fallback anyway.

        Every leg's socket timeout and the shrunken ``X-Deadline-Ms``
        forwarded downstream derive from the caller's deadline budget
        (default: the controller's own request timeout). The generate
        leg itself is never hedged — decoding is not idempotent.
        """
        self._m_requests.inc()
        ctx = parse_traceparent(traceparent)
        trace_id, parent_span = ctx if ctx else (new_trace_id(), "")
        dl = Deadline.from_header(deadline_ms,
                                  default_s=self.request_timeout_s)
        tokens = self._prompt_tokens(body)
        session = body.get("session")
        raw = json.dumps(body).encode()
        exclude: set[str] = set()
        attempt = 0
        transfer_tried = False
        while True:
            if dl.expired():
                # budget gone: a bounded clean failure beats a forward
                # the caller will never read
                return 504, json.dumps(
                    {"error": "deadline exhausted",
                     "attempts": attempt}).encode(), None
            try:
                member, how = self._pick_decode(tokens, session, exclude)
            except _ReplicaDown:
                self._m_no_replica.inc()
                self.flight.record("no_replica", trace_id=trace_id,
                                   attempts=attempt)
                return 503, json.dumps(
                    {"error": "no usable decode replica"}).encode(), None
            attempt += 1
            self._m_routed.inc(replica=member.name)
            if how == "sticky":
                self._m_sticky.inc()
            elif how == "affinity":
                self._m_affinity.inc()
            if (not transfer_tried
                    and len(tokens) >= self.disagg_threshold):
                transfer_tried = True
                prefills = self._pick_prefills(member.name)
                if prefills:
                    self._transfer_leg(prefills, member, body, tokens,
                                       trace_id, parent_span, dl)
            span_id = new_span_id()
            headers = {
                "Content-Type": "application/json",
                "traceparent": format_traceparent(trace_id, span_id),
                "X-Served-By": member.name,
                DEADLINE_HEADER: dl.header_value(),
            }
            if self.flight.enabled:
                self.flight.record("dispatch", replica=member.name,
                                   attempt=attempt, how=how,
                                   trace_id=trace_id)
            t0 = time.perf_counter()
            try:
                status, payload = self._forward(member, raw, headers, dl)
                self._span("dispatch", trace_id, span_id, parent_span,
                           t0, leg="decode", replica=member.name,
                           attempt=attempt, how=how, status=status)
                self._note_session(session, member.name)
                return status, payload, member.name
            except _ReplicaDown as e:
                self._span("dispatch", trace_id, span_id, parent_span,
                           t0, leg="decode", replica=member.name,
                           attempt=attempt, how=how, error=str(e))
                self._mark_unhealthy(member, str(e))
                self._m_retries.inc()
                exclude.add(member.name)
                log_event(_log, "fleet_retry", replica=member.name,
                          error=str(e), trace_id=trace_id)
            finally:
                with self._route_lock:
                    member.in_flight -= 1

    # ------------------------------------------------------------- #
    # fleet control                                                  #
    # ------------------------------------------------------------- #

    def _member(self, name: str) -> _Member | None:
        for m in self.members:
            if m.name == name:
                return m
        return None

    def _handle_fleet_post(self, handler, path: str, body: dict) -> None:
        name = str(body.get("replica", ""))
        member = self._member(name)
        if member is None:
            send_json(handler, 404,
                      {"error": f"unknown replica {name!r}"})
            return
        if path == "/fleet/role":
            role = str(body.get("role", ""))
            if role not in ROLES:
                send_json(handler, 400,
                          {"error": f"role must be one of {ROLES}"})
                return
            self._apply_role(member, role, why="manual")
            send_json(handler, 200, {"replica": name, "role": role})
            return
        draining = path == "/fleet/drain"
        ok, info = self._relay_drain(member, draining)
        with self._route_lock:
            note_access(f"controller.{name}.draining", write=True)
            if ok:
                member.draining = draining
            now_draining = member.draining
        log_event(_log, "fleet_drain" if draining else "fleet_undrain",
                  replica=name, relayed=ok)
        out = {
            "replica": name, "draining": now_draining,
            "relayed": ok, "replica_response": info,
        }
        if draining and ok and body.get("migrate"):
            # drain-with-migration: once the replica stops admitting,
            # relay its /migrate so live sessions finish elsewhere
            # instead of riding out the drain (or dying with it)
            out["migration"] = self._migrate_replica(member)
        self._write_journal()
        send_json(handler, 200 if ok else 502, out)

    def _migrate_replica(self, member: _Member) -> dict:
        """Relay ``member``'s ``POST /migrate`` with every OTHER
        usable decode-capable replica (closed breakers only — a
        migrating session must not probe a suspect replica) as the
        target list. Failure is soft: sessions that do not seat stay
        on the replica's ordinary drain path."""
        with self._route_lock:
            targets = [
                m.name for m in self.members
                if m is not member and m.dispatchable()
                and m.decode_capable()
            ]
        if not targets:
            self._m_migrations.inc(result="no_target")
            return {"error": "no migration targets"}
        info: dict = {}
        err = None
        try:
            conn = http.client.HTTPConnection(
                member.host, member.port,
                timeout=self.request_timeout_s)
            try:
                conn.request(
                    "POST", "/migrate",
                    body=json.dumps({"targets": targets}).encode(),
                    headers={"Content-Type": "application/json"})
                resp = conn.getresponse()
                raw = resp.read()
                try:
                    info = json.loads(raw.decode("utf-8"))
                except (ValueError, UnicodeDecodeError):
                    info = {}
                if resp.status != 200 and "error" not in info:
                    err = f"http {resp.status}"
            finally:
                conn.close()
        except (OSError, http.client.HTTPException) as e:
            err = str(e)
        if err:
            info = dict(info)
            info["error"] = err
        result = ("ok" if not info.get("error")
                  and not info.get("failed") else "failed")
        self._m_migrations.inc(result=result)
        self.flight.record("migration", replica=member.name,
                           result=result,
                           migrated=info.get("migrated"),
                           failed=info.get("failed"))
        log_event(_log, "fleet_migration", replica=member.name,
                  result=result, migrated=info.get("migrated"),
                  failed=info.get("failed"), error=info.get("error"))
        return info

    def _relay_drain(self, member: _Member,
                     draining: bool) -> tuple[bool, dict]:
        """POST the replica's own /drain or /undrain; the controller
        stops dispatching the moment the relay succeeds (it does not
        wait for the next health poll)."""
        try:
            conn = http.client.HTTPConnection(
                member.host, member.port,
                timeout=max(1.0, self.health_interval_s * 4))
            try:
                conn.request(
                    "POST", "/drain" if draining else "/undrain",
                    body=b"{}",
                    headers={"Content-Type": "application/json"})
                resp = conn.getresponse()
                raw = resp.read()
                try:
                    info = json.loads(raw.decode("utf-8"))
                except (ValueError, UnicodeDecodeError):
                    info = {}
                return resp.status == 200, info
            finally:
                conn.close()
        except (OSError, http.client.HTTPException) as e:
            return False, {"error": str(e)}

    def _apply_role(self, member: _Member, role: str, why: str) -> None:
        with self._route_lock:
            note_access(f"controller.{member.name}.role", write=True)
            old, member.role = member.role, role
            member.role_since = time.monotonic()
        self._m_rebalance.inc(role=role)
        self._refresh_role_gauges()
        log_event(_log, "fleet_role_change", replica=member.name,
                  old=old, new=role, why=why)
        self._write_journal()

    def _refresh_role_gauges(self) -> None:
        counts = {r: 0 for r in ROLES}
        with self._route_lock:
            for m in self.members:
                counts[m.role] += 1
        for role, n in counts.items():
            self._m_role.set(float(n), role=role)

    def _maybe_rebalance(self) -> None:
        if not self.rebalance_enabled:
            return
        with self._route_lock:
            samples = {
                m.name: {"role": m.role, "queue_depth": m.queue_depth,
                         "slo_burn": m.slo_burn}
                for m in self.members if m.usable()
            }
        for name, role in self.balancer.observe(time.monotonic(),
                                                samples):
            member = self._member(name)
            if member is not None:
                self._apply_role(member, role, why="rebalance")
                self.flight.record("rebalance", replica=name, role=role)

    # ------------------------------------------------------------- #
    # health                                                         #
    # ------------------------------------------------------------- #

    def _breaker_hook(self, name: str):
        """Transition listener for one replica's breaker: gauge,
        counter, and flight event per state change. Fires inside the
        breaker's own lock, so it must stay cheap and must not take
        ``_route_lock``."""
        def hook(old: str, new: str) -> None:
            self._m_breaker.set(
                {CLOSED: 0.0, HALF_OPEN: 0.5}.get(new, 1.0),
                replica=name)
            self._m_breaker_transitions.inc(replica=name, state=new)
            self.flight.record("breaker", replica=name,
                               old=old, new=new)
            log_event(_log, "fleet_breaker", replica=name,
                      old=old, new=new)
        return hook

    def _mark_unhealthy(self, member: _Member, why: str) -> None:
        member.breaker.record_failure()
        with self._route_lock:
            note_access(f"controller.{member.name}.healthy", write=True)
            flipped = member.healthy
            if flipped:
                member.healthy = False
        if flipped:
            self._m_healthy.set(0.0, replica=member.name)
            log_event(_log, "fleet_replica_down", replica=member.name,
                      error=why)

    def _poll_one(self, member: _Member) -> None:
        hp = None
        burn = None
        try:
            conn = http.client.HTTPConnection(
                member.host, member.port,
                timeout=max(0.25, self.health_interval_s))
            try:
                conn.request("GET", "/healthz")
                resp = conn.getresponse()
                raw = resp.read()
                try:
                    hp = json.loads(raw)
                except ValueError:
                    hp = None
                ok = resp.status == 200
                if ok:
                    # worst per-tenant SLO burn: the PR-9 gauges ride
                    # /metrics.json as tenants.<tid>.slo_burn
                    conn.request("GET", "/metrics.json")
                    mresp = conn.getresponse()
                    mraw = mresp.read()
                    if mresp.status == 200:
                        try:
                            mj = json.loads(mraw)
                            burn = max(
                                (float(t.get("slo_burn") or 0.0)
                                 for t in mj.get("tenants", {}).values()),
                                default=0.0,
                            )
                        except (ValueError, TypeError):
                            burn = None
            finally:
                conn.close()
        except (OSError, http.client.HTTPException):
            ok = False
        member.last_health = hp if isinstance(hp, dict) else None
        if ok and member.last_health is not None:
            hp = member.last_health
            cfg = hp.get("config_hash")
            if cfg:
                with self._route_lock:
                    note_access(
                        f"controller.{member.name}.config_hash",
                        write=True)
                    if member.config_hash is None:
                        member.config_hash = str(cfg)
                        newly_bad = False
                    else:
                        newly_bad = (member.config_hash != str(cfg)
                                     and not member.incompatible)
                        if newly_bad:
                            member.incompatible = True
                if newly_bad:
                    log_event(_log, "fleet_replica_incompatible",
                              replica=member.name,
                              expected=member.config_hash[:12],
                              got=str(cfg)[:12], level=logging.ERROR)
            with self._route_lock:
                note_access(f"controller.{member.name}.draining",
                            write=True)
                member.draining = bool(hp.get("draining"))
                member.queue_depth = int(hp.get("queue_depth") or 0)
                if burn is not None:
                    member.slo_burn = burn
        if ok:
            with self._route_lock:
                note_access(f"controller.{member.name}.healthy",
                            write=True)
                flipped = not member.healthy
                if flipped:
                    member.healthy = True
            if flipped:
                self._m_healthy.set(1.0, replica=member.name)
                log_event(_log, "fleet_replica_up", replica=member.name)
        else:
            self._mark_unhealthy(member, "healthz poll failed")

    def poll_health(self) -> None:
        """One synchronous poll + rebalance pass (tests use this to
        avoid sleeping for the background interval)."""
        for m in self.members:
            self._poll_one(m)
        self._maybe_rebalance()
        if self._active:
            self._write_journal()

    def _health_loop(self) -> None:
        while not self._stop.is_set():
            if self._active:
                self.poll_health()
            else:
                self._watch_primary()
            self._stop.wait(self.health_interval_s)

    # ------------------------------------------------------------- #
    # journal + standby failover                                     #
    # ------------------------------------------------------------- #

    def _write_journal(self) -> None:
        """Checkpoint controller state (roles, sticky sessions in LRU
        order, breaker snapshots, config hashes, drain flags) with an
        atomic tmp+rename so the standby never reads a torn file."""
        if self.journal_path is None:
            return
        with self._route_lock:
            note_access("controller._sessions", write=True)
            self._journal_seq += 1
            state = {
                "seq": self._journal_seq,
                "ts": time.time(),
                "controller": self.name,
                "roles": {m.name: m.role for m in self.members},
                "draining": [m.name for m in self.members if m.draining],
                "config_hashes": {
                    m.name: m.config_hash for m in self.members
                    if m.config_hash
                },
                "breakers": {
                    m.name: m.breaker.snapshot() for m in self.members
                },
                "sessions": list(self._sessions.items()),
            }
        tmp = self.journal_path.with_name(self.journal_path.name + ".tmp")
        try:
            tmp.write_text(json.dumps(state, sort_keys=True))
            os.replace(tmp, self.journal_path)
        except OSError as e:
            log_event(_log, "fleet_journal_write_failed", error=repr(e),
                      level=logging.ERROR)

    def restore_journal(self) -> bool:
        """Load the journal written by the (former) primary: roles,
        session stickiness, breaker state, expected config hashes.
        Returns False (and starts from the constructor state) when the
        journal is absent or unreadable — failover still works, it
        just loses stickiness and breaker history."""
        if self.journal_path is None or not self.journal_path.exists():
            return False
        try:
            state = json.loads(self.journal_path.read_text())
        except (OSError, ValueError) as e:
            log_event(_log, "fleet_journal_unreadable", error=repr(e),
                      level=logging.ERROR)
            return False
        with self._route_lock:
            note_access("controller._sessions", write=True)
            for name, role in (state.get("roles") or {}).items():
                m = self._member(str(name))
                if m is not None and role in ROLES:
                    m.role = str(role)
            for name in state.get("draining") or ():
                m = self._member(str(name))
                if m is not None:
                    m.draining = True
            for name, cfg in (state.get("config_hashes") or {}).items():
                m = self._member(str(name))
                if m is not None and m.config_hash is None:
                    m.config_hash = str(cfg)
            for name, snap in (state.get("breakers") or {}).items():
                m = self._member(str(name))
                if m is not None and isinstance(snap, dict):
                    m.breaker.restore(snap)
            self._sessions.clear()
            for pair in state.get("sessions") or ():
                if isinstance(pair, (list, tuple)) and len(pair) == 2:
                    self._sessions[str(pair[0])] = str(pair[1])
            self._journal_seq = int(state.get("seq") or 0)
        self._refresh_role_gauges()
        log_event(_log, "fleet_journal_restored",
                  seq=self._journal_seq,
                  sessions=len(self._sessions))
        return True

    def _watch_primary(self) -> None:
        """Standby mode: probe the primary's ``/healthz``;
        ``failover_after`` consecutive misses promote this standby."""
        host, _, port = str(self.standby_of).rpartition(":")
        ok = False
        try:
            conn = http.client.HTTPConnection(
                host or "127.0.0.1", int(port),
                timeout=max(0.25, self.health_interval_s))
            try:
                conn.request("GET", "/healthz")
                ok = conn.getresponse().status < 500
            finally:
                conn.close()
        except (OSError, http.client.HTTPException, ValueError):
            ok = False
        if ok:
            self._primary_misses = 0
            return
        self._primary_misses += 1
        if self._primary_misses >= self.failover_after:
            self.promote()

    def promote(self) -> None:
        """Standby -> primary: restore the journal, then RE-VERIFY
        against live fleet state with a full health sweep — the live
        ``/healthz``/``/metrics.json`` answers override anything stale
        in the journal (drain flags, queue depths, a replica that died
        since the last checkpoint)."""
        if self._active:
            return
        restored = self.restore_journal()
        self._active = True
        self._primary_misses = 0
        self._m_standby.set(0.0)
        self._m_failovers.inc()
        self.flight.record("failover", controller=self.name,
                           primary=self.standby_of,
                           journal_restored=restored,
                           journal_seq=self._journal_seq)
        log_event(_log, "fleet_failover", controller=self.name,
                  primary=self.standby_of, journal_restored=restored,
                  journal_seq=self._journal_seq, level=logging.WARNING)
        self.poll_health()

    def health_payload(self) -> dict:
        with self._route_lock:
            usable = [m.name for m in self.members if m.usable()]
            decode = [m.name for m in self.members
                      if m.usable() and m.decode_capable()]
            return {
                "ok": self._active and bool(decode),
                "active": self._active,
                "standby_of": self.standby_of,
                "usable": usable,
                "roles": {m.name: m.role for m in self.members},
                "disagg_threshold": self.disagg_threshold,
            }

    def fleet_state(self) -> dict:
        with self._route_lock:
            return {
                "replicas": {m.name: m.state() for m in self.members},
                "sessions": len(self._sessions),
                "disagg_threshold": self.disagg_threshold,
                "active": self._active,
                "journal_seq": self._journal_seq,
            }

    # ------------------------------------------------------------- #
    # lifecycle + flight recorder                                    #
    # ------------------------------------------------------------- #

    @property
    def address(self) -> tuple[str, int]:
        return self._httpd.server_address[:2]

    @property
    def name(self) -> str:
        return "%s:%d" % self.address

    def flight_bundle(self, reason: str) -> dict:
        return self.flight.dump(
            reason, tracer=self.tracer,
            extra={"controller": self.name,
                   "fleet": self.fleet_state()})

    def _dump_flight(self, reason: str) -> None:
        if not self.flight_dir:
            return
        try:
            path = Path(self.flight_dir) / (
                "flight-controller-%s-%s-%d.json" % (
                    self.name.replace(":", "-"), reason,
                    int(time.time() * 1000)))
            self.flight.dump_to(
                path, reason, tracer=self.tracer,
                extra={"controller": self.name,
                       "fleet": self.fleet_state()})
            log_event(_log, "flight_dump", reason=reason,
                      path=str(path))
        except Exception as e:
            log_event(_log, "flight_dump_failed", reason=reason,
                      error=repr(e), level=logging.ERROR)

    def start(self) -> "FleetController":
        self._http_thread.start()
        self._health_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._health_thread.ident:
            self._health_thread.join(timeout=5)

    def serve_forever(self) -> None:
        """Blocking convenience for the CLI; Ctrl-C stops, SIGTERM
        dumps a flight bundle first, then stops."""
        self.start()
        done = threading.Event()

        def _on_sigterm(signum, frame):
            self._dump_flight("sigterm")
            done.set()

        try:
            signal.signal(signal.SIGTERM, _on_sigterm)
        except ValueError:
            pass  # not the main thread (embedded use)
        try:
            while not done.is_set():
                time.sleep(1)
        except KeyboardInterrupt:
            pass
        finally:
            self.stop()
