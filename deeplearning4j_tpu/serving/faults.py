"""Deterministic fault injection for the serving engine.

The original DL4J runtime assumed workers die (Akka supervision trees,
ZooKeeper-backed state); the serving engine is this repo's equivalent
heavy-traffic surface, so it gets the equivalent treatment: a
``FaultInjector`` the engine consults at its host-side boundaries
("step" before each fused decode DISPATCH — with a multi-step horizon
that is one K-substep program, so check indices count horizons, not
tokens — and "prefill" once per admission, however many bucket/chunk
programs the prompt takes), raising one of three fault classes the
supervisor reacts to:

- :class:`TransientFault` — recoverable blip (think preempted RPC,
  donated-buffer retry). The engine retries the boundary with capped
  exponential backoff; if the fault persists past ``max_retries`` it is
  escalated (quarantine the implicated request if the fault names one,
  otherwise :class:`EngineCrash`).
- :class:`PermanentFault` — poisoned input; carries the implicated
  ``req_id``. The engine fails exactly that request (slot freed, status
  ``FAILED``, ``done`` set) and keeps serving everything else.
- :class:`EngineCrash` — the whole step loop is considered dead. The
  supervisor rebuilds engine state by deterministic replay
  (:meth:`ServingEngine.recover`).

Two injection modes, both deterministic:

- **scripted** — ``plan(site, at=k)`` fires at the k-th check of that
  site (0-based, ``times`` consecutive checks). Chaos tests use this to
  pin exact fault positions.
- **seeded rates** — per-check Bernoulli draws from one
  ``np.random.default_rng(seed)``; the engine's check sequence is
  deterministic, so a given seed replays the same fault pattern. The
  bench's faults row uses this to price recovery overhead.

``delay_s`` additionally injects latency (a plain sleep) at every
check — chaos for the clock rather than the control flow, used to make
timeout paths deterministic in tests.

Injection happens strictly on host, before the jitted call launches, so
device state is never half-written by an injected fault — recovery
paths still treat it as corrupt (see ``recover``), which is the
stronger assumption real faults need.
"""

from __future__ import annotations

import dataclasses
import time

import numpy as np

TRANSIENT = "transient"
PERMANENT = "permanent"
CRASH = "crash"
_KINDS = (TRANSIENT, PERMANENT, CRASH)


class TransientFault(RuntimeError):
    """Recoverable boundary fault — retry with backoff."""

    def __init__(self, msg: str, req_id: str | None = None):
        super().__init__(msg)
        self.req_id = req_id


class PermanentFault(RuntimeError):
    """Poisoned request — fail it, keep serving the rest."""

    def __init__(self, msg: str, req_id: str | None = None):
        super().__init__(msg)
        self.req_id = req_id


class EngineCrash(RuntimeError):
    """Engine loop considered dead; supervisor must rebuild by replay."""


@dataclasses.dataclass
class _Planned:
    site: str
    at: int
    kind: str
    req_id: str | None
    times: int


class FaultInjector:
    """Seeded/scripted fault source consulted at engine boundaries.

    ``check(site, req_id=...)`` either returns (no fault) or raises one
    of the fault classes above. Scripted plans are evaluated first, then
    the seeded per-check rates; ``max_faults`` caps the total number of
    rate-drawn faults (scripted ones always fire).
    """

    def __init__(
        self,
        seed: int = 0,
        *,
        transient_rate: float = 0.0,
        permanent_rate: float = 0.0,
        crash_rate: float = 0.0,
        sites: tuple[str, ...] = ("step", "prefill"),
        max_faults: int | None = None,
        delay_s: float = 0.0,
    ):
        if transient_rate + permanent_rate + crash_rate > 1.0:
            raise ValueError("fault rates sum past 1.0")
        self.transient_rate = transient_rate
        self.permanent_rate = permanent_rate
        self.crash_rate = crash_rate
        self.sites = tuple(sites)
        self.max_faults = max_faults
        self.delay_s = delay_s
        self._rng = np.random.default_rng(seed)
        self._plans: list[_Planned] = []
        self._calls: dict[str, int] = {}
        self.n_raised = 0

    def plan(self, site: str, at: int, kind: str = TRANSIENT, *,
             req_id: str | None = None, times: int = 1) -> "FaultInjector":
        """Script a fault at the ``at``-th check of ``site`` (0-based),
        firing for ``times`` consecutive checks. Returns self (chain)."""
        if kind not in _KINDS:
            raise ValueError(f"kind must be one of {_KINDS}, got {kind!r}")
        self._plans.append(_Planned(site, at, kind, req_id, times))
        return self

    def _raise(self, kind: str, site: str, n: int,
               req_id: str | None) -> None:
        self.n_raised += 1
        msg = f"injected {kind} fault at {site}#{n}"
        if kind == TRANSIENT:
            raise TransientFault(msg, req_id=req_id)
        if kind == PERMANENT:
            raise PermanentFault(msg, req_id=req_id)
        raise EngineCrash(msg)

    def check(self, site: str, req_id: str | None = None) -> None:
        """Called by the engine at a boundary; raises to inject."""
        n = self._calls.get(site, 0)
        self._calls[site] = n + 1
        if self.delay_s > 0:
            time.sleep(self.delay_s)
        for p in self._plans:
            if p.site == site and p.at <= n < p.at + p.times:
                self._raise(p.kind, site, n, p.req_id or req_id)
        if site not in self.sites:
            return
        if self.max_faults is not None and self.n_raised >= self.max_faults:
            return
        r = float(self._rng.random())
        if r < self.transient_rate:
            self._raise(TRANSIENT, site, n, req_id)
        elif r < self.transient_rate + self.permanent_rate:
            self._raise(PERMANENT, site, n, req_id)
        elif r < self.transient_rate + self.permanent_rate + self.crash_rate:
            self._raise(CRASH, site, n, req_id)
