"""Request queue for the serving engine.

Strict priority across classes (class 0 drains before class 1, etc.),
and — when a :class:`~deeplearning4j_tpu.serving.tenancy.TenantRegistry`
is attached — DEFICIT ROUND-ROBIN across tenants *within* each class,
weighted by tenant weight, so one flooding tenant cannot starve its
classmates: each tenant banks ``quantum * weight`` tokens of service
credit per scheduling visit and its head request is served once the
credit covers the request's token cost (prompt + max_new). Without a
registry every request lands in one implicit tenant and the scheduler
degenerates to the exact FIFO-within-class behavior it always had.

Admission control happens at ``submit`` time, not dequeue time, so a
caller holding a rejected request knows immediately:

- ``Backpressure`` when the queue is at ``max_queue_depth`` — the HTTP
  front end maps this to 429 so load sheds at the edge instead of
  growing an unbounded in-process queue;
- ``QuotaExceeded`` (a ``Backpressure``) when the tenant's token-rate
  bucket is dry — same 429, tagged per tenant in the metrics;
- ``AdmissionError`` when the request's token budget
  (``len(prompt) + max_new``) cannot fit the engine's cache slots at
  all — queueing it would deadlock the admission loop, since no slot
  will ever be big enough.

Thread-safe: the HTTP handler threads ``submit`` while the engine
thread ``pop``s.
"""

from __future__ import annotations

import dataclasses
import enum
import itertools
import math
import queue as queue_mod
import threading
import time
from collections import deque

import numpy as np

from deeplearning4j_tpu.analysis.sanitizers import note_access, wrap_lock
from deeplearning4j_tpu.serving.grammar import (
    MAX_LOGIT_BIAS,
    MAX_STOP_LEN,
    MAX_STOP_SEQUENCES,
    MAX_TOP_LOGPROBS,
    GrammarError,
    parse_response_format,
)


class RequestStatus(str, enum.Enum):
    """Request lifecycle. Terminal states set ``done`` and free the KV
    slot (if one was held); only FINISHED puts a full stream in
    ``engine.results`` (CANCELLED/EXPIRED store the partial stream)."""

    QUEUED = "queued"        # accepted by the scheduler, waiting for a slot
    RUNNING = "running"      # admitted; prefilled into a KV slot, decoding
    FINISHED = "finished"    # hit EOS or max_new; full stream available
    FAILED = "failed"        # poisoned (permanent/persistent fault)
    CANCELLED = "cancelled"  # caller invoked Request.cancel()
    EXPIRED = "expired"      # deadline_s elapsed before completion


class Backpressure(RuntimeError):
    """Queue at max depth — shed load upstream (HTTP 429)."""


class AdmissionError(ValueError):
    """Request can never be served (token budget exceeds slot size)."""


_ids = itertools.count()


def _next_id() -> str:
    return f"req-{next(_ids)}"


@dataclasses.dataclass
class Request:
    """One generation request.

    ``prompt`` is a 1-D int token array; ``max_new`` bounds generation;
    ``eos_token`` (optional) retires the slot early. ``priority`` 0 is
    most urgent. ``arrival_time`` is stamped by the scheduler at submit
    (perf_counter domain) and anchors TTFT. ``deadline_s`` (optional)
    is a wall-clock budget from arrival: the engine checks it at
    admission and at every step boundary and retires the request as
    EXPIRED (slot freed) the moment it elapses. ``cancel()`` may be
    called from any thread; the engine honors it within one step.

    Multi-tenant fields: ``tenant_id`` keys the scheduler's
    weighted-fair tier and the per-tenant metrics ("" = untenanted);
    ``adapter`` selects the LoRA bank row the slot decodes with (0 =
    base model). ``stream`` (optional ``queue.Queue``) receives each
    generated token as it arrives host-side, then ``None`` as the
    end-of-stream sentinel — the SSE front end drains it.

    Sampling-surface fields (engines built with
    ``sampling_surface=True``; see serving.grammar): ``temperature`` /
    ``top_k`` / ``top_p`` override the engine-wide sampler per request
    (None = engine default); ``stop`` is a list of token-id sequences
    matched host-side at readback (the match is stripped from the
    stream); ``logit_bias`` maps token id -> additive logit value;
    ``logprobs`` requests per-token logprobs and ``top_logprobs`` the
    per-position top-k alternatives; ``response_format`` constrains
    output to a regex or JSON schema (token-level DFA mask).
    """

    prompt: np.ndarray
    max_new: int
    priority: int = 1
    eos_token: int | None = None
    deadline_s: float | None = None
    tenant_id: str = ""
    adapter: int = 0
    stream: queue_mod.Queue | None = None
    temperature: float | None = None
    top_k: int | None = None
    top_p: float | None = None
    stop: list | None = None
    logit_bias: dict | None = None
    logprobs: bool = False
    top_logprobs: int = 0
    response_format: dict | str | None = None
    # resolved by the engine at submit/retire: the compiled grammar
    # (serving.grammar.CompiledGrammar) and per-token logprob records
    _grammar: object = dataclasses.field(
        default=None, repr=False, compare=False,
    )
    logprobs_out: list | None = dataclasses.field(
        default=None, repr=False, compare=False,
    )
    # distributed-tracing context (W3C traceparent, see obs.trace):
    # resolved/generated by the HTTP front end, carried so the engine's
    # admission span and the JSON logs join the fleet-wide trace.
    # parent_span_id is the upstream caller's span (the router's
    # dispatch span when the request came through the fleet router).
    trace_id: str = ""
    parent_span_id: str = ""
    id: str = dataclasses.field(default_factory=_next_id)
    arrival_time: float | None = None
    status: RequestStatus = RequestStatus.QUEUED
    error: str | None = None
    # set by the HTTP front end: signaled when the engine retires the
    # request, so a blocked handler thread can return the result
    done: threading.Event | None = None
    _cancel_evt: threading.Event = dataclasses.field(
        default_factory=threading.Event, init=False, repr=False,
        compare=False,
    )

    kind = "generate"

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt, np.int32).reshape(-1)
        if self.max_new < 1:
            raise AdmissionError(f"max_new must be >= 1, got {self.max_new}")
        if self.deadline_s is not None and self.deadline_s < 0:
            raise AdmissionError(
                f"deadline_s must be >= 0, got {self.deadline_s}"
            )
        if self.adapter < 0:
            raise AdmissionError(
                f"adapter must be >= 0, got {self.adapter}"
            )
        if self.temperature is not None and self.temperature < 0:
            raise AdmissionError(
                f"temperature must be >= 0, got {self.temperature}"
            )
        if self.top_k is not None and self.top_k < 1:
            raise AdmissionError(
                f"top_k must be >= 1, got {self.top_k}"
            )
        if self.top_p is not None and not (0.0 < self.top_p <= 1.0):
            raise AdmissionError(
                f"top_p must be in (0, 1], got {self.top_p}"
            )
        if self.stop is not None:
            self.stop = [
                [int(t) for t in np.asarray(s).reshape(-1)]
                for s in self.stop
            ]
            if len(self.stop) > MAX_STOP_SEQUENCES:
                raise AdmissionError(
                    f"at most {MAX_STOP_SEQUENCES} stop sequences, "
                    f"got {len(self.stop)}"
                )
            for s in self.stop:
                if not 1 <= len(s) <= MAX_STOP_LEN:
                    raise AdmissionError(
                        f"stop sequences must be 1..{MAX_STOP_LEN} "
                        f"tokens, got {len(s)}"
                    )
        if self.logit_bias is not None:
            try:
                self.logit_bias = {
                    int(k): float(v) for k, v in self.logit_bias.items()
                }
            except (TypeError, ValueError, AttributeError):
                raise AdmissionError(
                    "logit_bias must map token ids to numbers"
                ) from None
            if len(self.logit_bias) > MAX_LOGIT_BIAS:
                raise AdmissionError(
                    f"at most {MAX_LOGIT_BIAS} logit_bias entries, "
                    f"got {len(self.logit_bias)}"
                )
            if any(k < 0 for k in self.logit_bias):
                raise AdmissionError("logit_bias token ids must be >= 0")
        if not 0 <= int(self.top_logprobs) <= MAX_TOP_LOGPROBS:
            raise AdmissionError(
                f"top_logprobs must be 0..{MAX_TOP_LOGPROBS}, got "
                f"{self.top_logprobs}"
            )
        self.top_logprobs = int(self.top_logprobs)
        if self.top_logprobs:
            self.logprobs = True
        if self.response_format is not None:
            try:
                parse_response_format(self.response_format)
            except GrammarError as e:
                raise AdmissionError(
                    f"bad response_format: {e}"
                ) from None

    @property
    def uses_sampling_surface(self) -> bool:
        """Any per-request sampling-surface field set? Such requests
        must decode through the engine's masked step family (engines
        without ``sampling_surface=True`` reject them at submit)."""
        return (
            self.temperature is not None
            or self.top_k is not None
            or self.top_p is not None
            or bool(self.stop)
            or bool(self.logit_bias)
            or self.logprobs
            or self.response_format is not None
        )

    def token_cost(self) -> int:
        """Service cost in tokens — the unit the DRR tier and the
        tenant token buckets meter (the same prompt+max_new budget the
        per-slot admission check uses)."""
        return len(self.prompt) + self.max_new

    def cancel(self) -> None:
        """Request best-effort cancellation (thread-safe, idempotent).
        The engine frees the KV slot within one step boundary."""
        self._cancel_evt.set()

    @property
    def cancelled(self) -> bool:
        return self._cancel_evt.is_set()

    def expired(self, now: float | None = None) -> bool:
        """Deadline elapsed? (``now`` in perf_counter domain; measured
        from scheduler arrival so queue wait counts, like TTFT.)"""
        if self.deadline_s is None or self.arrival_time is None:
            return False
        if now is None:
            now = time.perf_counter()
        return (now - self.arrival_time) > self.deadline_s


def _empty_prompt() -> np.ndarray:
    return np.zeros(0, np.int32)


@dataclasses.dataclass
class EmbeddingRequest(Request):
    """An embeddings lookup riding the SAME queue as generation.

    Served host-side by the engine's admission loop from a zoo
    embedding model (word2vec/glove) — no KV slot, no device dispatch —
    but it flows through the scheduler (priority, DRR, quota,
    backpressure), the per-tenant metrics, and drain exactly like a
    generation request, which is the point: the serving stack is
    model-agnostic, not transformer-shaped. ``result`` is filled with
    ``{word: vector-or-None}`` before ``done`` is set."""

    prompt: np.ndarray = dataclasses.field(default_factory=_empty_prompt)
    max_new: int = 1
    model: str = "word2vec"
    words: tuple[str, ...] = ()
    result: dict | None = None

    kind = "embedding"

    def __post_init__(self):
        super().__post_init__()
        self.words = tuple(str(w) for w in self.words)
        if not self.words:
            raise AdmissionError("embedding request needs >= 1 word")

    def token_cost(self) -> int:
        return len(self.words)


@dataclasses.dataclass
class KVExportRequest(Request):
    """Disaggregated-prefill work item: prefill ``prompt`` into a
    transiently held slot, copy the resulting KV segment + pending
    logits row to host, and release the slot — no decode. Flows
    through the same queue as generation (priority, DRR, quota,
    backpressure) and NEEDS a free KV slot like generation does, so
    prefill replicas admission-schedule exports exactly as they would
    the generate requests they stand in for. ``result`` is filled with
    the wire-ready segment arrays (see ``serving.disagg``) before
    ``done`` is set."""

    max_new: int = 1  # slot budget held during export, never decoded
    result: dict | None = None

    kind = "kv_export"

    def __post_init__(self):
        super().__post_init__()
        if len(self.prompt) == 0:
            raise AdmissionError("kv_export needs a non-empty prompt")


@dataclasses.dataclass
class KVIngestRequest(Request):
    """Wire-delivered KV segment awaiting a seat in the prefix cache.

    Slotless: served at the admission boundary by the engine thread
    (the only thread allowed to touch the cache region), like
    embeddings. ``segment`` is the decoded wire dict
    (``serving.disagg.decode_segment``); ``result`` reports
    ``{"stored": bool, ...}`` before ``done`` is set — a decline is a
    soft failure (the sender falls back to local prefill, which is
    byte-identical anyway), never an engine error."""

    prompt: np.ndarray = dataclasses.field(default_factory=_empty_prompt)
    max_new: int = 1
    segment: dict | None = None
    result: dict | None = None

    kind = "kv_ingest"

    def __post_init__(self):
        super().__post_init__()
        if self.segment is None:
            raise AdmissionError("kv_ingest needs a decoded segment")

    def token_cost(self) -> int:
        # host+cache work only; the seated segment's cost is bounded
        # by the prefix cache's own capacity budget
        return len(self.segment.get("tokens", ()))


@dataclasses.dataclass
class KVSessionRequest(Request):
    """A LIVE generation session migrating in from a draining replica.

    Carries everything needed to resume mid-generation: the decoded
    KV segment covering prompt + tokens-so-far (``segment``), the
    tokens already emitted (``gen_tokens`` — replayed into slot state,
    never re-generated), and the source slot's sampling-key words
    (``key_data`` — seated verbatim so fold-in(key, position) draws
    the SAME per-token randomness the source would have). ``prompt``
    is the original prompt only; ``max_new`` the ORIGINAL budget (the
    engine derives the remaining budget from
    ``max_new - len(gen_tokens)``). Needs a real KV slot like
    generation; a decline is soft (``result["seated"] is False`` →
    the sender keeps its existing fail path for that session)."""

    segment: dict | None = None
    gen_tokens: tuple[int, ...] = ()
    key_data: np.ndarray | None = None
    result: dict | None = None

    kind = "kv_session"

    def __post_init__(self):
        super().__post_init__()
        if self.segment is None:
            raise AdmissionError("kv_session needs a decoded segment")
        self.gen_tokens = tuple(int(t) for t in self.gen_tokens)
        if len(self.prompt) == 0:
            raise AdmissionError("kv_session needs a non-empty prompt")
        if self.max_new - len(self.gen_tokens) < 1:
            raise AdmissionError("kv_session has no remaining budget")


class RequestScheduler:
    """Bounded multi-priority queue: strict priority across classes,
    weighted deficit-round-robin across tenants within a class, FIFO
    within a tenant. With no ``tenancy`` registry attached the whole
    thing degenerates to strict-priority FIFO (one implicit tenant)."""

    def __init__(
        self,
        max_queue_depth: int = 128,
        max_total_tokens: int | None = None,
        n_priorities: int = 3,
        prefix_affinity_tokens: int = 0,
        tenancy=None,
        drr_quantum: int = 64,
    ):
        self.max_queue_depth = max_queue_depth
        self.max_total_tokens = max_total_tokens
        # > 0 enables prefix-affinity ordering: ``pop`` may promote a
        # queued request whose first ``prefix_affinity_tokens`` prompt
        # tokens match the caller's hint (the previously admitted
        # prompt), so same-prefix requests land in the same admission
        # batch and the prefix cache gets back-to-back hits. Promotion
        # stays within one priority class — strict priority still wins
        # — and the promoted request's token cost is charged to its
        # tenant's deficit, so affinity cannot become a fairness leak.
        self.prefix_affinity_tokens = prefix_affinity_tokens
        self.n_priorities = n_priorities
        self.tenancy = tenancy
        if drr_quantum < 1:
            raise ValueError(f"drr_quantum must be >= 1, got {drr_quantum}")
        self.drr_quantum = drr_quantum
        self._lock = wrap_lock(threading.Lock(), "scheduler._lock")
        # submit() runs on HTTP handler threads while pop()/requeue()
        # run on the engine thread, so the queues only move under the
        # lock. Per class: tenant_id -> deque (FIFO within tenant),
        # plus the DRR rotation state (tenant order, rotation index,
        # banked deficits). Deficits reset when a tenant's queue
        # empties — idle tenants cannot bank credit (standard DRR).
        # ``fresh`` marks whether the tenant at ``idx`` is owed its
        # per-visit quantum: a serving tenant keeps idx with fresh
        # False and spends banked deficit across pops (DRR's serve-
        # while-deficit-lasts); new credit only flows when the
        # rotation actually visits.
        self._queues = [
            {} for _ in range(n_priorities)
        ]  # guarded-by: _lock
        # ``carry`` banks deficit adjustments that arrive while the
        # tenant is out of the rotation (piggyback prefill chunks
        # execute horizons after the pop charged the prompt — the
        # tenant's queue may have drained in between); applied when
        # the tenant next enqueues, so credit is never silently
        # dropped, but idle tenants still cannot bank fresh quanta.
        self._drr = [
            {"order": [], "idx": 0, "deficit": {}, "fresh": True,
             "carry": {}}
            for _ in range(n_priorities)
        ]  # guarded-by: _lock

    def _weight(self, tenant_id: str) -> float:
        if self.tenancy is not None:
            t = self.tenancy.get(tenant_id)
            if t is not None:
                return t.weight
        return 1.0

    def _depth_unlocked(self) -> int:  # lint: holds _lock
        return sum(
            len(q) for per_class in self._queues
            for q in per_class.values()
        )

    def __len__(self) -> int:
        with self._lock:
            return self._depth_unlocked()

    @property
    def depth(self) -> int:
        return len(self)

    def has_kind(self, kind: str) -> bool:
        """Any queued request of ``kind``? The engine's admission entry
        check: embedding requests stay admissible with zero free KV
        slots, so a full pool must not skip the admission loop while
        slotless work waits. O(depth), called only on the full-pool
        path."""
        with self._lock:
            return any(
                req.kind == kind
                for per_class in self._queues
                for q in per_class.values()
                for req in q
            )

    def _enqueue_unlocked(self, req: Request, front: bool) -> None:  # lint: holds _lock
        per_class = self._queues[req.priority]
        drr = self._drr[req.priority]
        tid = req.tenant_id
        q = per_class.get(tid)
        if q is None:
            q = per_class[tid] = deque()
            if tid not in drr["deficit"]:
                # re-entering the rotation: apply any deficit
                # adjustments banked while the tenant was absent
                # (piggyback chunk charges/refunds)
                drr["deficit"][tid] = drr["carry"].pop(tid, 0.0)
            if not drr["order"]:
                drr["fresh"] = True  # class was idle: restart rotation
            if front:
                # requeue of the only in-flight request of its tenant:
                # re-enter the rotation at the CURRENT position so the
                # recovered request is next, as the old FIFO guaranteed
                drr["order"].insert(drr["idx"], tid)
            else:
                drr["order"].append(tid)
        if front:
            q.appendleft(req)
        else:
            q.append(req)

    def _remove_tenant_if_empty(self, ci: int, tid: str) -> None:  # lint: holds _lock
        per_class = self._queues[ci]
        if per_class.get(tid):
            return
        per_class.pop(tid, None)
        drr = self._drr[ci]
        if tid in drr["order"]:
            pos = drr["order"].index(tid)
            was_current = pos == drr["idx"]
            drr["order"].remove(tid)
            if pos < drr["idx"]:
                drr["idx"] -= 1
            if drr["idx"] >= len(drr["order"]):
                drr["idx"] = 0  # wrap: rotation restarts at the front
            if was_current:
                # whoever now sits at idx is a NEW current tenant and
                # is owed its visit quantum
                drr["fresh"] = True
        drr["deficit"].pop(tid, None)

    def submit(self, req: Request) -> str:
        """Enqueue ``req``; returns its id. Raises ``Backpressure`` /
        ``QuotaExceeded`` / ``AdmissionError`` (see module docstring)."""
        total = len(req.prompt) + req.max_new
        if (req.kind == "generate" and self.max_total_tokens is not None
                and total > self.max_total_tokens):
            raise AdmissionError(
                f"request {req.id}: prompt+max_new ({total}) exceeds the "
                f"per-slot token budget ({self.max_total_tokens})"
            )
        if not 0 <= req.priority < self.n_priorities:
            raise AdmissionError(
                f"priority {req.priority} outside [0, {self.n_priorities})"
            )
        with self._lock:
            note_access("scheduler.queues", write=True)
            if self._depth_unlocked() >= self.max_queue_depth:
                raise Backpressure(
                    f"queue at max depth ({self.max_queue_depth})"
                )
            if self.tenancy is not None:
                # charge AFTER the depth check (a shed request must not
                # burn quota) and INSIDE the lock (depth + charge are
                # one admission decision). Lock order is always
                # scheduler._lock -> tenancy._lock.
                self.tenancy.charge(req.tenant_id, req.token_cost())
            req.arrival_time = time.perf_counter()
            req.status = RequestStatus.QUEUED
            self._enqueue_unlocked(req, front=False)
        return req.id

    def requeue(self, req: Request) -> None:
        """Put a popped-but-not-admitted request back at the FRONT of
        its tenant's queue (crash recovery: a request must never be
        dropped between pop and admission). Bypasses depth/budget/quota
        checks — the request was already admitted once — and refunds
        the token cost its pop charged to the tenant's deficit."""
        with self._lock:
            note_access("scheduler.queues", write=True)
            req.status = RequestStatus.QUEUED
            self._enqueue_unlocked(req, front=True)
            drr = self._drr[req.priority]
            drr["deficit"][req.tenant_id] = (
                drr["deficit"].get(req.tenant_id, 0.0) + req.token_cost()
            )

    def adjust_deficit(self, req: Request, delta: float) -> None:
        """Bank ``delta`` service credit for ``req``'s tenant in its
        priority class (positive = refund, negative = charge). The
        piggyback prefill path calls this to move a deferred prompt
        suffix's DRR charge from pop time to execution time: the
        suffix is credited back when the admission defers, then
        re-charged chunk by chunk as each prefill chunk actually
        dispatches — so a long prompt spread across horizons meters
        fairness exactly like a blocking admission, instead of leaking
        the spread-out work (the pre-piggyback bug was charging only
        at pop). When the tenant has left the rotation the adjustment
        lands in the class's carry bank and applies on re-enqueue."""
        with self._lock:
            note_access("scheduler.queues", write=True)
            drr = self._drr[req.priority]
            tid = req.tenant_id
            if tid in drr["deficit"]:
                drr["deficit"][tid] += delta
            else:
                drr["carry"][tid] = drr["carry"].get(tid, 0.0) + delta

    def cancel(self, req_id: str) -> bool:
        """Flag a still-queued request as cancelled (it is discarded at
        its admission turn). Returns False when the id is not queued."""
        with self._lock:
            for per_class in self._queues:
                for q in per_class.values():
                    for req in q:
                        if req.id == req_id:
                            req.cancel()
                            return True
        return False

    def cancel_all(self) -> int:
        """Flag every still-queued request as cancelled (drain-deadline
        preemption: each is discarded at its admission turn, so a
        stopping engine converges instead of decoding stragglers).
        Returns the number newly flagged."""
        n = 0
        with self._lock:
            for per_class in self._queues:
                for q in per_class.values():
                    for req in q:
                        if not req.cancelled:
                            req.cancel()
                            n += 1
        return n

    def _affinity_pop_unlocked(self, ci, key, admissible):  # lint: holds _lock
        """Oldest admissible request in class ``ci`` whose first k
        prompt tokens match ``key`` — across ALL tenant queues, charged
        to its tenant's deficit (which may go negative: the tenant pays
        the promotion back in later rotations)."""
        k = len(key)
        best = None
        for tid, q in self._queues[ci].items():
            for i, req in enumerate(q):
                if (len(req.prompt) >= k
                        and tuple(int(t) for t in req.prompt[:k]) == key
                        and (admissible is None or admissible(req))
                        and (best is None
                             or req.arrival_time < best[0].arrival_time)):
                    best = (req, tid, i)
        if best is None:
            return None
        req, tid, i = best
        del self._queues[ci][tid][i]
        drr = self._drr[ci]
        drr["deficit"][tid] = (
            drr["deficit"].get(tid, 0.0) - req.token_cost()
        )
        self._remove_tenant_if_empty(ci, tid)
        return req

    def _serve_head_unlocked(self, ci, tid):  # lint: holds _lock
        drr = self._drr[ci]
        req = self._queues[ci][tid].popleft()
        drr["deficit"][tid] -= req.token_cost()
        self._remove_tenant_if_empty(ci, tid)
        return req

    def _drr_pop_unlocked(self, ci, admissible):  # lint: holds _lock
        """Deficit-round-robin pop from class ``ci``: the rotation
        banks ``quantum * weight`` credit per VISIT; a tenant's head is
        served once its credit covers the head's token cost, and the
        serving tenant stays current across pops (spending its banked
        deficit) until the credit runs dry — textbook DRR, so long-run
        service within a class is proportional to tenant weight.
        Tenants whose head fails ``admissible`` (e.g. at their slot
        cap) are passed over, keeping their credit. When a whole
        rotation of fresh quanta serves nobody, the shortfall is banked
        in closed form (everyone gains the same number of rounds) so a
        huge head cost cannot spin the lock."""
        per_class = self._queues[ci]
        drr = self._drr[ci]
        order = drr["order"]
        if not order:
            return None
        for _rotation in range(2):
            any_admissible = False
            # n + 1 visits: the current tenant's first visit may be
            # stale (fresh False — quantum already granted), so one
            # full fresh rotation needs an extra step
            for _ in range(len(order) + 1):
                n = len(order)
                tid = order[drr["idx"]]
                if drr["fresh"]:
                    drr["deficit"][tid] = (
                        drr["deficit"].get(tid, 0.0)
                        + self.drr_quantum * self._weight(tid)
                    )
                    drr["fresh"] = False
                req = per_class[tid][0]
                if admissible is None or admissible(req):
                    any_admissible = True
                    if drr["deficit"][tid] >= req.token_cost():
                        return self._serve_head_unlocked(ci, tid)
                drr["idx"] = (drr["idx"] + 1) % n
                drr["fresh"] = True
            if not any_admissible:
                return None
            # a full rotation of quanta served nobody: bank the rounds
            # the closest tenant still needs, for EVERYONE (preserving
            # the weight ratios), then the next rotation must serve
            boost = None
            for tid in order:
                req = per_class[tid][0]
                if admissible is not None and not admissible(req):
                    continue
                need = req.token_cost() - drr["deficit"].get(tid, 0.0)
                inc = self.drr_quantum * self._weight(tid)
                rounds = max(0, math.ceil(need / inc) - 1)
                if boost is None or rounds < boost:
                    boost = rounds
            if boost:
                for tid in order:
                    drr["deficit"][tid] = (
                        drr["deficit"].get(tid, 0.0)
                        + boost * self.drr_quantum * self._weight(tid)
                    )
        return None  # unreachable: rotation 2 always serves

    def pop(self, affinity_hint: np.ndarray | None = None,
            admissible=None) -> Request | None:
        """Next request — or None when idle (or when nothing passes
        ``admissible``, a predicate the engine uses to skip tenants at
        their concurrent-slot cap without dequeuing their requests).

        Class selection is strict priority. Within the front non-empty
        class: with ``prefix_affinity_tokens`` > 0 and an
        ``affinity_hint`` (the prompt just admitted), the OLDEST
        request sharing the hint's first k tokens is promoted (its cost
        charged to its tenant's deficit); otherwise the weighted-DRR
        tenant rotation picks. A class where every request is blocked
        by ``admissible`` falls through to the next class — a
        slot-capped high-priority tenant must not idle the engine."""
        k = self.prefix_affinity_tokens
        with self._lock:
            note_access("scheduler.queues", write=True)
            for ci in range(self.n_priorities):
                if not any(self._queues[ci].values()):
                    continue
                if (k > 0 and affinity_hint is not None
                        and len(affinity_hint) >= k):
                    key = tuple(int(t) for t in affinity_hint[:k])
                    req = self._affinity_pop_unlocked(
                        ci, key, admissible
                    )
                    if req is not None:
                        return req
                req = self._drr_pop_unlocked(ci, admissible)
                if req is not None:
                    return req
        return None
