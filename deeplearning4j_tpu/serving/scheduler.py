"""Request queue for the serving engine.

Strict priority across classes (class 0 drains before class 1, etc.),
and — when a :class:`~deeplearning4j_tpu.serving.tenancy.TenantRegistry`
is attached — DEFICIT ROUND-ROBIN across tenants *within* each class,
weighted by tenant weight, so one flooding tenant cannot starve its
classmates: each tenant banks ``quantum * weight`` tokens of service
credit per scheduling visit and its head request is served once the
credit covers the request's token cost (prompt + max_new). Without a
registry every request lands in one implicit tenant and the scheduler
degenerates to the exact FIFO-within-class behavior it always had.

Admission control happens at ``submit`` time, not dequeue time, so a
caller holding a rejected request knows immediately:

- ``Backpressure`` when the queue is at ``max_queue_depth`` — the HTTP
  front end maps this to 429 so load sheds at the edge instead of
  growing an unbounded in-process queue;
- ``QuotaExceeded`` (a ``Backpressure``) when the tenant's token-rate
  bucket is dry — same 429, tagged per tenant in the metrics;
- ``AdmissionError`` when the request's token budget
  (``len(prompt) + max_new``) cannot fit the engine's cache slots at
  all — queueing it would deadlock the admission loop, since no slot
  will ever be big enough.

Thread-safe: the HTTP handler threads ``submit`` while the engine
thread ``pop``s.
"""

from __future__ import annotations

import dataclasses
import enum
import itertools
import math
import queue as queue_mod
import threading
import time
from collections import deque

import numpy as np

from deeplearning4j_tpu.analysis.sanitizers import note_access, wrap_lock


class RequestStatus(str, enum.Enum):
    """Request lifecycle. Terminal states set ``done`` and free the KV
    slot (if one was held); only FINISHED puts a full stream in
    ``engine.results`` (CANCELLED/EXPIRED store the partial stream)."""

    QUEUED = "queued"        # accepted by the scheduler, waiting for a slot
    RUNNING = "running"      # admitted; prefilled into a KV slot, decoding
    FINISHED = "finished"    # hit EOS or max_new; full stream available
    FAILED = "failed"        # poisoned (permanent/persistent fault)
    CANCELLED = "cancelled"  # caller invoked Request.cancel()
    EXPIRED = "expired"      # deadline_s elapsed before completion


class Backpressure(RuntimeError):
    """Queue at max depth — shed load upstream (HTTP 429)."""


class AdmissionError(ValueError):
    """Request can never be served (token budget exceeds slot size)."""


_ids = itertools.count()


def _next_id() -> str:
    return f"req-{next(_ids)}"


@dataclasses.dataclass
class Request:
    """One generation request.

    ``prompt`` is a 1-D int token array; ``max_new`` bounds generation;
    ``eos_token`` (optional) retires the slot early. ``priority`` 0 is
    most urgent. ``arrival_time`` is stamped by the scheduler at submit
    (perf_counter domain) and anchors TTFT. ``deadline_s`` (optional)
    is a wall-clock budget from arrival: the engine checks it at
    admission and at every step boundary and retires the request as
    EXPIRED (slot freed) the moment it elapses. ``cancel()`` may be
    called from any thread; the engine honors it within one step.

    Multi-tenant fields: ``tenant_id`` keys the scheduler's
    weighted-fair tier and the per-tenant metrics ("" = untenanted);
    ``adapter`` selects the LoRA bank row the slot decodes with (0 =
    base model). ``stream`` (optional ``queue.Queue``) receives each
    generated token as it arrives host-side, then ``None`` as the
    end-of-stream sentinel — the SSE front end drains it.
    """

    prompt: np.ndarray
    max_new: int
    priority: int = 1
    eos_token: int | None = None
    deadline_s: float | None = None
    tenant_id: str = ""
    adapter: int = 0
    stream: queue_mod.Queue | None = None
    id: str = dataclasses.field(default_factory=_next_id)
    arrival_time: float | None = None
    status: RequestStatus = RequestStatus.QUEUED
    error: str | None = None
    # set by the HTTP front end: signaled when the engine retires the
    # request, so a blocked handler thread can return the result
    done: threading.Event | None = None
    _cancel_evt: threading.Event = dataclasses.field(
        default_factory=threading.Event, init=False, repr=False,
        compare=False,
    )

    kind = "generate"

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt, np.int32).reshape(-1)
        if self.max_new < 1:
            raise AdmissionError(f"max_new must be >= 1, got {self.max_new}")
        if self.deadline_s is not None and self.deadline_s < 0:
            raise AdmissionError(
                f"deadline_s must be >= 0, got {self.deadline_s}"
            )
        if self.adapter < 0:
            raise AdmissionError(
                f"adapter must be >= 0, got {self.adapter}"
            )

    def token_cost(self) -> int:
        """Service cost in tokens — the unit the DRR tier and the
        tenant token buckets meter (the same prompt+max_new budget the
        per-slot admission check uses)."""
        return len(self.prompt) + self.max_new

    def cancel(self) -> None:
        """Request best-effort cancellation (thread-safe, idempotent).
        The engine frees the KV slot within one step boundary."""
        self._cancel_evt.set()

    @property
    def cancelled(self) -> bool:
        return self._cancel_evt.is_set()

    def expired(self, now: float | None = None) -> bool:
        """Deadline elapsed? (``now`` in perf_counter domain; measured
        from scheduler arrival so queue wait counts, like TTFT.)"""
        if self.deadline_s is None or self.arrival_time is None:
            return False
        if now is None:
            now = time.perf_counter()
        return (now - self.arrival_time) > self.deadline_s


def _empty_prompt() -> np.ndarray:
    return np.zeros(0, np.int32)


@dataclasses.dataclass
class EmbeddingRequest(Request):
    """An embeddings lookup riding the SAME queue as generation.

    Served host-side by the engine's admission loop from a zoo
    embedding model (word2vec/glove) — no KV slot, no device dispatch —
    but it flows through the scheduler (priority, DRR, quota,
    backpressure), the per-tenant metrics, and drain exactly like a
    generation request, which is the point: the serving stack is
    model-agnostic, not transformer-shaped. ``result`` is filled with
    ``{word: vector-or-None}`` before ``done`` is set."""

    prompt: np.ndarray = dataclasses.field(default_factory=_empty_prompt)
    max_new: int = 1
    model: str = "word2vec"
    words: tuple[str, ...] = ()
    result: dict | None = None

    kind = "embedding"

    def __post_init__(self):
        super().__post_init__()
        self.words = tuple(str(w) for w in self.words)
        if not self.words:
            raise AdmissionError("embedding request needs >= 1 word")

    def token_cost(self) -> int:
        return len(self.words)


class RequestScheduler:
    """Bounded multi-priority queue: strict priority across classes,
    weighted deficit-round-robin across tenants within a class, FIFO
    within a tenant. With no ``tenancy`` registry attached the whole
    thing degenerates to strict-priority FIFO (one implicit tenant)."""

    def __init__(
        self,
        max_queue_depth: int = 128,
        max_total_tokens: int | None = None,
        n_priorities: int = 3,
        prefix_affinity_tokens: int = 0,
        tenancy=None,
        drr_quantum: int = 64,
    ):
        self.max_queue_depth = max_queue_depth
        self.max_total_tokens = max_total_tokens
        # > 0 enables prefix-affinity ordering: ``pop`` may promote a
        # queued request whose first ``prefix_affinity_tokens`` prompt
        # tokens match the caller's hint (the previously admitted
        # prompt), so same-prefix requests land in the same admission
        # batch and the prefix cache gets back-to-back hits. Promotion
        # stays within one priority class — strict priority still wins
        # — and the promoted request's token cost is charged to its
        # tenant's deficit, so affinity cannot become a fairness leak.
        self.prefix_affinity_tokens = prefix_affinity_tokens
        self.n_priorities = n_priorities
        self.tenancy = tenancy
        if drr_quantum < 1:
            raise ValueError(f"drr_quantum must be >= 1, got {drr_quantum}")
        self.drr_quantum = drr_quantum
        self._lock = wrap_lock(threading.Lock(), "scheduler._lock")
        # submit() runs on HTTP handler threads while pop()/requeue()
        # run on the engine thread, so the queues only move under the
        # lock. Per class: tenant_id -> deque (FIFO within tenant),
        # plus the DRR rotation state (tenant order, rotation index,
        # banked deficits). Deficits reset when a tenant's queue
        # empties — idle tenants cannot bank credit (standard DRR).
        # ``fresh`` marks whether the tenant at ``idx`` is owed its
        # per-visit quantum: a serving tenant keeps idx with fresh
        # False and spends banked deficit across pops (DRR's serve-
        # while-deficit-lasts); new credit only flows when the
        # rotation actually visits.
        self._queues = [
            {} for _ in range(n_priorities)
        ]  # guarded-by: _lock
        self._drr = [
            {"order": [], "idx": 0, "deficit": {}, "fresh": True}
            for _ in range(n_priorities)
        ]  # guarded-by: _lock

    def _weight(self, tenant_id: str) -> float:
        if self.tenancy is not None:
            t = self.tenancy.get(tenant_id)
            if t is not None:
                return t.weight
        return 1.0

    def _depth_unlocked(self) -> int:  # lint: holds _lock
        return sum(
            len(q) for per_class in self._queues
            for q in per_class.values()
        )

    def __len__(self) -> int:
        with self._lock:
            return self._depth_unlocked()

    @property
    def depth(self) -> int:
        return len(self)

    def has_kind(self, kind: str) -> bool:
        """Any queued request of ``kind``? The engine's admission entry
        check: embedding requests stay admissible with zero free KV
        slots, so a full pool must not skip the admission loop while
        slotless work waits. O(depth), called only on the full-pool
        path."""
        with self._lock:
            return any(
                req.kind == kind
                for per_class in self._queues
                for q in per_class.values()
                for req in q
            )

    def _enqueue_unlocked(self, req: Request, front: bool) -> None:  # lint: holds _lock
        per_class = self._queues[req.priority]
        drr = self._drr[req.priority]
        tid = req.tenant_id
        q = per_class.get(tid)
        if q is None:
            q = per_class[tid] = deque()
            drr["deficit"].setdefault(tid, 0.0)
            if not drr["order"]:
                drr["fresh"] = True  # class was idle: restart rotation
            if front:
                # requeue of the only in-flight request of its tenant:
                # re-enter the rotation at the CURRENT position so the
                # recovered request is next, as the old FIFO guaranteed
                drr["order"].insert(drr["idx"], tid)
            else:
                drr["order"].append(tid)
        if front:
            q.appendleft(req)
        else:
            q.append(req)

    def _remove_tenant_if_empty(self, ci: int, tid: str) -> None:  # lint: holds _lock
        per_class = self._queues[ci]
        if per_class.get(tid):
            return
        per_class.pop(tid, None)
        drr = self._drr[ci]
        if tid in drr["order"]:
            pos = drr["order"].index(tid)
            was_current = pos == drr["idx"]
            drr["order"].remove(tid)
            if pos < drr["idx"]:
                drr["idx"] -= 1
            if drr["idx"] >= len(drr["order"]):
                drr["idx"] = 0  # wrap: rotation restarts at the front
            if was_current:
                # whoever now sits at idx is a NEW current tenant and
                # is owed its visit quantum
                drr["fresh"] = True
        drr["deficit"].pop(tid, None)

    def submit(self, req: Request) -> str:
        """Enqueue ``req``; returns its id. Raises ``Backpressure`` /
        ``QuotaExceeded`` / ``AdmissionError`` (see module docstring)."""
        total = len(req.prompt) + req.max_new
        if (req.kind == "generate" and self.max_total_tokens is not None
                and total > self.max_total_tokens):
            raise AdmissionError(
                f"request {req.id}: prompt+max_new ({total}) exceeds the "
                f"per-slot token budget ({self.max_total_tokens})"
            )
        if not 0 <= req.priority < self.n_priorities:
            raise AdmissionError(
                f"priority {req.priority} outside [0, {self.n_priorities})"
            )
        with self._lock:
            note_access("scheduler.queues", write=True)
            if self._depth_unlocked() >= self.max_queue_depth:
                raise Backpressure(
                    f"queue at max depth ({self.max_queue_depth})"
                )
            if self.tenancy is not None:
                # charge AFTER the depth check (a shed request must not
                # burn quota) and INSIDE the lock (depth + charge are
                # one admission decision). Lock order is always
                # scheduler._lock -> tenancy._lock.
                self.tenancy.charge(req.tenant_id, req.token_cost())
            req.arrival_time = time.perf_counter()
            req.status = RequestStatus.QUEUED
            self._enqueue_unlocked(req, front=False)
        return req.id

    def requeue(self, req: Request) -> None:
        """Put a popped-but-not-admitted request back at the FRONT of
        its tenant's queue (crash recovery: a request must never be
        dropped between pop and admission). Bypasses depth/budget/quota
        checks — the request was already admitted once — and refunds
        the token cost its pop charged to the tenant's deficit."""
        with self._lock:
            note_access("scheduler.queues", write=True)
            req.status = RequestStatus.QUEUED
            self._enqueue_unlocked(req, front=True)
            drr = self._drr[req.priority]
            drr["deficit"][req.tenant_id] = (
                drr["deficit"].get(req.tenant_id, 0.0) + req.token_cost()
            )

    def cancel(self, req_id: str) -> bool:
        """Flag a still-queued request as cancelled (it is discarded at
        its admission turn). Returns False when the id is not queued."""
        with self._lock:
            for per_class in self._queues:
                for q in per_class.values():
                    for req in q:
                        if req.id == req_id:
                            req.cancel()
                            return True
        return False

    def cancel_all(self) -> int:
        """Flag every still-queued request as cancelled (drain-deadline
        preemption: each is discarded at its admission turn, so a
        stopping engine converges instead of decoding stragglers).
        Returns the number newly flagged."""
        n = 0
        with self._lock:
            for per_class in self._queues:
                for q in per_class.values():
                    for req in q:
                        if not req.cancelled:
                            req.cancel()
                            n += 1
        return n

    def _affinity_pop_unlocked(self, ci, key, admissible):  # lint: holds _lock
        """Oldest admissible request in class ``ci`` whose first k
        prompt tokens match ``key`` — across ALL tenant queues, charged
        to its tenant's deficit (which may go negative: the tenant pays
        the promotion back in later rotations)."""
        k = len(key)
        best = None
        for tid, q in self._queues[ci].items():
            for i, req in enumerate(q):
                if (len(req.prompt) >= k
                        and tuple(int(t) for t in req.prompt[:k]) == key
                        and (admissible is None or admissible(req))
                        and (best is None
                             or req.arrival_time < best[0].arrival_time)):
                    best = (req, tid, i)
        if best is None:
            return None
        req, tid, i = best
        del self._queues[ci][tid][i]
        drr = self._drr[ci]
        drr["deficit"][tid] = (
            drr["deficit"].get(tid, 0.0) - req.token_cost()
        )
        self._remove_tenant_if_empty(ci, tid)
        return req

    def _serve_head_unlocked(self, ci, tid):  # lint: holds _lock
        drr = self._drr[ci]
        req = self._queues[ci][tid].popleft()
        drr["deficit"][tid] -= req.token_cost()
        self._remove_tenant_if_empty(ci, tid)
        return req

    def _drr_pop_unlocked(self, ci, admissible):  # lint: holds _lock
        """Deficit-round-robin pop from class ``ci``: the rotation
        banks ``quantum * weight`` credit per VISIT; a tenant's head is
        served once its credit covers the head's token cost, and the
        serving tenant stays current across pops (spending its banked
        deficit) until the credit runs dry — textbook DRR, so long-run
        service within a class is proportional to tenant weight.
        Tenants whose head fails ``admissible`` (e.g. at their slot
        cap) are passed over, keeping their credit. When a whole
        rotation of fresh quanta serves nobody, the shortfall is banked
        in closed form (everyone gains the same number of rounds) so a
        huge head cost cannot spin the lock."""
        per_class = self._queues[ci]
        drr = self._drr[ci]
        order = drr["order"]
        if not order:
            return None
        for _rotation in range(2):
            any_admissible = False
            # n + 1 visits: the current tenant's first visit may be
            # stale (fresh False — quantum already granted), so one
            # full fresh rotation needs an extra step
            for _ in range(len(order) + 1):
                n = len(order)
                tid = order[drr["idx"]]
                if drr["fresh"]:
                    drr["deficit"][tid] = (
                        drr["deficit"].get(tid, 0.0)
                        + self.drr_quantum * self._weight(tid)
                    )
                    drr["fresh"] = False
                req = per_class[tid][0]
                if admissible is None or admissible(req):
                    any_admissible = True
                    if drr["deficit"][tid] >= req.token_cost():
                        return self._serve_head_unlocked(ci, tid)
                drr["idx"] = (drr["idx"] + 1) % n
                drr["fresh"] = True
            if not any_admissible:
                return None
            # a full rotation of quanta served nobody: bank the rounds
            # the closest tenant still needs, for EVERYONE (preserving
            # the weight ratios), then the next rotation must serve
            boost = None
            for tid in order:
                req = per_class[tid][0]
                if admissible is not None and not admissible(req):
                    continue
                need = req.token_cost() - drr["deficit"].get(tid, 0.0)
                inc = self.drr_quantum * self._weight(tid)
                rounds = max(0, math.ceil(need / inc) - 1)
                if boost is None or rounds < boost:
                    boost = rounds
            if boost:
                for tid in order:
                    drr["deficit"][tid] = (
                        drr["deficit"].get(tid, 0.0)
                        + boost * self.drr_quantum * self._weight(tid)
                    )
        return None  # unreachable: rotation 2 always serves

    def pop(self, affinity_hint: np.ndarray | None = None,
            admissible=None) -> Request | None:
        """Next request — or None when idle (or when nothing passes
        ``admissible``, a predicate the engine uses to skip tenants at
        their concurrent-slot cap without dequeuing their requests).

        Class selection is strict priority. Within the front non-empty
        class: with ``prefix_affinity_tokens`` > 0 and an
        ``affinity_hint`` (the prompt just admitted), the OLDEST
        request sharing the hint's first k tokens is promoted (its cost
        charged to its tenant's deficit); otherwise the weighted-DRR
        tenant rotation picks. A class where every request is blocked
        by ``admissible`` falls through to the next class — a
        slot-capped high-priority tenant must not idle the engine."""
        k = self.prefix_affinity_tokens
        with self._lock:
            note_access("scheduler.queues", write=True)
            for ci in range(self.n_priorities):
                if not any(self._queues[ci].values()):
                    continue
                if (k > 0 and affinity_hint is not None
                        and len(affinity_hint) >= k):
                    key = tuple(int(t) for t in affinity_hint[:k])
                    req = self._affinity_pop_unlocked(
                        ci, key, admissible
                    )
                    if req is not None:
                        return req
                req = self._drr_pop_unlocked(ci, admissible)
                if req is not None:
                    return req
        return None
