"""Request queue for the serving engine.

FIFO within priority classes, strict priority across classes (class 0
drains before class 1, etc. — the simple strict policy; weighted-fair
would go here if starvation ever matters). Admission control happens at
``submit`` time, not dequeue time, so a caller holding a rejected
request knows immediately:

- ``Backpressure`` when the queue is at ``max_queue_depth`` — the HTTP
  front end maps this to 429 so load sheds at the edge instead of
  growing an unbounded in-process queue;
- ``AdmissionError`` when the request's token budget
  (``len(prompt) + max_new``) cannot fit the engine's cache slots at
  all — queueing it would deadlock the admission loop, since no slot
  will ever be big enough.

Thread-safe: the HTTP handler threads ``submit`` while the engine
thread ``pop``s.
"""

from __future__ import annotations

import dataclasses
import enum
import itertools
import threading
import time
from collections import deque

import numpy as np

from deeplearning4j_tpu.analysis.sanitizers import note_access, wrap_lock


class RequestStatus(str, enum.Enum):
    """Request lifecycle. Terminal states set ``done`` and free the KV
    slot (if one was held); only FINISHED puts a full stream in
    ``engine.results`` (CANCELLED/EXPIRED store the partial stream)."""

    QUEUED = "queued"        # accepted by the scheduler, waiting for a slot
    RUNNING = "running"      # admitted; prefilled into a KV slot, decoding
    FINISHED = "finished"    # hit EOS or max_new; full stream available
    FAILED = "failed"        # poisoned (permanent/persistent fault)
    CANCELLED = "cancelled"  # caller invoked Request.cancel()
    EXPIRED = "expired"      # deadline_s elapsed before completion


class Backpressure(RuntimeError):
    """Queue at max depth — shed load upstream (HTTP 429)."""


class AdmissionError(ValueError):
    """Request can never be served (token budget exceeds slot size)."""


_ids = itertools.count()


def _next_id() -> str:
    return f"req-{next(_ids)}"


@dataclasses.dataclass
class Request:
    """One generation request.

    ``prompt`` is a 1-D int token array; ``max_new`` bounds generation;
    ``eos_token`` (optional) retires the slot early. ``priority`` 0 is
    most urgent. ``arrival_time`` is stamped by the scheduler at submit
    (perf_counter domain) and anchors TTFT. ``deadline_s`` (optional)
    is a wall-clock budget from arrival: the engine checks it at
    admission and at every step boundary and retires the request as
    EXPIRED (slot freed) the moment it elapses. ``cancel()`` may be
    called from any thread; the engine honors it within one step.
    """

    prompt: np.ndarray
    max_new: int
    priority: int = 1
    eos_token: int | None = None
    deadline_s: float | None = None
    id: str = dataclasses.field(default_factory=_next_id)
    arrival_time: float | None = None
    status: RequestStatus = RequestStatus.QUEUED
    error: str | None = None
    # set by the HTTP front end: signaled when the engine retires the
    # request, so a blocked handler thread can return the result
    done: threading.Event | None = None
    _cancel_evt: threading.Event = dataclasses.field(
        default_factory=threading.Event, init=False, repr=False,
        compare=False,
    )

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt, np.int32).reshape(-1)
        if self.max_new < 1:
            raise AdmissionError(f"max_new must be >= 1, got {self.max_new}")
        if self.deadline_s is not None and self.deadline_s < 0:
            raise AdmissionError(
                f"deadline_s must be >= 0, got {self.deadline_s}"
            )

    def cancel(self) -> None:
        """Request best-effort cancellation (thread-safe, idempotent).
        The engine frees the KV slot within one step boundary."""
        self._cancel_evt.set()

    @property
    def cancelled(self) -> bool:
        return self._cancel_evt.is_set()

    def expired(self, now: float | None = None) -> bool:
        """Deadline elapsed? (``now`` in perf_counter domain; measured
        from scheduler arrival so queue wait counts, like TTFT.)"""
        if self.deadline_s is None or self.arrival_time is None:
            return False
        if now is None:
            now = time.perf_counter()
        return (now - self.arrival_time) > self.deadline_s


class RequestScheduler:
    """Bounded multi-priority FIFO with admission control."""

    def __init__(
        self,
        max_queue_depth: int = 128,
        max_total_tokens: int | None = None,
        n_priorities: int = 3,
        prefix_affinity_tokens: int = 0,
    ):
        self.max_queue_depth = max_queue_depth
        self.max_total_tokens = max_total_tokens
        # > 0 enables prefix-affinity ordering: ``pop`` may promote a
        # queued request whose first ``prefix_affinity_tokens`` prompt
        # tokens match the caller's hint (the previously admitted
        # prompt), so same-prefix requests land in the same admission
        # batch and the prefix cache gets back-to-back hits. Promotion
        # stays within one priority class — strict priority still wins.
        self.prefix_affinity_tokens = prefix_affinity_tokens
        self.n_priorities = n_priorities
        self._lock = wrap_lock(threading.Lock(), "scheduler._lock")
        # submit() runs on HTTP handler threads while pop()/requeue()
        # run on the engine thread, so the queues only move under the
        # lock
        self._queues = [deque() for _ in range(n_priorities)]  # guarded-by: _lock

    def _depth_unlocked(self) -> int:  # lint: holds _lock
        return sum(len(q) for q in self._queues)

    def __len__(self) -> int:
        with self._lock:
            return self._depth_unlocked()

    @property
    def depth(self) -> int:
        return len(self)

    def submit(self, req: Request) -> str:
        """Enqueue ``req``; returns its id. Raises ``Backpressure`` /
        ``AdmissionError`` (see module docstring)."""
        total = len(req.prompt) + req.max_new
        if self.max_total_tokens is not None and total > self.max_total_tokens:
            raise AdmissionError(
                f"request {req.id}: prompt+max_new ({total}) exceeds the "
                f"per-slot token budget ({self.max_total_tokens})"
            )
        if not 0 <= req.priority < self.n_priorities:
            raise AdmissionError(
                f"priority {req.priority} outside [0, {self.n_priorities})"
            )
        with self._lock:
            note_access("scheduler.queues", write=True)
            if self._depth_unlocked() >= self.max_queue_depth:
                raise Backpressure(
                    f"queue at max depth ({self.max_queue_depth})"
                )
            req.arrival_time = time.perf_counter()
            req.status = RequestStatus.QUEUED
            self._queues[req.priority].append(req)
        return req.id

    def requeue(self, req: Request) -> None:
        """Put a popped-but-not-admitted request back at the FRONT of
        its priority class (crash recovery: a request must never be
        dropped between pop and admission). Bypasses depth/budget
        checks — the request was already admitted once."""
        with self._lock:
            note_access("scheduler.queues", write=True)
            req.status = RequestStatus.QUEUED
            self._queues[req.priority].appendleft(req)

    def cancel(self, req_id: str) -> bool:
        """Flag a still-queued request as cancelled (it is discarded at
        its admission turn). Returns False when the id is not queued."""
        with self._lock:
            for q in self._queues:
                for req in q:
                    if req.id == req_id:
                        req.cancel()
                        return True
        return False

    def cancel_all(self) -> int:
        """Flag every still-queued request as cancelled (drain-deadline
        preemption: each is discarded at its admission turn, so a
        stopping engine converges instead of decoding stragglers).
        Returns the number newly flagged."""
        n = 0
        with self._lock:
            for q in self._queues:
                for req in q:
                    if not req.cancelled:
                        req.cancel()
                        n += 1
        return n

    def pop(self, affinity_hint: np.ndarray | None = None
            ) -> Request | None:
        """Highest-priority, oldest request — or None when idle.

        With ``prefix_affinity_tokens`` > 0 and an ``affinity_hint``
        (the prompt just admitted), the front non-empty class is
        scanned for the OLDEST request sharing the hint's first k
        tokens and that one is promoted; otherwise plain FIFO. The scan
        is bounded by the queue depth cap, and affinity never crosses a
        priority boundary, so strict priority and within-class fairness
        for non-matching requests are preserved (a matching request
        only ever moves EARLIER)."""
        k = self.prefix_affinity_tokens
        with self._lock:
            note_access("scheduler.queues", write=True)
            for q in self._queues:
                if not q:
                    continue
                if (k > 0 and affinity_hint is not None
                        and len(affinity_hint) >= k):
                    key = tuple(int(t) for t in affinity_hint[:k])
                    for i, req in enumerate(q):
                        if (len(req.prompt) >= k
                                and tuple(int(t) for t in req.prompt[:k])
                                == key):
                            del q[i]
                            return req
                return q.popleft()
        return None
