"""Request queue for the serving engine.

FIFO within priority classes, strict priority across classes (class 0
drains before class 1, etc. — the simple strict policy; weighted-fair
would go here if starvation ever matters). Admission control happens at
``submit`` time, not dequeue time, so a caller holding a rejected
request knows immediately:

- ``Backpressure`` when the queue is at ``max_queue_depth`` — the HTTP
  front end maps this to 429 so load sheds at the edge instead of
  growing an unbounded in-process queue;
- ``AdmissionError`` when the request's token budget
  (``len(prompt) + max_new``) cannot fit the engine's cache slots at
  all — queueing it would deadlock the admission loop, since no slot
  will ever be big enough.

Thread-safe: the HTTP handler threads ``submit`` while the engine
thread ``pop``s.
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
import time
from collections import deque

import numpy as np


class Backpressure(RuntimeError):
    """Queue at max depth — shed load upstream (HTTP 429)."""


class AdmissionError(ValueError):
    """Request can never be served (token budget exceeds slot size)."""


_ids = itertools.count()


def _next_id() -> str:
    return f"req-{next(_ids)}"


@dataclasses.dataclass
class Request:
    """One generation request.

    ``prompt`` is a 1-D int token array; ``max_new`` bounds generation;
    ``eos_token`` (optional) retires the slot early. ``priority`` 0 is
    most urgent. ``arrival_time`` is stamped by the scheduler at submit
    (perf_counter domain) and anchors TTFT.
    """

    prompt: np.ndarray
    max_new: int
    priority: int = 1
    eos_token: int | None = None
    id: str = dataclasses.field(default_factory=_next_id)
    arrival_time: float | None = None
    # set by the HTTP front end: signaled when the engine retires the
    # request, so a blocked handler thread can return the result
    done: threading.Event | None = None

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt, np.int32).reshape(-1)
        if self.max_new < 1:
            raise AdmissionError(f"max_new must be >= 1, got {self.max_new}")


class RequestScheduler:
    """Bounded multi-priority FIFO with admission control."""

    def __init__(
        self,
        max_queue_depth: int = 128,
        max_total_tokens: int | None = None,
        n_priorities: int = 3,
    ):
        self.max_queue_depth = max_queue_depth
        self.max_total_tokens = max_total_tokens
        self._queues = [deque() for _ in range(n_priorities)]
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return sum(len(q) for q in self._queues)

    @property
    def depth(self) -> int:
        return len(self)

    def submit(self, req: Request) -> str:
        """Enqueue ``req``; returns its id. Raises ``Backpressure`` /
        ``AdmissionError`` (see module docstring)."""
        total = len(req.prompt) + req.max_new
        if self.max_total_tokens is not None and total > self.max_total_tokens:
            raise AdmissionError(
                f"request {req.id}: prompt+max_new ({total}) exceeds the "
                f"per-slot token budget ({self.max_total_tokens})"
            )
        if not 0 <= req.priority < len(self._queues):
            raise AdmissionError(
                f"priority {req.priority} outside [0, {len(self._queues)})"
            )
        with self._lock:
            if len(self) >= self.max_queue_depth:
                raise Backpressure(
                    f"queue at max depth ({self.max_queue_depth})"
                )
            req.arrival_time = time.perf_counter()
            self._queues[req.priority].append(req)
        return req.id

    def pop(self) -> Request | None:
        """Highest-priority, oldest request — or None when idle."""
        with self._lock:
            for q in self._queues:
                if q:
                    return q.popleft()
        return None
