"""Continuous-batching serving engine on top of the transformer decode
stack: iteration-level scheduling (Orca, OSDI '22; vLLM, SOSP '23) over
a fixed-shape batch of KV-cache slots.

Public surface:

- :class:`~deeplearning4j_tpu.serving.scheduler.Request` /
  :class:`~deeplearning4j_tpu.serving.scheduler.RequestScheduler` —
  admission-controlled priority queue with backpressure.
- :class:`~deeplearning4j_tpu.serving.cache_pool.KVSlotPool` — slot
  recycling over one pre-allocated ``init_caches`` buffer.
- :class:`~deeplearning4j_tpu.serving.cache_pool.PagedKVPool` —
  block-paged variant: a shared pool of fixed-size KV blocks with
  refcounted per-slot block tables (``ServingEngine(paged=True)``).
- :class:`~deeplearning4j_tpu.serving.engine.ServingEngine` — the
  continuous-batching decode loop (admit / fused step / retire).
- :class:`~deeplearning4j_tpu.serving.metrics.ServingMetrics` —
  TTFT/TPOT/occupancy/queue-depth with p50/p99 summaries, bounded
  reservoirs, per-phase breakdown, and a Prometheus registry behind
  ``GET /metrics`` (see :mod:`deeplearning4j_tpu.obs`).
- :class:`~deeplearning4j_tpu.serving.server.ServingServer` — stdlib
  HTTP-JSON front end with graceful drain, health/readiness endpoints,
  Prometheus ``/metrics`` (+ optional sidecar port), and on-demand XLA
  profiling (``POST /profile``).
- :class:`~deeplearning4j_tpu.serving.prefix_cache.PrefixCache` —
  token-level radix tree mapping prompt prefixes to cached KV segments
  in a bounded device-side region (refcounted LRU), so admissions that
  share a prefix skip recomputing it (``--prefix-cache``).
- :class:`~deeplearning4j_tpu.serving.faults.FaultInjector` —
  deterministic (seeded or scripted) fault injection at engine
  boundaries, driving the supervised step loop / replay recovery
  (chaos tests: ``tests/test_serving_faults.py``).
- :class:`~deeplearning4j_tpu.serving.router.ReplicaRouter` — host-side
  front end over N engine replicas: prefix-affinity dispatch via a
  shadow token trie, least-loaded otherwise, per-replica health with
  retry onto survivors (``router`` subcommand).
- :class:`~deeplearning4j_tpu.serving.controller.FleetController` —
  disaggregated prefill/decode fleet control: role assignment with
  hysteretic rebalancing (:class:`~deeplearning4j_tpu.serving.controller.RoleBalancer`),
  long prompts prefilled on prefill replicas whose KV segments ship
  replica-to-replica over the :mod:`~deeplearning4j_tpu.serving.disagg`
  wire format, session-sticky routing, and rolling-restart draining
  (``controller`` subcommand).
- :class:`~deeplearning4j_tpu.serving.tenancy.TenantRegistry` /
  :class:`~deeplearning4j_tpu.serving.tenancy.TenantConfig` —
  multi-tenant serving: API-key resolution, per-tenant priority /
  deficit-round-robin weight / KV-slot cap / token-rate quota
  (:class:`~deeplearning4j_tpu.serving.tenancy.QuotaExceeded` → 429)
  and a default batched-LoRA adapter
  (``models.transformer.init_lora_bank``) per tenant.
"""

from deeplearning4j_tpu.serving.cache_pool import (  # noqa: F401
    KVSlotPool,
    PagedKVPool,
)
from deeplearning4j_tpu.serving.controller import (  # noqa: F401
    FleetController,
    RoleBalancer,
)
from deeplearning4j_tpu.serving.disagg import (  # noqa: F401
    WIRE_VERSION,
    WireError,
    decode_segment,
    encode_segment,
    model_config_hash,
)
from deeplearning4j_tpu.serving.engine import (  # noqa: F401
    ServingEngine,
    run_request_trace,
)
from deeplearning4j_tpu.serving.faults import (  # noqa: F401
    EngineCrash,
    FaultInjector,
    PermanentFault,
    TransientFault,
)
from deeplearning4j_tpu.serving.metrics import ServingMetrics  # noqa: F401
from deeplearning4j_tpu.serving.netfaults import ChaosProxy  # noqa: F401
from deeplearning4j_tpu.serving.prefix_cache import PrefixCache  # noqa: F401
from deeplearning4j_tpu.serving.router import ReplicaRouter  # noqa: F401
from deeplearning4j_tpu.serving.rpc import (  # noqa: F401
    CircuitBreaker,
    Deadline,
    IdempotencyRegistry,
    LatencyWindow,
    run_hedged,
)
from deeplearning4j_tpu.serving.scheduler import (  # noqa: F401
    AdmissionError,
    Backpressure,
    EmbeddingRequest,
    KVExportRequest,
    KVIngestRequest,
    KVSessionRequest,
    Request,
    RequestScheduler,
    RequestStatus,
)
from deeplearning4j_tpu.serving.server import ServingServer  # noqa: F401
from deeplearning4j_tpu.serving.tenancy import (  # noqa: F401
    QuotaExceeded,
    TenantConfig,
    TenantRegistry,
)
