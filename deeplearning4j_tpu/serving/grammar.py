"""Grammar-constrained decoding: JSON-schema / regex constraints
compiled into token-level DFAs over the model vocabulary.

The pipeline (Outlines / SGLang-constrained style, stdlib-only):

    regex  --parse-->  char-NFA  --subset construction-->  byte DFA
    JSON schema  --lowering-->  regex subset  --> (same path)

and then, against the tokenizer's byte strings (this repo serves a raw
byte-level vocabulary by default — token ``i`` IS byte ``i``), each DFA
is lowered to two token-level tables:

- an int32 **transition table** ``(n_states, V)`` — ``trans[s, t]`` is
  the DFA state after emitting token ``t`` from state ``s``, or ``-1``
  when ``t`` is not permitted there (advanced host-side at readback for
  the engine's mirror, and in-program off the chosen token so K>1
  decode horizons stay constrained);
- a bitmask-packed uint32 **mask table** ``(n_states, ceil(V/32))`` —
  bit ``t`` of row ``s`` set iff token ``t`` is permitted, unpacked
  in-program and applied as ``jnp.where(mask, logits, -inf)`` BEFORE
  the greedy/sampled draw.

Termination is baked in at compile time: the EOS token's bit is set
exactly in ACCEPTING states (its transition is a self-loop), and a
state whose only permitted token is EOS forces the stream to retire
through the engine's existing EOS machinery. Constrained requests must
therefore carry an ``eos_token``.

State numbering is grammar-local, 0-based, with ``start`` the entry
state. The ENGINE reserves global state 0 as the unconstrained
sentinel and seats each grammar at a nonzero base offset inside a
fixed-capacity combined table (:class:`GrammarTable`), so one compiled
program serves any mix of constrained and unconstrained slots.

Compiles are cached by ``sha256(kind, spec, tokenizer id, eos, V)`` in
an in-process LRU plus an optional on-disk store next to the probe
cache (:mod:`~deeplearning4j_tpu.serving.probe_cache`), and a state
budget turns pathological regexes into a 400 at submit instead of an
unbounded device table.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
import threading
from collections import OrderedDict

import numpy as np

__all__ = [
    "GrammarError",
    "GrammarBudgetError",
    "CompiledGrammar",
    "GrammarCache",
    "GrammarTable",
    "StopMatcher",
    "compile_regex",
    "compile_json_schema",
    "schema_to_regex",
    "default_token_bytes",
    "grammar_key",
    "parse_response_format",
    "validate_json_value",
    "MAX_LOGIT_BIAS",
    "MAX_TOP_LOGPROBS",
    "MAX_STOP_SEQUENCES",
    "MAX_STOP_LEN",
]

#: default ceiling on DFA states per grammar — a regex that blows past
#: it is rejected (HTTP 400), never silently truncated
DEFAULT_MAX_STATES = 256

#: per-slot sampling-surface widths BAKED INTO the masked step's traced
#: avals — the sparse logit-bias scatter rows are (slots, MAX_LOGIT_BIAS)
#: and the in-program logprob gather is the chosen token plus a static
#: top-MAX_TOP_LOGPROBS (requests asking for more are rejected at
#: submit, never silently clipped)
MAX_LOGIT_BIAS = 8
MAX_TOP_LOGPROBS = 8
#: stop-sequence bounds: host-side rolling suffix match at readback,
#: so these bound the per-slot hold-back buffer, not a device shape
MAX_STOP_SEQUENCES = 4
MAX_STOP_LEN = 16


class GrammarError(ValueError):
    """Malformed regex / unsupported JSON schema (client error)."""


class GrammarBudgetError(GrammarError):
    """The compiled DFA exceeds the engine's state-count budget."""


# -- regex parsing ----------------------------------------------------------
#
# Byte-level regex subset: literals, escapes (\d \w \s \n \t \r and
# escaped metacharacters), ``.``, character classes ``[a-z0-9_]`` /
# ``[^...]`` with ranges, grouping ``(...)`` (non-capturing — nothing
# captures here), alternation ``|``, and quantifiers ``* + ? {m} {m,}
# {m,n}``. Anchored fullmatch semantics (the whole stream must match).
# Character sets are 256-bit Python ints (bit b set = byte b matches),
# which makes NFA/DFA set algebra plain integer bitwise ops.

_ALL_BYTES = (1 << 256) - 1
_DOT = _ALL_BYTES & ~(1 << ord("\n"))


def _bits(chars) -> int:
    m = 0
    for c in chars:
        m |= 1 << c
    return m


_D = _bits(range(ord("0"), ord("9") + 1))
_W = _D | _bits(range(ord("a"), ord("z") + 1)) \
        | _bits(range(ord("A"), ord("Z") + 1)) | (1 << ord("_"))
_S = _bits(b" \t\n\r\f\v")
_ESCAPES = {
    ord("d"): _D, ord("w"): _W, ord("s"): _S,
    ord("D"): _ALL_BYTES & ~_D, ord("W"): _ALL_BYTES & ~_W,
    ord("S"): _ALL_BYTES & ~_S,
    ord("n"): 1 << ord("\n"), ord("t"): 1 << ord("\t"),
    ord("r"): 1 << ord("\r"), ord("f"): 1 << ord("\f"),
    ord("v"): 1 << ord("\v"), ord("0"): 1 << 0,
}

# AST nodes: ("lit", mask) | ("cat", [..]) | ("alt", [..])
#          | ("rep", node, lo, hi)  (hi None = unbounded)


class _Parser:
    def __init__(self, pattern: str):
        self.src = pattern.encode("utf-8", "strict")
        self.i = 0

    def error(self, msg: str):
        raise GrammarError(f"regex: {msg} at offset {self.i}")

    def peek(self):
        return self.src[self.i] if self.i < len(self.src) else None

    def take(self):
        c = self.peek()
        if c is None:
            self.error("unexpected end of pattern")
        self.i += 1
        return c

    def parse(self):
        node = self._alt()
        if self.i != len(self.src):
            self.error("unbalanced ')'")
        return node

    def _alt(self):
        branches = [self._cat()]
        while self.peek() == ord("|"):
            self.take()
            branches.append(self._cat())
        return branches[0] if len(branches) == 1 else ("alt", branches)

    def _cat(self):
        items = []
        while True:
            c = self.peek()
            if c is None or c in (ord("|"), ord(")")):
                break
            items.append(self._repeat())
        return ("cat", items)

    def _repeat(self):
        node = self._atom()
        while True:
            c = self.peek()
            if c == ord("*"):
                self.take()
                node = ("rep", node, 0, None)
            elif c == ord("+"):
                self.take()
                node = ("rep", node, 1, None)
            elif c == ord("?"):
                self.take()
                node = ("rep", node, 0, 1)
            elif c == ord("{"):
                node = ("rep", node, *self._braces())
            else:
                return node

    def _braces(self):
        self.take()  # '{'
        lo = self._int()
        hi = lo
        if self.peek() == ord(","):
            self.take()
            hi = None if self.peek() == ord("}") else self._int()
        if self.take() != ord("}"):
            self.error("expected '}'")
        if hi is not None and hi < lo:
            self.error(f"bad repeat bounds {{{lo},{hi}}}")
        if (hi if hi is not None else lo) > 4096:
            self.error("repeat bound too large (max 4096)")
        return lo, hi

    def _int(self):
        digits = []
        while self.peek() is not None and ord("0") <= self.peek() <= ord("9"):
            digits.append(self.take())
        if not digits:
            self.error("expected integer")
        return int(bytes(digits))

    def _atom(self):
        c = self.take()
        if c == ord("("):
            # swallow non-capturing prefix "?:" — groups never capture
            if self.peek() == ord("?"):
                self.take()
                if self.take() != ord(":"):
                    self.error("only (?: groups supported")
            node = self._alt()
            if self.take() != ord(")"):
                self.error("expected ')'")
            return node
        if c == ord("["):
            return ("lit", self._char_class())
        if c == ord("."):
            return ("lit", _DOT)
        if c == ord("\\"):
            return ("lit", self._escape())
        if c in (ord("*"), ord("+"), ord("?"), ord("{"), ord(")"),
                 ord("]"), ord("|")):
            self.error(f"unexpected metacharacter {chr(c)!r}")
        return ("lit", 1 << c)

    def _escape(self) -> int:
        c = self.take()
        if c in _ESCAPES:
            return _ESCAPES[c]
        if c == ord("x"):
            h = bytes([self.take(), self.take()])
            try:
                return 1 << int(h, 16)
            except ValueError:
                self.error(f"bad hex escape \\x{h.decode()!r}")
        return 1 << c  # escaped literal (\. \[ \\ ...)

    def _char_class(self) -> int:
        neg = False
        if self.peek() == ord("^"):
            self.take()
            neg = True
        mask = 0
        first = True
        while True:
            c = self.peek()
            if c is None:
                self.error("unterminated character class")
            if c == ord("]") and not first:
                self.take()
                break
            first = False
            c = self.take()
            if c == ord("\\"):
                m = self._escape()
                if m & (m - 1):  # multi-byte escape (\d \w \s): no range
                    mask |= m
                    continue
                lo = m.bit_length() - 1
            else:
                lo = c
            if (self.peek() == ord("-") and self.i + 1 < len(self.src)
                    and self.src[self.i + 1] != ord("]")):
                self.take()  # '-'
                hi = self.take()
                if hi == ord("\\"):
                    hm = self._escape()
                    if hm & (hm - 1):
                        self.error("class escape cannot end a range")
                    hi = hm.bit_length() - 1
                if hi < lo:
                    self.error(f"reversed range {chr(lo)}-{chr(hi)}")
                mask |= _bits(range(lo, hi + 1))
            else:
                mask |= 1 << lo
        return (_ALL_BYTES & ~mask) if neg else mask


# -- NFA (Thompson) + DFA (subset construction) -----------------------------


class _NFA:
    """Epsilon-NFA under construction: ``eps[s]`` epsilon successors,
    ``edges[s]`` list of (charset-mask, dst)."""

    def __init__(self):
        self.eps: list[list[int]] = []
        self.edges: list[list[tuple[int, int]]] = []

    def state(self) -> int:
        self.eps.append([])
        self.edges.append([])
        return len(self.eps) - 1

    def add(self, src: int, mask: int, dst: int):
        self.edges[src].append((mask, dst))

    def link(self, src: int, dst: int):
        self.eps[src].append(dst)


def _build_nfa(node, nfa: _NFA) -> tuple[int, int]:
    """Thompson-construct ``node``; returns (entry, exit) states."""
    kind = node[0]
    if kind == "lit":
        a, b = nfa.state(), nfa.state()
        nfa.add(a, node[1], b)
        return a, b
    if kind == "cat":
        a = prev = nfa.state()
        for item in node[1]:
            ia, ib = _build_nfa(item, nfa)
            nfa.link(prev, ia)
            prev = ib
        return a, prev
    if kind == "alt":
        a, b = nfa.state(), nfa.state()
        for item in node[1]:
            ia, ib = _build_nfa(item, nfa)
            nfa.link(a, ia)
            nfa.link(ib, b)
        return a, b
    if kind == "rep":
        _, inner, lo, hi = node
        a = prev = nfa.state()
        for _ in range(lo):
            ia, ib = _build_nfa(inner, nfa)
            nfa.link(prev, ia)
            prev = ib
        if hi is None:
            ia, ib = _build_nfa(inner, nfa)
            nfa.link(prev, ia)
            nfa.link(ib, ia)  # loop
            out = nfa.state()
            nfa.link(prev, out)
            nfa.link(ib, out)
            return a, out
        out = nfa.state()
        nfa.link(prev, out)
        for _ in range(hi - lo):
            ia, ib = _build_nfa(inner, nfa)
            nfa.link(prev, ia)
            nfa.link(ib, out)
            prev = ib
        nfa.link(prev, out)
        return a, out
    raise AssertionError(f"unknown node {kind}")


def _eps_closure(nfa: _NFA, states: frozenset[int]) -> frozenset[int]:
    seen = set(states)
    stack = list(states)
    while stack:
        s = stack.pop()
        for t in nfa.eps[s]:
            if t not in seen:
                seen.add(t)
                stack.append(t)
    return frozenset(seen)


def _regex_to_dfa(pattern: str, max_states: int):
    """Parse + determinize; returns (trans: list[dict byte->state],
    accepting: list[bool], start=0). The transition alphabet is
    partitioned into atomic byte classes first so subset construction
    walks classes, not 256 bytes."""
    ast = _Parser(pattern).parse()
    nfa = _NFA()
    entry, exit_ = _build_nfa(ast, nfa)

    # atomic byte-class partition: split 0..255 by every edge charset
    classes = [_ALL_BYTES]
    for edges in nfa.edges:
        for mask, _ in edges:
            nxt = []
            for cls in classes:
                inter = cls & mask
                if inter and inter != cls:
                    nxt.append(inter)
                    nxt.append(cls & ~mask)
                else:
                    nxt.append(cls)
            classes = nxt
    # one representative byte per class
    reps = []
    for cls in classes:
        reps.append((cls, (cls & -cls).bit_length() - 1))

    start = _eps_closure(nfa, frozenset([entry]))
    index = {start: 0}
    order = [start]
    trans: list[dict[int, int]] = [dict()]
    i = 0
    while i < len(order):
        cur = order[i]
        byte_map: dict[int, int] = {}
        for cls, _rep in reps:
            moved = set()
            for s in cur:
                for mask, dst in nfa.edges[s]:
                    if mask & cls:
                        moved.add(dst)
            if not moved:
                continue
            nxt = _eps_closure(nfa, frozenset(moved))
            j = index.get(nxt)
            if j is None:
                j = index[nxt] = len(order)
                order.append(nxt)
                trans.append(dict())
                if len(order) > max_states:
                    raise GrammarBudgetError(
                        f"regex compiles past the {max_states}-state "
                        f"budget"
                    )
            m = cls
            while m:
                b = (m & -m).bit_length() - 1
                byte_map[b] = j
                m &= m - 1
        trans[i] = byte_map
        i += 1
    accepting = [exit_ in st for st in order]

    # prune states that cannot reach an accepting state (dead ends
    # would otherwise stall the decode with an all-masked row)
    n = len(order)
    rev: list[set[int]] = [set() for _ in range(n)]
    for s, bm in enumerate(trans):
        for dst in bm.values():
            rev[dst].add(s)
    live = {s for s in range(n) if accepting[s]}
    stack = list(live)
    while stack:
        s = stack.pop()
        for p in rev[s]:
            if p not in live:
                live.add(p)
                stack.append(p)
    if 0 not in live:
        raise GrammarError("regex matches nothing")
    remap = {}
    for s in range(n):
        if s in live:
            remap[s] = len(remap)
    p_trans = []
    p_acc = []
    for s in range(n):
        if s not in live:
            continue
        p_trans.append({b: remap[d] for b, d in trans[s].items()
                        if d in live})
        p_acc.append(accepting[s])
    return p_trans, p_acc


# -- token-level compilation ------------------------------------------------


def default_token_bytes(vocab_size: int) -> list[bytes | None]:
    """The repo's serving default: a raw byte-level vocabulary where
    token ``i`` IS byte ``i`` (the HTTP layer's latin-1 convention).
    Tokens past 255 have no byte string and are never permitted."""
    return [bytes([i]) if i < 256 else None
            for i in range(int(vocab_size))]


class CompiledGrammar:
    """One grammar lowered to token tables (grammar-local states)."""

    __slots__ = ("key", "n_states", "start", "trans", "mask_words",
                 "accepting", "vocab_size", "eos_token")

    def __init__(self, key: str, trans: np.ndarray, mask_words: np.ndarray,
                 accepting: np.ndarray, start: int, eos_token: int):
        self.key = key
        self.trans = trans            # (S, V) int32, -1 = not permitted
        self.mask_words = mask_words  # (S, ceil(V/32)) uint32
        self.accepting = accepting    # (S,) bool
        self.n_states = int(trans.shape[0])
        self.vocab_size = int(trans.shape[1])
        self.start = int(start)
        self.eos_token = int(eos_token)

    def allows(self, state: int, token: int) -> bool:
        return bool(
            (self.mask_words[state, token >> 5] >> (token & 31)) & 1
        )

    def advance(self, state: int, token: int) -> int:
        nxt = int(self.trans[state, token])
        if nxt < 0:
            raise GrammarError(
                f"token {token} not permitted in state {state}"
            )
        return nxt

    def matches(self, tokens) -> bool:
        """Host-side validation: does the token stream (EOS excluded)
        land in an accepting state with every step permitted?"""
        s = self.start
        for t in tokens:
            t = int(t)
            if t == self.eos_token:
                return bool(self.accepting[s])
            if not self.allows(s, t):
                return False
            s = int(self.trans[s, t])
        return bool(self.accepting[s])


def _pack_masks(allowed: np.ndarray) -> np.ndarray:
    """(S, V) bool -> (S, ceil(V/32)) uint32, bit t of word t//32."""
    S, V = allowed.shape
    W = (V + 31) // 32
    padded = np.zeros((S, W * 32), np.uint8)
    padded[:, :V] = allowed.astype(np.uint8)
    bits = padded.reshape(S, W, 32).astype(np.uint32)
    shifts = np.arange(32, dtype=np.uint32)
    return (bits << shifts[None, None, :]).sum(axis=2, dtype=np.uint32)


def _dfa_to_tokens(byte_trans, accepting, token_bytes, eos_token,
                   key: str) -> CompiledGrammar:
    S = len(byte_trans)
    V = len(token_bytes)
    eos_token = int(eos_token)
    if not (0 <= eos_token < V):
        raise GrammarError(
            f"eos_token {eos_token} outside vocabulary of {V}"
        )
    trans = np.full((S, V), -1, np.int32)
    for t, tb in enumerate(token_bytes):
        if tb is None or t == eos_token or len(tb) == 0:
            continue
        for s in range(S):
            cur = s
            ok = True
            for b in tb:
                nxt = byte_trans[cur].get(b)
                if nxt is None:
                    ok = False
                    break
                cur = nxt
            if ok:
                trans[s, t] = cur
    acc = np.asarray(accepting, bool)
    # EOS: permitted exactly in accepting states, as a self-loop — the
    # engine's EOS machinery retires the stream on it
    trans[acc, eos_token] = np.nonzero(acc)[0].astype(np.int32)
    allowed = trans >= 0
    return CompiledGrammar(key, trans, _pack_masks(allowed), acc, 0,
                           eos_token)


def compile_regex(pattern: str, token_bytes, eos_token: int,
                  max_states: int = DEFAULT_MAX_STATES,
                  key: str | None = None) -> CompiledGrammar:
    byte_trans, accepting = _regex_to_dfa(pattern, max_states)
    if key is None:
        key = grammar_key("regex", pattern, "bytes",
                          eos_token, len(token_bytes))
    return _dfa_to_tokens(byte_trans, accepting, token_bytes,
                          eos_token, key)


# -- JSON schema lowering ---------------------------------------------------

_RE_SPECIAL = set(b".^$*+?()[]{}|\\-")


def _re_escape(s: str) -> str:
    out = []
    for ch in s.encode("utf-8").decode("latin-1"):
        if ord(ch) in _RE_SPECIAL:
            out.append("\\" + ch)
        else:
            out.append(ch)
    return "".join(out)


# control bytes excluded: json.loads rejects raw U+0000..U+001F inside
# strings, so the constrained stream must never be able to emit them
_STRING_RE = r'"(?:[^\x00-\x1f"\\]|\\["\\/bfnrt])*"'
_INT_RE = r"-?(?:0|[1-9][0-9]*)"
_NUMBER_RE = _INT_RE + r"(?:\.[0-9]+)?(?:[eE][+-]?[0-9]+)?"

#: arrays without an explicit maxItems are bounded here — a DFA cannot
#: count, so unbounded arrays unroll to this many items
DEFAULT_MAX_ITEMS = 8


def schema_to_regex(schema, depth: int = 0) -> str:
    """Lower a JSON-schema subset to the regex subset above. Supported:
    objects with fixed keys (``properties``, emitted in declaration
    order, all present), ``string``/``number``/``integer``/``boolean``/
    ``null``, ``enum`` of scalars, ``const``, and arrays of a supported
    ``items`` schema bounded by ``minItems``/``maxItems``. Canonical
    spacing (none) — outputs always ``json.loads``."""
    if depth > 16:
        raise GrammarError("schema nests too deep (max 16)")
    if not isinstance(schema, dict):
        raise GrammarError("schema must be an object")
    if "enum" in schema:
        opts = schema["enum"]
        if not isinstance(opts, list) or not opts:
            raise GrammarError("enum must be a non-empty list")
        return "(?:" + "|".join(
            _re_escape(json.dumps(v, separators=(",", ":")))
            for v in opts
        ) + ")"
    if "const" in schema:
        return _re_escape(
            json.dumps(schema["const"], separators=(",", ":"))
        )
    typ = schema.get("type")
    if typ == "string":
        return _STRING_RE
    if typ == "integer":
        return _INT_RE
    if typ == "number":
        return _NUMBER_RE
    if typ == "boolean":
        return "(?:true|false)"
    if typ == "null":
        return "null"
    if typ == "object":
        props = schema.get("properties")
        if not isinstance(props, dict) or not props:
            raise GrammarError(
                "object schema needs non-empty fixed 'properties'"
            )
        parts = []
        for name, sub in props.items():
            parts.append(
                _re_escape(json.dumps(str(name))) + ":"
                + schema_to_regex(sub, depth + 1)
            )
        return r"\{" + ",".join(parts) + r"\}"
    if typ == "array":
        items = schema.get("items")
        if items is None:
            raise GrammarError("array schema needs 'items'")
        lo = int(schema.get("minItems", 0))
        hi = int(schema.get("maxItems", max(lo, DEFAULT_MAX_ITEMS)))
        if lo < 0 or hi < lo:
            raise GrammarError(f"bad array bounds [{lo},{hi}]")
        if hi > 64:
            raise GrammarError("maxItems too large (max 64)")
        item = "(?:" + schema_to_regex(items, depth + 1) + ")"
        if hi == 0:
            return r"\[\]"
        body = item + "(?:," + item + "){%d,%d}" % (
            max(0, lo - 1), hi - 1
        )
        if lo == 0:
            body = "(?:" + body + ")?"
        return r"\[" + body + r"\]"
    raise GrammarError(f"unsupported schema type {typ!r}")


def compile_json_schema(schema, token_bytes, eos_token: int,
                        max_states: int = DEFAULT_MAX_STATES,
                        key: str | None = None) -> CompiledGrammar:
    pattern = schema_to_regex(schema)
    if key is None:
        key = grammar_key("json_schema", schema, "bytes",
                          eos_token, len(token_bytes))
    return compile_regex(pattern, token_bytes, eos_token, max_states,
                         key=key)


def parse_response_format(rf) -> tuple[str, object]:
    """Normalize an HTTP ``response_format`` body field to a
    ``(kind, spec)`` pair for the compile cache. Accepts the OpenAI
    shape ``{"type": "json_schema", "json_schema": {"schema": {...}}}``
    (with or without the inner ``"schema"`` wrapper) and
    ``{"type": "regex", "regex": "..."}``."""
    if not isinstance(rf, dict):
        raise GrammarError("response_format must be an object")
    typ = rf.get("type")
    if typ == "regex":
        pattern = rf.get("regex", rf.get("pattern"))
        if not isinstance(pattern, str) or not pattern:
            raise GrammarError(
                "response_format.regex must be a non-empty string"
            )
        return "regex", pattern
    if typ == "json_schema":
        spec = rf.get("json_schema", rf.get("schema"))
        if isinstance(spec, dict) and isinstance(
                spec.get("schema"), dict):
            spec = spec["schema"]
        if not isinstance(spec, dict):
            raise GrammarError(
                "response_format.json_schema must carry a schema object"
            )
        return "json_schema", spec
    raise GrammarError(
        f"response_format.type must be 'json_schema' or 'regex', "
        f"got {typ!r}"
    )


def validate_json_value(value, schema) -> bool:
    """Minimal host-side validator for the SUPPORTED schema subset
    (tests assert constrained outputs parse AND validate without an
    external jsonschema dependency). Mirrors :func:`schema_to_regex`:
    enum/const, scalar types, fixed-key objects, bounded arrays."""
    if "enum" in schema:
        return any(value == v for v in schema["enum"])
    if "const" in schema:
        return value == schema["const"]
    typ = schema.get("type")
    if typ == "string":
        return isinstance(value, str)
    if typ == "integer":
        return isinstance(value, int) and not isinstance(value, bool)
    if typ == "number":
        return (isinstance(value, (int, float))
                and not isinstance(value, bool))
    if typ == "boolean":
        return isinstance(value, bool)
    if typ == "null":
        return value is None
    if typ == "object":
        props = schema.get("properties", {})
        if not isinstance(value, dict):
            return False
        if set(value.keys()) != set(props.keys()):
            return False
        return all(
            validate_json_value(value[k], sub)
            for k, sub in props.items()
        )
    if typ == "array":
        if not isinstance(value, list):
            return False
        lo = int(schema.get("minItems", 0))
        hi = int(schema.get("maxItems",
                            max(lo, DEFAULT_MAX_ITEMS)))
        if not (lo <= len(value) <= hi):
            return False
        return all(
            validate_json_value(v, schema["items"]) for v in value
        )
    return False


def grammar_key(kind: str, spec, tokenizer_id: str, eos_token: int,
                vocab_size: int) -> str:
    """Cache identity of a compiled grammar: the constraint itself,
    the tokenizer the byte strings came from, the EOS baked into the
    accepting rows, and the vocabulary width of the tables."""
    blob = json.dumps(
        [kind, spec, tokenizer_id, int(eos_token), int(vocab_size)],
        sort_keys=True, separators=(",", ":"),
    )
    return hashlib.sha256(blob.encode()).hexdigest()


# -- compile cache ----------------------------------------------------------


class GrammarCache:
    """LRU of compiled grammars keyed by :func:`grammar_key`, with an
    optional on-disk store (one ``.npz`` per key in a directory next
    to the probe-verdict cache). ``get_or_compile`` reports how the
    grammar was obtained — ``"hit"`` (memory or disk) or ``"miss"``
    (freshly compiled) — for the
    ``serve_grammar_compiles_total{result}`` metrics."""

    def __init__(self, path: str | None = None, cap: int = 64):
        self._lock = threading.Lock()
        self._mem: OrderedDict[str, CompiledGrammar] = OrderedDict()
        self._cap = max(1, int(cap))
        self._dir = path
        if path:
            os.makedirs(path, exist_ok=True)

    def __len__(self) -> int:
        with self._lock:
            return len(self._mem)

    def _disk_path(self, key: str) -> str | None:
        return os.path.join(self._dir, key + ".npz") if self._dir else None

    def _load_disk(self, key: str) -> CompiledGrammar | None:
        p = self._disk_path(key)
        if p is None or not os.path.exists(p):
            return None
        try:
            with np.load(p) as z:
                return CompiledGrammar(
                    key, z["trans"].astype(np.int32),
                    z["mask_words"].astype(np.uint32),
                    z["accepting"].astype(bool),
                    int(z["start"]), int(z["eos_token"]),
                )
        except Exception:  # noqa: BLE001 — corrupt cache entry = miss
            return None

    def _store_disk(self, cg: CompiledGrammar) -> None:
        p = self._disk_path(cg.key)
        if p is None:
            return
        try:
            fd, tmp = tempfile.mkstemp(dir=self._dir, suffix=".tmp")
            with os.fdopen(fd, "wb") as f:
                np.savez(
                    f, trans=cg.trans, mask_words=cg.mask_words,
                    accepting=cg.accepting,
                    start=np.int32(cg.start),
                    eos_token=np.int32(cg.eos_token),
                )
            os.replace(tmp, p)
        except OSError:
            pass  # cache write failure is never a request failure

    def get_or_compile(self, kind: str, spec, token_bytes,
                       eos_token: int, tokenizer_id: str = "bytes",
                       max_states: int = DEFAULT_MAX_STATES,
                       ) -> tuple[CompiledGrammar, str]:
        key = grammar_key(kind, spec, tokenizer_id, eos_token,
                          len(token_bytes))
        with self._lock:
            cg = self._mem.get(key)
            if cg is not None:
                self._mem.move_to_end(key)
                return cg, "hit"
        cg = self._load_disk(key)
        result = "hit"
        if cg is None:
            result = "miss"
            if kind == "regex":
                cg = compile_regex(spec, token_bytes, eos_token,
                                   max_states, key=key)
            elif kind == "json_schema":
                cg = compile_json_schema(spec, token_bytes, eos_token,
                                         max_states, key=key)
            else:
                raise GrammarError(f"unknown grammar kind {kind!r}")
            self._store_disk(cg)
        with self._lock:
            self._mem[key] = cg
            self._mem.move_to_end(key)
            while len(self._mem) > self._cap:
                self._mem.popitem(last=False)
        return cg, result


# -- engine-side combined table ---------------------------------------------


class GrammarTable:
    """Fixed-capacity combined mask/transition table over every
    grammar currently seated in an engine. Row 0 is the unconstrained
    sentinel (all-permitted mask, identity-ish transitions) — the
    masked step folds it out with ``jnp.where(state > 0)``, so the row
    contents never reach an unconstrained stream. Each grammar is
    seated at a base offset with a refcount; retiring the last request
    drops the refcount to 0, and seat-time pressure evicts refcount-0
    grammars LRU-first. Live slots hold ABSOLUTE state indices into
    this table, so a seated grammar's rows NEVER move — freed rows go
    to an extent free-list (first-fit) instead of compacting.
    ``version`` bumps on every host-table mutation so the engine
    refreshes its device copies exactly when needed."""

    def __init__(self, capacity: int, vocab_size: int):
        self.capacity = int(capacity)
        self.vocab_size = int(vocab_size)
        W = (self.vocab_size + 31) // 32
        self.mask_words = np.zeros((self.capacity, W), np.uint32)
        self.trans = np.zeros((self.capacity, self.vocab_size), np.int32)
        # sentinel row 0: every token permitted, state stays 0
        self.mask_words[0] = np.uint32(0xFFFFFFFF)
        self.version = 1
        self._seated: dict[str, dict] = {}  # key -> {base, n, refs, lru}
        self._free: list[tuple[int, int]] = [(1, self.capacity - 1)]
        self._lru = 0

    @property
    def rows_used(self) -> int:
        return 1 + sum(e["n"] for e in self._seated.values())

    def _alloc(self, n: int) -> int | None:
        for i, (s, ln) in enumerate(self._free):
            if ln >= n:
                if ln == n:
                    del self._free[i]
                else:
                    self._free[i] = (s + n, ln - n)
                return s
        return None

    def _release_rows(self, start: int, n: int) -> None:
        self._free.append((start, n))
        self._free.sort()
        merged: list[tuple[int, int]] = []
        for s, ln in self._free:
            if merged and merged[-1][0] + merged[-1][1] == s:
                merged[-1] = (merged[-1][0], merged[-1][1] + ln)
            else:
                merged.append((s, ln))
        self._free = merged

    def _evict(self, key: str) -> None:
        e = self._seated.pop(key)
        self.mask_words[e["base"]:e["base"] + e["n"]] = 0
        self.trans[e["base"]:e["base"] + e["n"]] = 0
        self._release_rows(e["base"], e["n"])
        self.version += 1

    def seat(self, cg: CompiledGrammar) -> int:
        """Seat (or re-reference) a compiled grammar; returns the
        ABSOLUTE start state (base + cg.start). Raises
        :class:`GrammarBudgetError` when even eviction cannot fit
        it."""
        if cg.vocab_size != self.vocab_size:
            raise GrammarError(
                f"grammar compiled for V={cg.vocab_size}, table is "
                f"V={self.vocab_size}"
            )
        self._lru += 1
        e = self._seated.get(cg.key)
        if e is not None:
            e["refs"] += 1
            e["lru"] = self._lru
            return e["base"] + cg.start
        n = cg.n_states
        if n > self.capacity - 1:
            raise GrammarBudgetError(
                f"grammar needs {n} states, table capacity is "
                f"{self.capacity - 1}"
            )
        base = self._alloc(n)
        if base is None:
            idle = sorted(
                (k for k, e in self._seated.items() if e["refs"] == 0),
                key=lambda k: self._seated[k]["lru"],
            )
            for k in idle:
                self._evict(k)
                base = self._alloc(n)
                if base is not None:
                    break
        if base is None:
            raise GrammarBudgetError(
                f"grammar table full ({self.rows_used}/{self.capacity} "
                f"rows pinned by live requests)"
            )
        self.mask_words[base:base + n] = cg.mask_words
        t = cg.trans.astype(np.int64)
        self.trans[base:base + n] = np.where(
            t >= 0, t + base, 0
        ).astype(np.int32)
        self._seated[cg.key] = {
            "base": base, "n": n, "refs": 1, "lru": self._lru,
        }
        self.version += 1
        return base + cg.start

    def base_of(self, key: str) -> int | None:
        e = self._seated.get(key)
        return None if e is None else e["base"]

    def release(self, key: str) -> None:
        e = self._seated.get(key)
        if e is not None and e["refs"] > 0:
            e["refs"] -= 1

    def advance(self, state: int, token: int) -> int:
        """Host-mirror transition (absolute states; 0 stays 0)."""
        if state <= 0:
            return 0
        return int(self.trans[state, token])

    def allows(self, state: int, token: int) -> bool:
        if state <= 0:
            return True
        return bool(
            (self.mask_words[state, token >> 5] >> (token & 31)) & 1
        )


# -- stop sequences ---------------------------------------------------------


class StopMatcher:
    """Rolling suffix matcher for stop sequences over a token stream.

    Emission is hold-back buffered: a token is released only once it
    can no longer be part of a completed stop sequence, so an SSE
    stream never leaks a partial stop string. ``push`` returns
    ``(emitted, stripped)`` — ``stripped`` is the matched stop
    sequence's length (0 while no stop fired); on a match the held
    tokens ARE the stop sequence and are dropped, and the caller
    truncates the last ``stripped`` tokens from its record. ``flush``
    releases the hold-back when the stream ends for any other reason
    (EOS / budget)."""

    __slots__ = ("stops", "held")

    def __init__(self, stops):
        self.stops = [tuple(int(t) for t in s) for s in stops]
        if not self.stops or any(not s for s in self.stops):
            raise ValueError("stop sequences must be non-empty")
        self.held: list[int] = []

    def _longest_suffix_prefix(self) -> int:
        best = 0
        h = self.held
        for s in self.stops:
            top = min(len(s) - 1, len(h))
            for k in range(top, 0, -1):
                if k > best and tuple(h[-k:]) == s[:k]:
                    best = k
                    break
        return best

    def push(self, tok: int) -> tuple[list[int], int]:
        self.held.append(int(tok))
        for s in self.stops:
            if (len(self.held) >= len(s)
                    and tuple(self.held[-len(s):]) == s):
                emitted = self.held[:-len(s)]
                self.held = []
                return emitted, len(s)
        k = self._longest_suffix_prefix()
        if k == 0:
            emitted, self.held = self.held, []
            return emitted, 0
        emitted = self.held[:-k]
        self.held = self.held[-k:]
        return emitted, 0

    def flush(self) -> list[int]:
        emitted, self.held = self.held, []
        return emitted
