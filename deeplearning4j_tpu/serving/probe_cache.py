"""On-disk cache of parity-probe verdicts.

The engine gates every fast path — chunked crash replay, prefix-cache
reuse, batched admission, tensor-parallel decode — behind a one-time
bitwise parity probe. Verdicts are pure functions of (probe, model
config, backend, program geometry): nothing about a particular process
run enters the comparison, so a verdict computed once is valid for
every later engine instance on the same machine. This module persists
them, keyed by a digest of exactly those inputs, so repeated engine
construction (replica fleets, restarts, tests) skips the cold-start
probe dispatches.

The file is a flat JSON object ``{digest: bool}``. Writes go through a
same-directory temp file + ``os.replace`` so concurrent engines never
read a torn file; a corrupt or unreadable file degrades to an empty
cache (the probe just runs again). Losing a race between two writers
drops at most the other writer's fresh verdicts for this process — the
next engine recomputes and re-persists them.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile


def probe_key(probe: str, cfg_json: str, **geometry) -> str:
    """Stable digest for one probe verdict: the probe name, the full
    model config JSON, the backend platform + participating device
    count, and any program-geometry knobs the probe's compiled programs
    depend on (bucket sizes, slot counts, TP width...). The JAX version
    participates too: a verdict reflects the compiler that produced it,
    and an upgrade may change fusion/reduction order, so stale verdicts
    must miss rather than vouch for programs they never saw."""
    import jax

    payload = {
        "probe": probe,
        "cfg": cfg_json,
        "jax": jax.__version__,
        "platform": jax.devices()[0].platform,
        **{k: geometry[k] for k in sorted(geometry)},
    }
    blob = json.dumps(payload, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()


class ProbeCache:
    """Read-through/write-through verdict store over one JSON file."""

    def __init__(self, path: str):
        self.path = os.fspath(path)
        self._verdicts: dict[str, bool] = self._load()

    def _load(self) -> dict[str, bool]:
        try:
            with open(self.path) as f:
                data = json.load(f)
            return {
                k: bool(v) for k, v in data.items()
                if isinstance(k, str) and isinstance(v, bool)
            }
        except (OSError, ValueError):
            return {}

    def get(self, key: str) -> bool | None:
        """The persisted verdict, or None if never computed."""
        return self._verdicts.get(key)

    def put(self, key: str, verdict: bool) -> None:
        """Persist one verdict (atomic re-write of the whole file,
        merged over whatever is on disk right now)."""
        merged = self._load()
        merged.update(self._verdicts)
        merged[key] = bool(verdict)
        self._verdicts = merged
        d = os.path.dirname(self.path) or "."
        try:
            os.makedirs(d, exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
            try:
                with os.fdopen(fd, "w") as f:
                    json.dump(merged, f, sort_keys=True)
                os.replace(tmp, self.path)
            except BaseException:
                os.unlink(tmp)
                raise
        except OSError:
            # persistence is best-effort: an unwritable path costs a
            # re-probe next process, never a serving failure
            pass
