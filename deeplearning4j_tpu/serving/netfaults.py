"""Seeded in-process network chaos proxy for fleet tests.

``serving/faults.py`` injects faults INSIDE an engine at its host-side
boundaries; this module injects them BETWEEN fleet processes, on the
wire, where the resilient-RPC layer (deadlines, hedging, breakers) and
the KVSG frame validation are the code under test. A
:class:`ChaosProxy` listens on an ephemeral local port and forwards
TCP byte streams to a real target address, corrupting them per plan:

- ``refuse`` — accept then immediately close (connection refused-ish;
  drives breaker opens and hedge wins).
- ``drop`` — read the client's first chunk, forward NOTHING, close
  both sides (a request that vanishes; the client sees a reset/short
  read bounded by its socket timeout).
- ``truncate`` — forward only half of the first server→client chunk,
  then close: a mid-frame truncation. KVSG receivers must 400 this,
  HTTP clients must see a clean error — never a hang.
- ``corrupt`` — flip bytes in the first client→server chunk (corrupt
  header bytes on a KVSG push → wire validation declines with 400).
- ``latency`` — sleep ``latency_s`` before forwarding each chunk
  (drives the p99 hedge trigger deterministically).

Two injection modes, mirroring :class:`~.faults.FaultInjector`:
scripted ``plan(kind, at=k)`` fires on the k-th accepted connection
(1-based connection index, 0-based ``at``), and seeded per-connection
Bernoulli rates drawn from one ``random.Random(seed)`` in a fixed
order per connection — a given seed replays the same chaos.

``set_partition(True)`` refuses every new connection: an asymmetric
partition is two proxies with only one partitioned (A can reach B but
not vice versa). Partitions are hang-free by construction — the victim
sees connect/read errors immediately, and anything already connected
is bounded by its deadline-derived socket timeout.
"""

from __future__ import annotations

import random
import socket
import threading
import time

_KINDS = ("refuse", "drop", "truncate", "corrupt", "latency")
_CHUNK = 65536


class _Planned:
    __slots__ = ("kind", "at", "times")

    def __init__(self, kind: str, at: int, times: int):
        self.kind = kind
        self.at = at
        self.times = times


class ChaosProxy:
    """TCP forwarder to ``target=(host, port)`` with seeded faults.

    Point a fleet client at ``proxy.address`` instead of the real
    replica; per-connection faults follow the scripted plans first,
    then one seeded draw per kind in ``_KINDS`` order. Counters in
    ``self.counts`` record what actually fired.
    """

    def __init__(self, target: tuple[str, int], *, seed: int = 0,
                 latency_s: float = 0.05, latency_rate: float = 0.0,
                 drop_rate: float = 0.0, truncate_rate: float = 0.0,
                 corrupt_rate: float = 0.0, refuse_rate: float = 0.0):
        self.target = (str(target[0]), int(target[1]))
        self.latency_s = float(latency_s)
        self.rates = {
            "refuse": float(refuse_rate),
            "drop": float(drop_rate),
            "truncate": float(truncate_rate),
            "corrupt": float(corrupt_rate),
            "latency": float(latency_rate),
        }
        self._rng = random.Random(seed)
        self._rng_lock = threading.Lock()
        self._plans: list[_Planned] = []
        self._partitioned = False
        self._stopping = False
        self.n_connections = 0
        self.counts = {k: 0 for k in _KINDS}
        self.counts["refused_partition"] = 0
        self._lock = threading.Lock()
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("127.0.0.1", 0))
        self._srv.listen(64)
        self.port = self._srv.getsockname()[1]
        self.host = "127.0.0.1"
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="chaos-accept", daemon=True
        )
        self._accept_thread.start()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def plan(self, kind: str, at: int, *, times: int = 1) -> "ChaosProxy":
        """Script fault ``kind`` on the ``at``-th accepted connection
        (0-based, ``times`` consecutive). Returns self (chain)."""
        if kind not in _KINDS:
            raise ValueError(f"kind must be one of {_KINDS}, got {kind!r}")
        with self._lock:
            self._plans.append(_Planned(kind, int(at), int(times)))
        return self

    def set_partition(self, on: bool) -> None:
        """Refuse all NEW connections while on — one direction of an
        asymmetric partition (run a proxy per direction for both)."""
        with self._lock:
            self._partitioned = bool(on)

    def stop(self) -> None:
        with self._lock:
            self._stopping = True
        try:
            self._srv.close()
        except OSError:
            pass
        self._accept_thread.join(timeout=2.0)

    # -- internals ---------------------------------------------------

    def _faults_for(self, conn_idx: int) -> list[str]:
        out = []
        with self._lock:
            partitioned = self._partitioned
            for p in self._plans:
                if p.at <= conn_idx < p.at + p.times:
                    out.append(p.kind)
        if partitioned:
            return ["__partition__"]
        # seeded draws happen in fixed kind order so one seed replays
        # the same per-connection pattern regardless of thread timing
        with self._rng_lock:
            for kind in _KINDS:
                if self.rates[kind] > 0.0 and \
                        self._rng.random() < self.rates[kind]:
                    out.append(kind)
        return out

    def _accept_loop(self) -> None:
        while True:
            try:
                client, _ = self._srv.accept()
            except OSError:
                return  # listener closed by stop()
            with self._lock:
                if self._stopping:
                    client.close()
                    return
                idx = self.n_connections
                self.n_connections += 1
            faults = self._faults_for(idx)
            if "__partition__" in faults:
                self.counts["refused_partition"] += 1
                client.close()
                continue
            if "refuse" in faults:
                self.counts["refuse"] += 1
                client.close()
                continue
            threading.Thread(
                target=self._serve_conn, args=(client, faults),
                name=f"chaos-conn-{idx}", daemon=True
            ).start()

    def _serve_conn(self, client: socket.socket, faults: list[str]) -> None:
        try:
            upstream = socket.create_connection(self.target, timeout=5.0)
        except OSError:
            client.close()
            return
        for s in (client, upstream):
            s.settimeout(30.0)  # backstop; tests bound waits themselves
        for kind in faults:
            if kind in self.counts and kind != "refuse":
                self.counts[kind] += 1
        fwd = threading.Thread(
            target=self._pump, args=(client, upstream, faults, True),
            daemon=True,
        )
        rev = threading.Thread(
            target=self._pump, args=(upstream, client, faults, False),
            daemon=True,
        )
        fwd.start()
        rev.start()

    def _pump(self, src: socket.socket, dst: socket.socket,
              faults: list[str], client_to_server: bool) -> None:
        """Forward src→dst applying faults. ``drop``/``corrupt`` act on
        the first client→server chunk (the request/frame head);
        ``truncate`` acts on the first server→client chunk so the
        CLIENT sees a mid-frame cut. Any error tears down both sides —
        half-open connections are the hangs this suite exists to
        catch, so teardown is always bilateral."""
        first = True
        try:
            while True:
                try:
                    buf = src.recv(_CHUNK)
                except OSError:
                    break
                if not buf:
                    break
                if "latency" in faults:
                    time.sleep(self.latency_s)
                if first and client_to_server and "drop" in faults:
                    break  # swallow the request entirely
                if first and client_to_server and "corrupt" in faults:
                    b = bytearray(buf)
                    for i in range(0, len(b), max(1, len(b) // 16)):
                        b[i] ^= 0xFF
                    buf = bytes(b)
                if first and not client_to_server and "truncate" in faults:
                    try:
                        dst.sendall(buf[: max(1, len(buf) // 2)])
                    except OSError:
                        pass
                    break  # cut mid-frame
                try:
                    dst.sendall(buf)
                except OSError:
                    break
                first = False
        finally:
            for s in (src, dst):
                try:
                    s.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    s.close()
                except OSError:
                    pass
