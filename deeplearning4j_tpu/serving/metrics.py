"""Serving latency/utilization metrics.

Records through the existing JSONL :class:`MetricsWriter` (same format
the trainer's listener emits, so the same grep/plot tooling reads both)
and keeps in-memory series for percentile summaries:

- ``serve/ttft_seconds`` — time-to-first-token per request, measured
  from scheduler arrival (so queue wait counts — that is the number a
  user sees);
- ``serve/tpot_seconds`` — time-per-output-token per request over its
  decode phase (steps after the first token);
- ``serve/occupancy`` — ACTIVE SLOT COUNT per engine step (the
  effective decode batch; > 1 means batching actually interleaved
  requests), with the fraction as ``serve/occupancy_frac``;
- ``serve/queue_depth`` — queued (not yet admitted) requests, sampled
  per engine step;
- ``serve/queue_delay_seconds`` — submit-to-admission wait per request
  (the scheduling component of TTFT, separated out so horizon-induced
  admission latency is visible on its own);
- ``serve/sync_wait_seconds`` / ``serve/overlap_seconds`` — per
  readback, how long the host blocked on the device token sync vs how
  long it spent doing useful work (bookkeeping + next dispatch) while
  the horizon computed. ``dispatch_overlap_frac`` in ``summary()`` is
  overlap / (overlap + sync wait): ~0 means the host serializes with
  the device (the pre-pipelining behavior), near 1 means readback is
  fully hidden.

With a multi-step decode horizon (``decode_horizon`` > 1) a "step" in
the series above is one K-substep horizon dispatch; TTFT is still
measured to the host-visible first token, so it honestly includes the
up-to-K-substeps readback lag the pipeline introduces.

p50/p99 come from ``summary()``; with fewer than ~100 samples the p99
is just the max-ish tail order statistic — fine for a bench row.
"""

from __future__ import annotations

import numpy as np

from deeplearning4j_tpu.utils.metrics import MetricsWriter


def _pct(xs: list[float], p: float) -> float:
    return float(np.percentile(np.asarray(xs, np.float64), p))


class ServingMetrics:
    def __init__(self, writer: MetricsWriter | None = None,
                 prefix: str = "serve"):
        self.writer = writer
        self.prefix = prefix
        self.ttft: list[float] = []
        self.tpot: list[float] = []
        self.occupancy: list[float] = []
        self.queue_depth: list[int] = []
        self.queue_delay: list[float] = []
        self.sync_wait: list[float] = []
        self.overlap: list[float] = []
        # stamped by the engine at construction; reported in summary()
        # so a bench row records which horizon produced its numbers
        self.decode_horizon = 1
        self.n_finished = 0
        self.n_generated = 0
        # fault-tolerance counters (see serving.faults / engine docs):
        # retries = transient-fault boundary retries; restarts = engine
        # rebuilds by replay; failed/cancelled/expired = non-FINISHED
        # terminal request outcomes
        self.n_retries = 0
        self.n_restarts = 0
        self.n_failed = 0
        self.n_cancelled = 0
        self.n_expired = 0
        self._step = 0

    def _emit(self, tag: str, value: float, step: int | None = None) -> None:
        if self.writer is not None:
            self.writer.scalar(f"{self.prefix}/{tag}", value, step)

    def record_step(self, n_active: int, n_slots: int,
                    queue_depth: int) -> None:
        """Per-engine-step utilization sample (``n_active`` slots
        decoding this step, of ``n_slots``)."""
        self.occupancy.append(float(n_active))
        self.queue_depth.append(int(queue_depth))
        self._emit("occupancy", n_active, self._step)
        self._emit("occupancy_frac", n_active / n_slots, self._step)
        self._emit("queue_depth", queue_depth, self._step)
        self._step += 1

    def record_admitted(self, req_id: str, delay_s: float) -> None:
        """Request left the queue for a KV slot after ``delay_s``
        seconds of waiting (admission happens at horizon boundaries, so
        this is where decode_horizon > 1 shows up first)."""
        self.queue_delay.append(float(delay_s))
        self._emit("queue_delay_seconds", delay_s)

    def record_readback(self, sync_wait_s: float,
                        overlap_s: float) -> None:
        """One horizon readback: host blocked ``sync_wait_s`` on the
        token sync after ``overlap_s`` of overlapped host work."""
        self.sync_wait.append(float(sync_wait_s))
        self.overlap.append(float(overlap_s))
        self._emit("sync_wait_seconds", sync_wait_s)
        self._emit("overlap_seconds", overlap_s)

    def record_first_token(self, req_id: str, ttft_s: float) -> None:
        self.ttft.append(float(ttft_s))
        self._emit("ttft_seconds", ttft_s)

    def record_finished(self, req_id: str, n_tokens: int,
                        decode_s: float) -> None:
        """Request retired: ``n_tokens`` generated, ``decode_s`` wall
        seconds spent after the first token."""
        self.n_finished += 1
        self.n_generated += n_tokens
        if n_tokens > 1:
            tpot = decode_s / (n_tokens - 1)
            self.tpot.append(tpot)
            self._emit("tpot_seconds", tpot)

    def record_retry(self) -> None:
        """One transient-fault retry at an engine boundary."""
        self.n_retries += 1
        self._emit("retries_total", self.n_retries)

    def record_restart(self) -> None:
        """One engine-state rebuild by deterministic replay."""
        self.n_restarts += 1
        self._emit("restarts_total", self.n_restarts)

    def record_outcome(self, status) -> None:
        """Non-FINISHED terminal outcome (status is a
        ``RequestStatus`` or its string value)."""
        s = getattr(status, "value", status)
        if s == "failed":
            self.n_failed += 1
            self._emit("failed_total", self.n_failed)
        elif s == "cancelled":
            self.n_cancelled += 1
            self._emit("cancelled_total", self.n_cancelled)
        elif s == "expired":
            self.n_expired += 1
            self._emit("expired_total", self.n_expired)

    def summary(self) -> dict:
        """Aggregate view: p50/p99 latencies + mean utilization."""
        out = {
            "n_finished": self.n_finished,
            "n_generated": self.n_generated,
            "n_retries": self.n_retries,
            "n_restarts": self.n_restarts,
            "n_failed": self.n_failed,
            "n_cancelled": self.n_cancelled,
            "n_expired": self.n_expired,
            "steps": self._step,
            "decode_horizon": self.decode_horizon,
        }
        for name, xs in [("ttft", self.ttft), ("tpot", self.tpot),
                         ("queue_delay", self.queue_delay)]:
            if xs:
                out[f"{name}_p50_s"] = _pct(xs, 50)
                out[f"{name}_p99_s"] = _pct(xs, 99)
        if self.sync_wait:
            sync = float(np.sum(self.sync_wait))
            over = float(np.sum(self.overlap))
            out["sync_wait_mean_s"] = sync / len(self.sync_wait)
            if sync + over > 0:
                out["dispatch_overlap_frac"] = over / (sync + over)
        if self.occupancy:
            # mean slots actually decoding per step — the "effective
            # batch" a continuous batcher is supposed to keep > 1
            out["occupancy_mean"] = float(np.mean(self.occupancy))
            out["queue_depth_max"] = int(max(self.queue_depth))
        return out
