"""Serving latency/utilization metrics.

Three sinks behind one recording API, so the engine instruments each
event exactly once:

- a :class:`~deeplearning4j_tpu.obs.registry.MetricsRegistry` of
  Prometheus counters/histograms (``serve_*`` families) — what the
  serving server renders at ``GET /metrics`` for a fleet scraper;
- bounded in-memory :class:`~deeplearning4j_tpu.obs.registry.Reservoir`
  series for the ``summary()`` percentile view (exact counts/totals,
  sampled percentiles — a week of traffic costs the same memory as a
  minute);
- optionally the JSONL :class:`MetricsWriter` (same format the
  trainer's listener emits, so the same grep/plot tooling reads both).

The series:

- ``serve/ttft_seconds`` — time-to-first-token per request, measured
  from scheduler arrival (so queue wait counts — that is the number a
  user sees);
- ``serve/tpot_seconds`` — time-per-output-token per request over its
  decode phase (steps after the first token);
- ``serve/occupancy`` — ACTIVE SLOT COUNT per engine step (the
  effective decode batch; > 1 means batching actually interleaved
  requests), with the fraction as ``serve/occupancy_frac``;
- ``serve/queue_depth`` — queued (not yet admitted) requests, sampled
  per engine step;
- ``serve/queue_delay_seconds`` — submit-to-admission wait per request
  (the scheduling component of TTFT, separated out so horizon-induced
  admission latency is visible on its own);
- ``serve/sync_wait_seconds`` / ``serve/overlap_seconds`` — per
  readback, how long the host blocked on the device token sync vs how
  long it spent doing useful work (bookkeeping + next dispatch) while
  the horizon computed. ``dispatch_overlap_frac`` in ``summary()`` is
  overlap / (overlap + sync wait): ~0 means the host serializes with
  the device (the pre-pipelining behavior), near 1 means readback is
  fully hidden.

Per-phase accounting: every recorded second is also attributed to one
of four request phases — ``queue`` (submit → admission), ``prefill``
(admission prefill wall time), ``decode`` (horizon dispatch → token
block arrival), ``sync`` (the blocking slice of decode: the host-side
``np.asarray`` wait) — accumulated exactly in ``phase_seconds`` and
exported both as a labelled Prometheus histogram
(``serve_phase_seconds{phase=...}``) and as ``phase_frac`` in
``summary()``. This is the breakdown that justifies (or kills) tuning
work: an adaptive decode horizon only pays if ``queue`` dominates, a
batched same-bucket admission only if ``prefill`` does. Note ``sync``
is a sub-interval of ``decode`` (fractions tell where time GOES, not a
partition of wall time).

With a multi-step decode horizon (``decode_horizon`` > 1) a "step" in
the series above is one K-substep horizon dispatch; TTFT is still
measured to the host-visible first token, so it honestly includes the
up-to-K-substeps readback lag the pipeline introduces.

Multi-tenant serving adds a ``tenant`` dimension: terminal outcomes,
generated tokens, and rejections get tenant-labelled Prometheus
families (``serve_tenant_requests_total``/``serve_tenant_tokens_total``
/``serve_rejections_total``), and per-tenant TPOT/queue-delay
reservoirs feed a ``tenants`` block in ``summary()``. Single-tenant
deployments pay nothing: the tenant state is created lazily on the
first event that carries a non-empty tenant id, and all the unlabelled
families above are recorded exactly as before.

p50/p99 come from ``summary()``; with fewer than ~100 samples the p99
is just the max-ish tail order statistic — fine for a bench row.
"""

from __future__ import annotations

import threading

import numpy as np

from deeplearning4j_tpu.analysis.sanitizers import note_access, wrap_lock
from deeplearning4j_tpu.obs.registry import MetricsRegistry, Reservoir
from deeplearning4j_tpu.utils.metrics import MetricsWriter

#: the four request phases the per-phase breakdown attributes time to
PHASES = ("queue", "prefill", "decode", "sync")

#: reservoir size for the latency series (uniform sample; exact
#: n/total/min/max are kept alongside)
RESERVOIR_CAP = 4096

#: peak dense matmul FLOP/s per chip by jax device_kind prefix (bf16
#: inputs, f32 accumulation — the MXU-native rate; same table the
#: bench harness reports MFU against, duplicated here because the
#: package cannot import the repo-root bench script)
_PEAK_FLOPS = (
    ("TPU v6", 918e12),   # Trillium
    ("TPU v5p", 459e12),
    ("TPU v5 lite", 197e12),  # v5e
    ("TPU v5", 459e12),
    ("TPU v4", 275e12),
)

#: peak HBM bandwidth per chip (bytes/s), by device_kind prefix
_PEAK_HBM_BW = (
    ("TPU v6", 1640e9),   # Trillium
    ("TPU v5p", 2765e9),
    ("TPU v5 lite", 819e9),  # v5e
    ("TPU v5", 2765e9),
    ("TPU v4", 1228e9),
)

#: generous non-TPU fallbacks (modern server CPU with all cores +
#: AMX-class units / DDR5 channels) — on CI the gauges must stay
#: defined and inside (0, 1], not be calibrated
_FALLBACK_PEAK_FLOPS = 5e12
_FALLBACK_PEAK_HBM_BW = 1e12


def _device_peaks() -> tuple[float, float]:
    """``(peak flop/s, peak bytes/s)`` for device 0: table-resolved on
    TPU, the generous fallback elsewhere (the gauge help strings say
    which regime is calibrated)."""
    try:
        import jax

        dev = jax.devices()[0]
        if dev.platform == "tpu":
            kind = getattr(dev, "device_kind", "")
            flops = next(
                (p for pre, p in _PEAK_FLOPS if kind.startswith(pre)),
                _PEAK_FLOPS[-1][1],
            )
            bw = next(
                (p for pre, p in _PEAK_HBM_BW if kind.startswith(pre)),
                _PEAK_HBM_BW[-1][1],
            )
            return flops, bw
    except Exception:
        pass
    return _FALLBACK_PEAK_FLOPS, _FALLBACK_PEAK_HBM_BW


def _pct(res: Reservoir, p: float) -> float:
    return float(np.percentile(np.asarray(res.values, np.float64), p))


class ServingMetrics:
    def __init__(self, writer: MetricsWriter | None = None,
                 prefix: str = "serve",
                 registry: MetricsRegistry | None = None,
                 reservoir_cap: int = RESERVOIR_CAP):
        self.writer = writer
        self.prefix = prefix
        self.registry = registry if registry is not None else MetricsRegistry()
        self.ttft = Reservoir(reservoir_cap)
        self.tpot = Reservoir(reservoir_cap)
        self.occupancy = Reservoir(reservoir_cap)
        self.queue_depth = Reservoir(reservoir_cap)
        self.queue_delay = Reservoir(reservoir_cap)
        self.sync_wait = Reservoir(reservoir_cap)
        self.overlap = Reservoir(reservoir_cap)
        # exact per-phase wall-second totals (see module docstring)
        self.phase_seconds = {p: 0.0 for p in PHASES}
        # per-program-family device-time attribution (record_program):
        # measured at the horizon-readback boundary by the engine
        # thread only, like phase_seconds, so no lock
        self.program_seconds: dict[str, float] = {}
        self.program_dispatches: dict[str, int] = {}
        self._family_budgets: dict | None = None  # lazy .graftaudit.json
        self._peaks: tuple[float, float] | None = None  # lazy device peek
        # stamped by the engine at construction; reported in summary()
        # so a bench row records which horizon produced its numbers
        self.decode_horizon = 1
        self.n_finished = 0
        self.n_generated = 0
        # fault-tolerance counters (see serving.faults / engine docs):
        # retries = transient-fault boundary retries; restarts = engine
        # rebuilds by replay; failed/cancelled/expired = non-FINISHED
        # terminal request outcomes
        self.n_retries = 0
        self.n_restarts = 0
        self.n_failed = 0
        self.n_cancelled = 0
        self.n_expired = 0
        self.n_backpressure = 0
        # prefix-cache counters (see serving.prefix_cache): lookups by
        # outcome, prompt tokens whose prefill was skipped because their
        # KV came from a cached segment, segments inserted/evicted
        self.n_prefix_hits_full = 0
        self.n_prefix_hits_partial = 0
        self.n_prefix_misses = 0
        self.prefix_tokens_saved = 0
        self.n_prefix_inserts = 0
        self.n_prefix_evictions = 0
        # admissions coalesced into shared same-bucket prefill dispatches
        self.n_batched_admissions = 0
        # chunked-prefill piggyback (see engine): bounded prefill
        # chunks executed for deferred admissions (fused into a decode
        # dispatch or standalone), their token total, and wall seconds
        # occupied decode slots sat behind admission prefill work
        self.n_prefill_chunks = 0
        self.prefill_chunk_tokens = 0
        self.decode_stall_seconds = 0.0
        # embedding requests served host-side (no KV slot)
        self.n_embeddings = 0
        self.embed_latency = Reservoir(reservoir_cap)
        # disaggregated prefill/decode counters (see serving.disagg):
        # exports = segments prefilled here for another replica,
        # ingests = wire segments offered to the local prefix cache
        # (stored or declined), transfers = push attempts to a decode
        # replica's /v1/kv_segment, recorded by the HTTP layer
        self.n_kv_exports = 0
        self.kv_export_bytes = 0
        self.n_kv_ingests_stored = 0
        self.n_kv_ingests_declined = 0
        self.kv_ingest_bytes = 0
        self.n_transfers = 0
        self.n_transfer_failures = 0
        self.transfer_bytes = 0
        self.transfer_seconds = 0.0
        self.kv_export_latency = Reservoir(reservoir_cap)
        self.kv_ingest_latency = Reservoir(reservoir_cap)
        self.transfer_latency = Reservoir(reservoir_cap)
        # live session migration (drain-time export/seat/settle):
        # exports = slots parked here and shipped out, seats = migrated
        # sessions offered to this engine (seated or declined),
        # settlements = parked requests resolved by the destination's
        # outcome (ok) or by the fail fallback (failed)
        self.n_migrations_out = 0
        self.n_migrations_seated = 0
        self.n_migrations_declined = 0
        self.n_migrations_settled_ok = 0
        self.n_migrations_settled_failed = 0
        self.migration_seat_latency = Reservoir(reservoir_cap)
        self._reservoir_cap = reservoir_cap
        # per-tenant state, created lazily on the first event carrying a
        # non-empty tenant id. HTTP handler threads record rejections
        # while the engine thread records finishes, so creation and the
        # exact counters move under a lock (the Prometheus counters have
        # their own).
        self._tlock = wrap_lock(threading.Lock(), "metrics._tlock")
        self._tenants: dict[str, dict] = {}  # guarded-by: _tlock
        self.n_rejections: dict[str, int] = {}  # guarded-by: _tlock
        # tenant_id -> p99 TPOT objective in seconds; the burn gauge is
        # derived from the per-tenant reservoir at render time
        self._tenant_slos: dict[str, float] = {}  # guarded-by: _tlock
        self._step = 0

        # Prometheus instruments (get-or-create: a shared registry can
        # back several metrics objects without double registration)
        reg = self.registry
        self._c_requests = reg.counter(
            "serve_requests_total",
            "Terminal request outcomes by status.", ("outcome",),
        )
        self._c_tokens = reg.counter(
            "serve_tokens_generated_total", "Tokens generated (all requests).",
        )
        self._c_steps = reg.counter(
            "serve_engine_steps_total",
            "Decode horizons dispatched (K substeps each).",
        )
        self._c_retries = reg.counter(
            "serve_retries_total", "Transient-fault boundary retries.",
        )
        self._c_restarts = reg.counter(
            "serve_restarts_total", "Engine rebuilds by deterministic replay.",
        )
        self._c_backpressure = reg.counter(
            "serve_backpressure_total",
            "Submits rejected at max queue depth (HTTP 429).",
        )
        self._h_ttft = reg.histogram(
            "serve_ttft_seconds",
            "Time to first token, from scheduler arrival.",
        )
        self._h_tpot = reg.histogram(
            "serve_tpot_seconds", "Time per output token after the first.",
        )
        self._h_phase = reg.histogram(
            "serve_phase_seconds",
            "Per-event wall seconds by request phase "
            "(queue|prefill|decode|sync).", ("phase",),
        )
        self._c_prefix_lookups = reg.counter(
            "serve_prefix_lookups_total",
            "Prefix-cache lookups by outcome "
            "(hit_full|hit_partial|miss).", ("result",),
        )
        self._c_prefix_saved = reg.counter(
            "serve_prefix_tokens_saved_total",
            "Prompt tokens served from cached KV instead of prefill.",
        )
        self._c_prefix_inserts = reg.counter(
            "serve_prefix_inserts_total", "Prefix segments cached.",
        )
        self._c_prefix_evictions = reg.counter(
            "serve_prefix_evictions_total",
            "Prefix segments evicted (LRU, never pinned ones).",
        )
        self._c_batched = reg.counter(
            "serve_prefill_batched_total",
            "Admissions coalesced into shared same-bucket prefill "
            "dispatches.",
        )
        self._c_prefill_chunks = reg.counter(
            "serve_prefill_chunks_total",
            "Bounded prefill chunks executed for deferred piggyback "
            "admissions (fused or standalone).",
        )
        self._c_decode_stall = reg.counter(
            "serve_decode_stall_seconds_total",
            "Wall seconds occupied decode slots sat stalled behind "
            "admission prefill work.",
        )
        self._c_rejections = reg.counter(
            "serve_rejections_total",
            "Submits shed before queueing, by reason "
            "(backpressure|quota) and tenant.", ("reason", "tenant"),
        )
        self._c_tenant_requests = reg.counter(
            "serve_tenant_requests_total",
            "Terminal request outcomes by tenant.", ("tenant", "outcome"),
        )
        self._c_tenant_tokens = reg.counter(
            "serve_tenant_tokens_total",
            "Tokens generated per tenant.", ("tenant",),
        )
        self._g_slo_burn = reg.gauge(
            "serve_tenant_slo_burn",
            "Observed p99 TPOT / tenant SLO objective (> 1 = violating).",
            ("tenant",),
        )
        self._c_embeddings = reg.counter(
            "serve_embeddings_total",
            "Embedding requests served, by model.", ("model",),
        )
        self._h_embed = reg.histogram(
            "serve_embedding_seconds",
            "Embedding request service time (host-side lookup).",
        )
        self._c_kv_exports = reg.counter(
            "serve_kv_exports_total",
            "KV segments prefilled here and exported for a decode "
            "replica (disaggregated serving).",
        )
        self._c_kv_export_bytes = reg.counter(
            "serve_kv_export_bytes_total",
            "Raw segment bytes exported over the KV wire.",
        )
        self._h_kv_export = reg.histogram(
            "serve_kv_export_seconds",
            "Export service time: prefill + host snapshot.",
        )
        self._c_kv_ingests = reg.counter(
            "serve_kv_ingests_total",
            "Wire KV segments offered to the local prefix cache, by "
            "result (stored|declined).", ("result",),
        )
        self._c_kv_ingest_bytes = reg.counter(
            "serve_kv_ingest_bytes_total",
            "Raw segment bytes received over the KV wire.",
        )
        self._h_kv_ingest = reg.histogram(
            "serve_kv_ingest_seconds",
            "Ingest service time: validate + device seat.",
        )
        self._c_transfers = reg.counter(
            "serve_transfers_total",
            "KV segment pushes to a decode replica, by result "
            "(ok|failed).", ("result",),
        )
        self._c_transfer_bytes = reg.counter(
            "serve_transfer_bytes_total",
            "Frame bytes pushed to decode replicas over the KV wire.",
        )
        self._h_transfer = reg.histogram(
            "serve_transfer_seconds",
            "One KV segment push: POST /v1/kv_segment round trip.",
        )
        self._c_migrations_out = reg.counter(
            "serve_migrations_out_total",
            "Live sessions exported (parked) at drain for re-seating "
            "on another replica.",
        )
        self._c_migrations_in = reg.counter(
            "serve_migrations_in_total",
            "Migrated live sessions offered to this engine, by result "
            "(seated|declined).", ("result",),
        )
        self._c_migrations_settled = reg.counter(
            "serve_migrations_settled_total",
            "Parked requests resolved, by result (ok = destination "
            "finished the stream, failed = fallback preemption).",
            ("result",),
        )
        self._h_migration_seat = reg.histogram(
            "serve_migration_seat_seconds",
            "One migrated session seat: validate + device insert.",
        )
        self._c_grammar_compiles = reg.counter(
            "serve_grammar_compiles_total",
            "Grammar constraint resolutions at submit, by result "
            "(hit = LRU/disk cache, miss = fresh DFA compile, "
            "error = rejected 400).", ("result",),
        )
        self._c_stop_hits = reg.counter(
            "serve_stop_hits_total",
            "Requests finished by a stop-sequence match (host-side "
            "suffix match at readback).",
        )
        self._c_prog_seconds = reg.counter(
            "serve_program_seconds_total",
            "Wall seconds attributed to compiled program families at "
            "the horizon-readback boundary (dispatch call to "
            "post-sync flush — an honest upper bound that includes "
            "async overlap).", ("family",),
        )
        self._c_prog_dispatches = reg.counter(
            "serve_program_dispatches_total",
            "Program dispatches by compiled family.", ("family",),
        )
        self._g_mfu = reg.gauge(
            "serve_mfu",
            "Live model-flop utilization per program family: audited "
            "envelope flops x dispatches / measured seconds / device "
            "peak, clamped to 1. Exact at the committed audit "
            "geometry; a scale reference otherwise.", ("family",),
        )
        self._g_mbu = reg.gauge(
            "serve_mbu",
            "Live memory-bandwidth utilization per program family: "
            "audited arg+out bytes x dispatches / measured seconds / "
            "peak HBM bandwidth, clamped to 1. Exact at the committed "
            "audit geometry; a scale reference otherwise.", ("family",),
        )

    def _emit(self, tag: str, value: float, step: int | None = None) -> None:
        if self.writer is not None:
            self.writer.scalar(f"{self.prefix}/{tag}", value, step)

    def _tenant(self, tenant_id: str) -> dict:  # lint: holds _tlock
        """Per-tenant exact counters + reservoirs. Call holding
        ``_tlock``."""
        st = self._tenants.get(tenant_id)
        if st is None:
            note_access("metrics.tenants", write=True)
            st = self._tenants[tenant_id] = {
                "tpot": Reservoir(self._reservoir_cap),
                "queue_delay": Reservoir(self._reservoir_cap),
                "n_finished": 0,
                "n_generated": 0,
                "n_rejected": 0,
                "n_other": 0,
            }
        return st

    def record_phase(self, phase: str, seconds: float) -> None:
        """Attribute ``seconds`` of wall time to a request phase."""
        self.phase_seconds[phase] += seconds
        self._h_phase.observe(seconds, phase=phase)

    def record_program(self, family: str, seconds: float) -> None:
        """Attribute one program dispatch's measured wall interval to
        its compiled family. The engine calls this at the horizon-
        readback boundary (after THE designated sync), so ``seconds``
        spans dispatch call → proven-complete — an honest upper bound
        that includes whatever host work overlapped the device."""
        self.program_seconds[family] = (
            self.program_seconds.get(family, 0.0) + float(seconds)
        )
        self.program_dispatches[family] = (
            self.program_dispatches.get(family, 0) + 1
        )
        self._c_prog_seconds.inc(float(seconds), family=family)
        self._c_prog_dispatches.inc(family=family)

    def record_step(self, n_active: int, n_slots: int,
                    queue_depth: int) -> None:
        """Per-engine-step utilization sample (``n_active`` slots
        decoding this step, of ``n_slots``)."""
        self.occupancy.add(float(n_active))
        self.queue_depth.add(int(queue_depth))
        self._c_steps.inc()
        self._emit("occupancy", n_active, self._step)
        self._emit("occupancy_frac", n_active / n_slots, self._step)
        self._emit("queue_depth", queue_depth, self._step)
        self._step += 1

    def record_admitted(self, req_id: str, delay_s: float,
                        tenant: str = "") -> None:
        """Request left the queue for a KV slot after ``delay_s``
        seconds of waiting (admission happens at horizon boundaries, so
        this is where decode_horizon > 1 shows up first)."""
        self.queue_delay.add(float(delay_s))
        self.record_phase("queue", float(delay_s))
        self._emit("queue_delay_seconds", delay_s)
        if tenant:
            with self._tlock:
                self._tenant(tenant)["queue_delay"].add(float(delay_s))

    def record_prefill(self, req_id: str, seconds: float) -> None:
        """One admission prefill (all bucket/chunk dispatches)."""
        self.record_phase("prefill", float(seconds))

    def record_readback(self, sync_wait_s: float,
                        overlap_s: float) -> None:
        """One horizon readback: host blocked ``sync_wait_s`` on the
        token sync after ``overlap_s`` of overlapped host work. The
        horizon's decode interval (dispatch → block arrival) is their
        sum."""
        self.sync_wait.add(float(sync_wait_s))
        self.overlap.add(float(overlap_s))
        self.record_phase("decode", float(sync_wait_s) + float(overlap_s))
        self.record_phase("sync", float(sync_wait_s))
        self._emit("sync_wait_seconds", sync_wait_s)
        self._emit("overlap_seconds", overlap_s)

    def record_first_token(self, req_id: str, ttft_s: float) -> None:
        self.ttft.add(float(ttft_s))
        self._h_ttft.observe(ttft_s)
        self._emit("ttft_seconds", ttft_s)

    def record_finished(self, req_id: str, n_tokens: int,
                        decode_s: float, tenant: str = "") -> None:
        """Request retired: ``n_tokens`` generated, ``decode_s`` wall
        seconds spent after the first token."""
        self.n_finished += 1
        self.n_generated += n_tokens
        self._c_requests.inc(outcome="finished")
        self._c_tokens.inc(n_tokens)
        tpot = None
        if n_tokens > 1:
            tpot = decode_s / (n_tokens - 1)
            self.tpot.add(tpot)
            self._h_tpot.observe(tpot)
            self._emit("tpot_seconds", tpot)
        if tenant:
            self._c_tenant_requests.inc(tenant=tenant, outcome="finished")
            self._c_tenant_tokens.inc(n_tokens, tenant=tenant)
            with self._tlock:
                st = self._tenant(tenant)
                st["n_finished"] += 1
                st["n_generated"] += n_tokens
                if tpot is not None:
                    st["tpot"].add(tpot)

    def record_retry(self) -> None:
        """One transient-fault retry at an engine boundary."""
        self.n_retries += 1
        self._c_retries.inc()
        self._emit("retries_total", self.n_retries)

    def record_restart(self) -> None:
        """One engine-state rebuild by deterministic replay."""
        self.n_restarts += 1
        self._c_restarts.inc()
        self._emit("restarts_total", self.n_restarts)

    def record_backpressure(self) -> None:
        """One submit shed at max queue depth."""
        self.n_backpressure += 1
        self._c_backpressure.inc()

    def record_grammar_compile(self, result: str) -> None:
        """One grammar constraint resolution (hit|miss|error)."""
        self._c_grammar_compiles.inc(result=result)

    def record_stop_hit(self) -> None:
        """One request finished by a stop-sequence match."""
        self._c_stop_hits.inc()

    def record_rejection(self, reason: str, tenant: str = "") -> None:
        """One submit shed before queueing, with its reason
        (``backpressure`` = queue depth, ``quota`` = tenant token
        bucket dry). Recorded ALONGSIDE :meth:`record_backpressure`
        — that unlabelled counter keeps its pre-tenancy meaning while
        this family adds the reason/tenant breakdown."""
        self._c_rejections.inc(reason=reason, tenant=tenant)
        with self._tlock:
            self.n_rejections[reason] = self.n_rejections.get(reason, 0) + 1
            if tenant:
                self._tenant(tenant)["n_rejected"] += 1

    def record_embedding(self, model: str, n_words: int,
                         seconds: float, tenant: str = "") -> None:
        """One embedding request served host-side (``n_words`` lookups
        against the ``model`` embedder, no KV slot involved)."""
        self.n_embeddings += 1
        self.embed_latency.add(float(seconds))
        self._c_embeddings.inc(model=model)
        self._h_embed.observe(seconds)
        self._emit("embedding_seconds", seconds)
        if tenant:
            self._c_tenant_requests.inc(tenant=tenant, outcome="embedding")
            with self._tlock:
                self._tenant(tenant)["n_finished"] += 1

    def record_kv_export(self, n_tokens: int, nbytes: int,
                         seconds: float, tenant: str = "") -> None:
        """One KV segment prefilled here for a decode replica
        (``n_tokens`` of prompt, ``nbytes`` of raw segment bytes)."""
        self.n_kv_exports += 1
        self.kv_export_bytes += int(nbytes)
        self.kv_export_latency.add(float(seconds))
        self._c_kv_exports.inc()
        self._c_kv_export_bytes.inc(int(nbytes))
        self._h_kv_export.observe(seconds)
        self._emit("kv_export_seconds", seconds)
        if tenant:
            self._c_tenant_requests.inc(tenant=tenant, outcome="kv_export")
            with self._tlock:
                self._tenant(tenant)["n_finished"] += 1

    def record_kv_ingest(self, n_tokens: int, nbytes: int,
                         seconds: float, *, stored: bool,
                         tenant: str = "") -> None:
        """One wire segment offered to the local prefix cache.
        ``stored`` means the follow-up generate will full-hit; a
        decline is soft (the sender falls back to local prefill)."""
        if stored:
            self.n_kv_ingests_stored += 1
        else:
            self.n_kv_ingests_declined += 1
        self.kv_ingest_bytes += int(nbytes)
        self.kv_ingest_latency.add(float(seconds))
        self._c_kv_ingests.inc(result="stored" if stored else "declined")
        self._c_kv_ingest_bytes.inc(int(nbytes))
        self._h_kv_ingest.observe(seconds)
        self._emit("kv_ingest_seconds", seconds)
        if tenant:
            self._c_tenant_requests.inc(tenant=tenant, outcome="kv_ingest")
            with self._tlock:
                self._tenant(tenant)["n_finished"] += 1

    def record_transfer(self, nbytes: int, seconds: float, *,
                        ok: bool = True) -> None:
        """One KV segment push to a decode replica (HTTP layer).
        Failed pushes record their wall time but no bytes — the
        segment never landed."""
        self.n_transfers += 1
        self.transfer_latency.add(float(seconds))
        self.transfer_seconds += float(seconds)
        self._c_transfers.inc(result="ok" if ok else "failed")
        self._h_transfer.observe(seconds)
        self._emit("transfer_seconds", seconds)
        if ok:
            self.transfer_bytes += int(nbytes)
            self._c_transfer_bytes.inc(int(nbytes))
        else:
            self.n_transfer_failures += 1

    def record_migration_out(self, n_generated: int, seconds: float,
                             tenant: str = "") -> None:
        """One live slot exported (parked) for migration at drain."""
        self.n_migrations_out += 1
        self._c_migrations_out.inc()
        self._emit("migration_export_seconds", seconds)

    def record_migration_in(self, n_generated: int, seconds: float, *,
                            seated: bool, tenant: str = "") -> None:
        """One migrated session offered to this engine. A decline is
        soft — the source keeps its existing fail path."""
        if seated:
            self.n_migrations_seated += 1
        else:
            self.n_migrations_declined += 1
        self.migration_seat_latency.add(float(seconds))
        self._c_migrations_in.inc(
            result="seated" if seated else "declined"
        )
        self._h_migration_seat.observe(seconds)
        self._emit("migration_seat_seconds", seconds)
        if tenant and seated:
            self._c_tenant_requests.inc(tenant=tenant,
                                        outcome="migrated_in")

    def record_migration_settled(self, *, ok: bool,
                                 tenant: str = "") -> None:
        """One parked request resolved: the destination finished its
        stream (ok) or migration failed and the request fell back to
        the preemption path."""
        if ok:
            self.n_migrations_settled_ok += 1
        else:
            self.n_migrations_settled_failed += 1
        self._c_migrations_settled.inc(result="ok" if ok else "failed")

    def record_prefix_lookup(self, result: str, saved_tokens: int) -> None:
        """One admission-time prefix-cache lookup. ``result`` is
        ``hit_full``/``hit_partial``/``miss``; ``saved_tokens`` is how
        many prompt tokens the hit served from cached KV (the usable,
        grain-aligned match — 0 on a miss)."""
        self._c_prefix_lookups.inc(result=result)
        if result == "hit_full":
            self.n_prefix_hits_full += 1
        elif result == "hit_partial":
            self.n_prefix_hits_partial += 1
        else:
            self.n_prefix_misses += 1
        if saved_tokens:
            self.prefix_tokens_saved += int(saved_tokens)
            self._c_prefix_saved.inc(int(saved_tokens))
            self._emit("prefix_tokens_saved_total",
                       self.prefix_tokens_saved)

    def record_prefix_insert(self) -> None:
        """One new segment cached."""
        self.n_prefix_inserts += 1
        self._c_prefix_inserts.inc()

    def record_prefix_eviction(self) -> None:
        """One unpinned segment dropped by LRU pressure."""
        self.n_prefix_evictions += 1
        self._c_prefix_evictions.inc()

    def record_batched_admissions(self, n: int) -> None:
        """``n`` admissions served by ONE shared prefill dispatch
        (recorded once per coalesced group, n >= 2)."""
        self.n_batched_admissions += int(n)
        self._c_batched.inc(int(n))

    def record_prefill_chunk(self, tokens: int) -> None:
        """One bounded prefill chunk executed for a deferred
        (piggyback) admission — fused into a decode dispatch or run
        standalone under the per-horizon token budget."""
        self.n_prefill_chunks += 1
        self.prefill_chunk_tokens += int(tokens)
        self._c_prefill_chunks.inc()

    def record_decode_stall(self, seconds: float) -> None:
        """Wall time occupied decode slots waited on admission
        prefill work (measured piggyback-on AND -off, so the bench
        comparison prices the stall reduction honestly)."""
        self.decode_stall_seconds += float(seconds)
        self._c_decode_stall.inc(float(seconds))

    def record_outcome(self, status, tenant: str = "") -> None:
        """Non-FINISHED terminal outcome (status is a
        ``RequestStatus`` or its string value)."""
        s = getattr(status, "value", status)
        self._c_requests.inc(outcome=s)
        if tenant:
            self._c_tenant_requests.inc(tenant=tenant, outcome=s)
            with self._tlock:
                self._tenant(tenant)["n_other"] += 1
        if s == "failed":
            self.n_failed += 1
            self._emit("failed_total", self.n_failed)
        elif s == "cancelled":
            self.n_cancelled += 1
            self._emit("cancelled_total", self.n_cancelled)
        elif s == "expired":
            self.n_expired += 1
            self._emit("expired_total", self.n_expired)

    def set_tenant_slo(self, tenant_id: str, p99_tpot_s: float) -> None:
        """Declare a tenant's p99 TPOT objective (seconds). From then
        on every render publishes ``serve_tenant_slo_burn{tenant}`` =
        observed p99 / objective, once the tenant has TPOT samples."""
        if p99_tpot_s <= 0:
            raise ValueError("p99_tpot_s must be > 0")
        with self._tlock:
            self._tenant_slos[tenant_id] = float(p99_tpot_s)

    def _update_slo_burn(self) -> None:
        """Refresh the burn-rate gauges from the per-tenant TPOT
        reservoirs. Tenants with an SLO but no samples yet publish
        nothing (a 0 would read as a perfect SLO with zero traffic)."""
        with self._tlock:
            for tid, target in self._tenant_slos.items():
                st = self._tenants.get(tid)
                if st is not None and st["tpot"]:
                    burn = _pct(st["tpot"], 99) / target
                    self._g_slo_burn.set(burn, tenant=tid)

    def _update_program_util(self) -> None:
        """Refresh the per-family MFU/MBU gauges: measured seconds
        (``record_program``) divided into the static flop/byte budgets
        committed in ``.graftaudit.json``. The registry entry IS the
        live program (graftaudit enforces the surface), so the
        attribution is exact, not heuristic — exact at the audit
        geometry, where the envelope budgets match the dispatched
        shapes. Render-time only: the hot path never touches this."""
        if not self.program_dispatches:
            return
        if self._family_budgets is None:
            try:
                from deeplearning4j_tpu.analysis.programs import (
                    family_budgets,
                )

                self._family_budgets = family_budgets()
            except Exception:
                self._family_budgets = {}
        if not self._family_budgets:
            return  # no committed baseline: seconds-only attribution
        if self._peaks is None:
            self._peaks = _device_peaks()
        peak_flops, peak_bw = self._peaks
        for family, n in self.program_dispatches.items():
            budget = self._family_budgets.get(family)
            secs = self.program_seconds.get(family, 0.0)
            if budget is None or secs <= 0.0:
                continue
            self._g_mfu.set(
                min(1.0, budget["flops"] * n / secs / peak_flops),
                family=family,
            )
            self._g_mbu.set(
                min(1.0, budget["bytes"] * n / secs / peak_bw),
                family=family,
            )

    def render_prometheus(self) -> str:
        """The backing registry in Prometheus text format (what the
        serving server returns at ``GET /metrics``)."""
        self._update_slo_burn()
        self._update_program_util()
        return self.registry.render()

    def summary(self) -> dict:
        """Aggregate view: p50/p99 latencies + mean utilization +
        per-phase breakdown."""
        out = {
            "n_finished": self.n_finished,
            "n_generated": self.n_generated,
            "n_retries": self.n_retries,
            "n_restarts": self.n_restarts,
            "n_failed": self.n_failed,
            "n_cancelled": self.n_cancelled,
            "n_expired": self.n_expired,
            "steps": self._step,
            "decode_horizon": self.decode_horizon,
        }
        lookups = (self.n_prefix_hits_full + self.n_prefix_hits_partial
                   + self.n_prefix_misses)
        if lookups:
            out["prefix_lookups"] = lookups
            out["prefix_hit_rate"] = (
                (self.n_prefix_hits_full + self.n_prefix_hits_partial)
                / lookups
            )
            out["prefix_tokens_saved"] = self.prefix_tokens_saved
            out["prefix_inserts"] = self.n_prefix_inserts
            out["prefix_evictions"] = self.n_prefix_evictions
        if self.n_batched_admissions:
            out["batched_admissions"] = self.n_batched_admissions
        if self.n_prefill_chunks:
            out["prefill_chunks"] = self.n_prefill_chunks
            out["prefill_chunk_tokens"] = self.prefill_chunk_tokens
        if self.decode_stall_seconds > 0:
            out["decode_stall_s"] = round(self.decode_stall_seconds, 6)
        if self.n_embeddings:
            out["n_embeddings"] = self.n_embeddings
            out["embedding_p50_s"] = _pct(self.embed_latency, 50)
        if (self.n_kv_exports or self.n_transfers
                or self.n_kv_ingests_stored or self.n_kv_ingests_declined):
            d = {
                "kv_exports": self.n_kv_exports,
                "kv_export_bytes": self.kv_export_bytes,
                "kv_ingests_stored": self.n_kv_ingests_stored,
                "kv_ingests_declined": self.n_kv_ingests_declined,
                "kv_ingest_bytes": self.kv_ingest_bytes,
                "transfers": self.n_transfers,
                "transfer_failures": self.n_transfer_failures,
                "transfer_bytes": self.transfer_bytes,
            }
            if self.kv_export_latency:
                d["kv_export_p50_s"] = _pct(self.kv_export_latency, 50)
            if self.transfer_latency:
                d["transfer_p50_s"] = _pct(self.transfer_latency, 50)
                if self.transfer_seconds > 0:
                    d["transfer_bytes_per_s"] = (
                        self.transfer_bytes / self.transfer_seconds
                    )
            out["disagg"] = d
        if (self.n_migrations_out or self.n_migrations_seated
                or self.n_migrations_declined):
            d = {
                "migrations_out": self.n_migrations_out,
                "migrations_seated": self.n_migrations_seated,
                "migrations_declined": self.n_migrations_declined,
                "migrations_settled_ok": self.n_migrations_settled_ok,
                "migrations_settled_failed":
                    self.n_migrations_settled_failed,
            }
            if self.migration_seat_latency:
                d["seat_p50_s"] = _pct(self.migration_seat_latency, 50)
                d["seat_p99_s"] = _pct(self.migration_seat_latency, 99)
            out["migration"] = d
        with self._tlock:
            if self.n_rejections:
                out["rejections"] = dict(self.n_rejections)
            if self._tenants:
                tenants = {}
                for tid in sorted(self._tenants):
                    st = self._tenants[tid]
                    t = {
                        "n_finished": st["n_finished"],
                        "n_generated": st["n_generated"],
                    }
                    if st["n_rejected"]:
                        t["n_rejected"] = st["n_rejected"]
                    if st["n_other"]:
                        t["n_other_outcomes"] = st["n_other"]
                    if st["tpot"]:
                        t["tpot_p50_s"] = _pct(st["tpot"], 50)
                        t["tpot_p99_s"] = _pct(st["tpot"], 99)
                        slo = self._tenant_slos.get(tid)
                        if slo is not None:
                            t["slo_burn"] = t["tpot_p99_s"] / slo
                    if st["queue_delay"]:
                        t["queue_delay_p50_s"] = _pct(st["queue_delay"], 50)
                        t["queue_delay_p99_s"] = _pct(st["queue_delay"], 99)
                    tenants[tid] = t
                out["tenants"] = tenants
        for name, xs in [("ttft", self.ttft), ("tpot", self.tpot),
                         ("queue_delay", self.queue_delay)]:
            if xs:
                out[f"{name}_p50_s"] = _pct(xs, 50)
                out[f"{name}_p99_s"] = _pct(xs, 99)
        if self.sync_wait:
            sync = self.sync_wait.total
            over = self.overlap.total
            out["sync_wait_mean_s"] = sync / len(self.sync_wait)
            if sync + over > 0:
                out["dispatch_overlap_frac"] = over / (sync + over)
        if self.occupancy:
            # mean slots actually decoding per step — the "effective
            # batch" a continuous batcher is supposed to keep > 1
            out["occupancy_mean"] = self.occupancy.mean
            out["queue_depth_max"] = int(self.queue_depth.max)
        if self.program_dispatches:
            out["program_seconds"] = {
                f: round(v, 6)
                for f, v in sorted(self.program_seconds.items())
            }
            out["program_dispatches"] = dict(
                sorted(self.program_dispatches.items())
            )
        attributed = sum(self.phase_seconds.values())
        if attributed > 0:
            out["phase_seconds"] = {
                p: round(v, 6) for p, v in self.phase_seconds.items()
            }
            out["phase_frac"] = {
                p: round(v / attributed, 4)
                for p, v in self.phase_seconds.items()
            }
        return out
