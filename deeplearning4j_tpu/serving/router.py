"""Prefix-affinity replica router: one thin process in front of N
serving replicas.

Tensor parallelism (``ServingEngine(tp=...)``) scales one model copy
across chips; the router scales *throughput* across model copies. It
is deliberately dumb about models — it never tokenizes, never touches
a device, and holds no request state beyond in-flight counters — so a
replica fleet is just N ``ServingServer`` processes plus this.

Routing policy (in priority order):

1. **Prefix affinity.** The router keeps a host-side token trie per
   replica — a shadow of every prompt it has routed there. A new
   prompt goes to the healthy replica whose shadow reports the longest
   shared prefix, when that match reaches ``affinity_min_match``
   tokens: that replica's radix prefix cache (PR 5) almost certainly
   still holds the matching KV run, so routing anywhere else forfeits
   the prefill savings. The shadow is an over-approximation of the
   replica's real cache (it never sees evictions) — a stale hit costs
   one ordinary prefill, never a wrong answer, so the router stays
   decoupled from replica cache internals.
2. **Least loaded.** Otherwise the replica with the fewest router-side
   in-flight requests wins, round-robin on ties.

Failure handling mirrors the per-replica supervision already inside
``ServingServer``: an engine crash *inside* a replica is invisible
here (the replica's supervisor replays and the blocked forward simply
takes longer), while a dead replica *process* surfaces as a connect
error or 503 — the router marks it unhealthy, retries the request on
the remaining healthy replicas (generate submits are idempotent until
accepted: a connect/send failure means the replica never admitted it),
and a background poller flips the replica back to healthy once its
``/healthz`` answers 200 again.

Fleet tracing: the router is the natural trace root. It adopts the
caller's W3C ``traceparent`` (or starts a trace), and injects a fresh
dispatch span id downstream on EVERY forward attempt — including
retries onto survivors — so the merged Perfetto view (``trace-merge``)
shows the failed attempt and the retry as sibling spans under one
trace, each linked by a flow arrow to the replica's admission span.

RESILIENCE (PR 17): per-replica circuit breakers
(:class:`~deeplearning4j_tpu.serving.rpc.CircuitBreaker`,
closed/open/half-open with exponential probe backoff) gate dispatch —
a health-poll success alone never closes an open breaker, only a
successful forwarded request does — and every attempt honors the
caller's ``X-Deadline-Ms`` budget (socket timeouts derived from it,
shrunken budget re-forwarded downstream). Generate forwards are never
hedged: decoding is not idempotent.

Endpoints: ``POST /v1/generate`` (routed passthrough; replica status
codes and bodies are forwarded verbatim, plus ``X-Served-By``),
``GET /healthz`` (200 while >= 1 replica is healthy), ``GET /replicas``
(per-replica routing state), ``GET /metrics`` (Prometheus text for the
router's own counters/gauges, labelled per replica),
``GET /debug/dump`` (flight-recorder postmortem bundle).
"""

from __future__ import annotations

import http.client
import logging
import os
import signal
import threading
import time
from http.server import ThreadingHTTPServer
from pathlib import Path
from urllib.parse import urlparse

from deeplearning4j_tpu.analysis.sanitizers import note_access, wrap_lock
from deeplearning4j_tpu.obs.flight import FlightRecorder
from deeplearning4j_tpu.obs.logs import log_event
from deeplearning4j_tpu.obs.registry import MetricsRegistry
from deeplearning4j_tpu.obs.trace import (
    Tracer,
    format_traceparent,
    new_span_id,
    new_trace_id,
    parse_traceparent,
)
from deeplearning4j_tpu.serving.rpc import (
    CLOSED,
    DEADLINE_HEADER,
    HALF_OPEN,
    CircuitBreaker,
    Deadline,
)
from deeplearning4j_tpu.utils.httpjson import (
    QuietHandler,
    read_json_body,
    send_body,
    send_json,
)

_log = logging.getLogger(__name__)

#: Prometheus text exposition format version served at /metrics
PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

#: the router's single trace track (it has one logical timeline)
ROUTER_TRACK = "router"


class _ReplicaDown(Exception):
    """Transport-level failure talking to a replica (connect/send/read
    error or a 503) — the request was not accepted there."""


class PrefixShadow:
    """Host-side token trie over the prompts routed to one replica.

    ``longest_match`` is the router's estimate of how many prompt
    tokens the replica's prefix cache could reuse. Memory is bounded by
    ``max_nodes`` (one dict entry per distinct token position); at the
    cap the trie resets wholesale — crude, but affinity only needs
    recent history, and a cold shadow merely degrades to least-loaded
    routing until it re-learns.
    """

    __slots__ = ("_root", "_nodes", "max_nodes", "resets")

    def __init__(self, max_nodes: int = 1_000_000):
        self._root: dict = {}
        self._nodes = 0
        self.max_nodes = max_nodes
        self.resets = 0

    def insert(self, tokens) -> None:
        if self._nodes >= self.max_nodes:
            self._root = {}
            self._nodes = 0
            self.resets += 1
        node = self._root
        for t in tokens:
            t = int(t)
            nxt = node.get(t)
            if nxt is None:
                nxt = node[t] = {}
                self._nodes += 1
            node = nxt

    def longest_match(self, tokens) -> int:
        node = self._root
        n = 0
        for t in tokens:
            node = node.get(int(t))
            if node is None:
                break
            n += 1
        return n

    def __len__(self) -> int:
        return self._nodes


class _Replica:
    """Router-side view of one backend ``ServingServer``."""

    __slots__ = ("host", "port", "healthy", "in_flight", "routed",
                 "affinity_routed", "retried_away", "shadow",
                 "last_health", "lock", "draining", "incompatible",
                 "config_hash", "breaker")

    def __init__(self, host: str, port: int):
        self.host = host
        self.port = int(port)
        # optimistic until the first poll: a router started moments
        # before its replicas shouldn't 503 the first request wave.
        # healthy/in_flight/retried_away are flipped by HTTP handler
        # threads AND the health poller, so they only move under the
        # router's _route_lock
        self.healthy = True  # guarded-by: _route_lock
        self.in_flight = 0  # guarded-by: _route_lock
        self.routed = 0
        self.affinity_routed = 0
        self.retried_away = 0  # guarded-by: _route_lock
        self.shadow = PrefixShadow()
        self.last_health: dict | None = None
        self.lock = threading.Lock()
        # replica reports draining (POST /drain): stop dispatching to
        # it, resume when its health payload clears the flag
        self.draining = False  # guarded-by: _route_lock
        # first-seen model identity; a replica that comes back from a
        # restart with a DIFFERENT hash is permanently excluded — it
        # serves a different checkpoint now, not this fleet's model
        self.config_hash: str | None = None
        self.incompatible = False  # guarded-by: _route_lock
        # per-replica circuit breaker; dispatch gates on it instead of
        # the binary healthy flag alone (the flag stays as the
        # liveness VIEW). The router replaces this with one wired to
        # its transition hooks.
        self.breaker = CircuitBreaker()

    @property
    def name(self) -> str:
        return f"{self.host}:{self.port}"

    def state(self) -> dict:  # lint: holds _route_lock
        return {
            "healthy": self.healthy,
            "draining": self.draining,
            "incompatible": self.incompatible,
            "config_hash": self.config_hash,
            "in_flight": self.in_flight,
            "routed": self.routed,
            "affinity_routed": self.affinity_routed,
            "retried_away": self.retried_away,
            "shadow_nodes": len(self.shadow),
            "last_health": self.last_health,
            "breaker": self.breaker.snapshot(),
        }


def _parse_replica(spec) -> tuple[str, int]:
    """Accept ``(host, port)`` tuples or ``"host:port"`` strings."""
    if isinstance(spec, str):
        host, _, port = spec.rpartition(":")
        if not host or not port.isdigit():
            raise ValueError(f"replica spec {spec!r} is not host:port")
        return host, int(port)
    host, port = spec
    return str(host), int(port)


class ReplicaRouter:
    """HTTP router over N serving replicas; ``start()`` is non-blocking.

    ``affinity_min_match`` — minimum shared-prefix length (tokens)
    before affinity overrides least-loaded dispatch. ``health_interval_s``
    — background ``/healthz`` poll period; a replica is also marked
    unhealthy *immediately* when a forward to it fails at transport
    level, so the poll interval bounds recovery detection, not failure
    detection.
    """

    def __init__(self, replicas, host: str = "127.0.0.1", port: int = 0,
                 affinity_min_match: int = 8,
                 health_interval_s: float = 0.5,
                 request_timeout_s: float = 300.0,
                 tracer: Tracer | None = None,
                 flight: FlightRecorder | None = None,
                 flight_dir: str | None = None):
        if not replicas:
            raise ValueError("need at least one replica")
        self.replicas = [
            _Replica(*_parse_replica(spec)) for spec in replicas
        ]
        self.affinity_min_match = int(affinity_min_match)
        self.health_interval_s = float(health_interval_s)
        self.request_timeout_s = float(request_timeout_s)
        self.tracer = tracer if tracer is not None else Tracer(
            enabled=False, process_name="router")
        # enabled by default, like the replica engines: the postmortem
        # has to exist before the incident
        self.flight = flight if flight is not None else FlightRecorder()
        self.flight_dir = (flight_dir if flight_dir is not None
                           else os.environ.get("DL4J_TPU_FLIGHT_DIR")
                           or None)
        self._stop = threading.Event()
        self._route_lock = wrap_lock(
            threading.Lock(), "router._route_lock"
        )
        self._rr = 0  # round-robin tie-break cursor

        reg = self.registry = MetricsRegistry()
        self._m_requests = reg.counter(
            "router_requests_total", "Requests accepted by the router.")
        self._m_routed = reg.counter(
            "router_routed_total", "Requests dispatched, per replica.",
            labelnames=("replica",))
        self._m_affinity = reg.counter(
            "router_affinity_total",
            "Dispatches where prefix affinity overrode least-loaded.")
        self._m_retries = reg.counter(
            "router_retries_total",
            "Forwards retried on another replica after a transport "
            "failure.")
        self._m_no_replica = reg.counter(
            "router_no_replica_total",
            "Requests failed because no healthy replica remained.")
        self._m_healthy = reg.gauge(
            "router_replica_healthy", "1 while the replica is routable.",
            labelnames=("replica",))
        self._m_draining = reg.gauge(
            "router_replica_draining",
            "1 while the replica reports draining (POST /drain).",
            labelnames=("replica",))
        self._m_incompatible = reg.gauge(
            "router_replica_incompatible",
            "1 once the replica returned with a different model-config "
            "hash (restarted onto the wrong checkpoint).",
            labelnames=("replica",))
        self._m_in_flight = reg.gauge(
            "router_replica_in_flight",
            "Router-side in-flight requests, per replica.",
            labelnames=("replica",))
        self._h_e2e = reg.histogram(
            "router_e2e_seconds",
            "End-to-end routed latency: pick + forward, including any "
            "retries onto surviving replicas.")
        self._h_ttft = reg.histogram(
            "router_replica_ttft_seconds",
            "Per-replica time from forward to the replica's response "
            "headers. The routed passthrough buffers whole bodies, so "
            "for generate this is the replica's full service time — "
            "the router's honest first-byte bound.",
            labelnames=("replica",))
        self._m_breaker = reg.gauge(
            "router_breaker_state",
            "Circuit breaker per replica: 0 closed, 0.5 half-open, "
            "1 open.",
            labelnames=("replica",))
        self._m_breaker_transitions = reg.counter(
            "router_breaker_transitions_total",
            "Breaker state changes, per replica and new state.",
            labelnames=("replica", "state"))
        for r in self.replicas:
            self._m_healthy.set(1.0, replica=r.name)
            self._m_in_flight.set(0.0, replica=r.name)
            self._m_breaker.set(0.0, replica=r.name)
            r.breaker = CircuitBreaker(
                on_transition=self._breaker_hook(r.name))

        router = self

        class Handler(QuietHandler):
            def do_GET(self):
                path = urlparse(self.path).path
                if path == "/healthz":
                    payload = router.health_payload()
                    send_json(self, 200 if payload["ok"] else 503, payload)
                elif path == "/replicas":
                    send_json(self, 200, router.replica_states())
                elif path == "/metrics":
                    send_body(self, 200, reg.render().encode(),
                              PROM_CONTENT_TYPE)
                elif path == "/debug/dump":
                    send_json(self, 200,
                              router.flight_bundle("debug_dump"))
                else:
                    send_json(self, 404, {"error": "not found"})

            def do_POST(self):
                if urlparse(self.path).path != "/v1/generate":
                    send_json(self, 404, {"error": "not found"})
                    return
                if router._stop.is_set():
                    send_json(self, 503, {"error": "router stopped"})
                    return
                body = read_json_body(self)
                if body is None:
                    send_json(self, 400, {"error": "malformed JSON"})
                    return
                code, payload, served_by = router.route(
                    body, traceparent=self.headers.get("traceparent"),
                    deadline_ms=self.headers.get(DEADLINE_HEADER))
                # forward the replica's JSON verbatim, tagging which
                # backend actually served it (observability + tests)
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                if served_by is not None:
                    self.send_header("X-Served-By", served_by)
                self.end_headers()
                self.wfile.write(payload)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._health_thread = threading.Thread(
            target=self._health_loop, daemon=True)

    # ------------------------------------------------------------- #
    # routing                                                        #
    # ------------------------------------------------------------- #

    @staticmethod
    def _prompt_tokens(body: dict) -> list[int]:
        """The prompt as affinity tokens; text prompts use the repo's
        byte-level convention (latin-1 per byte), mirroring the
        replica's own parsing so shadow tries match what replicas
        cache."""
        prompt = body.get("prompt")
        if isinstance(prompt, str):
            return list(prompt.encode("latin-1", errors="replace"))
        if isinstance(prompt, list):
            try:
                return [int(t) for t in prompt]
            except (TypeError, ValueError):
                return []
        return []

    def _pick(self, tokens, exclude: set[str]) -> tuple[_Replica, bool]:
        """Choose a healthy replica for ``tokens``; returns
        ``(replica, via_affinity)``. Raises ``_ReplicaDown`` when no
        healthy candidate remains."""
        with self._route_lock:
            avail = [
                r for r in self.replicas
                if r.healthy and not r.draining and not r.incompatible
                and r.name not in exclude
            ]
            # breaker-gated: closed breakers are the normal pool; when
            # it is empty, ONE due probe through an open breaker is
            # admitted (half-open) so a recovered replica proves
            # itself on real traffic. allow() consumes the probe, so
            # only ask when no closed-breaker replica remains.
            candidates = [r for r in avail if r.breaker.state == CLOSED]
            if not candidates:
                candidates = [r for r in avail if r.breaker.allow()]
            if not candidates:
                raise _ReplicaDown("no healthy replica")
            best, best_match = None, -1
            for r in candidates:
                m = r.shadow.longest_match(tokens)
                # ties go to the less-loaded replica so identical
                # shadows (e.g. empty) don't pile onto one backend
                if m > best_match or (
                    m == best_match and r.in_flight < best.in_flight
                ):
                    best, best_match = r, m
            if best_match >= self.affinity_min_match:
                chosen, via_affinity = best, True
            else:
                self._rr += 1
                lo = min(r.in_flight for r in candidates)
                tied = [r for r in candidates if r.in_flight == lo]
                chosen = tied[self._rr % len(tied)]
                via_affinity = False
            chosen.in_flight += 1
            chosen.routed += 1
            if via_affinity:
                chosen.affinity_routed += 1
            if tokens:
                chosen.shadow.insert(tokens)
            self._m_in_flight.set(
                float(chosen.in_flight), replica=chosen.name)
            return chosen, via_affinity

    def _forward(self, replica: _Replica, raw: bytes, headers: dict,
                 dl: Deadline | None = None) -> tuple[int, bytes]:
        """POST the raw body to the replica's generate endpoint.
        Transport failures and 503 (draining / dead engine) raise
        ``_ReplicaDown`` so the caller retries elsewhere. The socket
        timeout derives from the request's deadline budget."""
        conn = http.client.HTTPConnection(
            replica.host, replica.port,
            timeout=(dl.timeout(self.request_timeout_s)
                     if dl is not None else self.request_timeout_s))
        try:
            t0 = time.perf_counter()
            conn.request("POST", "/v1/generate", body=raw,
                         headers=headers)
            resp = conn.getresponse()
            # response headers landed: the replica produced its first
            # byte (see the histogram's help for what that means here)
            ttft = time.perf_counter() - t0
            payload = resp.read()
            if resp.status == 503:
                raise _ReplicaDown(f"{replica.name} answered 503")
            replica.breaker.record_success()
            self._h_ttft.observe(ttft, replica=replica.name)
            return resp.status, payload
        except (OSError, http.client.HTTPException) as e:
            raise _ReplicaDown(f"{replica.name}: {e}") from e
        finally:
            conn.close()

    def route(self, body: dict,
              traceparent: str | None = None,
              deadline_ms: str | None = None
              ) -> tuple[int, bytes, str | None]:
        """Route one generate request; returns
        ``(status, payload_bytes, replica_name | None)``. Retries on
        the remaining healthy replicas after transport-level failures
        (the failed replica never accepted the request). Generate
        forwards are never HEDGED — decoding is not idempotent; only
        retry-after-failure is safe.

        The caller's ``X-Deadline-Ms`` budget bounds every attempt's
        socket timeout and is re-forwarded (shrunken) downstream; an
        exhausted budget answers a clean 504 instead of piling retries.

        Trace context: the caller's ``traceparent`` is adopted (or a
        trace started), and every forward attempt — retries included —
        carries a fresh dispatch span id downstream, so the replica's
        admission span parents to the attempt that actually reached it.
        """
        import json

        self._m_requests.inc()
        ctx = parse_traceparent(traceparent)
        trace_id, parent_span = ctx if ctx else (new_trace_id(), "")
        dl = Deadline.from_header(deadline_ms,
                                  default_s=self.request_timeout_s)
        tokens = self._prompt_tokens(body)
        raw = json.dumps(body).encode()
        exclude: set[str] = set()
        t_req = time.perf_counter()
        attempt = 0
        try:
            while True:
                if dl.expired():
                    return 504, json.dumps(
                        {"error": "deadline exhausted",
                         "attempts": attempt}).encode(), None
                try:
                    replica, via_affinity = self._pick(tokens, exclude)
                except _ReplicaDown:
                    self._m_no_replica.inc()
                    self.flight.record("no_replica", trace_id=trace_id,
                                       attempts=attempt)
                    return 503, json.dumps(
                        {"error": "no healthy replica"}).encode(), None
                attempt += 1
                self._m_routed.inc(replica=replica.name)
                if via_affinity:
                    self._m_affinity.inc()
                span_id = new_span_id()
                headers = {
                    "Content-Type": "application/json",
                    "traceparent": format_traceparent(trace_id, span_id),
                    "X-Served-By": replica.name,
                    DEADLINE_HEADER: dl.header_value(),
                }
                if self.flight.enabled:
                    self.flight.record(
                        "dispatch", replica=replica.name,
                        attempt=attempt, trace_id=trace_id,
                        via_affinity=via_affinity)
                t_try = time.perf_counter()
                try:
                    status, payload = self._forward(
                        replica, raw, headers, dl)
                    self._trace_dispatch(
                        trace_id, span_id, parent_span, replica.name,
                        attempt, t_try, status=status)
                    return status, payload, replica.name
                except _ReplicaDown as e:
                    self._trace_dispatch(
                        trace_id, span_id, parent_span, replica.name,
                        attempt, t_try, error=str(e))
                    self._mark_unhealthy(replica, str(e))
                    with self._route_lock:
                        replica.retried_away += 1
                    self._m_retries.inc()
                    exclude.add(replica.name)
                    self.flight.record("retry", replica=replica.name,
                                       trace_id=trace_id, error=str(e))
                    log_event(_log, "router_retry",
                              replica=replica.name, error=str(e),
                              trace_id=trace_id)
                finally:
                    with self._route_lock:
                        replica.in_flight -= 1
                        self._m_in_flight.set(
                            float(replica.in_flight),
                            replica=replica.name)
        finally:
            self._h_e2e.observe(time.perf_counter() - t_req)

    def _trace_dispatch(self, trace_id: str, span_id: str,
                        parent_span: str, replica: str, attempt: int,
                        t0: float, **extra) -> None:
        """One dispatch span on the router track; ``span_id`` is the
        id this attempt injected downstream, which is what makes the
        replica's admission span our child in the merged view."""
        if not self.tracer.enabled:
            return
        args = {"trace_id": trace_id, "span_id": span_id,
                "replica": replica, "attempt": attempt, **extra}
        if parent_span:
            args["parent_span_id"] = parent_span
        self.tracer.span(ROUTER_TRACK, "dispatch", t0,
                         time.perf_counter() - t0, **args)

    # ------------------------------------------------------------- #
    # health                                                         #
    # ------------------------------------------------------------- #

    def _breaker_hook(self, name: str):
        """Transition listener for one replica's breaker: gauge,
        counter, and flight event per state change. Fires inside the
        breaker's own lock, so it must stay cheap and must not take
        ``_route_lock``."""
        def hook(old: str, new: str) -> None:
            self._m_breaker.set(
                {CLOSED: 0.0, HALF_OPEN: 0.5}.get(new, 1.0),
                replica=name)
            self._m_breaker_transitions.inc(replica=name, state=new)
            self.flight.record("breaker", replica=name,
                               old=old, new=new)
            log_event(_log, "router_breaker", replica=name,
                      old=old, new=new)
        return hook

    def _mark_unhealthy(self, replica: _Replica, why: str) -> None:
        replica.breaker.record_failure()
        with self._route_lock:
            note_access(f"router.{replica.name}.healthy", write=True)
            flipped = replica.healthy
            if flipped:
                replica.healthy = False
        if flipped:
            self._m_healthy.set(0.0, replica=replica.name)
            log_event(_log, "router_replica_down",
                      replica=replica.name, error=why)

    def _poll_one(self, replica: _Replica) -> None:
        conn = http.client.HTTPConnection(
            replica.host, replica.port,
            timeout=max(0.25, self.health_interval_s))
        try:
            import json

            conn.request("GET", "/healthz")
            resp = conn.getresponse()
            raw = resp.read()
            try:
                replica.last_health = json.loads(raw)
            except ValueError:
                replica.last_health = None
            ok = resp.status == 200
        except (OSError, http.client.HTTPException):
            replica.last_health = None
            ok = False
        finally:
            conn.close()
        hp = (replica.last_health
              if isinstance(replica.last_health, dict) else None)
        if ok and hp is not None:
            # re-verify model identity on every successful poll: a
            # replica that restarted onto a different checkpoint comes
            # back ALIVE but must not silently rejoin the fleet — its
            # answers (and its KV segments) belong to another model
            cfg = hp.get("config_hash")
            if cfg:
                with self._route_lock:
                    note_access(
                        f"router.{replica.name}.config_hash", write=True)
                    if replica.config_hash is None:
                        replica.config_hash = str(cfg)
                        newly_bad = False
                    else:
                        newly_bad = (replica.config_hash != str(cfg)
                                     and not replica.incompatible)
                        if newly_bad:
                            replica.incompatible = True
                if newly_bad:
                    self._m_incompatible.set(1.0, replica=replica.name)
                    log_event(_log, "router_replica_incompatible",
                              replica=replica.name,
                              expected=replica.config_hash[:12],
                              got=str(cfg)[:12], level=logging.ERROR)
            draining = bool(hp.get("draining"))
            with self._route_lock:
                note_access(f"router.{replica.name}.draining", write=True)
                moved = draining != replica.draining
                if moved:
                    replica.draining = draining
            if moved:
                self._m_draining.set(float(draining), replica=replica.name)
                log_event(_log,
                          "router_replica_draining" if draining
                          else "router_replica_resumed",
                          replica=replica.name)
        if ok:
            with self._route_lock:
                note_access(f"router.{replica.name}.healthy", write=True)
                flipped = not replica.healthy
                if flipped:
                    replica.healthy = True
            if flipped:
                self._m_healthy.set(1.0, replica=replica.name)
                log_event(_log, "router_replica_up", replica=replica.name)
        else:
            self._mark_unhealthy(replica, "healthz poll failed")

    def poll_health(self) -> None:
        """One synchronous poll of every replica (tests use this to
        avoid sleeping for the background interval)."""
        for r in self.replicas:
            self._poll_one(r)

    def _health_loop(self) -> None:
        while not self._stop.is_set():
            self.poll_health()
            self._stop.wait(self.health_interval_s)

    def health_payload(self) -> dict:
        with self._route_lock:
            healthy = [r.name for r in self.replicas if r.healthy]
            return {
                "ok": bool(healthy),
                "healthy": healthy,
                "replicas": {r.name: r.healthy for r in self.replicas},
            }

    def replica_states(self) -> dict:
        with self._route_lock:
            return {r.name: r.state() for r in self.replicas}

    # ------------------------------------------------------------- #
    # lifecycle                                                      #
    # ------------------------------------------------------------- #

    @property
    def address(self) -> tuple[str, int]:
        return self._httpd.server_address[:2]

    @property
    def name(self) -> str:
        return "%s:%d" % self.address

    # ------------------------------------------------------------- #
    # flight recorder                                                #
    # ------------------------------------------------------------- #

    def flight_bundle(self, reason: str) -> dict:
        """The router's postmortem: event ring + routing state + the
        trace tail (the router registry has no ``summary()``; replica
        states carry the equivalent signal)."""
        return self.flight.dump(
            reason, tracer=self.tracer,
            extra={"router": self.name,
                   "replicas": self.replica_states()})

    def _dump_flight(self, reason: str) -> None:
        if not self.flight_dir:
            return
        try:
            path = Path(self.flight_dir) / (
                "flight-router-%s-%s-%d.json" % (
                    self.name.replace(":", "-"), reason,
                    int(time.time() * 1000)))
            self.flight.dump_to(
                path, reason, tracer=self.tracer,
                extra={"router": self.name,
                       "replicas": self.replica_states()})
            log_event(_log, "flight_dump", reason=reason,
                      path=str(path))
        except Exception as e:
            log_event(_log, "flight_dump_failed", reason=reason,
                      error=repr(e), level=logging.ERROR)

    def start(self) -> "ReplicaRouter":
        self._http_thread.start()
        self._health_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._health_thread.ident:
            self._health_thread.join(timeout=5)

    def serve_forever(self) -> None:
        """Blocking convenience for the CLI; Ctrl-C stops, SIGTERM
        dumps a flight bundle first (the orchestrator's kill is
        exactly when the postmortem is wanted), then stops."""
        self.start()
        done = threading.Event()

        def _on_sigterm(signum, frame):
            self._dump_flight("sigterm")
            done.set()

        try:
            signal.signal(signal.SIGTERM, _on_sigterm)
        except ValueError:
            pass  # not the main thread (embedded use)
        try:
            while not done.is_set():
                time.sleep(1)
        except KeyboardInterrupt:
            pass
        finally:
            self.stop()
