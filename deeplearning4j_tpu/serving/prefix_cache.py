"""Prefix cache: token-level radix tree over device-side KV segments.

Real serving traffic is dominated by shared prompt prefixes — system
prompts, few-shot templates, multi-turn histories — yet the baseline
admission path re-prefills every prompt from row 0. This module gives
the engine a bounded SEGMENT REGION (a second ``init_caches``
allocation with the same per-slot layout as the decode pool: same
Tpad, same dtype, same int8 scale planes, see
``KVSlotPool.alloc_region``) plus a radix tree mapping token sequences
to region slots, in the spirit of vLLM's PagedAttention pool and
SGLang's RadixAttention, specialized to this engine's fixed-slot
design: one segment = one full-prefix KV slab in one region slot, so
reuse is a single dynamic-slice copy instead of a paged gather.

The tree is a standard compressed radix trie: edges are token runs,
segments live at nodes (so a lookup can only match at node
boundaries — the same block-boundary granularity vLLM has, with the
engine additionally rounding partial matches down to its prefill
bucket grain so suffix chunk windows stay aligned). ``lookup`` walks
the query and returns the DEEPEST node holding a live segment whose
full path is a prefix of the query; ``insert`` splits edges as needed
and claims a region slot, evicting least-recently-used UNPINNED
segments to make room.

Refcounted pinning is the correctness boundary: the engine pins a
segment for every in-flight admission that reads it and unpins at
retirement, and ``_evict_one`` only ever considers ``refs == 0``
segments — so a segment referenced by an active slot is NEVER dropped,
no matter the memory pressure (the chaos eviction test pins this).
When every segment is pinned, ``insert`` simply declines (returns
None) rather than grow the region: the cache is bounded by
construction.

Everything here is host-side bookkeeping; the only device state is
``region``, which the engine reads/writes functionally with its jitted
fetch/store programs. ``reinit`` (crash recovery) re-creates the
region buffers zeroed and drops every segment — after a crash the
buffers must be assumed corrupt (with donation they may already be
invalidated), and recovery replay then runs every lookup against an
empty tree, i.e. through the same code path as a cold miss, keeping
replay byte-identical to the uninterrupted run.
"""

from __future__ import annotations

import heapq
from typing import Callable, Iterable

from deeplearning4j_tpu.serving.cache_pool import KVSlotPool


class Segment:
    """One cached prefix: ``length`` tokens of KV in region slot
    ``slot``, plus the (1, V) last-row logits captured at insert time —
    a FULL hit replays those logits directly, so a fully-cached
    admission dispatches zero prefill programs.

    Over a :class:`~deeplearning4j_tpu.serving.cache_pool.PagedKVPool`
    the storage is ``block_ids`` instead of a region slot: the pool
    block ids (cache-owned references) covering the prefix rows, mostly
    aliased straight off the donor slot's table plus at most one
    privately copied tail block. ``slot`` is then just a monotonic
    identity used for deterministic eviction tie-breaks."""

    __slots__ = ("slot", "length", "node", "refs", "last_use", "hits",
                 "logits", "alive", "block_ids")

    def __init__(self, slot: int, length: int, node: "_Node"):
        self.slot = slot
        self.length = length
        self.node = node
        self.refs = 0          # in-flight admissions reading this segment
        self.last_use = 0      # LRU tick, updated on lookup hit
        self.hits = 0          # lifetime lookup hits (eviction weighting)
        self.logits = None     # device (1, V) row, set by the engine
        self.alive = True      # False once evicted (guards stale unpins)
        self.block_ids = None  # paged mode: pool block ids, engine-set


class _Node:
    """Radix-trie node: ``edge`` is the token run from the parent,
    ``segment`` (optional) caches the prefix spelled by the root path
    ending here."""

    __slots__ = ("edge", "children", "parent", "segment")

    def __init__(self, edge: tuple, parent: "_Node | None"):
        self.edge = edge
        self.children: dict[int, _Node] = {}
        self.parent = parent
        self.segment: Segment | None = None


def _common_len(a: tuple, b: tuple) -> int:
    n = min(len(a), len(b))
    for i in range(n):
        if a[i] != b[i]:
            return i
    return n


class PrefixCache:
    """Radix tree + bounded segment region + refcounted LRU eviction.

    ``capacity_tokens`` is rounded down to whole region slots (each
    segment occupies a full Tpad slab — the fixed-slot analogue of a
    page budget); at least one slot is always allocated. ``on_evict``
    is called once per evicted segment (the engine wires it to the
    Prometheus eviction counter).
    """

    def __init__(self, pool: KVSlotPool, capacity_tokens: int,
                 on_evict: Callable[[Segment], None] | None = None,
                 min_seg_len: int = 1, hit_weight: float = 4.0,
                 config_hash: str | None = None):
        # every segment in this cache was computed under (or validated
        # against) this model-config identity; wire-delivered segments
        # carrying a different hash are rejected before insertion
        self.config_hash = config_hash
        self.tpad = pool.tpad
        self.paged = bool(getattr(pool, "is_paged", False))
        if self.paged:
            # Paged mode: no region at all. Segments live as
            # refcounted block lists INSIDE the pool's shared block
            # store (mostly aliases of the donor slot's blocks), so the
            # capacity budget bounds how many blocks the cache may keep
            # referenced, not a second allocation.
            self._pool = pool
            self.n_region_slots = 0
            self.capacity_blocks = max(
                1, int(capacity_tokens) // pool.block_size
            )
            self.capacity_tokens = self.capacity_blocks * pool.block_size
            self.region = None
            self._nbytes = 0
            self._next_id = 0  # monotonic Segment.slot (tie-breaks)
        else:
            self._pool = pool
            self.n_region_slots = max(1, int(capacity_tokens) // self.tpad)
            self.capacity_tokens = self.n_region_slots * self.tpad
            self._alloc_region = (
                lambda: pool.alloc_region(self.n_region_slots)
            )
            self.region = self._alloc_region()
            # region byte size is fixed for the cache's lifetime: take
            # it from the pool's host metadata so metrics scrapes never
            # walk the live device pytree (see KVSlotPool.region_nbytes)
            self._nbytes = pool.region_nbytes(self.n_region_slots)
        self.on_evict = on_evict
        self.min_seg_len = max(1, int(min_seg_len))  # branch-seg floor
        # eviction score = last_use + hit_weight * hits: each lifetime
        # hit buys the segment hit_weight LRU ticks of extra survival,
        # so a hot system-prompt segment outlives colder-but-newer ones
        # under churn instead of rotating out the moment traffic mixes
        # (flat LRU's failure mode). 0 restores pure LRU.
        self.hit_weight = float(hit_weight)
        self._root = _Node((), None)
        self._free: list[int] = list(range(self.n_region_slots))  # heap
        self._segments: set[Segment] = set()
        self._tick = 0
        self.n_evictions = 0
        self.n_inserts = 0
        self.n_insert_declined = 0  # region full of pinned segments

    # -- introspection -----------------------------------------------------

    @property
    def n_segments(self) -> int:
        return len(self._segments)

    @property
    def tokens_cached(self) -> int:
        return sum(s.length for s in self._segments)

    @property
    def n_pinned(self) -> int:
        return sum(1 for s in self._segments if s.refs > 0)

    @property
    def blocks_cached(self) -> int:
        """Paged mode: pool blocks the live segments logically span
        (``ceil(length/block_size)`` each — shared aliases counted once
        per segment, matching the capacity budget's bookkeeping)."""
        if not self.paged:
            return 0
        return sum(
            self._pool.blocks_needed(s.length) for s in self._segments
        )

    def nbytes(self) -> int:
        """Device bytes the cache accounts for (global logical bytes
        under TP): the fixed segment region in slab mode, or the live
        segments' block span in paged mode. Host metadata either way —
        scrapes never touch the live device arrays."""
        if self.paged:
            return self.blocks_cached * self._pool.block_nbytes()
        return self._nbytes

    def stats(self) -> dict:
        return {
            "segments": self.n_segments,
            "pinned": self.n_pinned,
            "tokens_cached": self.tokens_cached,
            "capacity_tokens": self.capacity_tokens,
            "evictions": self.n_evictions,
            "inserts": self.n_inserts,
            "insert_declined": self.n_insert_declined,
            "hits_recorded": sum(s.hits for s in self._segments),
        }

    # -- tree --------------------------------------------------------------

    def lookup(self, tokens: Iterable[int]) -> tuple[Segment | None, int]:
        """Longest cached prefix of ``tokens``: the deepest node on the
        query's root path holding a live segment. Returns
        ``(segment, matched_len)`` with ``matched_len ==
        segment.length`` (segments only exist at node boundaries), or
        ``(None, 0)``. A hit refreshes the segment's LRU tick."""
        q = tuple(int(t) for t in tokens)
        node, depth = self._root, 0
        best: Segment | None = None
        best_depth = 0
        while True:
            if node.segment is not None:
                best, best_depth = node.segment, depth
            child = node.children.get(q[depth]) if depth < len(q) else None
            if child is None:
                break
            e = child.edge
            if len(q) - depth < len(e) or q[depth:depth + len(e)] != e:
                break  # query diverges (or ends) mid-edge: no node there
            node, depth = child, depth + len(e)
        if best is not None:
            self._tick += 1
            best.last_use = self._tick
            best.hits += 1
        return best, best_depth

    def insert(self, tokens: Iterable[int]) -> list[Segment]:
        """Cache ``tokens`` as a new segment, claiming a region slot
        per segment (evicting unpinned LRU segments as needed).
        Returns the NEW segments needing device backing — the
        full-``tokens`` segment first, plus at most one segment at a
        newly observed BRANCH POINT: when this insert diverges from an
        existing path (edge split, or a new child under an existing
        interior node), the common prefix has now been seen with two
        different continuations — exactly the system-prompt sharing
        signal radix caches exist for — so it gets its own segment
        (length ≥ ``min_seg_len``), usable by future partial hits.
        Branch segments carry no stored logits (no request ended
        there), so they can never serve a FULL hit — the engine
        prefills their last row like any partial hit. The CALLER copies
        the KV slab into ``region`` at each ``segment.slot`` (a branch
        segment's slab is the same slab — rows past its length are
        stale, invisible under causal masking and overwritten by the
        suffix prefill). Empty when the prefix is already cached or
        every slot is pinned. Each returned segment starts PINNED
        (refs=1): not yet backed by device rows; the caller's unpin at
        request retirement makes it evictable."""
        q = tuple(int(t) for t in tokens)
        if not q:
            return []
        node, depth = self._root, 0
        branch: tuple[_Node, int] | None = None
        while depth < len(q):
            child = node.children.get(q[depth])
            if child is None:
                if node is not self._root and node.segment is None:
                    branch = (node, depth)  # existing branch node,
                    # sharing re-observed (e.g. after an eviction)
                nxt = _Node(q[depth:], node)
                node.children[q[depth]] = nxt
                node, depth = nxt, len(q)
                break
            c = _common_len(child.edge, q[depth:])
            if c == len(child.edge):
                node, depth = child, depth + c
                continue
            # split the edge at the divergence (or at query end)
            mid = _Node(child.edge[:c], node)
            node.children[q[depth]] = mid
            child.edge = child.edge[c:]
            child.parent = mid
            mid.children[child.edge[0]] = child
            if depth + c == len(q):
                node, depth = mid, len(q)
            else:
                branch = (mid, depth + c)
                nxt = _Node(q[depth + c:], mid)
                mid.children[nxt.edge[0]] = nxt
                node, depth = nxt, len(q)
            break
        out: list[Segment] = []
        # Branch FIRST: its _attach may evict, and if eviction prunes
        # away the branch node's other subtree the node drops to one
        # child and would be merged — the placeholder _attach puts on
        # before claiming makes it unprunable. The main leaf needs no
        # such shield: it has no descendants, so no eviction's upward
        # prune walk can reach it. (If the branch attach declines,
        # every slot is pinned and the main attach declines without
        # evicting either — no merge hazard on the bare branch node.)
        bseg = None
        if (branch is not None and branch[0].segment is None
                and branch[1] >= self.min_seg_len):
            bseg = self._attach(branch[0], branch[1])
        if node.segment is None:
            seg = self._attach(node, len(q))
            if seg is not None:
                out.append(seg)
            else:
                # drop the structural leaf just created; the upward
                # walk stops at the branch node (other children, plus
                # a segment if the branch attach succeeded)
                self._prune(node)
        if bseg is not None:
            out.append(bseg)
        return out

    def _attach(self, node: _Node, length: int) -> Segment | None:
        """Claim a region slot and attach a new pre-pinned segment to
        ``node``. The placeholder goes on BEFORE claiming: _claim_slot
        may evict, and eviction prunes/merges segment-less nodes —
        including this one, which would detach the node we are about to
        cache at. A node with a segment is never pruned, and the
        placeholder cannot be the eviction victim (it is not in
        ``_segments`` yet)."""
        seg = Segment(-1, length, node)
        seg.refs = 1
        node.segment = seg
        if self.paged:
            # Budget in blocks, not region slots: evict unpinned
            # segments until this one's block span fits, declining when
            # everything left is pinned (same bounded-by-construction
            # contract as the slab region).
            need = self._pool.blocks_needed(length)
            while self.blocks_cached + need > self.capacity_blocks:
                if not self._evict_one():
                    node.segment = None
                    self.n_insert_declined += 1
                    return None
            self._next_id += 1
            seg.slot = self._next_id
        else:
            slot = self._claim_slot()
            if slot is None:
                node.segment = None
                self.n_insert_declined += 1
                return None
            seg.slot = slot
        self._tick += 1
        seg.last_use = self._tick
        self._segments.add(seg)
        self.n_inserts += 1
        return seg

    # -- pinning / eviction ------------------------------------------------

    def pin(self, seg: Segment) -> None:
        """One more in-flight reader: the segment cannot be evicted
        until the matching :meth:`unpin`."""
        if seg.alive:
            seg.refs += 1

    def unpin(self, seg: Segment) -> None:
        """Release one reader. Safe on a segment dropped by ``reinit``
        (crash recovery clears pins wholesale)."""
        if seg.alive and seg.refs > 0:
            seg.refs -= 1

    def _claim_slot(self) -> int | None:
        if self._free:
            return heapq.heappop(self._free)
        if self._evict_one():
            return heapq.heappop(self._free)
        return None

    def _evict_one(self) -> bool:
        """Drop the UNPINNED segment with the lowest hit-weighted
        recency score (``last_use + hit_weight * hits`` — see
        ``__init__``; ties broken by raw recency, then slot index for
        determinism). Pinned segments (refs > 0 — referenced by an
        active slot's in-flight admission) are never candidates, so
        eviction can fail even at full capacity; the caller declines
        the insert instead."""
        victim: Segment | None = None
        vscore = None
        for seg in self._segments:
            if seg.refs:
                continue
            score = (seg.last_use + self.hit_weight * seg.hits,
                     seg.last_use, seg.slot)
            if victim is None or score < vscore:
                victim, vscore = seg, score
        if victim is None:
            return False
        self._drop(victim)
        self.n_evictions += 1
        if self.on_evict is not None:
            self.on_evict(victim)
        return True

    def drop(self, seg: Segment) -> None:
        """Abort an insert: the engine failed to back ``seg`` with
        device rows (paged mode — the tail-block allocation lost a race
        with admission pressure), so remove it before any lookup can
        hit unbacked storage. Safe no-op on a segment already gone."""
        if seg.alive:
            self._drop(seg)

    def reclaim(self) -> bool:
        """Evict one unpinned segment on demand, returning whether one
        was dropped. Paged admission uses this to hand cached blocks
        back to the pool's free heap when a fresh request doesn't fit."""
        return self._evict_one()

    def _drop(self, seg: Segment) -> None:
        seg.alive = False
        seg.logits = None
        seg.node.segment = None
        self._segments.discard(seg)
        if self.paged:
            if seg.block_ids:
                self._pool.decref(seg.block_ids)
            seg.block_ids = None
        else:
            heapq.heappush(self._free, seg.slot)
        self._prune(seg.node)

    def _prune(self, node: _Node) -> None:
        """Re-compress the trie after a removal: delete childless
        segment-less nodes bottom-up, then merge a single-child
        segment-less node into its child."""
        while (node is not self._root and node.segment is None
               and not node.children):
            parent = node.parent
            del parent.children[node.edge[0]]
            node = parent
        if (node is not self._root and node.segment is None
                and len(node.children) == 1):
            (child,) = node.children.values()
            child.edge = node.edge + child.edge
            child.parent = node.parent
            node.parent.children[node.edge[0]] = child

    # -- recovery ----------------------------------------------------------

    def reinit(self) -> None:
        """Crash recovery: re-create the region buffers zeroed and drop
        every segment AND every pin (the engine clears its per-slot
        segment refs in the same breath). Replay then misses on every
        lookup — the same code path as a cold start, so recovered
        streams stay byte-identical.

        Paged ordering contract: the engine calls ``pool.reinit()``
        FIRST (it resets every refcount and rebuilds the block free
        heap wholesale), so dropping segments here must NOT decref
        their block ids — the counts they referenced no longer exist."""
        if not self.paged:
            self.region = self._alloc_region()
        for seg in list(self._segments):
            seg.alive = False
            seg.logits = None
            seg.refs = 0
            seg.block_ids = None
        self._root = _Node((), None)
        self._free = list(range(self.n_region_slots))
        self._segments = set()
